#!/usr/bin/env python
"""Import a reference GAN `tf.train.Checkpoint` into an Orbax workdir.

The reference's GAN trainers checkpoint with `tf.train.Checkpoint` +
CheckpointManager — DCGAN saves objects `generator`/`discriminator`
(`DCGAN/tensorflow/main.py:34-39`), CycleGAN saves `generator_a2b`/
`generator_b2a`/`discriminator_a`/`discriminator_b` plus an `epoch` variable
(`CycleGAN/tensorflow/train.py:134-148`). This maps those weights onto our
Flax models (utils/gan_convert.py) and writes a trainer-compatible Orbax
checkpoint, so `DCGAN/jax/inference.py` / `CycleGAN/jax/inference.py` /
`--resume` pick up the reference's published weights.

Usage:
    python tools/import_gan_checkpoint.py --family dcgan \
        --ckpt ./checkpoints [--workdir runs/dcgan]
    python tools/import_gan_checkpoint.py --family cyclegan \
        --ckpt ./checkpoints-horse2zebra [--n-blocks 9] [--workdir runs/cyclegan]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _read_counter(reader, names=("epoch", "step")) -> int:
    """The reference persists the epoch (CycleGAN) / step (DCGAN) as a
    checkpointed tf.Variable — recover it for the Orbax save number."""
    for name in names:
        key = f"{name}/.ATTRIBUTES/VARIABLE_VALUE"
        if reader.has_tensor(key):
            return int(reader.get_tensor(key))
    return 0


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--family", required=True, choices=["dcgan", "cyclegan"])
    p.add_argument("--ckpt", required=True,
                   help="tf.train checkpoint prefix (.../ckpt-40) or the "
                        "reference's checkpoint directory (latest is used)")
    p.add_argument("--workdir", default=None)
    p.add_argument("--n-blocks", type=int, default=9,
                   help="CycleGAN generator residual blocks (reference: 9)")
    p.add_argument("--epoch", type=int, default=None,
                   help="epoch to record (default: the checkpoint's own "
                        "epoch/step counter)")
    args = p.parse_args(argv)

    import jax

    from deepvision_tpu.configs import get_config
    from deepvision_tpu.utils import gan_convert

    try:
        # one reader for the counter + every object: the files are scanned
        # once however many networks the family has
        reader = gan_convert.open_reader(args.ckpt)
    except FileNotFoundError as e:
        raise SystemExit(f"error: {e}")
    epoch = args.epoch if args.epoch is not None else _read_counter(reader)

    def check_shapes(what, init_tree, new_tree):
        """Every imported leaf must match the freshly-initialized state, so a
        wrong --n-blocks or truncated checkpoint fails HERE with the paths
        named, not at inference time."""
        init_flat = dict(jax.tree_util.tree_leaves_with_path(init_tree))
        new_flat = dict(jax.tree_util.tree_leaves_with_path(new_tree))
        missing = set(init_flat) - set(new_flat)
        extra = set(new_flat) - set(init_flat)
        if missing or extra:
            # sort the rendered strings: jax DictKey path tuples themselves
            # are not orderable
            raise SystemExit(
                f"{what}: structure mismatch — missing "
                f"{sorted(jax.tree_util.keystr(p) for p in missing)}, extra "
                f"{sorted(jax.tree_util.keystr(p) for p in extra)}")
        for path in init_flat:
            if init_flat[path].shape != new_flat[path].shape:
                raise SystemExit(
                    f"{what}{jax.tree_util.keystr(path)}: checkpoint shape "
                    f"{new_flat[path].shape} != model {init_flat[path].shape}")

    if args.family == "dcgan":
        from deepvision_tpu.core.gan import DCGANTrainer

        cfg = get_config("dcgan")
        workdir = args.workdir or os.path.join("runs", cfg.name)
        trainer = DCGANTrainer(cfg, workdir=workdir)
        g_params, g_stats = gan_convert.convert_object(reader, "generator")
        d_params, d_stats = gan_convert.convert_object(reader,
                                                       "discriminator")
        check_shapes("generator", trainer.gen_state.params, g_params)
        check_shapes("discriminator", trainer.disc_state.params, d_params)
        trainer.gen_state = trainer.gen_state.replace(
            params=g_params, batch_stats=g_stats)
        trainer.disc_state = trainer.disc_state.replace(params=d_params)
    else:
        from deepvision_tpu.core.gan import CycleGANTrainer

        cfg = get_config("cyclegan")
        workdir = args.workdir or os.path.join("runs", cfg.name)
        trainer = CycleGANTrainer(cfg, workdir=workdir,
                                  n_blocks=args.n_blocks)
        g_params, g_stats = {}, {}
        for name in ("a2b", "b2a"):
            g_params[name], g_stats[name] = gan_convert.convert_object(
                reader, f"generator_{name}", n_blocks=args.n_blocks)
        d_params, d_stats = {}, {}
        for name in ("a", "b"):
            d_params[name], d_stats[name] = gan_convert.convert_object(
                reader, f"discriminator_{name}")
        check_shapes("generators", trainer.gen_state.params, g_params)
        check_shapes("discriminators", trainer.disc_state.params, d_params)
        trainer.gen_state = trainer.gen_state.replace(
            params=g_params, batch_stats=g_stats)
        trainer.disc_state = trainer.disc_state.replace(
            params=d_params, batch_stats=d_stats)

    trainer.ckpt.save(epoch, trainer._payload())
    trainer.ckpt.flush()
    trainer.close()
    print(f"imported {args.family} checkpoint {args.ckpt} -> {workdir} "
          f"(epoch {epoch})")
    return workdir


if __name__ == "__main__":
    main()
