"""Benchmark: ResNet-50 ImageNet-shape training-step throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Baseline: the reference's ResNet-50 was trained on 1x P100 at batch 256
(`ResNet/pytorch/README.md:24,67`). A P100 sustains ~230 images/sec on ResNet-50
fp32 training (MLPerf-era public number); vs_baseline = ours / 230.

Robustness (the axon TPU relay can HANG — not error — for >12 minutes):
the measurement itself runs in a KILLABLE SUBPROCESS (`--worker`), so a
tunnel wedge mid-benchmark can never hang this process. The orchestrator
retries the TPU worker with growing timeouts inside an overall deadline
(BENCH_DEADLINE_SECS, default 780s — chosen to finish before the driver's
own patience runs out), then degrades in order of honesty:

  1. fresh TPU measurement            -> printed, cached to BENCH_CACHE.json
  2. last cached TPU measurement      -> printed with "stale": true + age
  3. CPU fallback (small shapes)      -> printed with platform=cpu

A stale-but-real chip number beats a fresh CPU number: the CPU fallback
reads as a ~100x regression against the P100 baseline and says nothing
about the TPU program (round-1 lesson, VERDICT.md). BENCH_CACHE.json is
deliberately COMMITTED (not gitignored): it is the cross-round provenance
record, refreshed whenever a bench run reaches the real chip. An explicit
`JAX_PLATFORMS=cpu python bench.py` benches the CPU and never answers from
the cache.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

P100_BASELINE_IMG_PER_SEC = 230.0
CACHE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "BENCH_CACHE.json")


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# worker: the actual measurement (runs on whatever platform env selects)
# ---------------------------------------------------------------------------

def worker() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    # persistent XLA cache: retried workers (and re-benches after a tunnel
    # flake) skip the 20-40s TPU / minutes-long CPU first compile. The
    # hit/miss counts land in the printed record so a bench attempt that
    # re-paid compile time says so (cache moved/disabled reads identically
    # to "slow chip" otherwise).
    from deepvision_tpu.cli import (compilation_cache_stats,
                                    setup_compilation_cache)
    setup_compilation_cache()

    from deepvision_tpu.core import steps
    from deepvision_tpu.core.config import OptimizerConfig, ScheduleConfig
    from deepvision_tpu.core.optim import build_optimizer
    from deepvision_tpu.core.train_state import TrainState, init_model
    from deepvision_tpu.models import MODELS
    from deepvision_tpu.parallel import mesh as mesh_lib

    n_dev = len(jax.devices())
    mesh = mesh_lib.make_mesh()
    platform = jax.devices()[0].platform
    batch = 256 if platform == "tpu" else 32  # per-chip ImageNet batch
    image_size = 224 if platform == "tpu" else 64

    # Headline vs grid-variant selection. Headline (env unset): the
    # recommended flagship `resnet50_lean` — checkpoint-compatible with
    # resnet50 (all-f32 state, tests/test_models_classification.py
    # TestLowpTrafficVariants) and measured +7.7% over it on-chip
    # (runs/r05_resnet50_tpu_profile/TRAFFIC.json). The traffic grid
    # (tools/bench_traffic.py) sets DEEPVISION_BENCH_KWARGS — '{}' for the
    # plain-resnet50 baseline, or explicit lowp flags — so its variants
    # stay comparable across rounds and never shadow the headline.
    # (empty string counts as unset, so `DEEPVISION_BENCH_KWARGS= python
    # bench.py` benches the headline instead of crashing json.loads)
    env_kwargs = os.environ.get("DEEPVISION_BENCH_KWARGS")
    if not env_kwargs:
        model_name, variant_kwargs = "resnet50_lean", {}
    else:
        model_name, variant_kwargs = "resnet50", json.loads(env_kwargs)
    model = MODELS.get(model_name)(num_classes=1000, **variant_kwargs)
    rng = jax.random.PRNGKey(0)
    params, batch_stats = init_model(model, rng,
                                     jnp.zeros((2, image_size, image_size, 3)))
    tx = build_optimizer(OptimizerConfig(name="momentum", learning_rate=0.1,
                                         weight_decay=1e-4),
                         ScheduleConfig(name="cosine", warmup_epochs=1),
                         steps_per_epoch=1000, total_epochs=90)
    state = TrainState.create(model.apply, params, tx, batch_stats)
    state = jax.device_put(state, mesh_lib.replicated(mesh))

    train_step = steps.make_classification_train_step(
        label_smoothing=0.1, compute_dtype=jnp.bfloat16, mesh=mesh)

    rs = np.random.RandomState(0)
    images = rs.randn(batch, image_size, image_size, 3).astype(np.float32)
    labels = rs.randint(0, 1000, size=(batch,)).astype(np.int32)
    sharded = mesh_lib.shard_batch_pytree(mesh, (images, labels))

    # warmup / compile (the float() transfer is the only honest sync on the
    # axon relay: block_until_ready returns before remote execution finishes)
    for _ in range(3):
        state, metrics = train_step(state, *sharded, rng)
    float(metrics["loss"])

    # optional XProf capture (the MFU attack path): a few post-warmup steps
    # traced inside the same killable worker, so a tunnel wedge mid-capture
    # can't hang the orchestrator
    profile_dir = os.environ.get("DEEPVISION_BENCH_PROFILE_DIR")
    if profile_dir:
        jax.profiler.start_trace(profile_dir)
        try:
            for _ in range(3):
                state, metrics = train_step(state, *sharded, rng)
            float(metrics["loss"])
        finally:
            jax.profiler.stop_trace()

    def timed(n):
        nonlocal state
        t0 = time.perf_counter()
        for _ in range(n):
            state, metrics = train_step(state, *sharded, rng)
        float(metrics["loss"])  # sync: depends on the full chain of steps
        return time.perf_counter() - t0

    # two loop lengths; the delta cancels constant dispatch/transfer latency
    n1, n2 = (5, 25) if platform == "tpu" else (1, 5)
    t1, t2 = timed(n1), timed(n2)
    dt, n_steps = t2 - t1, n2 - n1
    if dt <= 0:  # degenerate timing (clock noise) — fall back to the long run
        dt, n_steps = t2, n2

    # XLA cost-model bytes/step for the traffic grid (same caveat as
    # trace_report: logical bytes, not a DRAM counter). The relay's failure
    # mode is a HANG, not an exception, so a bare try/except can't protect
    # the already-finished measurement — run the AOT query on a daemon
    # thread with a bounded join and proceed without the number if it
    # wedges (the process can then still print and exit).
    cost_gb = None
    if os.environ.get("DEEPVISION_BENCH_COST"):
        import threading
        box = {}

        def _cost():
            try:
                ca = train_step.lower(state, *sharded, rng).compile() \
                    .cost_analysis()
                ca = ca[0] if isinstance(ca, (list, tuple)) else ca
                box["gb"] = round(float(ca["bytes accessed"]) / 1e9, 2)
            except Exception:
                pass

        t = threading.Thread(target=_cost, daemon=True)
        t.start()
        t.join(timeout=120.0)
        cost_gb = box.get("gb")

    variant_tag = "".join(
        f",{k}" for k, v in sorted(variant_kwargs.items()) if v)
    img_per_sec_per_chip = n_steps * batch / dt / n_dev
    print(json.dumps({
        "metric": f"{model_name}_train_images_per_sec_per_chip"
                  f"(b{batch},{image_size}px,{platform}{variant_tag})",
        **({"cost_model_gb_per_step": cost_gb} if cost_gb else {}),
        "value": round(img_per_sec_per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(img_per_sec_per_chip / P100_BASELINE_IMG_PER_SEC,
                             3),
        "platform": platform,
        # provenance: proves this record came from an actual worker run
        # (a hand-seeded cache entry can't know these)
        "device_kind": jax.devices()[0].device_kind,
        "jax_version": jax.__version__,
        "timed_steps": n_steps,
        # persistent-cache accounting for THIS worker run (hits mean the
        # warmup compile above was served from disk, not re-paid)
        "compile_cache": compilation_cache_stats(),
    }))


# ---------------------------------------------------------------------------
# orchestrator
# ---------------------------------------------------------------------------

def _run_worker(env: dict, timeout_s: float, argv=None):
    """Run a measurement worker (default: `bench.py --worker`) in its own
    session; return the parsed JSON record or None. killpg reaps tunnel
    helper processes on timeout. `argv` lets other benchmark orchestrators
    (tools/bench_sweep.py, tools/bench_dispatch.py) reuse the same
    wedge-proof runner for their own workers."""
    import signal
    proc = subprocess.Popen(
        argv or [sys.executable, os.path.abspath(__file__), "--worker"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        text=True, start_new_session=True)
    try:
        out, _ = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        proc.wait()
        return None
    if proc.returncode != 0:
        return None
    for line in reversed(out.strip().splitlines()):
        try:
            rec = json.loads(line)
            if "metric" in rec:
                return rec
        except json.JSONDecodeError:
            continue
    return None


def _load_cache():
    try:
        with open(CACHE_PATH) as fp:
            rec = json.load(fp)
        if rec.get("platform") != "tpu":
            return None
        # self-authentication: only _save_cache writes `cache_written_by`
        # (from the worker's device/version fields). A record lacking it was
        # seeded by hand (e.g. from a doc claim), not measured by bench.py —
        # surface that so the consumer can discount it (round-2 VERDICT).
        if "cache_written_by" not in rec:
            rec["provenance"] = "seeded"
        return rec
    except (OSError, json.JSONDecodeError):
        return None


def _save_cache(rec: dict) -> None:
    # MOVE the worker's provenance fields under cache_written_by (no
    # duplicated state): their presence there is what _load_cache trusts,
    # and a hand-seeded entry can't fabricate them plausibly
    rec = dict(rec)
    # per-run compile-cache accounting is meaningless replayed as a stale
    # record — drop it from the committed cache
    rec.pop("compile_cache", None)
    rec["cache_written_by"] = {
        "program": "bench.py",
        "jax_version": rec.pop("jax_version", "unknown"),
        "device_kind": rec.pop("device_kind", "unknown"),
        "timed_steps": rec.pop("timed_steps", "unknown"),
    }
    try:
        with open(CACHE_PATH, "w") as fp:
            json.dump(rec, fp, indent=1)
            fp.write("\n")
    except OSError as e:
        _log(f"could not persist bench cache: {e}")


def main() -> None:
    deadline = time.monotonic() + float(
        os.environ.get("BENCH_DEADLINE_SECS", "780"))
    env = dict(os.environ)
    cpu_requested = env.get("JAX_PLATFORMS") == "cpu"
    # any non-empty DEEPVISION_BENCH_KWARGS — including '{}', the traffic
    # grid's plain-resnet50 baseline — selects a grid variant, not the
    # headline (resnet50_lean; see worker()). Empty string = unset = the
    # headline, matching the worker's parse. Validate it here too so a typo
    # fails fast with a readable error instead of burning the deadline on
    # workers whose identical crash is piped to DEVNULL. The key allowlist
    # mirrors tools/bench_traffic.py's VARIANTS — extend both together.
    allowed = {"lowp_residual", "lowp_bn"}
    bad_kwargs = SystemExit(
        f"DEEPVISION_BENCH_KWARGS must be a JSON object with keys from "
        f"{sorted(allowed)} and boolean values, got: "
        f"{env.get('DEEPVISION_BENCH_KWARGS')!r}")
    try:
        parsed_kwargs = json.loads(env.get("DEEPVISION_BENCH_KWARGS") or "{}")
    except json.JSONDecodeError:
        # a missing quote must fail with the same readable message, not an
        # uncaught decoder traceback
        raise bad_kwargs from None
    if not isinstance(parsed_kwargs, dict) or \
            not set(parsed_kwargs) <= allowed or \
            not all(isinstance(v, bool) for v in parsed_kwargs.values()):
        # value types too: {"lowp_bn": [1]} is truthy and would silently
        # configure the model while tagging the metric
        raise bad_kwargs
    variant = bool(env.get("DEEPVISION_BENCH_KWARGS"))
    # an explicit CPU request means "bench the CPU", and a variant request
    # means "bench THAT variant": neither may be answered with the cached
    # headline TPU record
    cache = None if (cpu_requested or variant) else _load_cache()
    non_tpu_result = None  # a successful worker run on some other platform

    if not cpu_requested:
        # TPU attempts with growing timeouts until ~90s before the deadline
        # (reserve time for the cache/CPU fallback path). Fast nonzero exits
        # (broken plugin, connection refused) retry after a short pause;
        # timeouts mean the tunnel is wedged — longer waits help more.
        attempt, timeout_s = 0, 240.0
        while True:
            remaining = deadline - time.monotonic() - 90.0
            if remaining <= 60.0:
                break
            attempt += 1
            t = min(timeout_s, remaining)
            _log(f"TPU bench attempt {attempt} (timeout {t:.0f}s, "
                 f"{remaining:.0f}s of budget left)")
            t0 = time.monotonic()
            rec = _run_worker(env, t)
            if rec is not None:
                if rec.get("platform") == "tpu":
                    rec["measured_at"] = time.strftime(
                        "%Y-%m-%dT%H:%M:%SZ", time.gmtime())
                    # the committed cache is the HEADLINE record — a variant
                    # run (traffic grid) must not overwrite it
                    if not variant:
                        _save_cache(rec)
                    print(json.dumps(rec))
                    return
                # a successful non-TPU run (no TPU plugin on this machine):
                # keep it — retrying the same deterministic benchmark can't
                # produce a TPU number, so don't burn the budget on reruns
                _log(f"worker ran on {rec.get('platform')!r}, not tpu; "
                     f"keeping as fallback")
                non_tpu_result = rec
                break
            took = time.monotonic() - t0
            if took < 30:  # fast failure — no point hammering immediately
                time.sleep(min(30.0, max(0.0, deadline - time.monotonic() - 120)))
            timeout_s *= 1.5

    if non_tpu_result is not None and cache is None:
        print(json.dumps(non_tpu_result))
        return

    if cache is not None:
        # stale-but-real beats fresh-but-irrelevant: surface the last real
        # chip measurement with its age so the record is honest
        age = "unknown"
        if "measured_at" in cache:
            try:
                then = time.mktime(time.strptime(cache["measured_at"],
                                                 "%Y-%m-%dT%H:%M:%SZ"))
                age = int(time.time() - then)
            except ValueError:
                pass
        cache = dict(cache, stale=True, stale_age_seconds=age)
        _log("TPU unreachable; reporting last cached TPU measurement "
             f"(measured_at={cache.get('measured_at')})")
        print(json.dumps(cache))
        return

    _log("TPU unreachable and no cached TPU measurement; CPU fallback")
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    # the CPU fallback may be compiling ResNet-50 from scratch (minutes on
    # XLA-CPU the first time; the persistent cache makes reruns fast) — give
    # it a real floor even when the TPU attempts ate the deadline
    rec = _run_worker(env, max(480.0, deadline - time.monotonic()))
    if rec is None:  # even the CPU fallback failed — report that honestly
        failed_name = "resnet50" if variant else "resnet50_lean"
        rec = {"metric": f"{failed_name}_train_images_per_sec_per_chip(failed)",
               "value": 0.0, "unit": "images/sec/chip", "vs_baseline": 0.0,
               "platform": "none"}
    print(json.dumps(rec))


if __name__ == "__main__":
    if "--worker" in sys.argv:
        worker()
    else:
        main()
