"""Benchmark: ResNet-50 ImageNet-shape training-step throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline: the reference's ResNet-50 was trained on 1x P100 at batch 256
(`ResNet/pytorch/README.md:24,67`). A P100 sustains ~230 images/sec on ResNet-50
fp32 training (MLPerf-era public number); vs_baseline = ours / 230.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

P100_BASELINE_IMG_PER_SEC = 230.0


def _devices_with_cpu_fallback(probe_timeout_s: int = 240):
    """jax.devices(), falling back to CPU if the TPU backend is unreachable
    (tunnel flakes must yield a number, not a crash).

    The tunnel can HANG rather than error (observed: >10 min stuck claiming
    the relay), which would hang this process at the first backend touch.
    So the TPU is probed in a SUBPROCESS with a hard timeout first; only a
    healthy probe lets this process touch the default backend."""
    import os
    import subprocess
    import sys

    def _fall_back(reason):
        print(f"TPU backend unavailable ({reason}); falling back to CPU",
              file=sys.stderr, flush=True)
        jax.config.update("jax_platforms", "cpu")
        return jax.devices()

    # Probe unless CPU was explicitly requested: the unset/auto-discovery
    # default also initializes installed PJRT plugins and can hang the same
    # way. DEVNULL + its own session so a tunnel helper process inheriting
    # pipes can't block us past the timeout (killpg reaps the whole group).
    # Tunnel outages are usually transient, and a CPU-fallback number reads
    # as a ~170x regression next to a real-chip run — so retry the probe a
    # few times before giving up on the TPU.
    if jax.config.jax_platforms != "cpu":
        import signal
        attempts = 3
        for attempt in range(1, attempts + 1):
            probe = subprocess.Popen(
                [sys.executable, "-c", "import jax; jax.devices()"],
                env=dict(os.environ), stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL, start_new_session=True)
            try:
                rc = probe.wait(timeout=probe_timeout_s)
                if rc == 0:
                    break
                reason = f"probe exited {rc}"
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(os.getpgid(probe.pid), signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
                reason = f"probe timed out after {probe_timeout_s}s"
            if attempt == attempts:
                return _fall_back(f"{reason} ({attempts} attempts)")
            # timeouts = tunnel wedged, give it time to recover; fast nonzero
            # exits (broken/absent plugin, connection refused) retry
            # immediately so a deterministic failure costs seconds, not sleeps
            delay = 30 if "timed out" in reason else 0
            print(f"TPU probe attempt {attempt}/{attempts} failed ({reason}); "
                  f"retrying{f' in {delay}s' if delay else ''}",
                  file=sys.stderr, flush=True)
            if delay:
                time.sleep(delay)
    try:
        return jax.devices()
    except RuntimeError as e:
        return _fall_back(e)


def main():
    from deepvision_tpu.core import steps
    from deepvision_tpu.core.config import OptimizerConfig, ScheduleConfig
    from deepvision_tpu.core.optim import build_optimizer
    from deepvision_tpu.core.train_state import TrainState, init_model
    from deepvision_tpu.models import MODELS
    from deepvision_tpu.parallel import mesh as mesh_lib

    n_dev = len(_devices_with_cpu_fallback())
    mesh = mesh_lib.make_mesh()
    platform = jax.devices()[0].platform
    batch = 256 if platform == "tpu" else 32  # per-chip ImageNet batch
    image_size = 224 if platform == "tpu" else 64

    model = MODELS.get("resnet50")(num_classes=1000)
    rng = jax.random.PRNGKey(0)
    params, batch_stats = init_model(model, rng, jnp.zeros((2, image_size, image_size, 3)))
    tx = build_optimizer(OptimizerConfig(name="momentum", learning_rate=0.1,
                                         weight_decay=1e-4),
                         ScheduleConfig(name="cosine", warmup_epochs=1),
                         steps_per_epoch=1000, total_epochs=90)
    state = TrainState.create(model.apply, params, tx, batch_stats)
    repl = mesh_lib.replicated(mesh)
    state = jax.device_put(state, repl)

    train_step = steps.make_classification_train_step(
        label_smoothing=0.1, compute_dtype=jnp.bfloat16, mesh=mesh)

    rs = np.random.RandomState(0)
    images = rs.randn(batch, image_size, image_size, 3).astype(np.float32)
    labels = rs.randint(0, 1000, size=(batch,)).astype(np.int32)
    sharded = mesh_lib.shard_batch_pytree(mesh, (images, labels))

    # warmup / compile (the float() transfer is the only honest sync on the
    # axon relay: block_until_ready returns before remote execution finishes)
    for _ in range(3):
        state, metrics = train_step(state, *sharded, rng)
    float(metrics["loss"])

    def timed(n):
        nonlocal state
        t0 = time.perf_counter()
        for _ in range(n):
            state, metrics = train_step(state, *sharded, rng)
        float(metrics["loss"])  # sync: depends on the full chain of steps
        return time.perf_counter() - t0

    # two loop lengths; the delta cancels constant dispatch/transfer latency
    n1, n2 = (5, 25) if platform == "tpu" else (1, 5)
    t1, t2 = timed(n1), timed(n2)
    dt, n_steps = t2 - t1, n2 - n1
    if dt <= 0:  # degenerate timing (clock noise) — fall back to the long run
        dt, n_steps = t2, n2

    img_per_sec = n_steps * batch / dt
    img_per_sec_per_chip = img_per_sec / n_dev
    print(json.dumps({
        "metric": f"resnet50_train_images_per_sec_per_chip(b{batch},{image_size}px,{platform})",
        "value": round(img_per_sec_per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(img_per_sec_per_chip / P100_BASELINE_IMG_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
