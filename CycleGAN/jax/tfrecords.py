#!/usr/bin/env python
"""CycleGAN two-domain image dirs → per-domain TFRecords.

Parity target: `CycleGAN/tensorflow/tfrecords.py` — one TFRecord per
{trainA, trainB, testA, testB} split from `datasets/<name>/` image dirs, JPEG
images only (non-JPEG re-encoded rather than crashed on — the reference
swallows them with a print, `:30-32`).

Usage: python tfrecords.py --dataset monet2photo
"""

from __future__ import annotations

import argparse
import glob
import io
import os


def convert_to_tfexample(img_path: str):
    import tensorflow as tf
    from PIL import Image
    try:
        with open(img_path, "rb") as f:
            content = f.read()
        with Image.open(io.BytesIO(content)) as im:
            im.load()
            if im.format != "JPEG" or im.mode != "RGB":
                with io.BytesIO() as out:
                    im.convert("RGB").save(out, format="JPEG", quality=95)
                    content = out.getvalue()
            feature = {
                "image/encoded": tf.train.Feature(
                    bytes_list=tf.train.BytesList(value=[content])),
                "image/format": tf.train.Feature(
                    bytes_list=tf.train.BytesList(value=[b"JPEG"])),
                "image/width": tf.train.Feature(
                    int64_list=tf.train.Int64List(value=[im.width])),
                "image/height": tf.train.Feature(
                    int64_list=tf.train.Int64List(value=[im.height])),
                "image/filename": tf.train.Feature(
                    bytes_list=tf.train.BytesList(
                        value=[os.path.basename(img_path).encode()])),
            }
            return tf.train.Example(features=tf.train.Features(feature=feature))
    except Exception as e:  # bad image → skip with a warning (`:30-32`)
        print(f"WARNING: skipping {img_path}: {e}")
        return None


def main():
    import tensorflow as tf
    p = argparse.ArgumentParser(
        description="Convert TFRecords for a CycleGAN dataset.")
    p.add_argument("--dataset", required=True,
                   help="name under ./datasets/ with trainA/trainB[/testA/testB]")
    p.add_argument("--data-root", default="./datasets")
    p.add_argument("--out-root", default="./tfrecords")
    args = p.parse_args()

    out_dir = os.path.join(args.out_root, args.dataset)
    os.makedirs(out_dir, exist_ok=True)
    for split in ("trainA", "trainB", "testA", "testB"):
        files = sorted(glob.glob(
            os.path.join(args.data_root, args.dataset, split, "*")))
        if not files:
            continue
        out_path = os.path.join(out_dir, f"{split}.tfrecord")
        n = 0
        with tf.io.TFRecordWriter(out_path) as writer:
            for path in files:
                example = convert_to_tfexample(path)
                if example is not None:
                    writer.write(example.SerializeToString())
                    n += 1
        print(f"Finished converting {n} images for {split}")


if __name__ == "__main__":
    main()
