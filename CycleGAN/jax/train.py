#!/usr/bin/env python
"""Train CycleGAN on TPU — `python train.py --dataset <name> [--batch_size 4]`.

Per-family entrypoint matching the reference's UX
(`CycleGAN/tensorflow/train.py:24-31`: `--dataset` names the
`tfrecords/<dataset>/{trainA,trainB}.tfrecord` pair), backed by the shared
deepvision_tpu CycleGANTrainer (jitted generator phase → host ImagePool → jitted
discriminator phase).
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main():
    p = argparse.ArgumentParser(description="Train CycleGAN (TPU-native JAX).")
    p.add_argument("--dataset", help="dataset name under tfrecords/")
    p.add_argument("--batch_size", "--batch-size", type=int, default=None)
    p.add_argument("--epochs", type=int, default=None)
    p.add_argument("--image-size", type=int, default=256)
    p.add_argument("--workdir", default=None)
    p.add_argument("--resume", action="store_true")
    p.add_argument("--synthetic", action="store_true",
                   help="random two-domain data smoke run (the reference's "
                        "commented-out local test, train.py:338-342)")
    p.add_argument("--steps-per-epoch", type=int, default=2)
    p.add_argument("--profile-dir", default=None,
                   help="capture a jax.profiler trace of the first epoch here")
    p.add_argument("--recover-on-divergence", type=int, default=None,
                   metavar="N",
                   help="roll back to the last committed checkpoint and "
                        "retry (LR scaled down) up to N times when an "
                        "epoch's metrics go non-finite (default 0: halt)")
    p.add_argument("--compilation-cache",
                   default=os.environ.get("DEEPVISION_COMPILATION_CACHE",
                                          "auto"),
                   metavar="DIR|off", help="persistent XLA compilation cache "
                   "(see the shared trainer CLIs); 'off' disables")
    args = p.parse_args()

    from deepvision_tpu.cli import setup_compilation_cache
    from deepvision_tpu.configs import get_config
    from deepvision_tpu.core.gan import CycleGANTrainer
    from deepvision_tpu.data import gan as gan_data

    setup_compilation_cache(args.compilation_cache)

    cfg = get_config("cyclegan")
    if args.epochs:
        cfg = cfg.replace(total_epochs=args.epochs)
    if args.batch_size:
        cfg = cfg.replace(batch_size=args.batch_size)
    if args.recover_on_divergence is not None:
        cfg = cfg.replace(recover_on_divergence=args.recover_on_divergence)

    image_size = 64 if args.synthetic else args.image_size
    workdir = args.workdir or (
        f"runs/cyclegan-{args.dataset}" if args.dataset else "runs/cyclegan")

    if args.synthetic:
        steps_per_epoch = args.steps_per_epoch

        def train_fn(epoch):
            return gan_data.synthetic_two_domain_batches(
                cfg.batch_size, image_size=image_size,
                steps=steps_per_epoch, seed=epoch)
    else:
        if not args.dataset:
            p.error("--dataset is required without --synthetic")
        ds = gan_data.build_two_domain_dataset(
            f"tfrecords/{args.dataset}/trainA.tfrecord",
            f"tfrecords/{args.dataset}/trainB.tfrecord",
            batch_size=cfg.batch_size, image_size=image_size)
        # count batches up front so LinearDecay is anchored to the true epoch
        # length (the reference counts too, train.py:108-120)
        steps_per_epoch = sum(1 for _ in ds)
        print(f"Batch size: {cfg.batch_size}, "
              f"Total batches per epoch: {steps_per_epoch}")

        def train_fn(epoch, _ds=ds):
            return _ds.as_numpy_iterator()

    trainer = CycleGANTrainer(cfg, workdir=workdir, image_size=image_size,
                              steps_per_epoch=steps_per_epoch)
    if args.resume:
        got = trainer.resume()
        print(f"resumed from epoch {got}" if got else "no checkpoint found")

    from deepvision_tpu.core.trainer import fit_and_close
    metrics = fit_and_close(trainer, train_fn, profile_dir=args.profile_dir)
    print(f"done: {metrics}")


if __name__ == "__main__":
    main()
