#!/usr/bin/env bash
# Fetch a CycleGAN pair dataset and build its TFRecords
# (`CycleGAN/tensorflow/setup.sh` role). Usage: ./setup.sh [monet2photo]
set -euo pipefail
DATASET="${1:-monet2photo}"
BASE_URL="https://people.eecs.berkeley.edu/~taesung_park/CycleGAN/datasets"

mkdir -p datasets
if [ ! -d "datasets/${DATASET}" ]; then
  wget "${BASE_URL}/${DATASET}.zip"
  # extract to a temp dir and move into place so an interrupted unzip can't
  # leave a partial datasets/${DATASET}/ that later runs mistake for complete
  TMP="$(mktemp -d datasets/.extract.XXXXXX)"
  unzip -q "${DATASET}.zip" -d "${TMP}"
  mv "${TMP}/${DATASET}" "datasets/${DATASET}"
  rmdir "${TMP}"
  rm "${DATASET}.zip"
fi
python tfrecords.py --dataset "${DATASET}"
echo "done: tfrecords/${DATASET}"
