#!/usr/bin/env python
"""Split CelebA into male (trainA) / female (trainB) domains by the gender
attribute — parity with `CycleGAN/tensorflow/celeba.py` (hard-coded paths
replaced by flags; attribute parsed by column name instead of fixed offsets).

Usage: python celeba.py --attrs list_attr_celeba.txt --images img_align_celeba \
           --out datasets/celeba
"""

from __future__ import annotations

import argparse
import os
from shutil import copyfile


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--attrs", default="./list_attr_celeba.txt")
    p.add_argument("--images", default="./img_align_celeba")
    p.add_argument("--out", default="./datasets/celeba")
    args = p.parse_args()

    os.makedirs(os.path.join(args.out, "trainA"), exist_ok=True)  # male
    os.makedirs(os.path.join(args.out, "trainB"), exist_ok=True)  # female

    with open(args.attrs) as fp:
        fp.readline()                      # count line
        header = fp.readline().split()
        male_col = header.index("Male")
        n = {"trainA": 0, "trainB": 0}
        for line in fp:
            parts = line.split()
            if not parts:
                continue
            filename = parts[0]
            gender = int(parts[1 + male_col])
            split = "trainA" if gender == 1 else "trainB"
            copyfile(os.path.join(args.images, filename),
                     os.path.join(args.out, split, filename))
            n[split] += 1
    print(f"male (trainA): {n['trainA']}, female (trainB): {n['trainB']}")


if __name__ == "__main__":
    main()
