#!/usr/bin/env python
"""CycleGAN inference: restore generators, translate images, save input/output
pairs side by side (`CycleGAN/tensorflow/inference.py:34-63`).

Usage: python inference.py --workdir runs/cyclegan-x --direction a2b img1.jpg ...
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--workdir", default="runs/cyclegan")
    p.add_argument("--direction", default="a2b", choices=["a2b", "b2a"])
    p.add_argument("--image-size", type=int, default=256)
    p.add_argument("--out-dir", default="translated")
    p.add_argument("images", nargs="+")
    args = p.parse_args()

    import numpy as np
    from PIL import Image

    from deepvision_tpu.configs import get_config
    from deepvision_tpu.core.gan import CycleGANTrainer

    trainer = CycleGANTrainer(get_config("cyclegan"), workdir=args.workdir,
                              image_size=args.image_size)
    if trainer.resume() is None:
        print("WARNING: no checkpoint found — using random weights")

    size = args.image_size
    os.makedirs(args.out_dir, exist_ok=True)
    batch = np.stack([
        np.asarray(Image.open(f).convert("RGB").resize((size, size)),
                   np.float32) / 127.5 - 1.0 for f in args.images])
    out = trainer.translate(batch, args.direction)
    trainer.close()

    for path, src, dst in zip(args.images, batch, out):
        pair = np.concatenate([src, dst], axis=1)  # input | output
        pair = ((pair + 1.0) * 127.5).clip(0, 255).astype(np.uint8)
        name = os.path.join(args.out_dir,
                            f"{os.path.splitext(os.path.basename(path))[0]}"
                            f"_{args.direction}.png")
        Image.fromarray(pair).save(name)
        print(f"saved {name}")


if __name__ == "__main__":
    main()
