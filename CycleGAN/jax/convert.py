#!/usr/bin/env python
"""Convert a trained CycleGAN generator to TFLite — the role of the reference's
`CycleGAN/tensorflow/convert.py:8-14`, via jax2tf since our models are Flax.

Usage: python convert.py --workdir runs/cyclegan --direction a2b \
           --output photo2monet.tflite
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--workdir", default="runs/cyclegan")
    p.add_argument("--direction", default="a2b", choices=["a2b", "b2a"])
    p.add_argument("--image-size", type=int, default=256)
    p.add_argument("--output", default=None,
                   help="output .tflite path (default <direction>.tflite)")
    p.add_argument("--saved-model-dir", default=None,
                   help="also keep the intermediate SavedModel here")
    p.add_argument("--no-optimize", action="store_true")
    args = p.parse_args(argv)

    from deepvision_tpu.configs import get_config
    from deepvision_tpu.core.export import export_tflite
    from deepvision_tpu.core.gan import CycleGANTrainer

    trainer = CycleGANTrainer(get_config("cyclegan"), workdir=args.workdir,
                              image_size=args.image_size)
    if trainer.resume() is None:
        print("WARNING: no checkpoint found — exporting random weights")

    variables = {"params": trainer.gen_state.params[args.direction],
                 "batch_stats": trainer.gen_state.batch_stats[args.direction]}
    apply_fn = lambda v, x: trainer.generator.apply(v, x, train=False)  # noqa: E731
    out = args.output or f"{args.direction}.tflite"
    export_tflite(apply_fn, variables,
                  (args.image_size, args.image_size, 3), out,
                  optimize=not args.no_optimize,
                  saved_model_dir=args.saved_model_dir)
    trainer.close()
    print(f"wrote {out} ({os.path.getsize(out)} bytes)")


if __name__ == "__main__":
    main()
