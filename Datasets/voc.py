"""Pascal VOC → detection TFRecords (shared by VOC2007 and VOC2012).

Parity target: `Datasets/VOC2007/tfrecords.py` and the near-identical
`Datasets/VOC2012/tfrecords.py` (they differ only in paths and shard counts —
the md5-copy pattern this package replaces with one parameterized module).
Behavior preserved: XML annotation parse (`VOC2007/tfrecords.py:124-155`),
train/val/test split from ImageSets/Main (`:163-176`), class ids from the
names file order (`:178-181`), normalized-bbox range asserts (`:61-64`), and
`<split>_NNNN_of_MMMM.tfrecords` shard naming. Output feature schema matches
what the YOLO pipeline reads (`YOLO/tensorflow/preprocess.py:271-285`).
"""

from __future__ import annotations

import os
import sys
from xml.etree import ElementTree as ET

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from Datasets.common import (build_tfrecords, bytes_feature,  # noqa: E402
                             bytes_list_feature, float_feature, int64_feature)
from deepvision_tpu.data.class_names import VOC_CLASS_NAMES  # noqa: E402


def parse_one_xml(xml_path: str, image_dir: str, names_map: dict) -> dict:
    root = ET.parse(xml_path).getroot()
    filename = root.find(".//filename").text
    size_el = root.find("size")
    bboxes = []
    for obj in root.findall(".//object"):
        name = obj.find("name").text
        bb = obj.find("bndbox")
        diff_el = obj.find("difficult")
        bboxes.append({
            "class_text": name,
            "class_id": names_map[name],
            "difficult": int(diff_el.text) if diff_el is not None else 0,
            "xmin": int(float(bb.find("xmin").text)),
            "ymin": int(float(bb.find("ymin").text)),
            "xmax": int(float(bb.find("xmax").text)),
            "ymax": int(float(bb.find("ymax").text)),
        })
    return {
        "filepath": os.path.join(image_dir, filename),
        "filename": filename,
        "width": int(size_el.find("width").text),
        "height": int(size_el.find("height").text),
        "depth": int(size_el.find("depth").text),
        "bboxes": bboxes,
    }


def generate_tfexample(anno: dict):
    """One image + normalized boxes → tf.train.Example
    (`VOC2007/tfrecords.py:38-97`, including the [0,1] asserts)."""
    import tensorflow as tf
    with open(anno["filepath"], "rb") as f:
        content = f.read()
    width, height, depth = anno["width"], anno["height"], anno["depth"]
    if depth != 3:
        print(f"WARNING: image {anno['filename']} has depth {depth}")
    ids, texts, xmins, ymins, xmaxs, ymaxs, diffs = [], [], [], [], [], [], []
    for bbox in anno["bboxes"]:
        norm = [bbox["xmin"] / width, bbox["ymin"] / height,
                bbox["xmax"] / width, bbox["ymax"] / height]
        for v in norm:
            assert 0.0 <= v <= 1.0, (anno["filename"], norm)
        ids.append(bbox["class_id"])
        texts.append(bbox["class_text"])
        xmins.append(norm[0])
        ymins.append(norm[1])
        xmaxs.append(norm[2])
        ymaxs.append(norm[3])
        diffs.append(bbox.get("difficult", 0))
    feature = {
        "image/height": int64_feature(height),
        "image/width": int64_feature(width),
        "image/depth": int64_feature(depth),
        "image/object/bbox/xmin": float_feature(xmins),
        "image/object/bbox/ymin": float_feature(ymins),
        "image/object/bbox/xmax": float_feature(xmaxs),
        "image/object/bbox/ymax": float_feature(ymaxs),
        "image/object/class/label": int64_feature(ids),
        "image/object/difficult": int64_feature(diffs),
        "image/object/class/text": bytes_list_feature(texts),
        "image/encoded": bytes_feature(content),
        "image/filename": bytes_feature(anno["filename"]),
    }
    return tf.train.Example(features=tf.train.Features(feature=feature))


def convert(devkit_dir: str, out_dir: str, shards_per_split: int,
            splits=("train", "val", "test"), names=None):
    """Full conversion for one VOC year rooted at `devkit_dir`
    (e.g. ./VOCdevkit/VOC2007)."""
    names = names or VOC_CLASS_NAMES
    names_map = {n: i for i, n in enumerate(names)}
    anno_dir = os.path.join(devkit_dir, "Annotations")
    image_dir = os.path.join(devkit_dir, "JPEGImages")

    split_of = {}
    for split in splits:
        path = os.path.join(devkit_dir, "ImageSets", "Main", f"{split}.txt")
        if not os.path.exists(path):
            continue
        with open(path) as fp:
            for line in fp.read().splitlines():
                split_of[line.strip()] = split

    annotations = {s: [] for s in splits}
    for xml_file in sorted(os.listdir(anno_dir)):
        image_id = xml_file[:-4]
        split = split_of.get(image_id)
        if split is None:
            print(f"WARNING: unwanted image id {image_id}")
            continue
        annotations[split].append(
            parse_one_xml(os.path.join(anno_dir, xml_file), image_dir,
                          names_map))

    total = 0
    for split in splits:
        if annotations[split]:
            build_tfrecords(annotations[split], shards_per_split, split,
                            out_dir, generate_tfexample)
            total += len(annotations[split])
    print(f"Successfully wrote {total} annotations to TF Records.")
    return total
