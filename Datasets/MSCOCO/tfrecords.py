#!/usr/bin/env python
"""MSCOCO → detection TFRecords.

Parity target: `Datasets/MSCOCO/tfrecords.py` — COCO instances JSON →
per-image grouped TFExamples with normalized boxes, non-JPEG/non-RGB images
re-encoded to JPEG quality 95 (`:42-48`), contiguous 0-based class ids
(`:135-143`), 64 train / 8 val shards (`:13-14`), Ray workers → process pool.

Run from a directory containing ./annotations/instances_{train,val}2017.json
and ./{train,val}2017/ image dirs.
"""

from __future__ import annotations

import argparse
import io
import json
import os
import sys
from collections import defaultdict

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

from Datasets.common import (build_tfrecords, bytes_feature,  # noqa: E402
                             bytes_list_feature, float_feature, int64_feature)

NUM_TRAIN_SHARDS = 64  # reference `MSCOCO/tfrecords.py:13-14`
NUM_VAL_SHARDS = 8


def load_categories(coco_json: dict) -> dict:
    """COCO category_id (1-based, sparse) → (contiguous 0-based id, name)
    (`MSCOCO/tfrecords.py:135-143` wants ids starting at 0)."""
    cats = sorted(coco_json["categories"], key=lambda c: c["id"])
    return {c["id"]: (i, c["name"]) for i, c in enumerate(cats)}


def parse_annotations(coco_json: dict, image_dir: str) -> list:
    """Group instance annotations by image → list of per-image dicts."""
    categories = load_categories(coco_json)
    by_image = defaultdict(list)
    for anno in coco_json["annotations"]:
        class_id, class_text = categories[int(anno["category_id"])]
        x, y, w, h = anno["bbox"]  # COCO (x, y, width, height)
        by_image[anno["image_id"]].append({
            "class_id": class_id,
            "class_text": class_text,
            "xmin": float(x), "ymin": float(y),
            "xmax": float(x) + float(w), "ymax": float(y) + float(h),
        })
    return [{"filename": os.path.join(image_dir, f"{str(iid).rjust(12, '0')}.jpg"),
             "bboxes": bboxes} for iid, bboxes in by_image.items()]


def generate_tfexample(anno: dict):
    """(`MSCOCO/tfrecords.py:37-101`) — JPEG/RGB re-encode + normalized boxes
    clipped to [0, 1] (COCO boxes can overhang the image edge by a pixel)."""
    import tensorflow as tf
    from PIL import Image

    filename = anno["filename"]
    with open(filename, "rb") as f:
        content = f.read()
    image = Image.open(io.BytesIO(content))  # decode from the bytes just read
    if image.format != "JPEG" or image.mode != "RGB":
        with io.BytesIO() as out:
            image.convert("RGB").save(out, format="JPEG", quality=95)
            content = out.getvalue()
    width, height = image.size

    ids, texts, xmins, ymins, xmaxs, ymaxs = [], [], [], [], [], []
    for bbox in anno["bboxes"]:
        norm = [min(max(bbox["xmin"] / width, 0.0), 1.0),
                min(max(bbox["ymin"] / height, 0.0), 1.0),
                min(max(bbox["xmax"] / width, 0.0), 1.0),
                min(max(bbox["ymax"] / height, 0.0), 1.0)]
        ids.append(bbox["class_id"])
        texts.append(bbox["class_text"])
        xmins.append(norm[0])
        ymins.append(norm[1])
        xmaxs.append(norm[2])
        ymaxs.append(norm[3])

    feature = {
        "image/height": int64_feature(height),
        "image/width": int64_feature(width),
        "image/depth": int64_feature(3),
        "image/object/bbox/xmin": float_feature(xmins),
        "image/object/bbox/ymin": float_feature(ymins),
        "image/object/bbox/xmax": float_feature(xmaxs),
        "image/object/bbox/ymax": float_feature(ymaxs),
        "image/object/class/label": int64_feature(ids),
        "image/object/class/text": bytes_list_feature(texts),
        "image/encoded": bytes_feature(content),
        "image/filename": bytes_feature(os.path.basename(filename)),
    }
    return tf.train.Example(features=tf.train.Features(feature=feature))


def convert(annotations_dir: str, out_dir: str, year: str = "2017",
            image_root: str = "."):
    total = 0
    for split, shards in (("train", NUM_TRAIN_SHARDS), ("val", NUM_VAL_SHARDS)):
        path = os.path.join(annotations_dir, f"instances_{split}{year}.json")
        with open(path) as fp:
            coco_json = json.load(fp)
        annos = parse_annotations(coco_json,
                                  os.path.join(image_root, f"{split}{year}"))
        build_tfrecords(annos, shards, split, out_dir, generate_tfexample)
        total += len(annos)
    print(f"Successfully wrote {total} images to TF Records.")
    return total


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--annotations", default="./annotations")
    p.add_argument("--image-root", default=".",
                   help="directory containing the train2017/ val2017 image dirs")
    p.add_argument("--out", default="./tfrecords")
    p.add_argument("--year", default="2017")
    a = p.parse_args()
    convert(a.annotations, a.out, a.year, a.image_root)
