#!/usr/bin/env python
"""MPII human pose → TFRecords.

Parity target: `Datasets/MPII/tfrecords_mpii.py` — the train/validation JSON
annotation files → keypoint TFExamples: joints normalized by image size with
negative values preserved for missing joints (`:54-60`), visibility collapsed
to {0, 2} (`:62`), non-JPEG/non-RGB re-encode (`:44-49`), 64 train / 8 val
shards (`:14-15`), Ray workers → process pool. The reference's loguru logging
is plain prints here.

Run from a directory containing ./mpii_human_pose_v1_u12_2/{train,validation}.json
and ./mpii/images/.
"""

from __future__ import annotations

import argparse
import io
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

from Datasets.common import (build_tfrecords, bytes_feature,  # noqa: E402
                             float_feature, int64_feature)

NUM_TRAIN_SHARDS = 64  # reference `MPII/tfrecords_mpii.py:14-15`
NUM_VAL_SHARDS = 8


def parse_one_annotation(anno: dict, image_dir: str) -> dict:
    """(`tfrecords_mpii.py:113-123`)."""
    return {
        "filename": anno["image"],
        "filepath": os.path.join(image_dir, anno["image"]),
        "joints": anno["joints"],
        "joints_visibility": anno["joints_vis"],
    }


def generate_tfexample(anno: dict):
    """(`tfrecords_mpii.py:38-84`): joints normalized by image dims, negatives
    kept as missing-joint markers; visibility 0 stays 0, else 2."""
    import tensorflow as tf
    from PIL import Image

    with open(anno["filepath"], "rb") as f:
        content = f.read()
    image = Image.open(io.BytesIO(content))  # decode from the bytes just read
    if image.format != "JPEG" or image.mode != "RGB":
        with io.BytesIO() as out:
            image.convert("RGB").save(out, format="JPEG", quality=95)
            content = out.getvalue()
    width, height = image.size

    xs = [j[0] / width if j[0] >= 0 else float(j[0]) for j in anno["joints"]]
    ys = [j[1] / height if j[1] >= 0 else float(j[1]) for j in anno["joints"]]
    vs = [0 if v == 0 else 2 for v in anno["joints_visibility"]]

    feature = {
        "image/height": int64_feature(height),
        "image/width": int64_feature(width),
        "image/depth": int64_feature(3),
        "image/object/parts/x": float_feature(xs),
        "image/object/parts/y": float_feature(ys),
        "image/object/parts/v": int64_feature(vs),
        "image/encoded": bytes_feature(content),
        "image/filename": bytes_feature(anno["filename"]),
    }
    return tf.train.Example(features=tf.train.Features(feature=feature))


def convert(annotations_dir: str, image_dir: str, out_dir: str):
    total = 0
    for split, json_name, shards in (
            ("train", "train.json", NUM_TRAIN_SHARDS),
            ("val", "validation.json", NUM_VAL_SHARDS)):
        with open(os.path.join(annotations_dir, json_name)) as fp:
            annos = [parse_one_annotation(a, image_dir) for a in json.load(fp)]
        print(f"{split}: {len(annos)} annotations")
        build_tfrecords(annos, shards, split, out_dir, generate_tfexample)
        total += len(annos)
    print(f"Successfully wrote {total} annotations to TF Records.")
    return total


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--annotations", default="./mpii_human_pose_v1_u12_2")
    p.add_argument("--images", default="./mpii/images")
    p.add_argument("--out", default="./tfrecords_mpii")
    a = p.parse_args()
    convert(a.annotations, a.images, a.out)
