#!/usr/bin/env python
"""VOC2007 → TFRecords (reference: `Datasets/VOC2007/tfrecords.py`, 2 shards
per split, Ray workers → process pool). Run from a directory containing
./VOCdevkit/VOC2007."""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

from Datasets.voc import convert

NUM_SHARDS = 2  # reference `VOC2007/tfrecords.py:13-15`

if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--devkit", default="./VOCdevkit/VOC2007")
    p.add_argument("--out", default="./tfrecords_voc")
    p.add_argument("--shards", type=int, default=NUM_SHARDS)
    a = p.parse_args()
    convert(a.devkit, a.out, a.shards)
