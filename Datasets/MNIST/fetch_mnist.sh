#!/bin/bash
# Fetch the MNIST idx image+label files into Datasets/MNIST/dataset/ — the
# layout the reference's loader documents (`/root/reference/Datasets/MNIST/
# DATASET.md`) and `deepvision_tpu/data/mnist.py` parses. Needs network
# access; in a zero-egress environment use the bundled-digits gate instead
# (`python LeNet/jax/train.py -m lenet5_digits`).
#
# After fetching, the real-data accuracy tests activate:
#   python -m pytest tests/test_real_data.py -m slow
# and real-MNIST training works out of the box:
#   python LeNet/jax/train.py -m lenet5 --data-dir Datasets/MNIST/dataset
set -euo pipefail
cd "$(dirname "$0")"
mkdir -p dataset
# yann.lecun.com throttles/403s anonymous pulls; the GCS mirror is the
# canonical stable source.
BASE="https://storage.googleapis.com/cvdf-datasets/mnist"
for f in train-images-idx3-ubyte train-labels-idx1-ubyte \
         t10k-images-idx3-ubyte t10k-labels-idx1-ubyte; do
    if [ ! -f "dataset/$f" ]; then
        echo "fetching $f"
        curl -fsSL "$BASE/$f.gz" | gunzip > "dataset/$f.tmp"
        mv "dataset/$f.tmp" "dataset/$f"
    fi
done
echo "done: $(ls dataset | wc -l) files in $(pwd)/dataset"
