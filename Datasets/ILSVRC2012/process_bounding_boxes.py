#!/usr/bin/env python
"""ImageNet bounding-box XML → CSV.

Parity target: `Datasets/ILSVRC2012/process_bounding_boxes.py` — walks the
ILSVRC2012 bbox annotation tree and emits one CSV line per box,
`filename,xmin,ymin,xmax,ymax` with coordinates normalized by image size and
clamped to [0, 1] (the reference also guards min<max). Kept for tooling parity;
the classification pipeline itself doesn't consume boxes.

Usage: python process_bounding_boxes.py <xml_dir> [synsets.txt] > boxes.csv
"""

from __future__ import annotations

import os
import sys
from xml.etree import ElementTree as ET


def process_xml(path: str):
    root = ET.parse(path).getroot()
    filename = root.find("filename").text
    size = root.find("size")
    width = float(size.find("width").text)
    height = float(size.find("height").text)
    rows = []
    for obj in root.findall("object"):
        box = obj.find("bndbox")
        xmin = min(max(float(box.find("xmin").text) / width, 0.0), 1.0)
        ymin = min(max(float(box.find("ymin").text) / height, 0.0), 1.0)
        xmax = min(max(float(box.find("xmax").text) / width, 0.0), 1.0)
        ymax = min(max(float(box.find("ymax").text) / height, 0.0), 1.0)
        if xmin >= xmax or ymin >= ymax:
            continue
        rows.append(f"{filename},{xmin:.6f},{ymin:.6f},{xmax:.6f},{ymax:.6f}")
    return rows


def main():
    if len(sys.argv) < 2:
        print(__doc__, file=sys.stderr)
        sys.exit(1)
    xml_dir = sys.argv[1]
    allowed = None
    if len(sys.argv) > 2:
        with open(sys.argv[2]) as fp:
            allowed = {line.strip() for line in fp if line.strip()}
    count = 0
    for dirpath, _, files in os.walk(xml_dir):
        synset = os.path.basename(dirpath)
        if allowed is not None and synset not in allowed:
            continue
        for name in sorted(files):
            if not name.endswith(".xml"):
                continue
            for row in process_xml(os.path.join(dirpath, name)):
                print(row)
                count += 1
    print(f"wrote {count} boxes", file=sys.stderr)


if __name__ == "__main__":
    main()
