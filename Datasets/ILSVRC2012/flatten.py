#!/usr/bin/env python
"""Build the flat-directory ImageNet layout for `data/imagenet_flat.py`.

The reference used three tiny shell scripts for this
(`Datasets/ILSVRC2012/untar-script.sh`, `flatten-script.sh`,
`flatten-val-script.sh`): flatten the per-synset train dirs into one directory
of `<synset>_<name>.JPEG` files, and rename the 50k validation JPEGs to carry
their synset (from the validation-labels file). One script here covers both,
with hard links by default (no extra disk) and a `--copy` fallback for
filesystems without link support.

Usage (after the untar step in DATASET.md):
    python flatten.py --train-dir dataset/train --out dataset/train_flatten
    python flatten.py --val-dir dataset/validation \
        --val-labels imagenet_2012_validation_synset_labels.txt \
        --out dataset/val_flatten
"""

from __future__ import annotations

import argparse
import os
import shutil
import sys


def _place(src: str, dst: str, copy: bool) -> None:
    if os.path.exists(dst):
        return
    if copy:
        shutil.copy2(src, dst)
    else:
        os.link(src, dst)


def flatten_train(train_dir: str, out: str, copy: bool) -> int:
    """train/<synset>/<name>.JPEG → out/<synset>_<name>.JPEG (names already
    carry the synset prefix upstream, so this is a flatten, not a rename)."""
    os.makedirs(out, exist_ok=True)
    n = 0
    for synset in sorted(os.listdir(train_dir)):
        d = os.path.join(train_dir, synset)
        if not (os.path.isdir(d) and synset.startswith("n")):
            continue
        for fname in os.listdir(d):
            flat = fname if fname.startswith(synset) else f"{synset}_{fname}"
            _place(os.path.join(d, fname), os.path.join(out, flat), copy)
            n += 1
    return n


def flatten_val(val_dir: str, labels_path: str, out: str, copy: bool) -> int:
    """validation/ILSVRC2012_val_0000XXXX.JPEG + line-XXXX synset label →
    out/<synset>_val_0000XXXX.JPEG (the filename→label convention the flat
    loader parses)."""
    with open(labels_path) as fp:
        labels = [line.strip() for line in fp if line.strip()]
    files = sorted(f for f in os.listdir(val_dir)
                   if f.upper().endswith((".JPEG", ".JPG")))
    if len(files) != len(labels):
        sys.exit(f"ERROR: {len(files)} val images but {len(labels)} labels")
    os.makedirs(out, exist_ok=True)
    for fname, synset in zip(files, labels):
        stem = fname.split(".")[0].replace("ILSVRC2012_", "")
        _place(os.path.join(val_dir, fname),
               os.path.join(out, f"{synset}_{stem}.JPEG"), copy)
    return len(files)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--train-dir", help="untarred train/ (per-synset subdirs)")
    p.add_argument("--val-dir", help="untarred validation/ (flat JPEGs)")
    p.add_argument("--val-labels",
                   help="imagenet_2012_validation_synset_labels.txt")
    p.add_argument("--out", required=True)
    p.add_argument("--copy", action="store_true",
                   help="copy instead of hard-linking")
    args = p.parse_args()

    if args.train_dir:
        n = flatten_train(args.train_dir, args.out, args.copy)
    elif args.val_dir:
        if not args.val_labels:
            sys.exit("--val-dir requires --val-labels")
        n = flatten_val(args.val_dir, args.val_labels, args.out, args.copy)
    else:
        sys.exit("pass --train-dir or --val-dir")
    print(f"placed {n} files into {args.out}")


if __name__ == "__main__":
    main()
