#!/bin/bash
# Unpack the per-synset inner tars of ILSVRC2012_img_train.tar into one
# directory per synset (reference: Datasets/ILSVRC2012/untar-script.sh).
for a in *.tar; do
    b="${a%.tar}"
    mkdir -p "./$b"
    tar xf "$a" -C "./$b" && rm "$a"
done
