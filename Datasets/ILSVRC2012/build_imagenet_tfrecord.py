#!/usr/bin/env python
"""ImageNet (ILSVRC2012) → TFRecords.

Parity target: `Datasets/ILSVRC2012/build_imagenet_tfrecord.py` (the 710-line
TF-official derivative): 1024 train / 128 validation shards (`:111-118`),
`train_directory/<synset>/<file>.JPEG` layout, labels as 1-based indices into
the sorted synset list with 0 reserved for background (`:364-376`), human-
readable class text from the metadata file, PNG- and CMYK-encoded oddball
images re-encoded to RGB JPEG (`:238-335` ImageCoder), shard files named
`train-00000-of-01024` (`:399-418`), and a worker pool per shard range
(`:420-448` threads → processes here, bypassing the GIL for JPEG re-encode).

The TF-official bounding-box features are omitted: nothing in the reference
ever consumes them (its classification pipelines read only encoded+label).

Output feature schema matches what deepvision_tpu.data.imagenet.parse_example
reads: image/encoded + image/class/label (1-based).
"""

from __future__ import annotations

import argparse
import io
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

NUM_TRAIN_SHARDS = 1024  # reference `:111-118`
NUM_VAL_SHARDS = 128


def _load_synsets(labels_file: str) -> list:
    with open(labels_file) as fp:
        return [line.strip() for line in fp if line.strip()]


def _load_human_map(metadata_file: str) -> dict:
    """`n01440764\ttench, Tinca tinca` lines → dict (`:364-383`)."""
    out = {}
    with open(metadata_file) as fp:
        for line in fp:
            parts = line.strip().split("\t")
            if len(parts) == 2:
                out[parts[0]] = parts[1]
    return out


def _example(path: str, label: int, synset: str, human: str):
    import tensorflow as tf
    from PIL import Image

    from Datasets.common import bytes_feature, int64_feature

    with open(path, "rb") as f:
        content = f.read()
    image = Image.open(io.BytesIO(content))
    # PNG-masquerading-as-JPEG and CMYK fixups (`:268-335`)
    if image.format != "JPEG" or image.mode != "RGB":
        with io.BytesIO() as out:
            image.convert("RGB").save(out, format="JPEG", quality=95)
            content = out.getvalue()
        image = Image.open(io.BytesIO(content))
    width, height = image.size

    feature = {
        "image/height": int64_feature(height),
        "image/width": int64_feature(width),
        "image/colorspace": bytes_feature("RGB"),
        "image/channels": int64_feature(3),
        "image/class/label": int64_feature(label),
        "image/class/synset": bytes_feature(synset),
        "image/class/text": bytes_feature(human),
        "image/format": bytes_feature("JPEG"),
        "image/filename": bytes_feature(os.path.basename(path)),
        "image/encoded": bytes_feature(content),
    }
    return tf.train.Example(features=tf.train.Features(feature=feature))


def _tf_official_shard_path(out_dir: str, split: str, i: int, total: int) -> str:
    """`train-00000-of-01024` naming (`:399-418`)."""
    return os.path.join(out_dir,
                        f"{split}-{str(i).zfill(5)}-of-{str(total).zfill(5)}")


def _example_from_item(item):
    # module-level so ProcessPoolExecutor can pickle it
    return _example(*item)


def _build(items: list, split: str, num_shards: int, output_dir: str,
           num_workers: int):
    from Datasets.common import build_tfrecords
    build_tfrecords(items, num_shards, split, output_dir, _example_from_item,
                    num_workers=num_workers,
                    shard_path_fn=_tf_official_shard_path)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--train_directory", default="./train",
                   help="dir of <synset>/<image>.JPEG subdirs")
    p.add_argument("--validation_directory", default="./validation",
                   help="flat dir of validation images (sorted order matches "
                        "the validation labels file)")
    p.add_argument("--output_directory", default="./tfrecord")
    p.add_argument("--labels_file", default="./synsets.txt",
                   help="one synset per line; label = 1-based line index")
    p.add_argument("--imagenet_metadata_file",
                   default="./imagenet_2012_metadata.txt")
    p.add_argument("--validation_labels_file",
                   default="./imagenet_2012_validation_synset_labels.txt",
                   help="one synset per line, aligned to sorted val images")
    p.add_argument("--train_shards", type=int, default=NUM_TRAIN_SHARDS)
    p.add_argument("--validation_shards", type=int, default=NUM_VAL_SHARDS)
    p.add_argument("--num_workers", type=int, default=os.cpu_count())
    args = p.parse_args()

    synsets = _load_synsets(args.labels_file)
    label_of = {s: i + 1 for i, s in enumerate(synsets)}  # 0 = background
    humans = _load_human_map(args.imagenet_metadata_file)

    train_items = []
    for synset in synsets:
        syn_dir = os.path.join(args.train_directory, synset)
        if not os.path.isdir(syn_dir):
            continue
        for name in sorted(os.listdir(syn_dir)):
            train_items.append((os.path.join(syn_dir, name), label_of[synset],
                                synset, humans.get(synset, synset)))
    # shuffle deterministically so shards are class-mixed (`:561-576`)
    import random
    random.Random(12345).shuffle(train_items)
    print(f"train: {len(train_items)} images")

    val_items = []
    if os.path.isdir(args.validation_directory):
        with open(args.validation_labels_file) as fp:
            val_synsets = [line.strip() for line in fp if line.strip()]
        val_files = sorted(os.listdir(args.validation_directory))
        assert len(val_files) == len(val_synsets), \
            (len(val_files), len(val_synsets))
        for name, synset in zip(val_files, val_synsets):
            val_items.append((os.path.join(args.validation_directory, name),
                              label_of[synset], synset,
                              humans.get(synset, synset)))
    print(f"validation: {len(val_items)} images")

    _build(train_items, "train", args.train_shards, args.output_directory,
           args.num_workers)
    if val_items:
        _build(val_items, "validation", args.validation_shards,
               args.output_directory, args.num_workers)
    print("done")


if __name__ == "__main__":
    main()
