"""Shared dataset-conversion infrastructure.

Role parity with the reference's per-converter boilerplate: chunked shard lists
(`Datasets/VOC2007/tfrecords.py:20-35`), `@ray.remote` per-shard TFRecord
writers with a `ray.get` barrier (`:98-121`), and tf.train Feature helpers
(`:70-93`). The TPU build replaces Ray with the standard library's
`ProcessPoolExecutor` — the converters are offline host-side ETL with no
cross-worker state, so a process pool gives the same shard-level parallelism
without the extra dependency.
"""

from __future__ import annotations

import os
from concurrent.futures import ProcessPoolExecutor
from typing import Callable, List, Sequence


def int64_feature(values):
    import tensorflow as tf
    if not isinstance(values, (list, tuple)):
        values = [values]
    return tf.train.Feature(int64_list=tf.train.Int64List(value=list(values)))


def float_feature(values):
    import tensorflow as tf
    if not isinstance(values, (list, tuple)):
        values = [values]
    return tf.train.Feature(float_list=tf.train.FloatList(value=list(values)))


def bytes_feature(value):
    import tensorflow as tf
    if isinstance(value, str):
        value = value.encode()
    return tf.train.Feature(bytes_list=tf.train.BytesList(value=[value]))


def bytes_list_feature(values):
    import tensorflow as tf
    values = [v.encode() if isinstance(v, str) else v for v in values]
    return tf.train.Feature(bytes_list=tf.train.BytesList(value=values))


def chunkify(items: Sequence, n: int) -> List[list]:
    """Split into n near-equal chunks (`VOC2007/tfrecords.py:20-35`)."""
    size = len(items) // n
    chunks = []
    for i in range(n - 1):
        chunks.append(list(items[i * size:(i + 1) * size]))
    chunks.append(list(items[(n - 1) * size:]))
    return chunks


def shard_path(out_dir: str, split: str, index: int, total: int) -> str:
    """`train_0001_of_0064.tfrecords` naming (`VOC2007/tfrecords.py:113-120`)."""
    return os.path.join(
        out_dir, f"{split}_{str(index + 1).zfill(4)}_of_{str(total).zfill(4)}"
                 ".tfrecords")


def write_shard(chunk: list, path: str, example_fn: Callable) -> str:
    """Serialize one shard; `example_fn(item) -> tf.train.Example or None`."""
    import tensorflow as tf
    with tf.io.TFRecordWriter(path) as writer:
        for item in chunk:
            example = example_fn(item)
            if example is not None:
                writer.write(example.SerializeToString())
    return path


def build_tfrecords(annotations: Sequence, total_shards: int, split: str,
                    out_dir: str, example_fn: Callable,
                    num_workers: int = 0,
                    shard_path_fn: Callable = None) -> List[str]:
    """Parallel shard writer — the `build_tf_records` + Ray pattern
    (`VOC2007/tfrecords.py:109-121`) on a process pool. `shard_path_fn`
    overrides the file-naming convention (the ILSVRC builder uses the
    TF-official `train-00000-of-01024` style)."""
    os.makedirs(out_dir, exist_ok=True)
    chunks = chunkify(annotations, total_shards)
    shard_path_fn = shard_path_fn or shard_path
    paths = [shard_path_fn(out_dir, split, i, total_shards)
             for i in range(total_shards)]
    num_workers = num_workers or min(total_shards, os.cpu_count() or 1)
    if num_workers <= 1 or total_shards == 1:
        return [write_shard(c, p, example_fn) for c, p in zip(chunks, paths)]
    with ProcessPoolExecutor(max_workers=num_workers) as pool:
        futures = [pool.submit(write_shard, c, p, example_fn)
                   for c, p in zip(chunks, paths)]
        return [f.result() for f in futures]
