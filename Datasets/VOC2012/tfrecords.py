#!/usr/bin/env python
"""VOC2012 → TFRecords (reference: `Datasets/VOC2012/tfrecords.py`, 4 shards
per split; VOC2012 has no public test annotations → train/val only). Run from a
directory containing ./VOCdevkit/VOC2012."""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

from Datasets.voc import convert

NUM_SHARDS = 4  # reference `VOC2012/tfrecords.py:13-15`

if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--devkit", default="./VOCdevkit/VOC2012")
    p.add_argument("--out", default="./tfrecords_voc2012")
    p.add_argument("--shards", type=int, default=NUM_SHARDS)
    a = p.parse_args()
    convert(a.devkit, a.out, a.shards, splits=("train", "val"))
