#!/usr/bin/env python
"""Train ResNet models on TPU — `python train.py -m <model> [-c latest] [--synthetic]`.

Per-family entrypoint matching the reference's UX (ResNet/pytorch|tensorflow/train.py),
backed by the shared deepvision_tpu Trainer instead of a copy-pasted loop.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from deepvision_tpu.cli import run_classification

# configs, not architectures: resnet50_tpu is the same resnet50 model under
# the full large-batch pod recipe (see configs.py / README "ResNet-50 pod
# recipe")
MODELS = ["resnet34", "resnet50", "resnet101", "resnet152", "resnet50v2",
          "resnet50_tpu"]

if __name__ == "__main__":
    run_classification("ResNet", MODELS)
