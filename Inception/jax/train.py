#!/usr/bin/env python
"""Train Inception models on TPU — `python train.py -m <model> [-c latest] [--synthetic]`.

Per-family entrypoint matching the reference's UX (Inception/pytorch|tensorflow/train.py),
backed by the shared deepvision_tpu Trainer instead of a copy-pasted loop.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from deepvision_tpu.cli import run_classification

MODELS = ["inception_v1", "inception_v3"]

if __name__ == "__main__":
    run_classification("Inception", MODELS)
