# Repo-level targets (per-family Makefiles live in <Family>/jax/).
PY ?= python
CPU_ENV = PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
          XLA_FLAGS=--xla_force_host_platform_device_count=8

.PHONY: test test-all bench dryrun smoke preflight

preflight:   ## pod go/no-go: devices, input floor, train step, ckpt roundtrip
	$(PY) tools/preflight.py

test:        ## fast suite (slow-marked compiles excluded)
	env $(CPU_ENV) $(PY) -m pytest tests/ -x -q

test-all:    ## everything, including slow XLA-CPU compiles
	env $(CPU_ENV) $(PY) -m pytest tests/ -x -q -m ""

bench:       ## ResNet-50 step throughput (TPU if reachable, else CPU)
	$(PY) bench.py

dryrun:      ## 8-virtual-device multichip compile/exec check
	env $(CPU_ENV) $(PY) -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

smoke:       ## one synthetic epoch of the flagship trainer
	env $(CPU_ENV) $(PY) LeNet/jax/train.py -m lenet5 --synthetic --epochs 1
