# Repo-level targets (per-family Makefiles live in <Family>/jax/).
PY ?= python
CPU_ENV = PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
          XLA_FLAGS=--xla_force_host_platform_device_count=8
# the heavy-evidence files `make verify` runs in FULL (slow included); the
# verify target's second command sweeps slow-marked tests everywhere else,
# deriving its --ignore list from this variable so the two stay in sync
VERIFY_FILES = tests/test_multihost.py tests/test_preemption.py \
               tests/test_spatial.py tests/test_spatial_shardmap.py \
               tests/test_real_data.py tests/test_gan_quality.py

.PHONY: test test-all verify bench bench-serve bench-serve-int8 \
        bench-serve-mesh bench-serve-load \
        bench-serve-promote bench-serve-spike bench-serve-trace \
        bench-serve-tier bench-serve-flywheel \
        bench-input bench-epoch bench-attn dryrun smoke seg-smoke \
        vit-smoke serve-smoke \
        serve-fleet-smoke serve-tier-smoke flywheel-smoke \
        preflight preflight-record \
        lint lint-changed lint-concurrency \
        fsck check check-update-cost reshard-parity

lint:        ## jaxlint: donation / retrace / host-sync / trace / rng /
	## dtype-policy / sharding hazards (docs/LINTING.md) over the whole
	## project — framework, tools, tests, per-model entrypoints AND the
	## repo-root scripts (bench*.py, __graft_entry__.py); exit 1 on any
	## finding. Results are cached under .cache/jaxlint/ keyed by file
	## mtimes (an unchanged tree relints in ~0.1s); NO_CACHE=1 bypasses
	$(PY) -m deepvision_tpu.lint $(if $(NO_CACHE),--no-cache)

check:       ## jaxvet: jaxpr-level audit of EVERY registered config
	## (docs/CHECKING.md) — traces each real train/eval/predict step
	## abstractly on CPU (zero FLOPs) and enforces the IR invariants:
	## DTYPE (no f32 leak into a bf16 apply), DONATE (donation claimed ==
	## donation traced, all aliasable), COLL (spatial collectives on the
	## declared axes), COST (FLOPs/bytes vs CHECK_COST.json), SERVE
	## (bucket coverage). Exit 1 on any finding
	env $(CPU_ENV) $(PY) -m deepvision_tpu.check

check-update-cost: ## refresh the committed jaxvet cost baseline
	## (CHECK_COST.json) after an INTENDED model/step change — review the
	## diff like a benchmark result
	env $(CPU_ENV) $(PY) -m deepvision_tpu.check --update-cost

lint-concurrency: ## the jaxsync family alone (docs/LINTING.md
	## "Concurrency rules"): LCK001/2 unguarded writes and non-atomic
	## RMWs against inferred lock guards, LCK003 lock-order deadlock
	## cycles, LCK004 blocking calls under a lock, THR001 never-joined
	## non-daemon threads — the focused sweep for serve/-side changes
	## (--select runs bypass the result cache)
	$(PY) -m deepvision_tpu.lint --select LCK,THR

lint-changed: ## jaxlint over only the files `git diff` touches (staged or
	## not, vs HEAD) — seconds, for the inner loop; falls back to clean
	## when nothing changed
	@files=$$( (git diff --name-only HEAD; git ls-files --others \
	  --exclude-standard) | sort -u | grep '\.py$$' | grep -v '^tests/data/lint/' ); \
	if [ -z "$$files" ]; then echo "lint-changed: no changed .py files"; \
	else $(PY) -m deepvision_tpu.lint $$files; fi

reshard-parity: ## elastic-resume N->M parity matrix (docs/FAILURES.md
	## "Elastic resume"): train on the 8-virtual-device mesh, resume on
	## M in {1, N/2, 2N incl. SIGKILL} and across data->model-parallel
	## and data->spatial-parallel switches, and pin that the resumed
	## loss trajectory matches the uninterrupted run — plus the quick
	## leaf-exact save-on-8/restore-on-2 self-check
	env $(CPU_ENV) $(PY) tools/verify_reshard.py
	env $(CPU_ENV) $(PY) -m pytest -x -q -m "" tests/test_reshard.py \
	    -k "parity or elastic"

RUN_DIR ?= runs
fsck:        ## checkpoint-integrity audit (docs/FAILURES.md): verify every
	## committed epoch under RUN_DIR (default runs/) against its
	## manifest; exit 1 on corruption. Repair: add QUARANTINE=1
	$(PY) -m deepvision_tpu fsck $(RUN_DIR) $(if $(QUARANTINE),--quarantine)

preflight:   ## pod go/no-go: devices, input floor, train step, ckpt roundtrip
	$(PY) tools/preflight.py

ROUND ?= 0
preflight-record: ## run preflight on the virtual mesh, record PREFLIGHT_r$(ROUND).txt
	{ echo "# preflight transcript, round $(ROUND) ($$(date -u +%Y-%m-%dT%H:%M:%SZ))"; \
	  echo "# env: JAX_PLATFORMS=cpu, 8 virtual devices (axon tunnel not assumed up)"; \
	  env $(CPU_ENV) $(PY) tools/preflight.py --batch-size 64 --image-size 64; } \
	  > PREFLIGHT_r$(ROUND).txt; s=$$?; cat PREFLIGHT_r$(ROUND).txt; exit $$s

test:        ## fast suite (slow-marked excluded; warm XLA cache ~7 min on
	## one core, cold ~15 — tests/conftest.py shares a persistent
	## compilation cache at ~/.cache/deepvision_tpu/test-xla; opt out
	## with DEEPVISION_TEST_XLA_CACHE=off)
	env $(CPU_ENV) $(PY) -m pytest tests/ -x -q

test-all:    ## everything, including slow XLA-CPU compiles
	env $(CPU_ENV) $(PY) -m pytest tests/ -x -q -m ""

verify:      ## the heavy correctness evidence the default lane skips
	## (VERDICT r3 item 6): real 2-process multihost, SIGKILL preemption
	## resume, combined-mesh calibration smokes, shard_map parity, the
	## real-data accuracy gates, the GAN quality gate — plus every other
	## slow-marked test (the r5 lane rebalance moved several integration
	## tests there) — then the dryrun.
	env $(CPU_ENV) $(PY) -m pytest -x -q -m "" $(VERIFY_FILES)
	env $(CPU_ENV) $(PY) -m pytest -x -q -m slow tests/ \
	    $(addprefix --ignore=,$(VERIFY_FILES))
	env $(CPU_ENV) $(PY) -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

bench:       ## ResNet-50 step throughput (TPU if reachable, else CPU)
	$(PY) bench.py

bench-serve: ## dynamic-batching serving throughput + latency vs the naive
	## per-request dispatch loop (one JSON line; docs/SERVING.md)
	env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu $(PY) bench_serve.py

bench-input: ## input pipeline end-to-end: uint8 + device-augment vs the
	## host-f32 transform path — images/sec and bytes-to-device per
	## batch (one JSON line; docs/INPUT_PIPELINE.md)
	env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu $(PY) bench_input.py

bench-epoch: ## dispatch amortization: per-step vs steps_per_dispatch=k vs
	## whole-epoch on-device scan — steps/sec and dispatches/epoch at
	## all three dispatch counts, loss-trajectory parity gated at the
	## 2e-5 fusion bound, zero recompiles across epochs, and the
	## double-buffered staging overlap proof (one JSON line, exit 1 on
	## any gate; docs/INPUT_PIPELINE.md "On-device epochs")
	env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu $(PY) bench_epoch.py

bench-attn:  ## fused (Pallas flash) vs naive attention at the seq-196 ViT
	## working point: HBM-bytes cut on the jaxvet walker proxy gated at
	## 2x, bf16/f32 parity gated at 2e-2/2e-5, zero recompiles across a
	## promotion cycle with the fused kernel armed; CPU wall-clock rides
	## along with its regime note (one JSON line, exit 1 on any gate;
	## docs/ATTENTION.md)
	env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu $(PY) bench_attn.py

serve-smoke: ## serving-stack smoke: bucketed AOT cache, micro-batcher,
	## metrics, graceful drain — synthetic load, exit 0 on pass
	env $(CPU_ENV) $(PY) -m deepvision_tpu.serve -m lenet5 --smoke \
	    --duration 2

serve-fleet-smoke: ## multi-model fleet smoke: two engines behind one
	## process, per-model batchers/metrics, round-robin synthetic load —
	## every served model must answer (docs/SERVING.md "Fleet")
	env $(CPU_ENV) $(PY) -m deepvision_tpu.serve -m lenet5,lenet5_digits \
	    --smoke --duration 2

serve-tier-smoke: ## replica-tier smoke: router over 2 supervised replica
	## processes, synthetic load with a mid-run SIGKILL of replica 0 —
	## zero failed responses, ejection + supervised restart + readmission
	## (docs/SERVING.md "Replica tier")
	env $(CPU_ENV) $(PY) -m deepvision_tpu.serve.tier -m lenet5 \
	    --replicas 2 --smoke --kill-one --duration 4

flywheel-smoke: ## serve->train->serve flywheel smoke: commit one quick
	## lenet5 epoch, then serve it under synthetic load with the
	## DRIFT_SHIFT fault armed — the drift monitor must confirm the
	## shift, fine-tune a bounded epoch through the model's own trainer,
	## and promote it through the shadow/canary gate DURING the smoke;
	## the final JSON's flywheel section is asserted
	## (docs/FAILURES.md "Flywheel decisions")
	rm -rf /tmp/deepvision_flywheel_smoke
	env $(CPU_ENV) $(PY) LeNet/jax/train.py -m lenet5 --synthetic \
	    --epochs 1 --steps-per-epoch 8 \
	    --workdir /tmp/deepvision_flywheel_smoke/lenet5
	env $(CPU_ENV) DEEPVISION_FAULT_DRIFT_SHIFT=0:3.0 $(PY) \
	    -m deepvision_tpu.serve -m lenet5 \
	    --workdir /tmp/deepvision_flywheel_smoke/lenet5 \
	    --smoke --duration 30 --reload-every 3600 --promote-gate -0.5 \
	    --flywheel-every 0.5 \
	    | tee /tmp/deepvision_flywheel_smoke/smoke.out
	$(PY) -c "import json; \
rec = [json.loads(l) for l in open('/tmp/deepvision_flywheel_smoke/smoke.out') \
       if l.strip().startswith('{')][-1]; \
fw = rec['flywheel']['lenet5']; \
assert fw.get('promoted', 0) >= 1, f'no flywheel promotion: {rec}'; \
print('flywheel smoke: episode promoted, state', fw['state'])"

bench-serve-int8: ## int8-vs-bf16 serving: arm the calibrated quantization
	## gate (accuracy-delta vs the pinned shard), then the same closed-loop
	## load through each precision ladder — QPS, p99, bytes/batch one line
	env $(CPU_ENV) $(PY) bench_serve.py --int8

bench-serve-mesh: ## mesh-sharded (GSPMD) predict vs the single-chip
	## engine on 8 CPU virtual devices: per-chip resident weight bytes
	## (bar: cut >= 0.98x the model-axis size), p99 at batch-max,
	## largest-servable-per-chip-budget, and zero recompiles across a
	## promotion — one JSON line (docs/SERVING.md "Mesh serving")
	env $(CPU_ENV) $(PY) bench_serve.py --mesh

bench-serve-load: ## open-loop fleet load bench: sustained-QPS arrival
	## schedule over a 2-model fleet — sustained QPS, p99-under-load,
	## shed rate (one JSON line; docs/SERVING.md "Load bench")
	env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu $(PY) bench_serve.py --load

bench-serve-spike: ## overload transient: offered QPS steps 1x->3x->1x while
	## the shed-driven autoscaler scales the dispatcher pools —
	## time-to-absorb, shed during the transient, per-phase p99, and the
	## zero-recompile worker-spawn proof (one JSON line; docs/SERVING.md
	## "Overload control")
	env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu $(PY) bench_serve.py \
	    --load --spike

bench-serve-trace: ## Perfetto trace of the load bench: runs the open-loop
	## arrival schedule untraced then traced at default sampling, dumps
	## trace.json, and FAILS if tracing cost >3% of sustained QPS
	## (docs/OBSERVABILITY.md)
	env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu $(PY) bench_serve.py \
	    --load --trace-out trace.json

bench-serve-promote: ## accuracy-gated promotion under open-loop load: a
	## new epoch lands mid-bench and runs shadow->gate->canary->promote
	## while arrivals keep firing — promotion_secs, shed rate, p99 delta
	## through the swap, zero-mixed-generation audit (one JSON line;
	## docs/SERVING.md "Promotion")
	env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu $(PY) bench_serve.py \
	    --load --promote-at 1.5 --secs 5

bench-serve-flywheel: ## serve->train->serve flywheel under open-loop load:
	## the drift-shift fault fires mid-bench and the monitor must confirm
	## drift, fine-tune a bounded epoch, and promote it through the gate
	## while arrivals keep firing — time-to-detect, time-to-promoted,
	## goodput during the episode vs steady state (one JSON line;
	## docs/FAILURES.md "Flywheel decisions")
	env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu $(PY) bench_serve.py \
	    --flywheel

bench-serve-tier: ## replica-tier bench: warm-vs-cold replica boot through
	## the shared persistent compile cache (>=2x, zero warm recompiles),
	## then SIGKILL one of 3 replicas under an open-loop schedule — zero
	## failed responses after the ejection window, goodput within 5% of
	## pre-kill, supervised readmission (one JSON line; docs/SERVING.md
	## "Replica tier")
	env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu $(PY) bench_serve.py --tier

dryrun:      ## 8-virtual-device multichip compile/exec check
	env $(CPU_ENV) $(PY) -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

smoke:       ## one synthetic epoch of the flagship trainer
	env $(CPU_ENV) $(PY) LeNet/jax/train.py -m lenet5 --synthetic --epochs 1

seg-smoke:   ## one epoch of the segmentation family on synthetic
	## shapes-and-masks scenes (docs/SEGMENTATION.md) — prints val mIoU
	env $(CPU_ENV) $(PY) UNet/jax/train.py -m unet_synthetic --epochs 1 \
	    --batch-size 16

vit-smoke:   ## one synthetic epoch of the ViT family (naive attention on
	## CPU; the fused-kernel bars live in `make bench-attn`)
	env $(CPU_ENV) $(PY) ViT/jax/train.py -m vit_tiny --synthetic \
	    --epochs 1
