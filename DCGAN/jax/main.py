#!/usr/bin/env python
"""Train DCGAN on MNIST (TPU) — `python main.py [--synthetic] [--resume]`.

Per-family entrypoint matching the reference's UX (`DCGAN/tensorflow/main.py`),
backed by the shared deepvision_tpu DCGANTrainer: one jitted step with two
optimizers instead of two GradientTapes.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--data-dir", default="dataset/mnist")
    p.add_argument("--epochs", type=int, default=None)
    p.add_argument("--batch-size", type=int, default=None)
    p.add_argument("--workdir", default="runs/dcgan")
    p.add_argument("--resume", action="store_true")
    p.add_argument("--synthetic", action="store_true",
                   help="random data smoke run, no dataset needed")
    p.add_argument("--steps-per-epoch", type=int, default=4,
                   help="steps per epoch in --synthetic mode")
    p.add_argument("--profile-dir", default=None,
                   help="capture a jax.profiler trace of the first epoch here")
    p.add_argument("--recover-on-divergence", type=int, default=None,
                   metavar="N",
                   help="roll back to the last committed checkpoint and "
                        "retry (LR scaled down) up to N times when an "
                        "epoch's metrics go non-finite (default 0: halt)")
    p.add_argument("--compilation-cache",
                   default=os.environ.get("DEEPVISION_COMPILATION_CACHE",
                                          "auto"),
                   metavar="DIR|off", help="persistent XLA compilation cache "
                   "(see the shared trainer CLIs); 'off' disables")
    args = p.parse_args()

    from deepvision_tpu.cli import setup_compilation_cache
    from deepvision_tpu.configs import get_config
    from deepvision_tpu.core.gan import DCGANTrainer
    from deepvision_tpu.data import gan as gan_data

    setup_compilation_cache(args.compilation_cache)

    cfg = get_config("dcgan")
    if args.epochs:
        cfg = cfg.replace(total_epochs=args.epochs)
    if args.batch_size:
        cfg = cfg.replace(batch_size=args.batch_size)
    if args.recover_on_divergence is not None:
        cfg = cfg.replace(recover_on_divergence=args.recover_on_divergence)

    trainer = DCGANTrainer(cfg, workdir=args.workdir)
    if args.resume:
        got = trainer.resume()
        print(f"resumed from epoch {got}" if got else "no checkpoint found")

    if args.synthetic:
        def train_fn(epoch):
            return gan_data.synthetic_mnist_batches(
                cfg.batch_size, steps=args.steps_per_epoch, seed=epoch)
    else:
        def train_fn(epoch):
            return gan_data.mnist_gan_batches(args.data_dir, cfg.batch_size,
                                              seed=epoch)

    from deepvision_tpu.core.trainer import fit_and_close
    metrics = fit_and_close(trainer, train_fn, profile_dir=args.profile_dir)
    print(f"done: {metrics}")


if __name__ == "__main__":
    main()
