#!/usr/bin/env python
"""DCGAN inference: restore checkpoint, sample generated digits, save a PNG grid
(`DCGAN/tensorflow/inference.py:7-29` — matplotlib display swapped for a file,
this runs headless on TPU VMs).
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--workdir", default="runs/dcgan")
    p.add_argument("--num", type=int, default=16)
    p.add_argument("--out", default="generated.png")
    p.add_argument("--seed", type=int, default=42)
    args = p.parse_args()

    import jax
    import numpy as np
    from PIL import Image

    from deepvision_tpu.configs import get_config
    from deepvision_tpu.core.gan import DCGANTrainer

    trainer = DCGANTrainer(get_config("dcgan"), workdir=args.workdir)
    if trainer.resume() is None:
        print("WARNING: no checkpoint found — sampling from random weights")
    images = trainer.generate(args.num, jax.random.PRNGKey(args.seed))
    trainer.close()

    # tile into a roughly-square grid, [-1,1] → [0,255]
    n = int(np.ceil(np.sqrt(args.num)))
    grid = np.zeros((n * 28, n * 28), np.uint8)
    for i, img in enumerate(images):
        r, c = divmod(i, n)
        grid[r * 28:(r + 1) * 28, c * 28:(c + 1) * 28] = (
            (img[..., 0] * 127.5 + 127.5).clip(0, 255).astype(np.uint8))
    Image.fromarray(grid).save(args.out)
    print(f"saved {args.num} samples to {args.out}")


if __name__ == "__main__":
    main()
