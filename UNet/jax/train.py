#!/usr/bin/env python
"""Train semantic segmentation (U-Net over ResNet backbones) on TPU —
`python train.py -m unet_synthetic` / `-m unet_resnet50`.

The reference zoo has no dense-prediction family (PAPER.md §0); this
entrypoint runs the completed TPU-native implementation: pixel-wise CE
(+ optional dice), streaming confusion-matrix mIoU eval, paired device
augmentation, and end-to-end H-sharded training on the spatial mesh
(`-m unet_synthetic --spatial-parallel 2`, or the pre-wired
`unet_synthetic_sp2`). docs/SEGMENTATION.md.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from deepvision_tpu.cli import run_segmentation

MODELS = ["unet_resnet50", "unet_synthetic", "unet_synthetic_sp2",
          "unet_digits"]

if __name__ == "__main__":
    run_segmentation("UNet", MODELS)
