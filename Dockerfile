# TPU training image — the TPU-VM counterpart of the reference's CUDA image
# (`Hourglass/tensorflow/Dockerfile:1-21`: nvidia/cuda base + reqs + ENTRYPOINT).
# Run on a Cloud TPU VM (the TPU runtime is provided by the host libtpu).
FROM python:3.12-slim

WORKDIR /app

RUN pip install --no-cache-dir \
    "jax[tpu]" -f https://storage.googleapis.com/jax-releases/libtpu_releases.html \
    flax optax orbax-checkpoint chex einops numpy pillow \
    tensorflow-cpu  # host-side tf.data input pipelines only

COPY . /app

ENV PYTHONPATH=/app

# Override with e.g.:
#   docker run <img> python ResNet/jax/train.py -m resnet50 --data-dir gs://...
ENTRYPOINT ["python", "Hourglass/jax/main.py"]
