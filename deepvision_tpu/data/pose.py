"""Pose (MPII) input pipeline: TFRecords → cropped images + normalized keypoints.

Parity targets: the MPII TFRecord schema written by the reference converter
(`Datasets/MPII/tfrecords_mpii.py:38-84`: parts/x,y as floats normalized by image
size with <0 marking missing joints, parts/v ∈ {0, 2}) and the ROI-crop semantics
of `Hourglass/tensorflow/preprocess.py:43-88` (crop to the keypoint bounding box
plus a margin — randomized 0.1-0.3 at train time, `:17-23` — then shift/rescale
keypoints into crop coordinates).

NOTE: the reference preprocessor declares `parts/x` as int64 pixels and reads
`center/scale` keys its own converter never writes (`preprocess.py:180-185` vs
`tfrecords_mpii.py:65-77`) — its two halves disagree. We follow the converter's
schema (it defines the on-disk format) and express the crop margin as a fraction
of the keypoint extent instead of the absent `scale` field.

The per-keypoint gaussian rendering the reference does here on the host moves to
the device step (ops/heatmap.py). Batches are (images (B,S,S,3) f32 in [-1,1],
kp_x (B,16), kp_y (B,16), visibility (B,16)).
"""

from __future__ import annotations

from typing import Iterator, Tuple

import numpy as np

from .imagenet import _tf
from .util import to_uint8_pixels

NUM_JOINTS = 16  # MPII


def parse_example(serialized, tf):
    features = {
        "image/encoded": tf.io.FixedLenFeature([], tf.string),
        "image/object/parts/x": tf.io.VarLenFeature(tf.float32),
        "image/object/parts/y": tf.io.VarLenFeature(tf.float32),
        "image/object/parts/v": tf.io.VarLenFeature(tf.int64),
    }
    parsed = tf.io.parse_single_example(serialized, features)
    kp_x = tf.sparse.to_dense(parsed["image/object/parts/x"])
    kp_y = tf.sparse.to_dense(parsed["image/object/parts/y"])
    vis = tf.cast(tf.sparse.to_dense(parsed["image/object/parts/v"]), tf.float32)
    return parsed["image/encoded"], kp_x, kp_y, vis


def crop_roi(image, kp_x, kp_y, vis, margin, tf):
    """Crop to the visible-keypoint bounding box + margin (fraction of the
    keypoint extent), re-normalizing keypoints to the crop
    (`preprocess.py:43-88`)."""
    h = tf.cast(tf.shape(image)[0], tf.float32)
    w = tf.cast(tf.shape(image)[1], tf.float32)
    ok = (kp_x >= 0.0) & (kp_y >= 0.0)
    big = tf.where(ok, kp_x, tf.ones_like(kp_x) * 2.0)
    sml = tf.where(ok, kp_x, tf.ones_like(kp_x) * -1.0)
    xmin = tf.reduce_min(big)
    xmax = tf.reduce_max(sml)
    big_y = tf.where(ok, kp_y, tf.ones_like(kp_y) * 2.0)
    sml_y = tf.where(ok, kp_y, tf.ones_like(kp_y) * -1.0)
    ymin = tf.reduce_min(big_y)
    ymax = tf.reduce_max(sml_y)

    extent = tf.maximum(xmax - xmin, ymax - ymin)
    pad = margin * tf.maximum(extent, 1e-3)
    exmin = tf.clip_by_value(xmin - pad, 0.0, 1.0)
    eymin = tf.clip_by_value(ymin - pad, 0.0, 1.0)
    exmax = tf.clip_by_value(xmax + pad, 0.0, 1.0)
    eymax = tf.clip_by_value(ymax + pad, 0.0, 1.0)

    off_y = tf.cast(eymin * h, tf.int32)
    off_x = tf.cast(exmin * w, tf.int32)
    tgt_h = tf.maximum(tf.cast((eymax - eymin) * h, tf.int32), 1)
    tgt_w = tf.maximum(tf.cast((exmax - exmin) * w, tf.int32), 1)
    image = image[off_y:off_y + tgt_h, off_x:off_x + tgt_w, :]

    new_w = exmax - exmin
    new_h = eymax - eymin
    kp_x = tf.where(ok, (kp_x - exmin) / tf.maximum(new_w, 1e-6),
                    tf.ones_like(kp_x) * -1.0)
    kp_y = tf.where(ok, (kp_y - eymin) / tf.maximum(new_h, 1e-6),
                    tf.ones_like(kp_y) * -1.0)
    return image, kp_x, kp_y


def preprocess(serialized, image_size: int, training: bool, tf,
               normalize_on_host: bool = True):
    encoded, kp_x, kp_y, vis = parse_example(serialized, tf)
    image = tf.cast(tf.io.decode_jpeg(encoded, channels=3), tf.float32)
    margin = (tf.random.uniform([], 0.1, 0.3) if training
              else tf.constant(0.2))  # `preprocess.py:17-23`
    # all-missing annotations (every joint < 0) would collapse the crop to a
    # zero-size slice — skip the crop for those records
    has_kp = tf.reduce_any((kp_x >= 0.0) & (kp_y >= 0.0))
    image, kp_x, kp_y = tf.cond(
        has_kp,
        lambda: crop_roi(image, kp_x, kp_y, vis, margin, tf),
        lambda: (image, kp_x, kp_y))
    image = tf.image.resize(image, [image_size, image_size])
    if normalize_on_host:
        image = image / 127.5 - 1.0
    else:
        # raw uint8: the step normalizes on device (UNIT_RANGE_NORM)
        image = to_uint8_pixels(image, tf)

    def fix(t):
        t = t[:NUM_JOINTS]
        t = tf.pad(t, [[0, NUM_JOINTS - tf.shape(t)[0]]], constant_values=-1.0)
        t.set_shape([NUM_JOINTS])
        return t

    image.set_shape([image_size, image_size, 3])
    return image, fix(kp_x), fix(kp_y), fix(vis)


def build_dataset(file_pattern: str, *, batch_size: int, image_size: int = 256,
                  training: bool = True, shuffle_buffer: int = 512,
                  num_process: int = 1, process_index: int = 0, seed: int = 0,
                  normalize_on_host: bool = True):
    """Per-host tf.data pose pipeline (cf. `create_dataset`,
    `Hourglass/tensorflow/train.py:175-190`). `normalize_on_host=False`
    emits raw uint8 (the step normalizes on device, `--device-normalize`)."""
    tf = _tf()
    AUTOTUNE = tf.data.AUTOTUNE
    files = tf.data.Dataset.list_files(file_pattern, shuffle=training, seed=seed)
    if num_process > 1:
        files = files.shard(num_process, process_index)
    ds = tf.data.TFRecordDataset(files, num_parallel_reads=AUTOTUNE)
    if training:
        ds = ds.shuffle(shuffle_buffer, seed=seed)
    ds = ds.map(lambda s: preprocess(s, image_size, training, tf,
                                     normalize_on_host=normalize_on_host),
                num_parallel_calls=AUTOTUNE)
    ds = ds.batch(batch_size, drop_remainder=True)
    return ds.prefetch(AUTOTUNE)


def synthetic_batches(*, batch_size: int, image_size: int = 64,
                      num_joints: int = NUM_JOINTS, steps: int = 2,
                      seed: int = 0) -> Iterator[Tuple[np.ndarray, ...]]:
    rs = np.random.RandomState(seed)
    for _ in range(steps):
        images = rs.rand(batch_size, image_size, image_size, 3).astype(
            np.float32) * 2.0 - 1.0
        kp_x = rs.uniform(0.1, 0.9, (batch_size, num_joints)).astype(np.float32)
        kp_y = rs.uniform(0.1, 0.9, (batch_size, num_joints)).astype(np.float32)
        vis = (rs.rand(batch_size, num_joints) > 0.2).astype(np.float32) * 2.0
        yield images, kp_x, kp_y, vis
