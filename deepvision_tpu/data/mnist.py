"""MNIST idx-format parser + batch iterator.

Parity target: `LeNet/pytorch/data_load.py:12-57` — parses the raw idx binary files,
pads 28x28 → 32x32, normalizes with the reference's mean/std (0.1307/0.3081), and
yields NHWC float32 batches. Pure numpy; no torch/tf dependency on the input path.
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import Iterator, Optional, Tuple

import numpy as np

MEAN, STD = 0.1307, 0.3081  # reference Normalize values, LeNet/pytorch/train.py


def _open(path: str):
    return gzip.open(path, "rb") if path.endswith(".gz") else open(path, "rb")


def read_idx_images(path: str) -> np.ndarray:
    """Parse an idx3-ubyte image file (magic 2051) → (N, 28, 28) uint8."""
    with _open(path) as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        if magic != 2051:
            raise ValueError(f"{path}: bad magic {magic} (want 2051)")
        data = np.frombuffer(f.read(n * rows * cols), dtype=np.uint8)
    return data.reshape(n, rows, cols)


def read_idx_labels(path: str) -> np.ndarray:
    """Parse an idx1-ubyte label file (magic 2049) → (N,) uint8."""
    with _open(path) as f:
        magic, n = struct.unpack(">II", f.read(8))
        if magic != 2049:
            raise ValueError(f"{path}: bad magic {magic} (want 2049)")
        return np.frombuffer(f.read(n), dtype=np.uint8)


def preprocess(images: np.ndarray) -> np.ndarray:
    """uint8 (N,28,28) → normalized float32 (N,32,32,1), pad 28→32 like the
    reference (`LeNet/pytorch/data_load.py:40-44`)."""
    x = np.pad(images, ((0, 0), (2, 2), (2, 2)), mode="constant").astype(np.float32)
    x = (x / 255.0 - MEAN) / STD
    return x[..., None]


FILES = {
    "train": ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
    "test": ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"),
}


def load_raw_split(data_dir: str, split: str) -> Tuple[np.ndarray, np.ndarray]:
    """Raw uint8 (N,28,28) images + int32 labels for a split, resolving
    bare-vs-.gz idx files — the shared load path under both the normalized
    classification pipeline (`load_split`) and consumers that apply their
    own scaling (the GAN gate's [-1,1], `tests/test_gan_quality.py`)."""
    img_name, lbl_name = FILES[split]
    img_path, lbl_path = os.path.join(data_dir, img_name), os.path.join(data_dir, lbl_name)
    if not os.path.exists(img_path) and os.path.exists(img_path + ".gz"):
        img_path += ".gz"
    if not os.path.exists(lbl_path) and os.path.exists(lbl_path + ".gz"):
        lbl_path += ".gz"
    return read_idx_images(img_path), read_idx_labels(lbl_path).astype(np.int32)


def load_split(data_dir: str, split: str) -> Tuple[np.ndarray, np.ndarray]:
    images, labels = load_raw_split(data_dir, split)
    return preprocess(images), labels


class MnistBatches:
    def __init__(self, images: np.ndarray, labels: np.ndarray, batch_size: int,
                 shuffle: bool = True, seed: int = 0, drop_remainder: bool = True):
        self.images, self.labels = images, labels
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.rng = np.random.RandomState(seed)
        self.drop_remainder = drop_remainder

    def __iter__(self):
        idx = np.arange(len(self.labels))
        if self.shuffle:
            self.rng.shuffle(idx)
        end = len(idx) - (len(idx) % self.batch_size) if self.drop_remainder else len(idx)
        for i in range(0, end, self.batch_size):
            sel = idx[i:i + self.batch_size]
            yield self.images[sel], self.labels[sel]

    def __len__(self):
        n = len(self.labels) // self.batch_size
        return n if self.drop_remainder else -(-len(self.labels) // self.batch_size)
