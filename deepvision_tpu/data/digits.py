"""Real handwritten-digits pipeline (scikit-learn's bundled UCI digits).

Role: the offline stand-in for the reference's real-MNIST LeNet runs
(`LeNet/pytorch/train.py:15-32`, published 99.07% top-1
`LeNet/pytorch/README.md:47`; TF 98.58% `LeNet/tensorflow/README.md:41`).
The MNIST *image* files are not obtainable in a zero-egress environment (the
reference vendors only the label files, `Datasets/MNIST/`), so the real-data
accuracy gate trains on the UCI Optical Recognition of Handwritten Digits
set that ships inside scikit-learn: 1797 real 8x8 grayscale scans of
handwritten digits. Images are upsampled 8->32 px so the unchanged `lenet5`
model and trainer run exactly the production MNIST code path; when real
MNIST is present (`Datasets/MNIST/fetch_mnist.sh`), `data/mnist.py` is the
pipeline and `tests/test_real_data.py` asserts the >=98.5% bar.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

TRAIN_EXAMPLES = 1437   # 80/20 split of the 1797 scans (seeded, fixed)
VAL_EXAMPLES = 360
SPLIT_SEED = 20260801


def _upsample(images: np.ndarray, factor: int = 4) -> np.ndarray:
    """(N, 8, 8) -> (N, 32, 32) by pixel replication. Nearest-neighbor keeps
    the scan's real intensity statistics (no interpolation-invented values)
    and is shape-compatible with the 32px LeNet stem."""
    return images.repeat(factor, axis=1).repeat(factor, axis=2)


def load_raw(image_size: int = 32) -> Tuple[np.ndarray, np.ndarray]:
    """All 1797 scans as (N, image_size, image_size, 1) float32 in [0, 1]
    plus labels — the one place the sklearn load/scale/upsample happens
    (the GAN quality gate consumes this form directly)."""
    from sklearn.datasets import load_digits
    bunch = load_digits()
    images = bunch.images.astype(np.float32) / 16.0      # (1797, 8, 8) in [0,1]
    labels = bunch.target.astype(np.int32)
    images = _upsample(images, image_size // 8)[..., None]
    return images, labels


def load_splits(image_size: int = 32
                ) -> Tuple[Tuple[np.ndarray, np.ndarray],
                           Tuple[np.ndarray, np.ndarray]]:
    """Deterministic (train, test) splits as normalized float32 NHWC.

    Pixels arrive 0..16; normalized per-channel with the TRAIN split's own
    mean/std (the role MEAN/STD fill in `data/mnist.py`, computed rather
    than hard-coded because unlike MNIST there is no published constant).
    """
    images, labels = load_raw(image_size)
    images = images[..., 0]
    order = np.random.RandomState(SPLIT_SEED).permutation(len(labels))
    images, labels = images[order], labels[order]
    tr_x, te_x = images[:TRAIN_EXAMPLES], images[TRAIN_EXAMPLES:]
    tr_y, te_y = labels[:TRAIN_EXAMPLES], labels[TRAIN_EXAMPLES:]
    mean, std = float(tr_x.mean()), float(tr_x.std())
    tr_x = ((tr_x - mean) / std)[..., None]
    te_x = ((te_x - mean) / std)[..., None]
    return (tr_x, tr_y), (te_x, te_y)
