"""Real handwritten-digits pipeline (scikit-learn's bundled UCI digits).

Role: the offline stand-in for the reference's real-MNIST LeNet runs
(`LeNet/pytorch/train.py:15-32`, published 99.07% top-1
`LeNet/pytorch/README.md:47`; TF 98.58% `LeNet/tensorflow/README.md:41`).
The MNIST *image* files are not obtainable in a zero-egress environment (the
reference vendors only the label files, `Datasets/MNIST/`), so the real-data
accuracy gate trains on the UCI Optical Recognition of Handwritten Digits
set that ships inside scikit-learn: 1797 real 8x8 grayscale scans of
handwritten digits. Images are upsampled 8->32 px so the unchanged `lenet5`
model and trainer run exactly the production MNIST code path; when real
MNIST is present (`Datasets/MNIST/fetch_mnist.sh`), `data/mnist.py` is the
pipeline and `tests/test_real_data.py` asserts the >=98.5% bar.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

TRAIN_EXAMPLES = 1437   # 80/20 split of the 1797 scans (seeded, fixed)
VAL_EXAMPLES = 360
SPLIT_SEED = 20260801


def _upsample(images: np.ndarray, factor: int = 4) -> np.ndarray:
    """(N, 8, 8) -> (N, 32, 32) by pixel replication. Nearest-neighbor keeps
    the scan's real intensity statistics (no interpolation-invented values)
    and is shape-compatible with the 32px LeNet stem."""
    return images.repeat(factor, axis=1).repeat(factor, axis=2)


def load_raw(image_size: int = 32) -> Tuple[np.ndarray, np.ndarray]:
    """All 1797 scans as (N, image_size, image_size, 1) float32 in [0, 1]
    plus labels — the one place the sklearn load/scale/upsample happens
    (the GAN quality gate consumes this form directly)."""
    from sklearn.datasets import load_digits
    bunch = load_digits()
    images = bunch.images.astype(np.float32) / 16.0      # (1797, 8, 8) in [0,1]
    labels = bunch.target.astype(np.int32)
    images = _upsample(images, image_size // 8)[..., None]
    return images, labels


def load_splits(image_size: int = 32
                ) -> Tuple[Tuple[np.ndarray, np.ndarray],
                           Tuple[np.ndarray, np.ndarray]]:
    """Deterministic (train, test) splits as normalized float32 NHWC.

    Pixels arrive 0..16; normalized per-channel with the TRAIN split's own
    mean/std (the role MEAN/STD fill in `data/mnist.py`, computed rather
    than hard-coded because unlike MNIST there is no published constant).
    """
    images, labels = load_raw(image_size)
    images = images[..., 0]
    order = np.random.RandomState(SPLIT_SEED).permutation(len(labels))
    images, labels = images[order], labels[order]
    tr_x, te_x = images[:TRAIN_EXAMPLES], images[TRAIN_EXAMPLES:]
    tr_y, te_y = labels[:TRAIN_EXAMPLES], labels[TRAIN_EXAMPLES:]
    mean, std = float(tr_x.mean()), float(tr_x.std())
    tr_x = ((tr_x - mean) / std)[..., None]
    te_x = ((te_x - mean) / std)[..., None]
    return (tr_x, tr_y), (te_x, te_y)


# -- real-pixel detection scenes (VERDICT r4 item 7, offline form) -------------
#
# The reference's detection families never published an mAP
# (`YOLO/tensorflow/README.md:29` "work in progress"), and its hosted h5
# weights are unreachable from the zero-egress sandbox — so the committed
# real-data detection artifact composes the SAME real scans the LeNet gate
# uses into detection scenes: each 64px canvas carries 1-4 real digits
# pasted into distinct quadrants (disjoint by construction -> unambiguous
# ground truth), labels are the digit classes, boxes the paste rectangles.
# Real pixels, synthetic composition — the detection analog of the
# lenet5_digits accuracy gate (runs/r04_lenet5_digits_cpu).

DETECT_MAX_BOXES = 100  # ops/yolo.py MAX_BOXES pad (import cycle avoided)


def detection_scenes(images: np.ndarray, labels: np.ndarray, *,
                     n_scenes: int, canvas: int = 64, digit_px: int = 16,
                     seed: int = 0) -> Tuple[np.ndarray, ...]:
    """Compose scans (N, 8, 8) in [0,1] + labels into detection batches.

    Returns (scenes, boxes, classes, valid) in the padded-GT layout every
    detection trainer consumes (`data/detection.py::synthetic_batches`):
    scenes (S, canvas, canvas, 3) float32 in [-1, 1], boxes normalized
    x1y1x2y2. Quadrant placement: up to 4 digits per scene, one per
    canvas/2-quadrant, jittered inside it — boxes can touch but never
    overlap, so mAP on these scenes measures detection, not tie-breaking.
    """
    if digit_px % 8 != 0:
        raise ValueError(f"digit_px={digit_px} must be a multiple of the "
                         f"8px scan size (pixel-replication upsample) — a "
                         f"non-multiple would render 8*(digit_px//8) pixels "
                         f"under a digit_px-sized GT box")
    rs = np.random.RandomState(seed)
    q = canvas // 2
    jitter = q - digit_px
    scale = digit_px // 8
    scenes = np.zeros((n_scenes, canvas, canvas, 3), np.float32)
    boxes = np.zeros((n_scenes, DETECT_MAX_BOXES, 4), np.float32)
    classes = np.zeros((n_scenes, DETECT_MAX_BOXES), np.int32)
    valid = np.zeros((n_scenes, DETECT_MAX_BOXES), np.float32)
    for s in range(n_scenes):
        n_digits = rs.randint(1, 5)
        quads = rs.permutation(4)[:n_digits]
        for slot, quad in enumerate(quads):
            i = rs.randint(len(images))
            digit = images[i].repeat(scale, axis=0).repeat(scale, axis=1)
            qy, qx = divmod(int(quad), 2)
            y0 = qy * q + rs.randint(0, jitter + 1)
            x0 = qx * q + rs.randint(0, jitter + 1)
            scenes[s, y0:y0 + digit_px, x0:x0 + digit_px, :] = digit[..., None]
            boxes[s, slot] = (x0 / canvas, y0 / canvas,
                              (x0 + digit_px) / canvas,
                              (y0 + digit_px) / canvas)
            classes[s, slot] = labels[i]
            valid[s, slot] = 1.0
    return scenes * 2.0 - 1.0, boxes, classes, valid


def scan_splits() -> Tuple[Tuple[np.ndarray, np.ndarray],
                           Tuple[np.ndarray, np.ndarray]]:
    """The raw 8x8 scans under the SAME seeded split as the classification
    gate: (train scans, labels), (held-out scans, labels)."""
    images, labels = load_raw(image_size=8)
    images = images[..., 0]
    order = np.random.RandomState(SPLIT_SEED).permutation(len(labels))
    images, labels = images[order], labels[order]
    return ((images[:TRAIN_EXAMPLES], labels[:TRAIN_EXAMPLES]),
            (images[TRAIN_EXAMPLES:], labels[TRAIN_EXAMPLES:]))


def detection_splits(*, canvas: int = 64, digit_px: int = 16,
                     train_scenes: int = 512, val_scenes: int = 128,
                     train_seed: int = 1):
    """Deterministic (train, val) detection-scene sets: train scenes compose
    only train-split scans, val scenes only the held-out 360 — so val
    measures generalization to unseen handwriting, not re-detection of seen
    crops. `train_seed` lets the trainer re-compose FRESH train scenes each
    epoch (composition is free; scene diversity is the real regularizer) —
    the val set stays pinned at seed 2."""
    (tr_x, tr_y), (va_x, va_y) = scan_splits()
    tr = detection_scenes(tr_x, tr_y, n_scenes=train_scenes, canvas=canvas,
                          digit_px=digit_px, seed=train_seed)
    va = detection_scenes(va_x, va_y, n_scenes=val_scenes, canvas=canvas,
                          digit_px=digit_px, seed=2)
    return tr, va


def detection_val_scenes(*, canvas: int, n_scenes: int):
    """THE pinned validation scene set (seed 2, held-out scans only) — the
    single owner of the identity that training validates against and both
    family evaluators score (cli.py digits_detect, ObjectsAsPoints/ and
    YOLO/jax/evaluate.py). Change it here or nowhere."""
    _, (va_x, va_y) = scan_splits()
    return detection_scenes(va_x, va_y, n_scenes=n_scenes, canvas=canvas,
                            seed=2)


def detection_batches(split: Tuple[np.ndarray, ...], *, batch_size: int,
                      shuffle_seed: int = None):
    """Iterate a detection-scene split in batches (drop-remainder, the
    detection trainers' fixed-shape contract)."""
    scenes, boxes, classes, valid = split
    idx = np.arange(len(scenes))
    if shuffle_seed is not None:
        np.random.RandomState(shuffle_seed).shuffle(idx)
    for lo in range(0, len(idx) - batch_size + 1, batch_size):
        sel = idx[lo:lo + batch_size]
        yield scenes[sel], boxes[sel], classes[sel], valid[sel]
