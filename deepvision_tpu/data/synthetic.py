"""Synthetic in-memory datasets.

The reference kept a commented-out random-tensor harness for local testing
(`CycleGAN/tensorflow/train.py:338-342`); here it is a first-class backend so every
trainer can run end-to-end with no data on disk (used by tests and smoke runs).
"""

from __future__ import annotations

from typing import Iterator, Optional, Tuple

import numpy as np


class SyntheticClassification:
    """Deterministic fake (image, label) batches with a fixed learnable signal:
    the label is encoded in the mean of the image, so a model can actually fit it —
    useful for loss-goes-down tests."""

    def __init__(self, batch_size: int, image_size: int = 32, channels: int = 3,
                 num_classes: int = 10, num_batches: int = 8, seed: int = 0,
                 learnable: bool = True, emit_uint8: bool = False):
        """`emit_uint8=True` yields raw [0,255] uint8 pixel batches (the
        `--device-augment` staging contract, data/device_augment.py) with
        the same label-in-the-mean learnable signal mapped into pixel space
        — pass the PADDED `config.decode_image_size` as `image_size`; the
        jitted augment crops back down to the model's input."""
        self.batch_size = batch_size
        self.image_size = image_size
        self.channels = channels
        self.num_classes = num_classes
        self.num_batches = num_batches
        self.seed = seed
        self.learnable = learnable
        self.emit_uint8 = emit_uint8

    def __iter__(self) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        rng = np.random.RandomState(self.seed)
        for _ in range(self.num_batches):
            labels = rng.randint(0, self.num_classes, size=(self.batch_size,))
            images = rng.randn(self.batch_size, self.image_size, self.image_size,
                               self.channels).astype(np.float32)
            if self.learnable:
                images += (labels / self.num_classes - 0.5)[:, None, None, None] * 4.0
            if self.emit_uint8:
                # same signal, pixel units: unit-ish floats -> mean 128,
                # ~32px std, label shift up to +-64px — survives the
                # device-side (x/255 - mean)/std remap with room to spare
                images = np.clip(images * 32.0 + 128.0, 0.0, 255.0)
                images = np.round(images).astype(np.uint8)
            yield images, labels.astype(np.int32)

    def __len__(self):
        return self.num_batches
