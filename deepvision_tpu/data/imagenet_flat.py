"""Flat-directory ImageNet loader (the reference's PyTorch data flavor).

Parity target: `ImageNet2012Dataset` (`ResNet/pytorch/data_load.py:14-69`) — a
single flattened directory of JPEGs whose filenames start with their WordNet
synset id (`n01440764_10026.JPEG`), labels resolved through the synset list
(`Datasets/ILSVRC2012/synsets.txt`, flattening scripts
`Datasets/ILSVRC2012/flatten-script.sh`). Redesigned for feeding TPU hosts:

- PIL decode (no cv2 dependency) in a thread pool — JPEG decode releases the
  GIL, so this parallels like the reference's `num_workers=16` loader procs
  without fork overhead;
- batches are NHWC numpy arrays ready for `device_put` (the `DataLoader`
  role of `ResNet/pytorch/train.py:229-234`): float32 by default, compact
  uint8 at the padded decode size in `host_decode_only` mode
  (`--device-augment`, docs/INPUT_PIPELINE.md);
- per-epoch seeded shuffling (the reference never seeds, SURVEY.md §5.2).

The TFRecord pipeline (`data/imagenet.py`) is the fast path for pods; this
loader covers the reference's simpler disk layout and is handy for subsets.
"""

from __future__ import annotations

import os
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Iterator, Optional, Sequence, Tuple

import numpy as np

from .transforms import eval_transform, train_transform

IMG_EXTS = (".jpeg", ".jpg", ".png")


def load_synsets(path: str) -> dict:
    """synset id → contiguous class index, in file order
    (`Datasets/ILSVRC2012/synsets.txt` ordering)."""
    with open(path) as fp:
        return {line.strip(): i for i, line in enumerate(fp) if line.strip()}


class FlatImageNet:
    """Iterable over (images, labels) batches from one flat directory.

    `synsets` may be a path to synsets.txt or a prebuilt {synset: index} dict.
    Labels come from the filename prefix before the first underscore
    (`data_load.py:36-44` semantics).
    """

    def __init__(self, root_dir: str, synsets, *, batch_size: int,
                 transform: Optional[Callable] = None, training: bool = True,
                 image_size: int = 224, seed: int = 0, workers: int = 16,
                 drop_remainder: Optional[bool] = None,
                 num_shards: int = 1, shard_index: int = 0,
                 host_decode_only: bool = False):
        """`batch_size` is the PER-HOST batch; on a pod pass
        `num_shards=jax.process_count(), shard_index=jax.process_index()` so
        each host reads a disjoint slice of the directory (the
        `files.shard(...)` role of the TFRecord pipelines).

        `host_decode_only=True` is the `--device-augment` contract
        (docs/INPUT_PIPELINE.md): the host only decodes + resizes to the
        padded square (`config.decode_image_size`) and batches stay **uint8
        NHWC** — ~4x less host->device traffic, with crop/flip/jitter/
        normalize fused into the jitted step (data/device_augment.py)."""
        from .transforms import (host_decode_eval_transform,
                                 host_decode_train_transform)
        self.root_dir = root_dir
        self.synset_to_idx = (load_synsets(synsets) if isinstance(synsets, str)
                              else dict(synsets))
        self.batch_size = batch_size
        self.training = training
        self.host_decode_only = host_decode_only
        if transform is not None:
            self.transform = transform
        elif host_decode_only:
            self.transform = (host_decode_train_transform(image_size)
                              if training
                              else host_decode_eval_transform(image_size))
        else:
            self.transform = (train_transform(image_size) if training
                              else eval_transform(image_size))
        self.seed = seed
        self.workers = workers
        self.drop_remainder = training if drop_remainder is None else drop_remainder

        all_files = sorted(
            f for f in os.listdir(root_dir)
            if f.lower().endswith(IMG_EXTS) and "_" in f
            and f.split("_", 1)[0] in self.synset_to_idx)
        self.files = all_files[shard_index::num_shards]
        if not self.files:
            raise FileNotFoundError(
                f"no labeled images (synset_*.JPEG) under {root_dir!r} "
                f"(shard {shard_index}/{num_shards})")
        self.epoch = 0
        # Every host must run the SAME number of jitted (collective) steps or
        # the pod deadlocks; shard sizes differ by up to 1 file, so each host
        # caps its batch count at the smallest shard's count (min over shards —
        # computable locally since sharding is deterministic). Single-host
        # (num_shards=1) is exact.
        def shard_batches(n_files: int) -> int:
            return (n_files // batch_size if self.drop_remainder
                    else -(-n_files // batch_size))
        self._num_batches = min(
            shard_batches(len(all_files[s::num_shards]))
            for s in range(num_shards))

    def __len__(self) -> int:
        return self._num_batches

    def _load_one(self, args) -> Tuple[np.ndarray, int]:
        fname, rng = args
        from PIL import Image
        with Image.open(os.path.join(self.root_dir, fname)) as im:
            arr = np.asarray(im.convert("RGB"))
        label = self.synset_to_idx[fname.split("_", 1)[0]]
        return self.transform(arr, rng), label

    def __iter__(self) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        order = np.arange(len(self.files))
        root_rng = np.random.default_rng((self.seed, self.epoch))
        if self.training:
            root_rng.shuffle(order)
        self.epoch += 1

        starts = [i * self.batch_size for i in range(self._num_batches)]

        def submit(pool, start):
            idx = order[start:start + self.batch_size]
            rngs = root_rng.spawn(len(idx))
            return [pool.submit(self._load_one, (self.files[i], r))
                    for i, r in zip(idx, rngs)]

        # one-batch lookahead: batch N+1 decodes while N trains (the prefetch
        # the tf.data path gets from `.prefetch(AUTOTUNE)`)
        with ThreadPoolExecutor(max_workers=self.workers) as pool:
            pending = submit(pool, starts[0]) if starts else None
            for n, start in enumerate(starts):
                futures = pending
                pending = (submit(pool, starts[n + 1])
                           if n + 1 < len(starts) else None)
                pairs = [f.result() for f in futures]
                # decode-only batches stay uint8 (the whole point of the
                # staging split); transformed batches are f32 as before
                dtype = np.uint8 if self.host_decode_only else np.float32
                images = np.stack([p[0] for p in pairs]).astype(dtype)
                labels = np.asarray([p[1] for p in pairs], np.int32)
                yield images, labels
