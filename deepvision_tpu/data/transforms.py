"""Composable host-side image transforms (numpy, NHWC).

Parity target: the 7 transform classes every PyTorch classification dir copies
(`ResNet/pytorch/data_load.py:72-296`): Rescale, RandomCrop, CenterCrop,
RandomHorizontalFlip, ToTensor, Normalize, ColorJitter. Differences are
deliberate TPU-first choices:

- images stay **HWC float32** end to end (TPU convs are NHWC; the reference's
  ToTensor transposes to CHW for torch) — the equivalent here is `ToFloat`,
  which only scales uint8 → [0, 1];
- random transforms take an explicit `numpy.random.Generator` instead of
  mutating global RNG state, so input pipelines are seedable per epoch
  (SURVEY.md §5.2: the reference never seeds its PyTorch pipelines).

`Compose` threads the rng through; deterministic transforms ignore it.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import numpy as np

Size = Union[int, Tuple[int, int]]


def _resize(image: np.ndarray, h: int, w: int) -> np.ndarray:
    """Bilinear resize via PIL (cv2-free; PIL ships with the image).

    uint8 goes through the fast RGB path; float inputs resize per-channel in
    float32 ("F" mode) so any value range survives (e.g. Rescale composed after
    ToFloat/Normalize).
    """
    from PIL import Image
    if image.dtype == np.uint8:
        return np.asarray(Image.fromarray(image).resize((w, h), Image.BILINEAR))
    chans = [np.asarray(Image.fromarray(
        np.ascontiguousarray(image[..., c], dtype=np.float32), mode="F")
        .resize((w, h), Image.BILINEAR)) for c in range(image.shape[-1])]
    return np.stack(chans, axis=-1)


class Rescale:
    """Resize: int = shorter side (aspect preserved), tuple = exact (h, w)
    (`data_load.py:72-101`)."""

    def __init__(self, output_size: Size):
        self.output_size = output_size

    def __call__(self, image: np.ndarray, rng=None) -> np.ndarray:
        h, w = image.shape[:2]
        if isinstance(self.output_size, int):
            if h < w:
                nh, nw = self.output_size, int(round(w * self.output_size / h))
            else:
                nh, nw = int(round(h * self.output_size / w)), self.output_size
        else:
            nh, nw = self.output_size
        return _resize(image, nh, nw)


class RandomCrop:
    """Uniform random (h, w) crop (`data_load.py:104-113`)."""

    def __init__(self, output_size: Size):
        self.size = ((output_size, output_size)
                     if isinstance(output_size, int) else output_size)

    def __call__(self, image: np.ndarray, rng: np.random.Generator) -> np.ndarray:
        h, w = image.shape[:2]
        ch, cw = self.size
        top = int(rng.integers(0, h - ch + 1))
        left = int(rng.integers(0, w - cw + 1))
        return image[top:top + ch, left:left + cw]


class CenterCrop:
    """Center (h, w) crop (`data_load.py:116-143`)."""

    def __init__(self, output_size: Size):
        self.size = ((output_size, output_size)
                     if isinstance(output_size, int) else output_size)

    def __call__(self, image: np.ndarray, rng=None) -> np.ndarray:
        h, w = image.shape[:2]
        ch, cw = self.size
        top = (h - ch) // 2
        left = (w - cw) // 2
        return image[top:top + ch, left:left + cw]


class RandomHorizontalFlip:
    """50% (default) left-right flip (`data_load.py:146-173`)."""

    def __init__(self, prob: float = 0.5):
        self.prob = prob

    def __call__(self, image: np.ndarray, rng: np.random.Generator) -> np.ndarray:
        if rng.random() < self.prob:
            return image[:, ::-1]
        return image


class ColorJitter:
    """Random brightness/contrast/saturation jitter (`data_load.py:213-296`).
    Factors drawn uniformly from [max(0, 1-x), 1+x]; applied on [0, 255]."""

    def __init__(self, brightness: float = 0.0, contrast: float = 0.0,
                 saturation: float = 0.0):
        self.brightness = brightness
        self.contrast = contrast
        self.saturation = saturation

    @staticmethod
    def _factor(rng, x: float) -> float:
        return float(rng.uniform(max(0.0, 1.0 - x), 1.0 + x))

    def __call__(self, image: np.ndarray, rng: np.random.Generator) -> np.ndarray:
        img = image.astype(np.float32)
        if self.brightness:
            img = img * self._factor(rng, self.brightness)
        if self.contrast:
            mean = img.mean(axis=(0, 1), keepdims=True)
            img = (img - mean) * self._factor(rng, self.contrast) + mean
        if self.saturation:
            gray = img.mean(axis=2, keepdims=True)
            img = (img - gray) * self._factor(rng, self.saturation) + gray
        return np.clip(img, 0.0, 255.0)


class ToFloat:
    """uint8 [0, 255] → float32 [0, 1]; stays HWC (the NHWC-native stand-in
    for the reference's CHW `ToTensor`, `data_load.py:176-194`)."""

    def __call__(self, image: np.ndarray, rng=None) -> np.ndarray:
        return np.asarray(image, np.float32) / 255.0


class Normalize:
    """Channelwise (x - mean) / std on [0, 1] floats (`data_load.py:197-210`);
    defaults are the ImageNet statistics the reference uses."""

    def __init__(self, mean: Sequence[float] = (0.485, 0.456, 0.406),
                 std: Sequence[float] = (0.229, 0.224, 0.225)):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)

    def __call__(self, image: np.ndarray, rng=None) -> np.ndarray:
        return (image.astype(np.float32) - self.mean) / self.std


class Compose:
    """Apply transforms in order, threading one rng through
    (`transforms.Compose` role, `ResNet/pytorch/train.py:315-331`)."""

    def __init__(self, transforms: Sequence):
        self.transforms = list(transforms)

    def __call__(self, image: np.ndarray,
                 rng: Optional[np.random.Generator] = None) -> np.ndarray:
        rng = rng or np.random.default_rng()
        for t in self.transforms:
            image = t(image, rng)
        return image


def train_transform(image_size: int = 224) -> Compose:
    """The reference's training stack (`ResNet/pytorch/train.py:315-323`):
    Rescale(256) → flip → RandomCrop(224) → jitter → float → normalize."""
    return Compose([
        Rescale(int(image_size * 256 / 224)),
        RandomHorizontalFlip(),
        RandomCrop(image_size),
        ColorJitter(brightness=0.2, contrast=0.2, saturation=0.2),
        ToFloat(),
        Normalize(),
    ])


def eval_transform(image_size: int = 224) -> Compose:
    """Validation stack (`ResNet/pytorch/train.py:325-331`):
    Rescale(256) → CenterCrop(224) → float → normalize."""
    return Compose([
        Rescale(int(image_size * 256 / 224)),
        CenterCrop(image_size),
        ToFloat(),
        Normalize(),
    ])


def host_decode_train_transform(image_size: int = 224) -> Compose:
    """Host half of the device-augment split (`--device-augment`,
    data/device_augment.py): decode + exact resize to the padded square,
    emitting **uint8** — crop/flip/jitter/normalize all happen batched on
    the device. The exact (D, D) resize keeps staged batch shapes static
    (one XLA program), unlike the aspect-preserving Rescale(256)."""
    from ..core.config import decode_image_size
    d = decode_image_size(image_size)
    return Compose([Rescale((d, d))])


def host_decode_eval_transform(image_size: int = 224) -> Compose:
    """Host half of the eval split: aspect resize + center crop to the
    padded square, uint8 out. The device's centered `image_size` crop of
    this centered crop equals the direct `eval_transform` crop (nested
    centered crops compose), so the split path matches the host path up to
    f32 rounding — pinned by tests/test_device_augment.py."""
    from ..core.config import decode_image_size
    d = decode_image_size(image_size)
    return Compose([Rescale(d), CenterCrop(d)])
