"""GAN input pipelines: MNIST for DCGAN, two-domain TFRecords for CycleGAN.

Parity targets: DCGAN's keras-datasets MNIST normalized to [-1, 1]
(`DCGAN/tensorflow/main.py:21-26`), and CycleGAN's zipped two-domain TFRecord
pipeline with flip → resize 286 → random-crop 256 → [-1, 1]
(`CycleGAN/tensorflow/train.py:74-117`), reading the single-feature TFRecords of
`CycleGAN/tensorflow/tfrecords.py:9-73`.
"""

from __future__ import annotations

from typing import Iterator, Optional, Tuple

import numpy as np

from .imagenet import _tf
from .mnist import read_idx_images


def mnist_gan_batches(data_dir: str, batch_size: int, *, seed: int = 0,
                      drop_remainder: bool = True) -> Iterator[np.ndarray]:
    """(B, 28, 28, 1) float32 in [-1, 1] (`DCGAN/tensorflow/main.py:21-26`)."""
    import os
    for name in ("train-images-idx3-ubyte", "train-images-idx3-ubyte.gz"):
        path = os.path.join(data_dir, name)
        if os.path.exists(path):
            break
    images = read_idx_images(path).astype(np.float32)
    images = (images - 127.5) / 127.5
    images = images[..., None]
    rs = np.random.RandomState(seed)
    order = rs.permutation(len(images))
    for i in range(0, len(order) - (batch_size - 1 if drop_remainder else 0),
                   batch_size):
        yield images[order[i:i + batch_size]]


def synthetic_mnist_batches(batch_size: int, steps: int = 2,
                            seed: int = 0) -> Iterator[np.ndarray]:
    rs = np.random.RandomState(seed)
    for _ in range(steps):
        yield rs.uniform(-1, 1, (batch_size, 28, 28, 1)).astype(np.float32)


def _parse_cyclegan(serialized, image_size, training, tf):
    features = {"image/encoded": tf.io.FixedLenFeature([], tf.string)}
    parsed = tf.io.parse_single_example(serialized, features)
    image = tf.image.decode_jpeg(parsed["image/encoded"], channels=3)
    if training:
        image = tf.image.random_flip_left_right(image)
        resize = int(image_size * 286 / 256)  # 286 at 256 (`train.py:89-92`)
        image = tf.image.resize(image, [resize, resize])
        image = tf.image.random_crop(image, [image_size, image_size, 3])
    else:
        image = tf.image.resize(image, [image_size, image_size])
    image = tf.cast(image, tf.float32) / 127.5 - 1.0
    image.set_shape([image_size, image_size, 3])
    return image


def build_two_domain_dataset(tfrecord_a: str, tfrecord_b: str, *,
                             batch_size: int, image_size: int = 256,
                             training: bool = True, shuffle_buffer: int = 10000,
                             seed: int = 0):
    """Zipped (image_a, image_b) dataset (`CycleGAN/tensorflow/train.py:114-117`)."""
    tf = _tf()
    AUTOTUNE = tf.data.AUTOTUNE

    def one(path):
        ds = tf.data.TFRecordDataset(path)
        return ds.map(lambda s: _parse_cyclegan(s, image_size, training, tf),
                      num_parallel_calls=AUTOTUNE)

    ds = tf.data.Dataset.zip((one(tfrecord_a), one(tfrecord_b)))
    if training:
        ds = ds.shuffle(shuffle_buffer, seed=seed)
    ds = ds.batch(batch_size, drop_remainder=True)
    return ds.prefetch(AUTOTUNE)


def synthetic_two_domain_batches(batch_size: int, image_size: int = 64,
                                 steps: int = 2, seed: int = 0
                                 ) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """The reference's commented-out local-testing harness, made real
    (`CycleGAN/tensorflow/train.py:338-342`)."""
    rs = np.random.RandomState(seed)
    for _ in range(steps):
        a = rs.uniform(-1, 1, (batch_size, image_size, image_size, 3))
        b = rs.uniform(-1, 1, (batch_size, image_size, image_size, 3))
        yield a.astype(np.float32), b.astype(np.float32)
