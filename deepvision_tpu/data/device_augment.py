"""Device-side batched augmentation: uint8 staging + jitted transforms.

The host pipelines' per-image PIL/numpy transforms (data/transforms.py) run on
host threads and ship float32 batches — 4x the host->device bytes of the raw
pixels, and host CPU that cannot keep a chip fed (the trainer logs
`prefetch_queue_depth` precisely because input starvation is the observed
stall mode). This module is the tf.data/DALI counterpart for the jit world:
the host only decodes and resizes to a slightly padded square
(`config.decode_image_size`, the reference's Rescale(256)->crop(224)
headroom), ships compact **uint8 NHWC**, and every dense per-pixel op —
RandomCrop, RandomHorizontalFlip, ColorJitter, mean/std normalize — runs
batched on the accelerator as part of the jitted train step (one fused XLA
program; math in f32, output in the step's compute dtype).

RNG contract: the train step drives the returned `device_train_augment` with
a key folded from `TrainState.step` exactly like mixup
(`core/steps.make_classification_train_step`), so runs stay seed-reproducible
per (seed, step) regardless of host thread scheduling — something the host
pipelines can only approximate with per-image spawned generators.

Host/device split (docs/INPUT_PIPELINE.md):

  host   decode JPEG -> resize to (D, D) uint8        D = decode_image_size(S)
  device train: random DxD->SxS crop (per-example `dynamic_slice` offsets)
               + per-example flip + per-example ColorJitter factors
               + (x/255 - mean)/std -> compute dtype
         eval:  center DxD->SxS crop + normalize (deterministic, no rng)

The eval stage composes EXACTLY with the host `eval_transform` path: a
centered S-crop of a centered D-crop equals the direct centered S-crop, so
`make_eval_augment` output matches the host pipeline bit-for-bit up to f32
rounding (pinned by tests/test_device_augment.py).
"""

from __future__ import annotations

import functools
from typing import Callable, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..core.config import IMAGENET_MEAN, IMAGENET_STD, decode_image_size

__all__ = ["decode_image_size", "make_train_augment", "make_eval_augment",
           "make_paired_train_augment", "make_paired_eval_augment",
           "channel_stats", "check_spatial_capability"]

# Families whose steps can fuse device augmentation at all (the Trainer
# hierarchy enforces this: LossWatchedTrainer refuses for detection/pose/
# centernet because their steps never call the augment), and the subset
# whose augment composes with an H-sharded spatial mesh. Segmentation
# qualifies for the spatial mesh because its steps run the paired
# crop/flip BEFORE the H-shard (full-height uint8 in, cropped tensors are
# then constrained/row-sliced); the classification step instead fuses the
# per-example dynamic_slice inside the spatially-constrained forward, where
# the crop would gather across the 'spatial' shards.
DEVICE_AUGMENT_SPATIAL_FAMILIES = frozenset({"segmentation"})


def check_spatial_capability(family: str, spatial_parallel: int) -> None:
    """Per-family device-augment capability check for spatial meshes — the
    one owner of the policy (the Trainer calls this instead of a blanket
    rejection). Raises ValueError naming which families DO support device
    augmentation on the spatial mesh."""
    if spatial_parallel <= 1 or family in DEVICE_AUGMENT_SPATIAL_FAMILIES:
        return
    supported = ", ".join(sorted(DEVICE_AUGMENT_SPATIAL_FAMILIES))
    raise ValueError(
        f"device_augment with spatial_parallel={spatial_parallel} is "
        f"supported for the {supported} family only (its steps augment "
        f"BEFORE the H-shard); the {family!r} family fuses the per-example "
        f"random crop inside the spatially-sharded forward, where the "
        f"dynamic_slice would gather across the 'spatial' shards — use the "
        f"host pipeline for {family!r} on spatial meshes")


def channel_stats(values: Sequence[float], channels: int) -> Tuple[float, ...]:
    """Adapt length-C' normalization stats to a C-channel input: passthrough
    on match, else collapse to the channel mean replicated C times (the
    grayscale MNIST-family configs carry the 3-channel ImageNet stats —
    broadcasting those against a (B,H,W,1) batch would silently widen it to
    3 channels and crash the model with a kernel shape error)."""
    values = tuple(float(v) for v in values)
    if len(values) == channels:
        return values
    return (sum(values) / len(values),) * channels

# matches the host train_transform's ColorJitter(0.2, 0.2, 0.2) defaults
DEFAULT_JITTER: Tuple[float, float, float] = (0.2, 0.2, 0.2)


def _to_unit_f32(images) -> jnp.ndarray:
    """uint8 (or raw [0,255] float) pixels -> f32 [0,255]. Division and
    normalization stay in f32 so uint8 values are exact; the caller drops to
    the compute dtype once, at the end."""
    return images.astype(jnp.float32)


def _normalize(images: jnp.ndarray, mean, std) -> jnp.ndarray:
    """[0,255] f32 -> (x/255 - mean)/std, channel-last (same [0,1]-unit
    statistics as the host Normalize and the steps' input_norm)."""
    mean = jnp.asarray(mean, jnp.float32)
    std = jnp.asarray(std, jnp.float32)
    return (images / 255.0 - mean) / std


def _batched_crop(images: jnp.ndarray, tops: jnp.ndarray, lefts: jnp.ndarray,
                  size: int) -> jnp.ndarray:
    """Per-example (size, size) crops via vmapped dynamic_slice — the gather
    stays fused in the augment program (no host round trip, no padding)."""
    def one(img, top, left):
        return jax.lax.dynamic_slice(
            img, (top, left, 0), (size, size, img.shape[-1]))
    return jax.vmap(one)(images, tops, lefts)


def _factor(key, strength: float, batch: int) -> jnp.ndarray:
    """Per-example jitter factor ~ U[max(0, 1-s), 1+s], shaped to broadcast
    over HWC — the host ColorJitter._factor contract, drawn per image."""
    return jax.random.uniform(
        key, (batch, 1, 1, 1), jnp.float32,
        minval=max(0.0, 1.0 - strength), maxval=1.0 + strength)


def _crop_flip_draws(rng, b: int, h: int, w: int, image_size: int,
                     flip_prob: float):
    """THE per-example geometric randomness of the train augment — one
    (tops, lefts, flip) draw plus the three ColorJitter keys, split in the
    order `make_train_augment` has always used. The paired image/mask
    factory consumes exactly these draws, so a mask's crop offsets and flip
    decisions can never drift from its image's (the determinism contract
    tests/test_device_augment.py pins per (seed, step))."""
    k_crop, k_flip, k_b, k_c, k_s = jax.random.split(rng, 5)
    offs = jax.random.randint(
        k_crop, (2, b), 0, max(h - image_size, w - image_size) + 1)
    tops = jnp.minimum(offs[0], h - image_size)
    lefts = jnp.minimum(offs[1], w - image_size)
    flip = jax.random.bernoulli(k_flip, flip_prob, (b,))
    return tops, lefts, flip, (k_b, k_c, k_s)


def _photometric(imgs: jnp.ndarray, jitter_keys, jitter, b: int
                 ) -> jnp.ndarray:
    """ColorJitter on [0,255] f32: brightness -> contrast -> saturation,
    the host class's application order; factors drawn per example. Applied
    to IMAGES only — masks are label fields, never jittered."""
    brightness, contrast, saturation = jitter
    k_b, k_c, k_s = jitter_keys
    if brightness:
        imgs = imgs * _factor(k_b, brightness, b)
    if contrast:
        m = imgs.mean(axis=(1, 2), keepdims=True)
        imgs = (imgs - m) * _factor(k_c, contrast, b) + m
    if saturation:
        gray = imgs.mean(axis=3, keepdims=True)
        imgs = (imgs - gray) * _factor(k_s, saturation, b) + gray
    return jnp.clip(imgs, 0.0, 255.0)


def make_train_augment(
    image_size: int,
    *,
    mean: Sequence[float] = IMAGENET_MEAN,
    std: Sequence[float] = IMAGENET_STD,
    jitter: Tuple[float, float, float] = DEFAULT_JITTER,
    flip_prob: float = 0.5,
    compute_dtype: jnp.dtype = jnp.bfloat16,
) -> Callable:
    """Build `device_train_augment(images_u8, rng) -> images` for the train
    step: per-example RandomCrop to `image_size`, RandomHorizontalFlip,
    ColorJitter (brightness/contrast/saturation on [0,255], matching the host
    ColorJitter order and factor ranges), then (x/255 - mean)/std in f32 and
    a single cast to `compute_dtype`.

    `images_u8` is (B, D, D, C) uint8 with D >= image_size (the host's
    decode-only output, `config.decode_image_size`); D == image_size
    degenerates to the identity crop. Pure jnp — trace it inside the train
    step's jit (one fused program) or `jax.jit` it standalone (bench/tests).
    """
    brightness, contrast, saturation = jitter

    def device_train_augment(images, rng):
        b, h, w = images.shape[0], images.shape[1], images.shape[2]
        tops, lefts, flip, jkeys = _crop_flip_draws(rng, b, h, w, image_size,
                                                    flip_prob)
        imgs = _to_unit_f32(images)
        # RandomCrop: uniform per-example offsets in [0, D - S]
        imgs = _batched_crop(imgs, tops, lefts, image_size)
        # RandomHorizontalFlip, per example
        imgs = jnp.where(flip[:, None, None, None], imgs[:, :, ::-1, :], imgs)
        imgs = _photometric(imgs, jkeys, (brightness, contrast, saturation),
                            b)
        return _normalize(imgs, mean, std).astype(compute_dtype)

    return device_train_augment


def make_paired_train_augment(
    image_size: int,
    *,
    mean: Sequence[float] = IMAGENET_MEAN,
    std: Sequence[float] = IMAGENET_STD,
    jitter: Tuple[float, float, float] = DEFAULT_JITTER,
    flip_prob: float = 0.5,
    compute_dtype: jnp.dtype = jnp.bfloat16,
) -> Callable:
    """Build `paired_train_augment(images_u8, masks_u8, rng) -> (images,
    masks)` for DENSE-prediction train steps (segmentation): the image takes
    the full `make_train_augment` stack, and the mask takes EXACTLY the same
    per-example crop offsets and flip decisions — both consumed from the one
    `_crop_flip_draws` call, so the pairing is correct by construction, not
    by parallel bookkeeping.

    Masks are label fields: the crop is the same `dynamic_slice` (nearest-
    neighbor by definition — no interpolation can invent class ids), the
    flip the same axis reversal, and NO jitter or normalize is applied.
    `masks_u8` is (B, D, D) uint8 (or any int dtype); returned masks are
    (B, S, S) int32.
    """
    brightness, contrast, saturation = jitter

    def paired_train_augment(images, masks, rng):
        b, h, w = images.shape[0], images.shape[1], images.shape[2]
        tops, lefts, flip, jkeys = _crop_flip_draws(rng, b, h, w, image_size,
                                                    flip_prob)
        imgs = _to_unit_f32(images)
        imgs = _batched_crop(imgs, tops, lefts, image_size)
        imgs = jnp.where(flip[:, None, None, None], imgs[:, :, ::-1, :], imgs)
        imgs = _photometric(imgs, jkeys, (brightness, contrast, saturation),
                            b)
        m = masks.astype(jnp.int32)[..., None]
        m = _batched_crop(m, tops, lefts, image_size)[..., 0]
        m = jnp.where(flip[:, None, None], m[:, :, ::-1], m)
        return _normalize(imgs, mean, std).astype(compute_dtype), m

    return paired_train_augment


def make_paired_eval_augment(
    image_size: int,
    *,
    mean: Sequence[float] = IMAGENET_MEAN,
    std: Sequence[float] = IMAGENET_STD,
    compute_dtype: jnp.dtype = jnp.bfloat16,
) -> Callable:
    """Build `paired_eval_augment(images_u8, masks_u8) -> (images, masks)`:
    the same deterministic centered crop on BOTH tensors + normalize on the
    image only. Degenerate case (D == image_size) is the identity crop —
    the image half then equals plain on-device normalization and the mask
    passes through untouched (the eval-parity anchor pinned in tests)."""

    def paired_eval_augment(images, masks):
        h, w = images.shape[1], images.shape[2]
        top = (h - image_size) // 2
        left = (w - image_size) // 2
        imgs = _to_unit_f32(
            images[:, top:top + image_size, left:left + image_size, :])
        m = masks.astype(jnp.int32)[:, top:top + image_size,
                                    left:left + image_size]
        return _normalize(imgs, mean, std).astype(compute_dtype), m

    return paired_eval_augment


def make_eval_augment(
    image_size: int,
    *,
    mean: Sequence[float] = IMAGENET_MEAN,
    std: Sequence[float] = IMAGENET_STD,
    compute_dtype: jnp.dtype = jnp.bfloat16,
) -> Callable:
    """Build `device_eval_augment(images_u8) -> images`: deterministic center
    crop to `image_size` + normalize, the device half of the host
    `eval_transform` path (no rng — eval stays bit-stable across runs)."""

    def device_eval_augment(images):
        h, w = images.shape[1], images.shape[2]
        top = (h - image_size) // 2
        left = (w - image_size) // 2
        imgs = _to_unit_f32(
            images[:, top:top + image_size, left:left + image_size, :])
        return _normalize(imgs, mean, std).astype(compute_dtype)

    return device_eval_augment


@functools.lru_cache(maxsize=None)
def _jitted(factory_args) -> Callable:
    kind, image_size, mean, std, jitter, flip_prob, dtype = factory_args
    if kind == "train":
        fn = make_train_augment(image_size, mean=mean, std=std, jitter=jitter,
                                flip_prob=flip_prob,
                                compute_dtype=jnp.dtype(dtype))
    else:
        fn = make_eval_augment(image_size, mean=mean, std=std,
                               compute_dtype=jnp.dtype(dtype))
    return jax.jit(fn)


def device_train_augment(images, rng, *, image_size: int,
                         mean: Sequence[float] = IMAGENET_MEAN,
                         std: Sequence[float] = IMAGENET_STD,
                         jitter: Tuple[float, float, float] = DEFAULT_JITTER,
                         flip_prob: float = 0.5,
                         compute_dtype=jnp.bfloat16):
    """One-shot jitted convenience wrapper (bench/tools); the Trainer traces
    the factory's closure inside its own step jit instead. Cached per
    config so repeated calls don't re-jit (JIT001)."""
    fn = _jitted(("train", image_size, tuple(mean), tuple(std), tuple(jitter),
                  flip_prob, jnp.dtype(compute_dtype).name))
    return fn(images, rng)


def device_eval_augment(images, *, image_size: int,
                        mean: Sequence[float] = IMAGENET_MEAN,
                        std: Sequence[float] = IMAGENET_STD,
                        compute_dtype=jnp.bfloat16):
    """One-shot jitted convenience wrapper for the eval stage."""
    fn = _jitted(("eval", image_size, tuple(mean), tuple(std), None, 0.0,
                  jnp.dtype(compute_dtype).name))
    return fn(images)
