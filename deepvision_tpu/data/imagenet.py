"""ImageNet input pipeline — tf.data TFRecords feeding the TPU from the host.

Parity targets: the TFRecord feature map of the reference's trainer
(`ResNet/tensorflow/train.py:150-160`, the TF-official ImageNet TFRecord schema
produced by `Datasets/ILSVRC2012/build_imagenet_tfrecord.py`) and the role of the
"ResNet preprocessing" (`ResNet/tensorflow/data_load.py:158-193`: aspect-preserving
resize → crop → flip → normalize). The implementation is fresh tf.image code, with the
decode-and-crop fusion (`decode_and_crop_jpeg`) and per-host sharding
(`shard(process_count, process_index)`) the TPU pod pipeline needs — the equivalent of
`experimental_distribute_dataset` splitting the global batch
(`YOLO/tensorflow/train.py:291-294`).

Outputs float32 NHWC in [0,1] normalized by ImageNet mean/std, labels int32 in [0,1000).
"""

from __future__ import annotations

import functools
from typing import Iterator, Optional, Tuple

import numpy as np

from ..core.config import IMAGENET_MEAN, IMAGENET_STD
from .util import to_uint8_pixels

MEAN_RGB = np.array(IMAGENET_MEAN, np.float32)   # torchvision-convention
STDDEV_RGB = np.array(IMAGENET_STD, np.float32)

CROP_FRACTION = 0.875  # eval: 224/256 central crop


def _tf():
    import tensorflow as tf
    tf.config.set_visible_devices([], "GPU")  # host-side only
    try:
        tf.config.set_visible_devices([], "TPU")
    except Exception:
        pass
    return tf


def parse_example(serialized, tf):
    """TF-official ImageNet TFRecord schema: image/encoded + image/class/label
    (1-indexed, so subtract 1)."""
    features = {
        "image/encoded": tf.io.FixedLenFeature([], tf.string),
        "image/class/label": tf.io.FixedLenFeature([], tf.int64, default_value=-1),
    }
    parsed = tf.io.parse_single_example(serialized, features)
    return parsed["image/encoded"], tf.cast(parsed["image/class/label"] - 1, tf.int32)


def distorted_crop(encoded, image_size, tf):
    """Inception-style sample_distorted_bounding_box crop fused with JPEG decode —
    the modern recipe (needed for the 75.3% bar; the reference used resize+random
    crop). Falls back to a central crop when no box is found."""
    shape = tf.io.extract_jpeg_shape(encoded)
    bbox = tf.zeros([1, 1, 4], tf.float32)  # whole image
    begin, size, _ = tf.image.sample_distorted_bounding_box(
        shape, bounding_boxes=bbox, min_object_covered=0.1,
        aspect_ratio_range=(3 / 4, 4 / 3), area_range=(0.08, 1.0),
        max_attempts=10, use_image_if_no_bounding_boxes=True)
    offset_y, offset_x, _ = tf.unstack(begin)
    target_h, target_w, _ = tf.unstack(size)
    image = tf.image.decode_and_crop_jpeg(
        encoded, tf.stack([offset_y, offset_x, target_h, target_w]), channels=3)
    image = tf.image.resize(image, [image_size, image_size],
                            method=tf.image.ResizeMethod.BICUBIC)
    return image


def central_crop(encoded, image_size, tf, crop_fraction=CROP_FRACTION):
    """Aspect-preserving resize so the crop is `image_size` at
    `crop_fraction`, then central crop — the reference's eval path semantics
    (`ResNet/tensorflow/data_load.py:123-158`). `crop_fraction=1.0` resizes
    the short side to exactly `image_size` (the host_decode_only stage: the
    device's later centered crop then supplies the usual fraction)."""
    shape = tf.io.extract_jpeg_shape(encoded)
    h, w = shape[0], shape[1]
    padded = tf.cast(tf.round(image_size / crop_fraction), tf.int32)
    scale = tf.cast(padded, tf.float32) / tf.cast(tf.minimum(h, w), tf.float32)
    new_h = tf.cast(tf.round(tf.cast(h, tf.float32) * scale), tf.int32)
    new_w = tf.cast(tf.round(tf.cast(w, tf.float32) * scale), tf.int32)
    offset_y = (new_h - image_size) // 2
    offset_x = (new_w - image_size) // 2
    image = tf.image.decode_jpeg(encoded, channels=3)
    image = tf.image.resize(image, [new_h, new_w],
                            method=tf.image.ResizeMethod.BICUBIC)
    return tf.slice(image, [offset_y, offset_x, 0], [image_size, image_size, 3])


def preprocess(encoded, label, image_size, training, tf, normalize_on_host=True,
               mean=None, std=None, host_decode_only=False):
    if host_decode_only:
        # the `--device-augment` staging contract (docs/INPUT_PIPELINE.md):
        # decode + resize to the padded square, emit uint8 — crop/flip/
        # jitter/normalize run batched inside the jitted step
        # (data/device_augment.py). `image_size` here is already the padded
        # decode size (build_dataset resolves it). Train resizes exactly
        # (static staged shapes); eval center-crops at fraction 1.0 so the
        # device's nested centered crop equals the plain eval path.
        if training:
            image = tf.image.resize(
                tf.image.decode_jpeg(encoded, channels=3),
                [image_size, image_size],
                method=tf.image.ResizeMethod.BICUBIC)
        else:
            image = central_crop(encoded, image_size, tf, crop_fraction=1.0)
        image = to_uint8_pixels(image, tf)
        image.set_shape([image_size, image_size, 3])
        return image, label
    if training:
        image = distorted_crop(encoded, image_size, tf)
        image = tf.image.random_flip_left_right(image)
    else:
        image = central_crop(encoded, image_size, tf)
    # bicubic resize overshoots outside [0,255] on high-contrast edges; clip
    # in BOTH normalization modes so the uint8 path (which must clip to fit
    # the dtype) and the float path stay equivalent up to quantization
    image = tf.clip_by_value(image, 0.0, 255.0)
    if normalize_on_host:
        image = tf.cast(image, tf.float32) / 255.0
        image = (image - (MEAN_RGB if mean is None else np.asarray(mean, np.float32))) \
            / (STDDEV_RGB if std is None else np.asarray(std, np.float32))
    else:
        # raw uint8 pixels: the device normalizes ((x/255 - mean)/std inside
        # the jitted step) — host->device transfer drops to 1/4 the bytes,
        # the lever that matters when a pod is input-bound (SURVEY.md §7.2.1)
        image = to_uint8_pixels(image, tf)
    image.set_shape([image_size, image_size, 3])
    return image, label


def build_dataset(file_pattern: str, *, batch_size: int, image_size: int = 224,
                  training: bool = True, shuffle_buffer: int = 10000,
                  num_process: int = 1, process_index: int = 0,
                  num_parallel_calls: Optional[int] = None, cache: bool = False,
                  seed: int = 0, normalize_on_host: bool = True,
                  mean=None, std=None, host_decode_only: bool = False):
    """Per-host tf.data pipeline over sharded TFRecords.

    `batch_size` here is the PER-HOST batch (global / process_count); the caller
    shards it over local devices via the mesh.

    `normalize_on_host=False` emits uint8 pixels (mean/std applied on device by
    the train/eval step's `input_norm`) — 4x less host->device traffic.
    `mean`/`std` override the ImageNet channel statistics (pass
    `DataConfig.mean/std` so both normalization modes see the same values).

    `host_decode_only=True` (the `--device-augment` contract) goes further:
    decode + resize to `config.decode_image_size(image_size)` only, uint8
    NHWC out, with ALL augmentation fused into the jitted step
    (data/device_augment.py). Overrides the normalize flags — there is
    nothing left on the host to normalize.
    """
    tf = _tf()
    if host_decode_only:
        from ..core.config import decode_image_size
        image_size = decode_image_size(image_size)
    AUTOTUNE = tf.data.AUTOTUNE
    files = tf.data.Dataset.list_files(file_pattern, shuffle=training, seed=seed)
    if num_process > 1:
        files = files.shard(num_process, process_index)
    ds = files.interleave(
        lambda f: tf.data.TFRecordDataset(f, buffer_size=16 * 1024 * 1024),
        cycle_length=16, block_length=16, num_parallel_calls=AUTOTUNE,
        deterministic=not training)
    if cache:
        ds = ds.cache()
    if training:
        ds = ds.shuffle(shuffle_buffer, seed=seed).repeat()
    ds = ds.map(lambda s: preprocess(*parse_example(s, tf), image_size, training,
                                     tf, normalize_on_host=normalize_on_host,
                                     mean=mean, std=std,
                                     host_decode_only=host_decode_only),
                num_parallel_calls=num_parallel_calls or AUTOTUNE,
                deterministic=not training)
    ds = ds.batch(batch_size, drop_remainder=True)
    ds = ds.prefetch(AUTOTUNE)
    return ds


def epoch_iterator(ds, steps: Optional[int] = None) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Numpy batches for the Trainer; bounded to `steps` for repeated datasets."""
    it = ds.as_numpy_iterator()
    for i, batch in enumerate(it):
        if steps is not None and i >= steps:
            break
        yield batch
