"""Class-name tables for display and evaluation output.

The reference ships these as metadata files (`Datasets/MSCOCO/
mscoco_2017_names.txt`, `Datasets/VOC200*/voc_*_names.txt`); here they are
importable constants (the VOC list also drives the converter's label ids,
`Datasets/voc.py`). Index == class id as written by the converters.
"""

VOC_CLASS_NAMES = [
    "aeroplane", "bicycle", "bird", "boat", "bottle", "bus", "car", "cat",
    "chair", "cow", "diningtable", "dog", "horse", "motorbike", "person",
    "pottedplant", "sheep", "sofa", "train", "tvmonitor",
]

# MSCOCO 2017, the 80 detection categories in annotation-id order
COCO_CLASS_NAMES = [
    "person", "bicycle", "car", "motorcycle", "airplane", "bus", "train",
    "truck", "boat", "traffic light", "fire hydrant", "stop sign",
    "parking meter", "bench", "bird", "cat", "dog", "horse", "sheep", "cow",
    "elephant", "bear", "zebra", "giraffe", "backpack", "umbrella", "handbag",
    "tie", "suitcase", "frisbee", "skis", "snowboard", "sports ball", "kite",
    "baseball bat", "baseball glove", "skateboard", "surfboard",
    "tennis racket", "bottle", "wine glass", "cup", "fork", "knife", "spoon",
    "bowl", "banana", "apple", "sandwich", "orange", "broccoli", "carrot",
    "hot dog", "pizza", "donut", "cake", "chair", "couch", "potted plant",
    "bed", "dining table", "toilet", "tv", "laptop", "mouse", "remote",
    "keyboard", "cell phone", "microwave", "oven", "toaster", "sink",
    "refrigerator", "book", "clock", "vase", "scissors", "teddy bear",
    "hair drier", "toothbrush",
]


def names_for(dataset_num_classes: int):
    """Best-effort table by class count (80 → COCO, 20 → VOC, else ids)."""
    if dataset_num_classes == len(COCO_CLASS_NAMES):
        return COCO_CLASS_NAMES
    if dataset_num_classes == len(VOC_CLASS_NAMES):
        return VOC_CLASS_NAMES
    return [str(i) for i in range(dataset_num_classes)]
