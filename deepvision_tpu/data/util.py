"""Shared helpers for the tf.data pipelines."""

from __future__ import annotations


def to_uint8_pixels(image, tf):
    """Emit raw uint8 pixels for device-side normalization
    (`--device-normalize`): clip to [0,255] (bicubic resize can overshoot),
    round, cast. The one definition all pipelines share, so the
    round/clip contract with the jitted step's `input_norm`
    (`core/steps._normalize_input`) cannot silently diverge per family."""
    return tf.cast(tf.round(tf.clip_by_value(image, 0.0, 255.0)), tf.uint8)
