"""Detection input pipeline: VOC/COCO TFRecords → padded ground-truth batches.

Parity targets: the TFRecord feature schema written by the reference's converters
(`Datasets/VOC2007/tfrecords.py:70-93`, `Datasets/MSCOCO/tfrecords.py:37-101`) and
read by `YOLO/tensorflow/preprocess.py:271-285`; the augmentations of
`Preprocessor.__call__` (`preprocess.py:13-35`): 50% horizontal flip with bbox
mirroring (`:37-50`), 50% bbox-preserving random crop (`:52-119`), resize to the
output shape, and `/127.5 - 1` normalization.

TPU-first split of responsibilities: the host does decode/augment/resize and pads
ground truth to a STATIC `MAX_BOXES`; the per-scale dense label encoding the
reference does here with an autograph loop (`preprocess.py:137-224`) happens on
device inside the jitted train step (ops/yolo.py) — static shapes end to end.

Batches are (images (B,H,W,3) f32 in [-1,1], boxes (B,100,4) corner-normalized,
classes (B,100) int32, valid (B,100) f32).
"""

from __future__ import annotations

from typing import Iterator, Tuple

import numpy as np

from ..ops.yolo import MAX_BOXES
from .util import to_uint8_pixels
from .imagenet import _tf


def parse_example(serialized, tf):
    """Reference schema (`YOLO/tensorflow/preprocess.py:271-285`) plus the
    `image/object/difficult` flags our VOC converter adds (absent in older
    records → zeros) for devkit-faithful evaluation."""
    features = {
        "image/encoded": tf.io.FixedLenFeature([], tf.string),
        "image/object/class/label": tf.io.VarLenFeature(tf.int64),
        "image/object/bbox/xmin": tf.io.VarLenFeature(tf.float32),
        "image/object/bbox/ymin": tf.io.VarLenFeature(tf.float32),
        "image/object/bbox/xmax": tf.io.VarLenFeature(tf.float32),
        "image/object/bbox/ymax": tf.io.VarLenFeature(tf.float32),
        "image/object/difficult": tf.io.VarLenFeature(tf.int64),
    }
    parsed = tf.io.parse_single_example(serialized, features)
    classes = tf.cast(tf.sparse.to_dense(parsed["image/object/class/label"]),
                      tf.int32)
    boxes = tf.stack([
        tf.sparse.to_dense(parsed["image/object/bbox/xmin"]),
        tf.sparse.to_dense(parsed["image/object/bbox/ymin"]),
        tf.sparse.to_dense(parsed["image/object/bbox/xmax"]),
        tf.sparse.to_dense(parsed["image/object/bbox/ymax"]),
    ], axis=1)  # (n, 4) normalized corners
    difficult = tf.cast(tf.sparse.to_dense(parsed["image/object/difficult"]),
                        tf.float32)
    # records written without the field parse to an empty list → all-easy
    difficult = tf.cond(tf.shape(difficult)[0] > 0, lambda: difficult,
                        lambda: tf.zeros_like(tf.cast(classes, tf.float32)))
    return parsed["image/encoded"], boxes, classes, difficult


def random_flip(image, boxes, tf):
    """50% horizontal flip, mirroring xmin/xmax (`preprocess.py:37-50`)."""
    def flip():
        xmin, ymin, xmax, ymax = tf.unstack(boxes, axis=-1)
        return (tf.image.flip_left_right(image),
                tf.stack([1.0 - xmax, ymin, 1.0 - xmin, ymax], axis=-1))
    return tf.cond(tf.random.uniform([]) < 0.5, flip, lambda: (image, boxes))


def random_crop_keep_boxes(image, boxes, tf):
    """50% random crop guaranteed to contain every box (`preprocess.py:52-119`):
    crop bounds drawn uniformly between the union of boxes and the image edge,
    then boxes re-normalized to the crop."""
    def crop():
        min_xmin = tf.reduce_min(boxes[:, 0])
        min_ymin = tf.reduce_min(boxes[:, 1])
        max_xmax = tf.reduce_max(boxes[:, 2])
        max_ymax = tf.reduce_max(boxes[:, 3])
        xmin_d = tf.random.uniform([], 0.0, tf.maximum(min_xmin, 1e-6))
        ymin_d = tf.random.uniform([], 0.0, tf.maximum(min_ymin, 1e-6))
        xmax_d = tf.random.uniform([], 0.0, tf.maximum(1.0 - max_xmax, 1e-6))
        ymax_d = tf.random.uniform([], 0.0, tf.maximum(1.0 - max_ymax, 1e-6))

        w_scale = 1.0 - xmin_d - xmax_d
        h_scale = 1.0 - ymin_d - ymax_d
        xmin, ymin, xmax, ymax = tf.unstack(boxes, axis=-1)
        new_boxes = tf.stack([(xmin - xmin_d) / w_scale,
                              (ymin - ymin_d) / h_scale,
                              (xmax - xmin_d) / w_scale,
                              (ymax - ymin_d) / h_scale], axis=-1)

        h = tf.cast(tf.shape(image)[0], tf.float32)
        w = tf.cast(tf.shape(image)[1], tf.float32)
        off_h = tf.cast(ymin_d * h, tf.int32)
        off_w = tf.cast(xmin_d * w, tf.int32)
        tgt_h = tf.cast(tf.math.ceil(h_scale * h), tf.int32)
        tgt_w = tf.cast(tf.math.ceil(w_scale * w), tf.int32)
        tgt_h = tf.minimum(tgt_h, tf.shape(image)[0] - off_h)
        tgt_w = tf.minimum(tgt_w, tf.shape(image)[1] - off_w)
        return image[off_h:off_h + tgt_h, off_w:off_w + tgt_w, :], new_boxes

    has_boxes = tf.shape(boxes)[0] > 0
    do_crop = tf.logical_and(tf.random.uniform([]) < 0.5, has_boxes)
    return tf.cond(do_crop, crop, lambda: (image, boxes))


def preprocess(serialized, image_size: int, training: bool, tf,
               with_difficult: bool = False, normalize_on_host: bool = True):
    encoded, boxes, classes, difficult = parse_example(serialized, tf)
    image = tf.cast(tf.io.decode_jpeg(encoded, channels=3), tf.float32)
    if training:
        image, boxes = random_flip(image, boxes, tf)
        image, boxes = random_crop_keep_boxes(image, boxes, tf)
    image = tf.image.resize(image, [image_size, image_size])
    if normalize_on_host:
        image = image / 127.5 - 1.0  # `preprocess.py:25`
    else:
        # raw uint8: the step normalizes on device (UNIT_RANGE_NORM) —
        # 4x less host->device traffic (`--device-normalize`)
        image = to_uint8_pixels(image, tf)

    n = tf.minimum(tf.shape(boxes)[0], MAX_BOXES)
    boxes = tf.pad(boxes[:n], [[0, MAX_BOXES - n], [0, 0]])
    classes = tf.pad(classes[:n], [[0, MAX_BOXES - n]])
    valid = tf.pad(tf.ones([n], tf.float32), [[0, MAX_BOXES - n]])
    image.set_shape([image_size, image_size, 3])
    boxes.set_shape([MAX_BOXES, 4])
    classes.set_shape([MAX_BOXES])
    valid.set_shape([MAX_BOXES])
    if with_difficult:
        difficult = tf.pad(difficult[:n], [[0, MAX_BOXES - n]])
        difficult.set_shape([MAX_BOXES])
        return image, boxes, classes, valid, difficult
    return image, boxes, classes, valid


def build_dataset(file_pattern: str, *, batch_size: int, image_size: int = 416,
                  training: bool = True, shuffle_buffer: int = 512,
                  num_process: int = 1, process_index: int = 0, seed: int = 0,
                  with_difficult: bool = False, drop_remainder: bool = True,
                  normalize_on_host: bool = True):
    """Per-host tf.data detection pipeline (cf. `create_dataset`,
    `YOLO/tensorflow/train.py:260-273`, plus per-host sharding for pods).

    `drop_remainder` defaults to True (static shapes for the jitted train/val
    steps); mAP evaluation passes False so the val tail isn't silently dropped
    (costs one extra compile for the final ragged batch).
    """
    tf = _tf()
    AUTOTUNE = tf.data.AUTOTUNE
    files = tf.data.Dataset.list_files(file_pattern, shuffle=training, seed=seed)
    if num_process > 1:
        files = files.shard(num_process, process_index)
    ds = tf.data.TFRecordDataset(files, num_parallel_reads=AUTOTUNE)
    if training:
        ds = ds.shuffle(shuffle_buffer, seed=seed)
    ds = ds.map(lambda s: preprocess(s, image_size, training, tf,
                                     with_difficult=with_difficult,
                                     normalize_on_host=normalize_on_host),
                num_parallel_calls=AUTOTUNE)
    ds = ds.batch(batch_size, drop_remainder=drop_remainder)
    return ds.prefetch(AUTOTUNE)


def synthetic_batches(*, batch_size: int, image_size: int = 64,
                      num_classes: int = 4, steps: int = 2, num_boxes: int = 3,
                      seed: int = 0) -> Iterator[Tuple[np.ndarray, ...]]:
    """Random but well-formed detection batches for tests/benchmarks (the
    fake-data idea the reference left commented out,
    `CycleGAN/tensorflow/train.py:338-342`)."""
    rs = np.random.RandomState(seed)
    for _ in range(steps):
        images = rs.rand(batch_size, image_size, image_size, 3).astype(
            np.float32) * 2.0 - 1.0
        xy1 = rs.uniform(0.0, 0.6, (batch_size, MAX_BOXES, 2))
        wh = rs.uniform(0.05, 0.4, (batch_size, MAX_BOXES, 2))
        boxes = np.concatenate([xy1, np.minimum(xy1 + wh, 1.0)],
                               axis=-1).astype(np.float32)
        classes = rs.randint(0, num_classes,
                             (batch_size, MAX_BOXES)).astype(np.int32)
        valid = np.zeros((batch_size, MAX_BOXES), np.float32)
        valid[:, :num_boxes] = 1.0
        yield images, boxes, classes, valid
