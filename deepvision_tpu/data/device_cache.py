"""Device-resident epoch cache for whole-epoch scan training.

The r05 dispatch grid (docs/TUNING.md item 8) showed per-dispatch latency —
not FLOPs — is the remaining training lever off-chip, and
`steps_per_dispatch` only amortizes a handful of steps. For datasets that
fit HBM (synthetic, digits, MNIST, the segmentation scenes) this module
stages the FULL epoch on device ONCE; `core/steps.make_epoch_train_step`
then scans the jitted step over the resident slices — one XLA launch and
zero host round-trips per epoch (`TrainConfig.epoch_on_device`).

Contract: the data must be **epoch-stationary** — the cache stages the
first trained epoch's stream and replays it; per-epoch variety comes from
the device-side shuffle (a permutation folded from (seed, epoch), see
`make_epoch_train_step`) and the per-(seed, step) augment draws, NOT from
the host pipeline re-running. Datasets that re-compose examples each epoch
(digits_detect scenes) lose that recomposition under this mode — the CLI
prints a note where it applies.

Overflow is a fallback, never a crash: `build_epoch_cache` sizes the epoch
against the HBM budget WHILE collecting and, on overflow (or a ragged
stream the scan cannot stack), emits the named `EpochCacheOverflowWarning`
and hands back an iterator replaying the already-pulled batches plus the
rest of the stream — the caller trains that epoch (and the rest of the
run) through the default double-buffered staged path
(`parallel/prefetch.py`) with nothing lost.
"""

from __future__ import annotations

import dataclasses
import os
import time
import warnings
from typing import Iterable, Iterator, Optional, Tuple

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..parallel import mesh as mesh_lib

# Share of the per-device HBM limit the cache may claim when the backend
# reports one (TPU memory_stats); the rest belongs to params/optimizer
# state/activations. Overridable for tests and odd hosts via
# DEEPVISION_EPOCH_CACHE_MAX_BYTES (an absolute TOTAL byte cap).
HBM_BUDGET_FRACTION = 0.5


class EpochCacheOverflowWarning(UserWarning):
    """The epoch does not fit the device cache (HBM budget exceeded, or the
    batch stream is ragged/empty and cannot be stacked for the scan);
    training falls back to the staged per-batch path."""


def epoch_sharding(mesh, ndim: int, dim2: Optional[int] = None
                   ) -> NamedSharding:
    """Sharding for a stacked `(steps, batch, ...)` epoch array: leading
    steps axis replicated (scan slices it), the rest laid out exactly like
    a single staged batch (`mesh_lib.batch_sharding` — batch over 'data',
    H over 'spatial' where it divides). `dim2` is the per-batch H extent
    when known."""
    inner = mesh_lib.batch_sharding(mesh, ndim - 1, dim1=dim2)
    return NamedSharding(mesh, P(*([None] + list(inner.spec))))


def hbm_budget_bytes() -> Optional[int]:
    """Total byte budget for the cache, or None for unlimited.

    DEEPVISION_EPOCH_CACHE_MAX_BYTES wins when set. Otherwise, when the
    backend reports a per-device `bytes_limit` (TPU), the budget is
    HBM_BUDGET_FRACTION of the limit summed over local devices — the cache
    shards its batch axis over 'data', so the total is what competes with
    HBM. CPU backends report no limit: unlimited (host RAM is the real
    ceiling there, and the staged fallback saves nothing of it)."""
    env = os.environ.get("DEEPVISION_EPOCH_CACHE_MAX_BYTES")
    if env:
        return int(float(env))
    try:
        devices = jax.local_devices()
        stats = devices[0].memory_stats() or {}
    except Exception:
        return None
    limit = stats.get("bytes_limit")
    if not limit:
        return None
    return int(HBM_BUDGET_FRACTION * float(limit) * len(devices))


@dataclasses.dataclass
class DeviceEpochCache:
    """One epoch staged device-resident, ready for the epoch scan.

    `arrays` is the batch tuple stacked along a leading steps axis — the
    positional args of `make_epoch_train_step` — under `epoch_sharding`.
    The ledger fields mirror DevicePrefetcher's so the one-time staging
    cost is visible in logs next to the per-batch path's numbers."""
    arrays: Tuple[jax.Array, ...]
    steps: int
    examples_per_step: int
    nbytes: int          # host bytes staged (dtype-honest, like the ledger)
    stage_secs: float    # wall time of the one device_put + barrier

    @property
    def n_batch_args(self) -> int:
        return len(self.arrays)


def _replay_then(collected, rest: Iterator) -> Iterator:
    """The overflow fallback stream: already-pulled batches, then the rest
    of the source — the epoch the caller was about to train, intact."""
    for b in collected:
        yield b
    for b in rest:
        yield b


def build_epoch_cache(mesh, batches: Iterable, *, shuffle: bool = False,
                      max_bytes: Optional[int] = None, name: str = "train"
                      ) -> Tuple[Optional[DeviceEpochCache],
                                 Optional[Iterator]]:
    """Collect one epoch of host batches and stage them device-resident.

    Returns `(cache, None)` on success, or `(None, fallback_iterator)` when
    the epoch cannot be cached — budget overflow, a ragged stream (batches
    whose shapes/dtypes differ step to step cannot be stacked for the
    scan), or an empty stream. Every fallback emits the named
    EpochCacheOverflowWarning so the mode switch is loud, and the returned
    iterator loses no data.

    `shuffle=True` doubles the accounted footprint: the device-side
    permutation gathers a transient shuffled copy of the epoch.
    """
    budget = max_bytes if max_bytes is not None else hbm_budget_bytes()
    factor = 2.0 if shuffle else 1.0
    it = iter(batches)
    collected = []
    nbytes = 0
    spec = None  # ((shape, dtype), ...) of the first batch
    for b in it:
        b = tuple(np.asarray(x) for x in b)
        bspec = tuple((x.shape, x.dtype) for x in b)
        if spec is None:
            spec = bspec
        elif bspec != spec:
            warnings.warn(
                f"[{name}] epoch_on_device: batch {len(collected)} has "
                f"shape/dtype {bspec} != first batch {spec} — a ragged "
                f"stream cannot be stacked for the epoch scan; falling "
                f"back to the staged per-batch path (drop_remainder "
                f"pipelines stack cleanly)", EpochCacheOverflowWarning,
                stacklevel=2)
            return None, _replay_then(collected + [b], it)
        nbytes += sum(x.nbytes for x in b)
        collected.append(b)
        if budget is not None and nbytes * factor > budget:
            warnings.warn(
                f"[{name}] epoch_on_device: epoch exceeds the device cache "
                f"budget ({nbytes * factor / 1e9:.2f} GB accounted "
                f"{'incl. the shuffle copy ' if shuffle else ''}vs "
                f"{budget / 1e9:.2f} GB) after {len(collected)} batches — "
                f"falling back to the double-buffered staged path "
                f"(parallel/prefetch.py)", EpochCacheOverflowWarning,
                stacklevel=2)
            return None, _replay_then(collected, it)
    if not collected:
        warnings.warn(f"[{name}] epoch_on_device: empty epoch stream — "
                      f"nothing to cache", EpochCacheOverflowWarning,
                      stacklevel=2)
        return None, iter(())
    t0 = time.perf_counter()
    stacked = tuple(np.stack([b[j] for b in collected])
                    for j in range(len(collected[0])))

    def _put(a):
        sharding = epoch_sharding(mesh, a.ndim,
                                  dim2=a.shape[2] if a.ndim == 5 else None)
        # per-host batch rows, like shard_batch_pytree: plain device_put on
        # a cross-process sharding would treat the array as a GLOBAL value
        # and allgather-assert equality across hosts
        if jax.process_count() > 1 and not sharding.is_fully_addressable:
            return jax.make_array_from_process_local_data(sharding, a)
        return jax.device_put(a, sharding)

    arrays = tuple(_put(a) for a in stacked)
    for a in arrays:
        jax.block_until_ready(a)
    stage_secs = time.perf_counter() - t0
    return DeviceEpochCache(arrays=arrays, steps=len(collected),
                            examples_per_step=int(collected[0][0].shape[0]),
                            nbytes=nbytes, stage_secs=stage_secs), None
