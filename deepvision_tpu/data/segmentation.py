"""Segmentation datasets: synthetic shapes-and-masks + real digit scenes.

The reference zoo has no dense-prediction workload (PAPER.md §0 covers
classification/detection/pose/GANs), so this module supplies the two data
recipes the segmentation family (core/segment.py) trains on, mirroring the
conventions of the neighboring pipelines:

- `SyntheticSegmentation` — the `SyntheticClassification` analog: deterministic
  in-memory (image, mask) batches with a fixed learnable signal (each class has
  a distinct mean color, so per-pixel classification is actually fittable —
  loss-goes-down and mIoU-goes-up tests need that). Emits either normalized
  float batches at the model's input size or raw uint8 image+mask pairs at the
  padded decode size (the `--device-augment` staging contract,
  `data/device_augment.py::make_paired_train_augment`).

- digit scenes — the real-data recipe following the YOLO/CenterNet digits
  pattern (`data/digits.py`): real UCI handwriting scans composed onto a
  canvas, with the per-pixel ground truth derived from the pasted digit's own
  intensity (class = digit + 1; background = 0). Real pixels, synthetic
  composition, zero egress; train scenes compose only train-split scans and
  the pinned val set only held-out handwriting, exactly like the detection
  gate.
"""

from __future__ import annotations

from typing import Iterator, Tuple

import numpy as np

from .digits import SPLIT_SEED, scan_splits  # noqa: F401 (shared split seed)

# Intensity threshold separating a pasted digit's foreground pixels from the
# canvas when deriving the mask (scans are [0,1]; strokes sit well above it)
DIGIT_FOREGROUND_THRESH = 0.25


def class_palette(num_classes: int, channels: int = 3) -> np.ndarray:
    """Deterministic (num_classes, channels) float palette in [0.15, 0.85]:
    class 0 (background) is dark, the rest well-separated — the one color
    table both the generator and any visualization tool read."""
    rs = np.random.RandomState(20260804)
    pal = 0.15 + 0.7 * rs.rand(max(num_classes, 1), channels)
    pal[0] = 0.1
    return pal.astype(np.float32)


class SyntheticSegmentation:
    """Deterministic fake (image, mask) batches with a learnable signal.

    Each scene starts as background (class 0) and pastes 1-3 axis-aligned
    rectangles of random foreground classes; pixels take the class's palette
    color plus Gaussian noise, and the mask carries the class id — so a
    pixel's color predicts its class and even a 1x1-conv head can fit it.

    `emit_uint8=True` yields raw uint8 pixel images AND uint8 masks at the
    constructor's `image_size` (pass the PADDED `config.decode_image_size`);
    the paired jitted augment crops both back down to the model's input.
    Default mode yields float32 images normalized to [-1, 1] (the detection
    pipelines' convention) and int32 masks at `image_size`.
    """

    def __init__(self, batch_size: int, image_size: int = 64,
                 channels: int = 3, num_classes: int = 6,
                 num_batches: int = 8, seed: int = 0,
                 emit_uint8: bool = False):
        if num_classes < 2:
            raise ValueError(f"segmentation needs >= 2 classes (background "
                             f"+ 1), got {num_classes}")
        self.batch_size = batch_size
        self.image_size = image_size
        self.channels = channels
        self.num_classes = num_classes
        self.num_batches = num_batches
        self.seed = seed
        self.emit_uint8 = emit_uint8
        self._palette = class_palette(num_classes, channels)

    def _scene(self, rs: np.random.RandomState
               ) -> Tuple[np.ndarray, np.ndarray]:
        s = self.image_size
        mask = np.zeros((s, s), np.int32)
        image = np.broadcast_to(self._palette[0], (s, s, self.channels)).copy()
        for _ in range(rs.randint(1, 4)):
            c = rs.randint(1, self.num_classes)
            h = rs.randint(s // 4, s // 2 + 1)
            w = rs.randint(s // 4, s // 2 + 1)
            y0 = rs.randint(0, s - h + 1)
            x0 = rs.randint(0, s - w + 1)
            mask[y0:y0 + h, x0:x0 + w] = c
            image[y0:y0 + h, x0:x0 + w] = self._palette[c]
        image = image + rs.randn(s, s, self.channels).astype(np.float32) * 0.05
        return np.clip(image, 0.0, 1.0), mask

    def __iter__(self) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        rs = np.random.RandomState(self.seed)
        for _ in range(self.num_batches):
            images = np.empty((self.batch_size, self.image_size,
                               self.image_size, self.channels), np.float32)
            masks = np.empty((self.batch_size, self.image_size,
                              self.image_size), np.int32)
            for i in range(self.batch_size):
                images[i], masks[i] = self._scene(rs)
            if self.emit_uint8:
                yield (np.round(images * 255.0).astype(np.uint8),
                       masks.astype(np.uint8))
            else:
                # [0,1] -> [-1,1], the detection/pose pipelines' convention
                # (UNIT_RANGE_NORM); masks stay int32 class ids
                yield images * 2.0 - 1.0, masks

    def __len__(self):
        return self.num_batches


# -- real-pixel segmentation scenes (the digits recipe) ------------------------

def segmentation_scenes(images: np.ndarray, labels: np.ndarray, *,
                        n_scenes: int, canvas: int = 64, digit_px: int = 16,
                        seed: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    """Compose raw scans (N, 8, 8) in [0,1] + labels into (scenes, masks).

    Same quadrant placement as `digits.detection_scenes` (1-4 digits, one per
    quadrant, jittered — regions can touch but never overlap), but the ground
    truth is DENSE: mask = digit_class + 1 on the pasted digit's foreground
    pixels (its own intensity above DIGIT_FOREGROUND_THRESH), 0 elsewhere.
    Scenes are float32 [-1, 1] NHWC; masks int32 (S, canvas, canvas) with
    num_classes = 11 (background + 10 digits).
    """
    if digit_px % 8 != 0:
        raise ValueError(f"digit_px={digit_px} must be a multiple of the 8px "
                         f"scan size (pixel-replication upsample)")
    rs = np.random.RandomState(seed)
    q = canvas // 2
    jitter = q - digit_px
    scale = digit_px // 8
    scenes = np.zeros((n_scenes, canvas, canvas, 3), np.float32)
    masks = np.zeros((n_scenes, canvas, canvas), np.int32)
    for s in range(n_scenes):
        n_digits = rs.randint(1, 5)
        quads = rs.permutation(4)[:n_digits]
        for quad in quads:
            i = rs.randint(len(images))
            digit = images[i].repeat(scale, axis=0).repeat(scale, axis=1)
            qy, qx = divmod(int(quad), 2)
            y0 = qy * q + rs.randint(0, jitter + 1)
            x0 = qx * q + rs.randint(0, jitter + 1)
            scenes[s, y0:y0 + digit_px, x0:x0 + digit_px, :] = digit[..., None]
            fg = digit > DIGIT_FOREGROUND_THRESH
            masks[s, y0:y0 + digit_px, x0:x0 + digit_px][fg] = labels[i] + 1
    return scenes * 2.0 - 1.0, masks


def segmentation_val_scenes(*, canvas: int, n_scenes: int
                            ) -> Tuple[np.ndarray, np.ndarray]:
    """THE pinned validation scene set (seed 2, held-out scans only) — the
    segmentation analog of `digits.detection_val_scenes`: val measures
    generalization to unseen handwriting, not re-segmentation of seen
    crops."""
    _, (va_x, va_y) = scan_splits()
    return segmentation_scenes(va_x, va_y, n_scenes=n_scenes, canvas=canvas,
                               seed=2)


def segmentation_batches(split: Tuple[np.ndarray, np.ndarray], *,
                         batch_size: int, shuffle_seed: int = None
                         ) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Iterate a (scenes, masks) split in drop-remainder batches (the dense
    trainers' fixed-shape contract, like `digits.detection_batches`)."""
    scenes, masks = split
    idx = np.arange(len(scenes))
    if shuffle_seed is not None:
        np.random.RandomState(shuffle_seed).shuffle(idx)
    for lo in range(0, len(idx) - batch_size + 1, batch_size):
        sel = idx[lo:lo + batch_size]
        yield scenes[sel], masks[sel]
