"""YOLO V3 — Darknet-53 backbone + 3-scale FPN detection head, in Flax.

Parity target: `YOLO/tensorflow/yolov3.py:23-235` (DarknetConv / DarknetResidual /
Darknet / YoloV3 functional builders). Same topology: conv-BN-LeakyReLU(0.1) blocks,
residual stages (1,2,8,8,4), detection towers of alternating 1x1/3x3 convs, nearest
×2 upsample + concat for the medium/small scales, final 1x1 conv to
3·(5+num_classes) channels reshaped to (N, g, g, 3, 5+C).

TPU-first choices: NHWC bf16 compute with f32 BatchNorm/params (MXU-friendly), sync
global-batch BN under GSPMD, and `width_mult`/`stage_blocks` knobs so tests compile a
tiny variant in seconds. Train mode returns the 3 raw heads ordered stride 8→16→32
(matching the reference's (y_small, y_medium, y_large) = 52/26/13 grids at 416px);
eval mode additionally decodes absolute boxes like the Lambda layers at
`yolov3.py:224-232`.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..ops.yolo import ANCHORS_WH, decode_boxes
from ..utils.registry import MODELS


class ConvBNLeaky(nn.Module):
    """DarknetConv (`yolov3.py:23-41`): same-padded conv, no bias, BN, LeakyReLU 0.1."""
    features: int
    kernel: int = 3
    strides: int = 1
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(self.features, (self.kernel, self.kernel),
                    strides=(self.strides, self.strides), padding="SAME",
                    use_bias=False, dtype=self.dtype)(x)
        # epsilon matches the reference's Keras BatchNormalization default
        # (1e-3, `yolov3.py:36`) so its h5 weights compute the same function
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-3, dtype=jnp.float32)(x)
        return nn.leaky_relu(x, 0.1).astype(self.dtype)


class DarknetResidual(nn.Module):
    """1x1 squeeze → 3x3 expand + shortcut (`yolov3.py:44-51`)."""
    features1: int
    features2: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        y = ConvBNLeaky(self.features1, 1, dtype=self.dtype)(x, train)
        y = ConvBNLeaky(self.features2, 3, dtype=self.dtype)(y, train)
        return x + y


class Darknet53(nn.Module):
    """Darknet-53 backbone (`yolov3.py:54-92`) → features at strides 8/16/32."""
    stage_blocks: Sequence[int] = (1, 2, 8, 8, 4)
    width_mult: float = 1.0
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False) -> Tuple[jnp.ndarray, ...]:
        w = lambda f: max(1, int(f * self.width_mult))  # noqa: E731
        conv = partial(ConvBNLeaky, dtype=self.dtype)
        x = conv(w(32), 3)(x, train)
        outs = []
        for stage, (blocks, f) in enumerate(
                zip(self.stage_blocks, (64, 128, 256, 512, 1024))):
            x = conv(w(f), 3, strides=2)(x, train)
            for _ in range(blocks):
                x = DarknetResidual(w(f // 2), w(f), dtype=self.dtype)(x, train)
            if stage >= 2:
                outs.append(x)  # strides 8, 16, 32
        return tuple(outs)


class _DetectionTower(nn.Module):
    """5-conv tower + 3x3/1x1 prediction head for one scale
    (`yolov3.py:110-133` and its medium/small copies)."""
    features: int                  # 512 / 256 / 128
    final_filters: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        f = self.features
        conv = partial(ConvBNLeaky, dtype=self.dtype)
        x = conv(f, 1)(x, train)
        x = conv(f * 2, 3)(x, train)
        x = conv(f, 1)(x, train)
        x = conv(f * 2, 3)(x, train)
        x = conv(f, 1)(x, train)
        y = conv(f * 2, 3)(x, train)
        y = nn.Conv(self.final_filters, (1, 1), padding="SAME",
                    dtype=jnp.float32, name="final_conv")(y)
        return x, y  # x feeds the next (finer) scale; y is the raw prediction


def _upsample2x(x):
    """Nearest-neighbor ×2 (`UpSampling2D`, `yolov3.py:151`; darknet upsamples by
    interpolation)."""
    b, h, w, c = x.shape
    return jax.image.resize(x, (b, h * 2, w * 2, c), method="nearest")


class YoloV3(nn.Module):
    """Full detector (`yolov3.py:95-235`).

    Train mode: tuple of 3 raw heads (B, g, g, 3, 5+C), strides (8, 16, 32).
    Eval/inference: tuple of 3 decoded (boxes_xywh, objectness, class_probs)
    triples. `decode` defaults to `not train` (the reference splits this with its
    `training=` constructor flag, `yolov3.py:221-235`); pass `decode=False` with
    `train=False` to get raw heads for validation loss.
    """
    num_classes: int = 80
    width_mult: float = 1.0
    stage_blocks: Sequence[int] = (1, 2, 8, 8, 4)
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False, decode: bool = None):
        if decode is None:
            decode = not train
        w = lambda f: max(1, int(f * self.width_mult))  # noqa: E731
        final_filters = 3 * (5 + self.num_classes)
        x_small, x_medium, x_large = Darknet53(
            self.stage_blocks, self.width_mult, self.dtype,
            name="darknet53")(x, train)

        xl, y_large = _DetectionTower(w(512), final_filters, self.dtype,
                                      name="tower_large")(x_large, train)
        xm = ConvBNLeaky(w(256), 1, dtype=self.dtype, name="lateral_medium")(xl, train)
        xm = jnp.concatenate([_upsample2x(xm), x_medium], axis=-1)
        xm, y_medium = _DetectionTower(w(256), final_filters, self.dtype,
                                       name="tower_medium")(xm, train)
        xs = ConvBNLeaky(w(128), 1, dtype=self.dtype, name="lateral_small")(xm, train)
        xs = jnp.concatenate([_upsample2x(xs), x_small], axis=-1)
        _, y_small = _DetectionTower(w(128), final_filters, self.dtype,
                                     name="tower_small")(xs, train)

        def _reshape(y):
            b, g1, g2, _ = y.shape
            return y.reshape(b, g1, g2, 3, 5 + self.num_classes)

        # output order: finest grid first (stride 8) = reference (small, medium,
        # large object scale), anchors 0-2 / 3-5 / 6-8
        raw = tuple(_reshape(y) for y in (y_small, y_medium, y_large))
        if not decode:
            return raw
        return tuple(
            decode_boxes(y, ANCHORS_WH[3 * i:3 * i + 3], self.num_classes)
            for i, y in enumerate(raw))


MODELS.register("yolov3", YoloV3)
