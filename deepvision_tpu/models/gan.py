"""GAN model zoo: DCGAN (MNIST) and CycleGAN generators/discriminators in Flax.

Parity targets:
- DCGAN (`DCGAN/tensorflow/models.py:8-65`): 28×28 conv discriminator
  (conv64/conv128 stride 2 + LeakyReLU + dropout 0.3 → dense 1 logit) and the
  transposed-conv generator (dense 7·7·256 → CT128 s1 → CT64 s2 → CT1 s2 tanh,
  BN + LeakyReLU between, no biases) with its shape contract asserted.
- CycleGAN (`CycleGAN/tensorflow/models.py:8-104`): 9-ResNet-block generator with
  reflection padding (c7s1-64, d128, d256, R256×9, u128, u64, c7s1-3) and the
  70×70 PatchGAN discriminator (C64-C128-C256-C512 → 1-channel patch logits).

Keras defaults preserved: LeakyReLU α=0.3 for DCGAN, α=0.2 for the PatchGAN;
BatchNorm everywhere the reference has it (the CycleGAN paper uses instance norm —
the reference chose BN, and we match the reference).
"""

from __future__ import annotations

from functools import partial

import flax.linen as nn
import jax.numpy as jnp

from ..utils.registry import MODELS


class DCGANGenerator(nn.Module):
    """`make_generator_model` (`DCGAN/tensorflow/models.py:30-65`): 100-d noise →
    (28, 28, 1) tanh image."""
    noise_dim: int = 100
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, z, train: bool = False):
        bn = partial(nn.BatchNorm, use_running_average=not train, momentum=0.99,
                     epsilon=1e-3, dtype=jnp.float32)
        ct = partial(nn.ConvTranspose, padding="SAME", use_bias=False,
                     dtype=self.dtype)
        x = nn.Dense(7 * 7 * 256, use_bias=False, dtype=self.dtype)(z)
        x = nn.leaky_relu(bn()(x), 0.3).astype(self.dtype)
        x = x.reshape(x.shape[0], 7, 7, 256)
        x = ct(128, (5, 5), strides=(1, 1))(x)
        assert x.shape[1:] == (7, 7, 128), x.shape
        x = nn.leaky_relu(bn()(x), 0.3).astype(self.dtype)
        x = ct(64, (5, 5), strides=(2, 2))(x)
        assert x.shape[1:] == (14, 14, 64), x.shape
        x = nn.leaky_relu(bn()(x), 0.3).astype(self.dtype)
        x = ct(1, (5, 5), strides=(2, 2))(x)
        assert x.shape[1:] == (28, 28, 1), x.shape
        return jnp.tanh(x.astype(jnp.float32))


class DCGANDiscriminator(nn.Module):
    """`make_discriminator_model` (`DCGAN/tensorflow/models.py:8-27`): image →
    single real/fake logit."""
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(nn.Conv, padding="SAME", dtype=self.dtype)
        x = conv(64, (5, 5), strides=(2, 2))(x.astype(self.dtype))
        x = nn.leaky_relu(x, 0.3)
        x = nn.Dropout(0.3, deterministic=not train)(x)
        x = conv(128, (5, 5), strides=(2, 2))(x)
        x = nn.leaky_relu(x, 0.3)
        x = nn.Dropout(0.3, deterministic=not train)(x)
        x = x.reshape(x.shape[0], -1)
        return nn.Dense(1, dtype=jnp.float32)(x)


def _reflect_pad(x, pad: int):
    """`ReflectionPad2d` (`CycleGAN/tensorflow/models.py:8-14`)."""
    return jnp.pad(x, [(0, 0), (pad, pad), (pad, pad), (0, 0)], mode="reflect")


class CycleGANResBlock(nn.Module):
    """Reflect-padded 3x3 residual block (`CycleGAN/tensorflow/models.py:17-38`)."""
    features: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        bn = partial(nn.BatchNorm, use_running_average=not train, momentum=0.99,
                     epsilon=1e-3, dtype=jnp.float32)
        conv = partial(nn.Conv, padding="VALID", use_bias=False, dtype=self.dtype)
        y = _reflect_pad(x, 1)
        y = conv(self.features, (3, 3))(y)
        y = nn.relu(bn()(y)).astype(self.dtype)
        y = _reflect_pad(y, 1)
        y = conv(self.features, (3, 3))(y)
        y = bn()(y).astype(self.dtype)
        return x + y


class CycleGANGenerator(nn.Module):
    """c7s1-64, d128, d256, R256×n, u128, u64, c7s1-3 with reflection pads
    (`CycleGAN/tensorflow/models.py:41-78`)."""
    n_blocks: int = 9
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        bn = partial(nn.BatchNorm, use_running_average=not train, momentum=0.99,
                     epsilon=1e-3, dtype=jnp.float32)
        x = _reflect_pad(x.astype(self.dtype), 3)
        x = nn.Conv(64, (7, 7), padding="VALID", use_bias=False,
                    dtype=self.dtype)(x)
        x = nn.relu(bn()(x)).astype(self.dtype)
        for f in (128, 256):  # encode
            x = nn.Conv(f, (3, 3), strides=(2, 2), padding="SAME",
                        use_bias=False, dtype=self.dtype)(x)
            x = nn.relu(bn()(x)).astype(self.dtype)
        for _ in range(self.n_blocks):  # transform
            x = CycleGANResBlock(256, self.dtype)(x, train)
        for f in (128, 64):  # decode
            x = nn.ConvTranspose(f, (3, 3), strides=(2, 2), padding="SAME",
                                 use_bias=False, dtype=self.dtype)(x)
            x = nn.relu(bn()(x)).astype(self.dtype)
        x = _reflect_pad(x, 3)
        x = nn.Conv(3, (7, 7), padding="VALID", dtype=jnp.float32)(x)
        return jnp.tanh(x)


class PatchGANDiscriminator(nn.Module):
    """70×70 PatchGAN (`CycleGAN/tensorflow/models.py:81-104`): (H, W, 3) →
    (H/8, W/8, 1) patch logits."""
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        bn = partial(nn.BatchNorm, use_running_average=not train, momentum=0.99,
                     epsilon=1e-3, dtype=jnp.float32)
        conv = partial(nn.Conv, padding="SAME", dtype=self.dtype)
        x = conv(64, (4, 4), strides=(2, 2))(x.astype(self.dtype))
        x = nn.leaky_relu(x, 0.2)
        for f, s in ((128, 2), (256, 2), (512, 1)):
            x = conv(f, (4, 4), strides=(s, s), use_bias=False)(x)
            x = nn.leaky_relu(bn()(x), 0.2).astype(self.dtype)
        return conv(1, (4, 4), strides=(1, 1), dtype=jnp.float32)(x)


MODELS.register("dcgan_generator", DCGANGenerator)
MODELS.register("dcgan_discriminator", DCGANDiscriminator)
MODELS.register("cyclegan_generator", CycleGANGenerator)
MODELS.register("patchgan_discriminator", PatchGANDiscriminator)
# family aliases so the dcgan/cyclegan configs resolve; the GAN trainers build
# the full generator+discriminator pairs themselves (core/gan.py)
MODELS.register("dcgan", DCGANGenerator)
MODELS.register("cyclegan", CycleGANGenerator)
