"""Model zoo — importing this package registers all models in MODELS."""

from . import (alexnet, gan, hourglass, inception, lenet, mobilenet,  # noqa: F401
               resnet, shufflenet, vgg, yolo)

from ..utils.registry import MODELS  # noqa: F401
