"""Model zoo — importing this package registers all models in MODELS."""

from . import (alexnet, inception, lenet, mobilenet, resnet, shufflenet,  # noqa: F401
               vgg)

from ..utils.registry import MODELS  # noqa: F401
