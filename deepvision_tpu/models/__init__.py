"""Model zoo — importing this package registers all models in MODELS."""

from . import lenet, resnet  # noqa: F401

from ..utils.registry import MODELS  # noqa: F401
