"""Model zoo — importing this package registers all models in MODELS."""

from . import (alexnet, centernet, gan, hourglass, inception, lenet,  # noqa: F401
               mobilenet, resnet, segment, shufflenet, vgg, vit, yolo)

from ..utils.registry import MODELS  # noqa: F401
