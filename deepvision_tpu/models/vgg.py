"""VGG-16 / VGG-19 (Simonyan & Zisserman 2014, configurations D and E).

Parity target: `VGG/pytorch/models/vgg16.py:8-127` / `vgg19.py:7-128` — plain 3x3 conv
stacks with 2x2 max-pools and three FC layers, manual weight init
(`vgg16.py:112-127` → normal(0, 0.01) dense, kaiming conv).
"""

from __future__ import annotations

from typing import Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from ..utils.registry import MODELS
from .common import he_normal_fanout

# channels per stage; (vgg16, vgg19) differ only in convs per stage: (2,2,3,3,3) vs
# (2,2,4,4,4)
_STAGES: Tuple[int, ...] = (64, 128, 256, 512, 512)
_DEPTHS = {"vgg16": (2, 2, 3, 3, 3), "vgg19": (2, 2, 4, 4, 4)}


class VGG(nn.Module):
    depths: Sequence[int]
    num_classes: int = 1000
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        for stage, (features, depth) in enumerate(zip(_STAGES, self.depths)):
            for _ in range(depth):
                x = nn.Conv(features, (3, 3), padding="SAME", dtype=self.dtype,
                            kernel_init=he_normal_fanout)(x)
                x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        dense_init = nn.initializers.normal(0.01)
        x = nn.relu(nn.Dense(4096, dtype=self.dtype, kernel_init=dense_init)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.relu(nn.Dense(4096, dtype=self.dtype, kernel_init=dense_init)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32, kernel_init=dense_init)(x)
        return x.astype(jnp.float32)


MODELS.register("vgg16", lambda **kw: VGG(depths=_DEPTHS["vgg16"], **kw))
MODELS.register("vgg19", lambda **kw: VGG(depths=_DEPTHS["vgg19"], **kw))
