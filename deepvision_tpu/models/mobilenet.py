"""MobileNet V1 (Howard et al. 2017, "MobileNets: Efficient Convolutional Neural
Networks for Mobile Vision Applications").

Parity target: `MobileNet/pytorch/models/mobilenet_v1.py:10-155` — 13
depthwise-separable blocks with width multiplier alpha; the reference implements the
depthwise conv with `groups=in_channels` (`:120`), the Flax equivalent is
`feature_group_count=in_channels` (XLA lowers this to a true depthwise conv on TPU).
"""

from __future__ import annotations

from functools import partial

import flax.linen as nn
import jax.numpy as jnp

from ..utils.registry import MODELS
from .common import he_normal_fanout


class DepthwiseSeparable(nn.Module):
    """dw 3x3 + BN + relu → pw 1x1 + BN + relu
    (`mobilenet_v1.py:95-134`, `MobileNet/tensorflow/models/mobilenet_v1.py:7-26`)."""
    features: int
    strides: int = 1
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        in_ch = x.shape[-1]
        x = nn.Conv(in_ch, (3, 3), strides=(self.strides, self.strides),
                    padding=[(1, 1), (1, 1)],  # torch pad 1: SAME differs at
                    feature_group_count=in_ch,  # stride 2 (`mobilenet_v1.py:112`)
                    use_bias=False,
                    kernel_init=he_normal_fanout, dtype=self.dtype, name="dw")(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9, epsilon=1e-5,
                         dtype=jnp.float32)(x)
        x = nn.relu(x).astype(self.dtype)
        x = nn.Conv(self.features, (1, 1), use_bias=False,
                    kernel_init=he_normal_fanout, dtype=self.dtype, name="pw")(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9, epsilon=1e-5,
                         dtype=jnp.float32)(x)
        return nn.relu(x).astype(self.dtype)


# (features, stride) after the stem — paper Table 1.
_V1_BODY = [(64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
            (512, 1), (512, 1), (512, 1), (512, 1), (512, 1), (1024, 2), (1024, 1)]


@MODELS.register("mobilenet_v1")
class MobileNetV1(nn.Module):
    num_classes: int = 1000
    alpha: float = 1.0          # width multiplier (reference `MobileNetV1(alpha=1)`)
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        def c(ch):
            return max(8, int(ch * self.alpha))
        x = x.astype(self.dtype)
        x = nn.Conv(c(32), (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)],
                    use_bias=False,  # torch pad-1 geometry (`mobilenet_v1.py:30`)
                    kernel_init=he_normal_fanout, dtype=self.dtype, name="stem")(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9, epsilon=1e-5,
                         dtype=jnp.float32)(x)
        x = nn.relu(x).astype(self.dtype)
        for i, (features, stride) in enumerate(_V1_BODY):
            x = DepthwiseSeparable(c(features), stride, dtype=self.dtype,
                                   name=f"block{i}")(x, train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x.astype(jnp.float32)
