"""Shared building blocks for the model zoo."""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp
from flax.linen.initializers import variance_scaling

# He/Kaiming normal fan-out — the init the reference uses for ResNet/VGG
# (`ResNet/pytorch/models/resnet50.py:150-160` nn.init.kaiming_normal_(fan_out)).
he_normal_fanout = variance_scaling(2.0, "fan_out", "truncated_normal")


class ConvBN(nn.Module):
    """Conv → BatchNorm → (optional) ReLU.

    The repeated conv+BN+relu triple of the reference zoo (e.g. `BasicConv2d`,
    `Inception/pytorch/models/inception_v1.py:193-200`). BN runs in f32 regardless of
    compute dtype; under jit+GSPMD its batch reduction spans the global batch
    (sync-BN).
    """
    features: int
    kernel: Tuple[int, int] = (3, 3)
    strides: Tuple[int, int] = (1, 1)
    padding: Any = "SAME"
    groups: int = 1
    use_bias: bool = False
    relu: bool = True
    use_bn: bool = True   # False → plain conv(+bias)+relu, the reference's
                          # BN-free `BasicConv2d` (needed to import its
                          # checkpoints; BN=True is this repo's modern recipe)
    dtype: jnp.dtype = jnp.bfloat16
    bn_momentum: float = 0.9
    bn_epsilon: float = 1e-5

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(self.features, self.kernel, strides=self.strides, padding=self.padding,
                    feature_group_count=self.groups,
                    use_bias=self.use_bias or not self.use_bn,
                    kernel_init=he_normal_fanout, dtype=self.dtype)(x)
        if self.use_bn:
            x = nn.BatchNorm(use_running_average=not train, momentum=self.bn_momentum,
                             epsilon=self.bn_epsilon, dtype=jnp.float32)(x)
        if self.relu:
            x = nn.relu(x)
        return x.astype(self.dtype)


def lrn(x, depth_radius: int = 2, bias: float = 2.0, alpha: float = 1e-4,
        beta: float = 0.75, torch_size: int = 0):
    """Local response normalization (AlexNet §3.3; reference uses nn.LocalResponseNorm
    `AlexNet/pytorch/models/alexnet_v1.py` and a custom Keras layer
    `AlexNet/tensorflow/models/alexnet_v2.py:10-22`). Cross-channel, NHWC.

    Defaults are the paper's (n=5, k=2). `torch_size=n` instead reproduces
    `torch.nn.LocalResponseNorm(n)` exactly — k=1, alpha divided by n, and
    torch's ASYMMETRIC n-tap window (n//2 channels before, (n-1)//2 after) —
    the form the reference's models actually call (with n = the full channel
    count, `alexnet_v1.py:41,59`), so imported checkpoints compute the same
    function (tests/test_torch_convert.py::test_alexnet2_numerical_parity)."""
    if torch_size:
        before, after = torch_size // 2, (torch_size - 1) // 2
        bias, alpha = 1.0, alpha / torch_size
    else:
        before = after = depth_radius
    n = before + after + 1
    x32 = x.astype(jnp.float32)
    sq = x32 * x32
    # O(C) sliding-window sum over channels: pad, cumsum, subtract shifted
    run = jnp.cumsum(jnp.pad(sq, [(0, 0)] * (x.ndim - 1) + [(before, after)]),
                     axis=-1)
    run = jnp.pad(run, [(0, 0)] * (x.ndim - 1) + [(1, 0)])
    win = run[..., n:] - run[..., :-n]
    denom = jnp.power(bias + alpha * win, beta)
    return (x32 / denom).astype(x.dtype)
