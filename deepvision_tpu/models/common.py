"""Shared building blocks for the model zoo."""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp
from flax.linen.initializers import variance_scaling

# He/Kaiming normal fan-out — the init the reference uses for ResNet/VGG
# (`ResNet/pytorch/models/resnet50.py:150-160` nn.init.kaiming_normal_(fan_out)).
he_normal_fanout = variance_scaling(2.0, "fan_out", "truncated_normal")


class ConvBN(nn.Module):
    """Conv → BatchNorm → (optional) ReLU.

    The repeated conv+BN+relu triple of the reference zoo (e.g. `BasicConv2d`,
    `Inception/pytorch/models/inception_v1.py:193-200`). BN runs in f32 regardless of
    compute dtype; under jit+GSPMD its batch reduction spans the global batch
    (sync-BN).
    """
    features: int
    kernel: Tuple[int, int] = (3, 3)
    strides: Tuple[int, int] = (1, 1)
    padding: Any = "SAME"
    groups: int = 1
    use_bias: bool = False
    relu: bool = True
    dtype: jnp.dtype = jnp.bfloat16
    bn_momentum: float = 0.9
    bn_epsilon: float = 1e-5

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(self.features, self.kernel, strides=self.strides, padding=self.padding,
                    feature_group_count=self.groups, use_bias=self.use_bias,
                    kernel_init=he_normal_fanout, dtype=self.dtype)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=self.bn_momentum,
                         epsilon=self.bn_epsilon, dtype=jnp.float32)(x)
        if self.relu:
            x = nn.relu(x)
        return x.astype(self.dtype)


def lrn(x, depth_radius: int = 2, bias: float = 2.0, alpha: float = 1e-4,
        beta: float = 0.75):
    """Local response normalization (AlexNet §3.3; reference uses nn.LocalResponseNorm
    `AlexNet/pytorch/models/alexnet_v1.py` and a custom Keras layer
    `AlexNet/tensorflow/models/alexnet_v2.py:10-22`). Cross-channel, NHWC."""
    x32 = x.astype(jnp.float32)
    sq = x32 * x32
    c = x.shape[-1]
    # sum over a window of 2*depth_radius+1 channels via padded cumulative window
    pads = [(0, 0)] * (x.ndim - 1) + [(depth_radius, depth_radius)]
    sq = jnp.pad(sq, pads)
    win = sum(sq[..., i:i + c] for i in range(2 * depth_radius + 1))
    denom = jnp.power(bias + alpha * win, beta)
    return (x32 / denom).astype(x.dtype)
