"""CenterNet / ObjectsAsPoints — 2-stack order-5 hourglass detector in Flax.

Parity target: `ObjectsAsPoints/tensorflow/model.py:17-179` — the CenterNet
large-hourglass variant: per-order filter/(residual count) tables
(`:17-32`), post-activation residual blocks with BN'd 1x1 identity lifts
(`:35-69`), stride-2 lower branches (no maxpool, unlike Hourglass-104), and
per-stack detection heads emitting (class heatmap, size wh, offset xy) at
stride 4 (`:72-91`).

The reference left this family WIP (its trainer's loss list is empty and the
run is commented out, `ObjectsAsPoints/tensorflow/train.py:35,248`); this
implementation is complete — losses/encoding in ops/centernet.py. Two latent
reference bugs are fixed rather than copied: the lower-branch `low3` loop
discards its own output (`model.py:118-121` loops on low3 but final block reads
low2), and the inter-stack re-injection overwrites the residual input with
`ResidualBlock(x, ...)`, discarding the computed add (`model.py:174-176`); both
follow the cited upstream CenterNet code here.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..utils.registry import MODELS

# `ObjectsAsPoints/tensorflow/model.py:17-32`
ORDER_TO_FILTERS = {5: (256, 256), 4: (256, 384), 3: (384, 384),
                    2: (384, 384), 1: (384, 512)}
ORDER_TO_NUM_RESIDUAL = {5: (2, 2), 4: (2, 2), 3: (2, 2), 2: (2, 2), 1: (2, 4)}


class ResidualBlock(nn.Module):
    """Post-activation residual (`model.py:35-69`): conv1x1-BN-ReLU →
    conv3x3-BN, BN'd 1x1 shortcut on channel/stride change, ReLU after add."""
    features: int
    strides: int = 1
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        bn = partial(nn.BatchNorm, use_running_average=not train, momentum=0.99,
                     epsilon=1e-3, dtype=jnp.float32)
        conv = partial(nn.Conv, padding="SAME", use_bias=False, dtype=self.dtype)
        identity = x
        if x.shape[-1] != self.features or self.strides > 1:
            identity = conv(self.features, (1, 1),
                            strides=(self.strides, self.strides))(x)
            identity = bn()(identity).astype(self.dtype)
        y = conv(self.features, (1, 1), strides=(self.strides, self.strides))(x)
        y = nn.relu(bn()(y)).astype(self.dtype)
        y = conv(self.features, (3, 3))(y)
        y = bn()(y).astype(self.dtype)
        return nn.relu(identity + y)


class CenterNetHourglass(nn.Module):
    """Recursive order-N module (`model.py:94-127`), stride-2 lower branch."""
    order: int
    width_mult: float = 1.0
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        w = lambda f: max(2, int(f * self.width_mult))  # noqa: E731
        curr_f, next_f = ORDER_TO_FILTERS[self.order]
        curr_r, next_r = ORDER_TO_NUM_RESIDUAL[self.order]
        block = partial(ResidualBlock, dtype=self.dtype)

        up1 = x
        for _ in range(curr_r):
            up1 = block(w(curr_f))(up1, train)

        low = block(w(next_f), strides=2)(x, train)
        for _ in range(curr_r - 1):
            low = block(w(next_f))(low, train)
        if self.order > 1:
            low = CenterNetHourglass(self.order - 1, self.width_mult,
                                     self.dtype)(low, train)
        else:
            for _ in range(next_r):
                low = block(w(next_f))(low, train)
        # low3: curr_r-1 same-width blocks then one back to curr_f (fixing the
        # reference's discarded-loop bug, model.py:118-121)
        for _ in range(curr_r - 1):
            low = block(w(next_f))(low, train)
        low = block(w(curr_f))(low, train)

        b, h, ww, c = low.shape
        up2 = jax.image.resize(low, (b, h * 2, ww * 2, c), method="nearest")
        return up1 + up2


class DetectionHead(nn.Module):
    """3x3 conv (no BN, `model.py:72-78`) → 3x3 conv per output; heatmap head
    bias init -2.19 so initial sigmoid ≈ 0.1 (standard CenterNet focal-loss
    prior, absent from the WIP reference)."""
    num_classes: int
    width_mult: float = 1.0
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False) -> Dict[str, jnp.ndarray]:
        w = max(2, int(256 * self.width_mult))
        del train

        def tower(filters, name, bias_init=0.0):
            y = nn.Conv(w, (3, 3), padding="SAME", dtype=self.dtype,
                        name=f"{name}_conv1")(x)
            y = nn.relu(y)
            return nn.Conv(filters, (3, 3), padding="SAME", dtype=jnp.float32,
                           bias_init=nn.initializers.constant(bias_init),
                           name=f"{name}_conv2")(y)

        return {"heatmap": tower(self.num_classes, "heatmap", bias_init=-2.19),
                "size": tower(2, "size"),
                "offset": tower(2, "offset")}


class ObjectsAsPoints(nn.Module):
    """Full detector (`model.py:130-179`): stride-4 stem → num_stack hourglasses
    with inter-stack re-injection → per-stack head dicts."""
    num_classes: int = 80
    num_stack: int = 2
    order: int = 5
    width_mult: float = 1.0
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False) -> Tuple[Dict[str, jnp.ndarray], ...]:
        w = lambda f: max(2, int(f * self.width_mult))  # noqa: E731
        bn = partial(nn.BatchNorm, use_running_average=not train, momentum=0.99,
                     epsilon=1e-3, dtype=jnp.float32)
        # stem (`model.py:140-145`)
        x = nn.Conv(w(128), (7, 7), strides=(2, 2), padding="SAME",
                    use_bias=False, dtype=self.dtype)(x)
        x = nn.relu(bn()(x)).astype(self.dtype)
        x = ResidualBlock(w(256), strides=2, dtype=self.dtype)(x, train)

        intermediate = x
        ys = []
        for stack in range(self.num_stack):
            y = CenterNetHourglass(self.order, self.width_mult,
                                   self.dtype)(intermediate, train)
            y = nn.Conv(w(256), (3, 3), padding="SAME",
                        dtype=self.dtype, name=f"post_hg_{stack}")(y)
            y = nn.relu(bn()(y)).astype(self.dtype)
            ys.append(DetectionHead(self.num_classes, self.width_mult,
                                    self.dtype, name=f"head_{stack}")(y, train))
            if stack < self.num_stack - 1:
                # re-injection with BN on both 1x1s (`model.py:164-176`), keeping
                # the residual block ON the added result (reference discards it)
                x1 = nn.Conv(w(256), (1, 1), dtype=self.dtype)(y)
                x1 = bn()(x1).astype(self.dtype)
                x2 = nn.Conv(w(256), (1, 1), dtype=self.dtype)(intermediate)
                x2 = bn()(x2).astype(self.dtype)
                intermediate = ResidualBlock(w(256), dtype=self.dtype)(
                    nn.relu(x1 + x2), train)
        return tuple(ys)


MODELS.register("centernet", ObjectsAsPoints)
MODELS.register("objects_as_points", ObjectsAsPoints)
