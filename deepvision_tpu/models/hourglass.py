"""Stacked Hourglass network (Newell et al. 2016) in Flax.

Parity target: `Hourglass/tensorflow/hourglass104.py:19-159` — pre-activation
bottleneck blocks (BN→ReLU→1x1/3x3/1x1, half-width middle), recursive order-4
hourglass modules with maxpool-down / nearest-upsample branches, a stride-2 stem
(7x7/64 → bottleneck 128 → pool → bottlenecks 128/256), and `num_stack` stacks
each emitting a (H/4, W/4, num_heatmap) prediction with intermediate supervision
re-injection (1x1 convs added back, `:154-157`).

Note: the reference's stack loop shadows its loop variable (`for i in
range(num_stack)` / inner `for i in range(num_residual)`, `:136-138`), so the
"not last stack" test compares the inner index — correct only because
num_residual=1. Implemented here without the shadow.

TPU-first: NHWC bf16 compute / f32 BN, `width_mult`/`num_stack`/`order` knobs so
tests compile a tiny variant quickly.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..utils.registry import MODELS
from .common import he_normal_fanout


class PreActBottleneck(nn.Module):
    """BN→ReLU→conv ×3 bottleneck, half-width middle, optional 1x1 identity lift
    (`hourglass104.py:19-67`)."""
    features: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(nn.Conv, padding="SAME", kernel_init=he_normal_fanout,
                       dtype=self.dtype)
        bn = partial(nn.BatchNorm, use_running_average=not train, momentum=0.9,
                     epsilon=1e-3, dtype=jnp.float32)
        identity = x
        if x.shape[-1] != self.features:
            identity = conv(self.features, (1, 1), name="proj")(x)
        y = nn.relu(bn()(x)).astype(self.dtype)
        y = conv(self.features // 2, (1, 1))(y)
        y = nn.relu(bn()(y)).astype(self.dtype)
        y = conv(self.features // 2, (3, 3))(y)
        y = nn.relu(bn()(y)).astype(self.dtype)
        y = conv(self.features, (1, 1))(y)
        return identity + y


class HourglassModule(nn.Module):
    """Recursive order-N hourglass (`hourglass104.py:70-98`): residual upper
    branch; maxpool → residuals → recurse/residuals → residuals → ×2 upsample."""
    order: int
    features: int
    num_residual: int = 1
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        block = partial(PreActBottleneck, self.features, dtype=self.dtype)
        up1 = block()(x, train)
        for _ in range(self.num_residual):
            up1 = block()(up1, train)

        low = nn.max_pool(x, (2, 2), strides=(2, 2))
        for _ in range(self.num_residual):
            low = block()(low, train)
        if self.order > 1:
            low = HourglassModule(self.order - 1, self.features,
                                  self.num_residual, self.dtype)(low, train)
        else:
            for _ in range(self.num_residual):
                low = block()(low, train)
        for _ in range(self.num_residual):
            low = block()(low, train)

        b, h, w, c = low.shape
        up2 = jax.image.resize(low, (b, h * 2, w * 2, c), method="nearest")
        return up1 + up2


class StackedHourglass(nn.Module):
    """`StackedHourglassNetwork` (`hourglass104.py:113-159`): stem → num_stack
    hourglasses with intermediate supervision. Returns a tuple of num_stack
    (B, H/4, W/4, num_heatmap) raw heatmap predictions."""
    num_heatmap: int = 16
    num_stack: int = 4
    num_residual: int = 1
    order: int = 4
    width_mult: float = 1.0
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False) -> Tuple[jnp.ndarray, ...]:
        w = lambda f: max(2, int(f * self.width_mult))  # noqa: E731
        conv = partial(nn.Conv, padding="SAME", kernel_init=he_normal_fanout,
                       dtype=self.dtype)
        # stem (`hourglass104.py:121-133`)
        x = conv(w(64), (7, 7), strides=(2, 2), name="stem_conv")(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-3, dtype=jnp.float32)(x)
        x = nn.relu(x).astype(self.dtype)
        x = PreActBottleneck(w(128), self.dtype)(x, train)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = PreActBottleneck(w(128), self.dtype)(x, train)
        x = PreActBottleneck(w(256), self.dtype)(x, train)

        f = w(256)
        ys = []
        for stack in range(self.num_stack):
            x = HourglassModule(self.order, f, self.num_residual,
                                self.dtype)(x, train)
            for _ in range(self.num_residual):
                x = PreActBottleneck(f, self.dtype)(x, train)
            # linear layer (`hourglass104.py:101-110,142`)
            x = conv(f, (1, 1))(x)
            x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                             epsilon=1e-3, dtype=jnp.float32)(x)
            x = nn.relu(x).astype(self.dtype)
            y = nn.Conv(self.num_heatmap, (1, 1), padding="SAME",
                        kernel_init=he_normal_fanout, dtype=jnp.float32,
                        name=f"head_{stack}")(x)
            ys.append(y)
            if stack < self.num_stack - 1:  # intermediate re-injection
                x = (conv(f, (1, 1))(x) +
                     conv(f, (1, 1))(y.astype(self.dtype)))
        return tuple(ys)


MODELS.register("hourglass104", StackedHourglass)
