"""Inception V1 / GoogLeNet (Szegedy et al. 2014, "Going Deeper with Convolutions").

Parity target: `Inception/pytorch/models/inception_v1.py:9-200` — stem, 9 inception
modules with LRN after the stem convs, two auxiliary classifiers (4a, 4d outputs), and
dropout 0.4 before the head. Training mode returns (main, aux1, aux2); unlike the
reference (which never combined them — `Inception/pytorch/README.md:44`), the shared
loss weights aux heads by 0.3 (paper §5).

The reference's Inception V3 is a 5-line stub (`inception_v3.py:1-5`); here V3
(Szegedy et al. 2015, "Rethinking the Inception Architecture") is implemented in full —
factorized 7x7, grid-reduction blocks, and a single aux head.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from ..utils.registry import MODELS
from .common import ConvBN, lrn


class InceptionModule(nn.Module):
    """4-branch inception block: 1x1 / 1x1→3x3 / 1x1→5x5 / pool→1x1
    (`inception_v1.py:127-158`)."""
    b1: int
    b2_reduce: int
    b2: int
    b3_reduce: int
    b3: int
    b4: int
    use_bn: bool = True
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        cb = partial(ConvBN, dtype=self.dtype, use_bn=self.use_bn)
        y1 = cb(self.b1, (1, 1))(x, train)
        y2 = cb(self.b2_reduce, (1, 1))(x, train)
        y2 = cb(self.b2, (3, 3))(y2, train)
        y3 = cb(self.b3_reduce, (1, 1))(x, train)
        y3 = cb(self.b3, (5, 5))(y3, train)
        y4 = nn.max_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        y4 = cb(self.b4, (1, 1))(y4, train)
        return jnp.concatenate([y1, y2, y3, y4], axis=-1)


class AuxClassifier(nn.Module):
    """5x5/3 avg-pool → 1x1 conv(128) → FC(1024) → dropout(0.7) → FC(classes)
    (`inception_v1.py:161-190`)."""
    num_classes: int
    use_bn: bool = True
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.avg_pool(x, (5, 5), strides=(3, 3))
        x = ConvBN(128, (1, 1), dtype=self.dtype, use_bn=self.use_bn)(x, train)
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(1024, dtype=self.dtype)(x))
        x = nn.Dropout(0.7, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


# (b1, b2_reduce, b2, b3_reduce, b3, b4) per module — paper Table 1.
_V1_CFG = {
    "3a": (64, 96, 128, 16, 32, 32),
    "3b": (128, 128, 192, 32, 96, 64),
    "4a": (192, 96, 208, 16, 48, 64),
    "4b": (160, 112, 224, 24, 64, 64),
    "4c": (128, 128, 256, 24, 64, 64),
    "4d": (112, 144, 288, 32, 64, 64),
    "4e": (256, 160, 320, 32, 128, 128),
    "5a": (256, 160, 320, 32, 128, 128),
    "5b": (384, 192, 384, 48, 128, 128),
}


@MODELS.register("googlenet")
@MODELS.register("inception_v1")
class InceptionV1(nn.Module):
    num_classes: int = 1000
    aux: bool = True
    use_bn: bool = True  # False = the reference's exact BN-free BasicConv2d
                         # stack + its torch LRN windows (checkpoint import)
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        cb = partial(ConvBN, dtype=self.dtype, use_bn=self.use_bn)
        im = partial(InceptionModule, use_bn=self.use_bn, dtype=self.dtype)
        # explicit pad 3: SAME pads (2,3) at stride 2, shifting every window
        # vs the reference's symmetric padding=3 (`inception_v1.py:27`)
        x = cb(64, (7, 7), strides=(2, 2), padding=[(3, 3), (3, 3)],
               name="stem1")(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        x = lrn(x) if self.use_bn else lrn(x, torch_size=64)
        x = cb(64, (1, 1), name="stem2a")(x, train)
        x = cb(192, (3, 3), name="stem2b")(x, train)
        x = lrn(x) if self.use_bn else lrn(x, torch_size=192)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")

        x = im(*_V1_CFG["3a"], name="mod3a")(x, train)
        x = im(*_V1_CFG["3b"], name="mod3b")(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        x = im(*_V1_CFG["4a"], name="mod4a")(x, train)
        aux1_in = x
        x = im(*_V1_CFG["4b"], name="mod4b")(x, train)
        x = im(*_V1_CFG["4c"], name="mod4c")(x, train)
        x = im(*_V1_CFG["4d"], name="mod4d")(x, train)
        aux2_in = x
        x = im(*_V1_CFG["4e"], name="mod4e")(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        x = im(*_V1_CFG["5a"], name="mod5a")(x, train)
        x = im(*_V1_CFG["5b"], name="mod5b")(x, train)

        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dropout(0.4, deterministic=not train)(x)
        main = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        main = main.astype(jnp.float32)

        if train and self.aux:
            a1 = AuxClassifier(self.num_classes, use_bn=self.use_bn,
                               dtype=self.dtype, name="aux1")(aux1_in, train)
            a2 = AuxClassifier(self.num_classes, use_bn=self.use_bn,
                               dtype=self.dtype, name="aux2")(aux2_in, train)
            return main, a1, a2
        return main


# ---------------------------------------------------------------------------
# Inception V3


class InceptionA(nn.Module):
    pool_features: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        cb = partial(ConvBN, dtype=self.dtype)
        b1 = cb(64, (1, 1))(x, train)
        b2 = cb(48, (1, 1))(x, train)
        b2 = cb(64, (5, 5))(b2, train)
        b3 = cb(64, (1, 1))(x, train)
        b3 = cb(96, (3, 3))(b3, train)
        b3 = cb(96, (3, 3))(b3, train)
        b4 = nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        b4 = cb(self.pool_features, (1, 1))(b4, train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class ReductionA(nn.Module):
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        cb = partial(ConvBN, dtype=self.dtype)
        b1 = cb(384, (3, 3), strides=(2, 2), padding="VALID")(x, train)
        b2 = cb(64, (1, 1))(x, train)
        b2 = cb(96, (3, 3))(b2, train)
        b2 = cb(96, (3, 3), strides=(2, 2), padding="VALID")(b2, train)
        b3 = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b1, b2, b3], axis=-1)


class InceptionB(nn.Module):
    """Factorized 7x7 block."""
    c7: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        cb = partial(ConvBN, dtype=self.dtype)
        c7 = self.c7
        b1 = cb(192, (1, 1))(x, train)
        b2 = cb(c7, (1, 1))(x, train)
        b2 = cb(c7, (1, 7))(b2, train)
        b2 = cb(192, (7, 1))(b2, train)
        b3 = cb(c7, (1, 1))(x, train)
        b3 = cb(c7, (7, 1))(b3, train)
        b3 = cb(c7, (1, 7))(b3, train)
        b3 = cb(c7, (7, 1))(b3, train)
        b3 = cb(192, (1, 7))(b3, train)
        b4 = nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        b4 = cb(192, (1, 1))(b4, train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class ReductionB(nn.Module):
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        cb = partial(ConvBN, dtype=self.dtype)
        b1 = cb(192, (1, 1))(x, train)
        b1 = cb(320, (3, 3), strides=(2, 2), padding="VALID")(b1, train)
        b2 = cb(192, (1, 1))(x, train)
        b2 = cb(192, (1, 7))(b2, train)
        b2 = cb(192, (7, 1))(b2, train)
        b2 = cb(192, (3, 3), strides=(2, 2), padding="VALID")(b2, train)
        b3 = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b1, b2, b3], axis=-1)


class InceptionC(nn.Module):
    """Expanded-filter-bank output block."""
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        cb = partial(ConvBN, dtype=self.dtype)
        b1 = cb(320, (1, 1))(x, train)
        b2 = cb(384, (1, 1))(x, train)
        b2 = jnp.concatenate([cb(384, (1, 3))(b2, train),
                              cb(384, (3, 1))(b2, train)], axis=-1)
        b3 = cb(448, (1, 1))(x, train)
        b3 = cb(384, (3, 3))(b3, train)
        b3 = jnp.concatenate([cb(384, (1, 3))(b3, train),
                              cb(384, (3, 1))(b3, train)], axis=-1)
        b4 = nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        b4 = cb(192, (1, 1))(b4, train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class AuxClassifierV3(nn.Module):
    num_classes: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.avg_pool(x, (5, 5), strides=(3, 3))
        x = ConvBN(128, (1, 1), dtype=self.dtype)(x, train)
        x = ConvBN(768, tuple(x.shape[1:3]), padding="VALID", dtype=self.dtype)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


@MODELS.register("inception_v3")
class InceptionV3(nn.Module):
    """299x299 input canonical; any size >= 75 works."""
    num_classes: int = 1000
    aux: bool = True
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        cb = partial(ConvBN, dtype=self.dtype)
        x = x.astype(self.dtype)
        x = cb(32, (3, 3), strides=(2, 2), padding="VALID")(x, train)
        x = cb(32, (3, 3), padding="VALID")(x, train)
        x = cb(64, (3, 3))(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = cb(80, (1, 1), padding="VALID")(x, train)
        x = cb(192, (3, 3), padding="VALID")(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = InceptionA(32, dtype=self.dtype)(x, train)
        x = InceptionA(64, dtype=self.dtype)(x, train)
        x = InceptionA(64, dtype=self.dtype)(x, train)
        x = ReductionA(dtype=self.dtype)(x, train)
        x = InceptionB(128, dtype=self.dtype)(x, train)
        x = InceptionB(160, dtype=self.dtype)(x, train)
        x = InceptionB(160, dtype=self.dtype)(x, train)
        x = InceptionB(192, dtype=self.dtype)(x, train)
        aux_in = x
        x = ReductionB(dtype=self.dtype)(x, train)
        x = InceptionC(dtype=self.dtype)(x, train)
        x = InceptionC(dtype=self.dtype)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        main = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        main = main.astype(jnp.float32)
        if train and self.aux:
            a = AuxClassifierV3(self.num_classes, dtype=self.dtype, name="aux")(aux_in, train)
            return main, a
        return main
