"""AlexNet (Krizhevsky et al. 2012) V1 and V2 ("One weird trick", Krizhevsky 2014).

Parity targets: `AlexNet/pytorch/models/alexnet_v1.py:11-125` (one-tower original with
LRN and overlapping 3x3/2 max-pool) and `alexnet_v2.py:12-75` / the Keras functional
variant `AlexNet/tensorflow/models/alexnet_v2.py:25-70`.
"""

from __future__ import annotations

from functools import partial

import flax.linen as nn
import jax.numpy as jnp

from ..utils.registry import MODELS
from .common import lrn


@MODELS.register("alexnet1")
class AlexNetV1(nn.Module):
    """Original AlexNet: conv1 11x11/4 → LRN → pool, conv2 5x5 grouped-in-paper
    (single tower here, like the reference), conv3-5 3x3, two 4096 FC + dropout."""
    num_classes: int = 1000
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        conv = partial(nn.Conv, dtype=self.dtype,
                       bias_init=nn.initializers.ones)  # paper: bias 1 in some layers
        x = nn.Conv(96, (11, 11), strides=(4, 4), padding=[(2, 2), (2, 2)],
                    dtype=self.dtype)(x)  # pad 2, matching `alexnet_v1.py:33`
                                          # (output 55x55 → FC sees 6x6x256)
        x = nn.relu(x)
        x = lrn(x, torch_size=96)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = conv(256, (5, 5), padding="SAME")(x)
        x = nn.relu(x)
        x = lrn(x, torch_size=256)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = conv(384, (3, 3), padding="SAME")(x)
        x = nn.relu(x)
        x = conv(384, (3, 3), padding="SAME")(x)
        x = nn.relu(x)
        x = conv(256, (3, 3), padding="SAME")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.relu(nn.Dense(4096, dtype=self.dtype)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.relu(nn.Dense(4096, dtype=self.dtype)(x))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


@MODELS.register("alexnet2")
class AlexNetV2(nn.Module):
    """"One weird trick" variant as the reference builds it: single tower,
    widths 64/192/384/384/256, LRN retained after the first two conv blocks
    "for study purpose" (`AlexNet/pytorch/models/alexnet_v2.py:30-50`)."""
    num_classes: int = 1000
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = nn.Conv(64, (11, 11), strides=(4, 4), padding=[(2, 2), (2, 2)],
                    dtype=self.dtype)(x)
        x = nn.relu(x)
        x = lrn(x, torch_size=64)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = nn.Conv(192, (5, 5), padding="SAME", dtype=self.dtype)(x)
        x = nn.relu(x)
        x = lrn(x, torch_size=192)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = nn.Conv(384, (3, 3), padding="SAME", dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Conv(384, (3, 3), padding="SAME", dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Conv(256, (3, 3), padding="SAME", dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.relu(nn.Dense(4096, dtype=self.dtype)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.relu(nn.Dense(4096, dtype=self.dtype)(x))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x.astype(jnp.float32)
