"""ShuffleNet V1 (Zhang et al. 2017, "ShuffleNet: An Extremely Efficient
Convolutional Neural Network for Mobile Devices").

The reference left this as an empty stub (`ShuffleNet/pytorch/models/shufflenet_v1.py`,
0 lines; README says work-in-progress `ShuffleNet/pytorch/README.md:1`). Implemented in
full here: grouped 1x1 convs + channel shuffle + depthwise 3x3, stages 2-4, the g=3
configuration by default.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from ..utils.registry import MODELS
from .common import he_normal_fanout


def channel_shuffle(x: jnp.ndarray, groups: int) -> jnp.ndarray:
    """Transpose the (groups, ch/groups) channel view — pure reshape/transpose,
    free on TPU (layout change folded by XLA)."""
    n, h, w, c = x.shape
    x = x.reshape(n, h, w, groups, c // groups)
    x = jnp.swapaxes(x, 3, 4)
    return x.reshape(n, h, w, c)


def _gconv(x, features, groups, dtype, name=None):
    return nn.Conv(features, (1, 1), feature_group_count=groups, use_bias=False,
                   kernel_init=he_normal_fanout, dtype=dtype, name=name)(x)


class ShuffleUnit(nn.Module):
    features: int
    groups: int = 3
    stride: int = 1
    first_unit_no_gconv: bool = False  # stage2 first unit: plain 1x1 (paper §3.2)
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        def bn(y, relu=True):
            y = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                             epsilon=1e-5, dtype=jnp.float32)(y)
            return (nn.relu(y) if relu else y).astype(self.dtype)

        in_ch = x.shape[-1]
        bottleneck = self.features // 4
        # stride-2 units concat with a 3x3 avg-pool shortcut, so the residual branch
        # produces (features - in_ch) channels
        out_ch = self.features - in_ch if self.stride == 2 else self.features
        g1 = 1 if self.first_unit_no_gconv else self.groups

        y = _gconv(x, bottleneck, g1, self.dtype, name="gconv1")
        y = bn(y)
        y = channel_shuffle(y, self.groups) if g1 > 1 else y
        y = nn.Conv(bottleneck, (3, 3), strides=(self.stride, self.stride),
                    feature_group_count=bottleneck, use_bias=False,
                    kernel_init=he_normal_fanout, dtype=self.dtype, name="dw")(y)
        y = bn(y, relu=False)
        y = _gconv(y, out_ch, self.groups, self.dtype, name="gconv2")
        y = bn(y, relu=False)

        if self.stride == 2:
            shortcut = nn.avg_pool(x, (3, 3), strides=(2, 2), padding="SAME")
            return nn.relu(jnp.concatenate([shortcut, y], axis=-1)).astype(self.dtype)
        return nn.relu(x + y).astype(self.dtype)


# output channels per stage for each group count g — paper Table 1.
_STAGE_CH = {1: (144, 288, 576), 2: (200, 400, 800), 3: (240, 480, 960),
             4: (272, 544, 1088), 8: (384, 768, 1536)}
_STAGE_REPEATS = (4, 8, 4)


@MODELS.register("shufflenet_v1")
class ShuffleNetV1(nn.Module):
    num_classes: int = 1000
    groups: int = 3
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = nn.Conv(24, (3, 3), strides=(2, 2), use_bias=False,
                    kernel_init=he_normal_fanout, dtype=self.dtype, name="stem")(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9, epsilon=1e-5,
                         dtype=jnp.float32)(x)
        x = nn.relu(x).astype(self.dtype)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        channels = _STAGE_CH[self.groups]
        for stage, (ch, reps) in enumerate(zip(channels, _STAGE_REPEATS)):
            for unit in range(reps):
                x = ShuffleUnit(
                    ch, groups=self.groups, stride=2 if unit == 0 else 1,
                    first_unit_no_gconv=(stage == 0 and unit == 0),
                    dtype=self.dtype, name=f"stage{stage + 2}_unit{unit}")(x, train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x.astype(jnp.float32)
