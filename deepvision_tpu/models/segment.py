"""U-Net-style semantic segmentation head over the ResNet backbone family.

The first dense-prediction model of the zoo (the reference covers
classification/detection/pose/GANs only — PAPER.md §0): a ResNet encoder
(stem + 4 stages, reusing `models/resnet.py`'s BasicBlock/BottleneckBlock and
the shared `_BN`) with a U-Net decoder that upsamples nearest-x2, concats the
matching encoder skip, and refines with 3x3 conv+BN+ReLU at each level, ending
in an f32 1x1 head emitting per-pixel class logits at the INPUT resolution.

Spatial-mesh compatibility is a design constraint, not an afterthought: every
decoder op is row-local under H-sharding — nearest-x2 `jax.image.resize` maps
output row i to local input row i//2, channel concat and 1x1/3x3 SAME convs
are handled by the halo machinery, and BatchNorm syncs over the mesh axes —
so the whole network runs H-sharded end to end with NO all_to_all transition
(`parallel/spatial_shard.default_transition` returns None for this class,
like CenterNet and StackedHourglass).

Dtype policy matches the zoo: bf16 compute convs, f32 BN + f32 head
(`nn.Conv(num_classes, (1,1), dtype=jnp.float32)`) — the deliberate f32 head
jaxvet's DTYPE family allowlists via the num_classes dimension.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..utils.registry import MODELS
from .common import he_normal_fanout
from .resnet import BasicBlock, BottleneckBlock, _BN

# widest decoder level: full-size backbones carry 2048-wide stride-32
# features; decoding at that width would dwarf the encoder for no mIoU
DECODER_MAX_WIDTH = 256


class UNetSegmenter(nn.Module):
    """ResNet-encoder U-Net: stem/2 -> maxpool -> stages (strides 4..) ->
    nearest-x2 decoder with skip concats -> f32 1x1 logits at stride 1."""
    num_classes: int
    stage_sizes: Sequence[int] = (3, 4, 6, 3)
    block: type = BottleneckBlock
    width: int = 64
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        factor = 2 ** (len(self.stage_sizes) + 1)
        if x.shape[1] % factor or x.shape[2] % factor:
            # a misaligned size would only fail later as an opaque concat
            # shape error deep in the decoder — name the contract instead
            raise ValueError(
                f"UNetSegmenter with {len(self.stage_sizes)} stages needs "
                f"H/W divisible by {factor} (skip/upsample alignment), got "
                f"{x.shape[1]}x{x.shape[2]}")
        conv = partial(nn.Conv, use_bias=False, kernel_init=he_normal_fanout,
                       dtype=self.dtype)
        x = x.astype(self.dtype)
        x = conv(self.width, (7, 7), strides=(2, 2),
                 padding=[(3, 3), (3, 3)], name="stem_conv")(x)
        x = _BN()(x, train).astype(self.dtype)
        skips = [x]                                   # stride 2
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])
        for i, num_blocks in enumerate(self.stage_sizes):
            for j in range(num_blocks):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block(self.width * 2 ** i, strides=strides,
                               dtype=self.dtype)(x, train=train)
            skips.append(x)                           # strides 4, 8, 16, ...

        def refine(y, features, name):
            y = conv(features, (3, 3), padding=[(1, 1), (1, 1)],
                     name=f"{name}_conv")(y)
            return _BN()(y, train).astype(self.dtype)

        y = skips.pop()
        for level, skip in enumerate(reversed(skips)):
            b, h, w, c = y.shape
            y = jax.image.resize(y, (b, h * 2, w * 2, c), method="nearest")
            y = jnp.concatenate([y, skip.astype(self.dtype)], axis=-1)
            y = refine(y, min(DECODER_MAX_WIDTH, skip.shape[-1]),
                       f"dec{level}")
        b, h, w, c = y.shape                          # stride 2 now
        y = jax.image.resize(y, (b, h * 2, w * 2, c), method="nearest")
        y = refine(y, min(DECODER_MAX_WIDTH, self.width), "dec_full")
        logits = nn.Conv(self.num_classes, (1, 1), dtype=jnp.float32,
                         name="head")(y)
        return logits.astype(jnp.float32)


MODELS.register("unet_resnet50", partial(
    UNetSegmenter, stage_sizes=(3, 4, 6, 3), block=BottleneckBlock))
# CPU-feasible tiny variant for the synthetic/digits recipes — the segmentation
# analog of centernet_digits' width-cut hourglass
MODELS.register("unet_small", partial(
    UNetSegmenter, stage_sizes=(1, 1), block=BasicBlock, width=8))
