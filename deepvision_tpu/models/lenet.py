"""LeNet-5 (LeCun et al. 1998, "Gradient-Based Learning Applied to Document
Recognition").

Parity target: `LeNet/pytorch/models/lenet5.py:8-67` and
`LeNet/tensorflow/models/lenet5.py:7-34` — classic C1/S2/C3/S4/C5/F6 stack with tanh
activations and average pooling, input 32x32x1 (MNIST padded 28→32 by the loader,
`LeNet/pytorch/data_load.py:40-44`). NHWC layout for TPU.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from ..utils.registry import MODELS


@MODELS.register("lenet5")
class LeNet5(nn.Module):
    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = nn.Conv(6, (5, 5), padding="VALID", dtype=self.dtype, name="c1")(x)
        x = jnp.tanh(x)
        # the reference squashes AFTER the subsampling layers too (S2/S4 are
        # "pool → trainable-free tanh" there, `lenet5.py:30-42`)
        x = jnp.tanh(nn.avg_pool(x, (2, 2), strides=(2, 2)))
        x = nn.Conv(16, (5, 5), padding="VALID", dtype=self.dtype, name="c3")(x)
        x = jnp.tanh(x)
        x = jnp.tanh(nn.avg_pool(x, (2, 2), strides=(2, 2)))
        x = nn.Conv(120, (5, 5), padding="VALID", dtype=self.dtype, name="c5")(x)
        x = jnp.tanh(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(84, dtype=self.dtype, name="f6")(x)
        x = jnp.tanh(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="output")(x)
        return x.astype(jnp.float32)
