"""ResNet family (He et al. 2015, "Deep Residual Learning for Image Recognition";
V2 from He et al. 2016, "Identity Mappings in Deep Residual Networks").

Parity targets:
- ResNet-34 basic-block (`ResNet/pytorch/models/resnet34.py:8-143`)
- ResNet-50/152 bottleneck with projection shortcuts + He fan-out init
  (`ResNet/pytorch/models/resnet50.py:8-165`, `resnet152.py`)
- ResNet-50 V2 pre-activation (`ResNet/tensorflow/models/resnet50v2.py`)

TPU-first choices: NHWC layout, bf16 compute / f32 BN+params, zero-init of the last
BN gamma in each residual block (standard large-batch recipe, needed for the
BASELINE.md 75.3% target; not in the reference).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from ..utils.registry import MODELS
from .common import he_normal_fanout


class _BN(nn.Module):
    scale_init: Callable = nn.initializers.ones
    relu: bool = True
    # BatchNorm *computation* dtype for the normalize/scale/shift pass. The
    # batch-stat reductions stay f32 regardless (flax `_compute_stats`
    # force_float32_reductions), and scale/bias params + running stats stay
    # f32 (param_dtype default), so checkpoints are dtype-identical either
    # way — only the materialized normalize output changes width. f32 here
    # is the parity default; the `lowp_bn` experiment passes the compute
    # dtype to halve every BN round trip through HBM (docs/TUNING.md).
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool):
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9, epsilon=1e-5,
                         dtype=self.dtype, scale_init=self.scale_init)(x)
        if self.relu:
            x = nn.relu(x)
        return x


class BasicBlock(nn.Module):
    """Two 3x3 convs + identity/projection shortcut
    (`ResNet/pytorch/models/resnet34.py:92-143`)."""
    features: int
    strides: Tuple[int, int] = (1, 1)
    dtype: jnp.dtype = jnp.bfloat16
    # BasicBlock always strides its first conv (both here and in the
    # reference), so the flag is accepted for API uniformity and is a no-op
    stride_on_first: bool = False
    # The reference projects the FIRST block of every stage even when shapes
    # already match (`resnet34.py:116-128` downsample=True on block 0, incl.
    # the stride-1 64→64 conv2x stage) — required to import its checkpoints.
    always_project: bool = False
    lowp_residual: bool = False  # HBM-traffic experiment A (docs/TUNING.md)
    lowp_bn: bool = False        # HBM-traffic experiment B

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(nn.Conv, use_bias=False, kernel_init=he_normal_fanout,
                       dtype=self.dtype)
        bn = partial(_BN, dtype=self.dtype if self.lowp_bn else jnp.float32)
        join = self.dtype if (self.lowp_residual or self.lowp_bn) \
            else jnp.float32
        residual = x
        # explicit pad 1: torch pad-1 geometry; SAME differs at stride 2
        y = conv(self.features, (3, 3), strides=self.strides,
                 padding=[(1, 1), (1, 1)])(x)
        y = bn()(y, train).astype(self.dtype)
        y = conv(self.features, (3, 3), padding=[(1, 1), (1, 1)])(y)
        y = bn(scale_init=nn.initializers.zeros, relu=False)(y, train)
        if self.always_project or residual.shape != y.shape:
            residual = conv(self.features, (1, 1), strides=self.strides,
                            name="proj")(residual)
            residual = bn(relu=False)(residual, train)
        # join dtype: f32 add (the parity default — identity residuals are
        # bf16 but the add promotes) vs compute-dtype add under the lowp
        # experiments, which turns the relu(y+residual) epilogue bf16 —
        # the r04 trace's 33.4ms f32 loop fusion (runs/r04_resnet50_tpu_profile)
        return nn.relu(y.astype(join) + residual.astype(join)) \
            .astype(self.dtype)


class BottleneckBlock(nn.Module):
    """1x1 reduce → 3x3 → 1x1 expand (×4) + projection shortcut
    (`ResNet/pytorch/models/resnet50.py:96-165`). Stride on the 3x3 (torch-B
    style, the modern-recipe default); `stride_on_first=True` reproduces the
    reference's stride-on-conv1 placement (`resnet50.py:101-106`) so its
    checkpoints import exactly (utils/torch_convert.py)."""
    features: int
    strides: Tuple[int, int] = (1, 1)
    expansion: int = 4
    dtype: jnp.dtype = jnp.bfloat16
    stride_on_first: bool = False
    always_project: bool = False  # accepted for stage-policy uniformity with
                                  # BasicBlock; bottleneck first blocks always
                                  # change channels so this is normally moot
    lowp_residual: bool = False  # HBM-traffic experiment A (docs/TUNING.md)
    lowp_bn: bool = False        # HBM-traffic experiment B

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(nn.Conv, use_bias=False, kernel_init=he_normal_fanout,
                       dtype=self.dtype)
        bn = partial(_BN, dtype=self.dtype if self.lowp_bn else jnp.float32)
        join = self.dtype if (self.lowp_residual or self.lowp_bn) \
            else jnp.float32
        out_features = self.features * self.expansion
        s1 = self.strides if self.stride_on_first else (1, 1)
        s2 = (1, 1) if self.stride_on_first else self.strides
        residual = x
        y = conv(self.features, (1, 1), strides=s1)(x)
        y = bn()(y, train).astype(self.dtype)
        y = conv(self.features, (3, 3), strides=s2,
                 padding=[(1, 1), (1, 1)])(y)  # torch pad-1 geometry
        y = bn()(y, train).astype(self.dtype)
        y = conv(out_features, (1, 1))(y)
        y = bn(scale_init=nn.initializers.zeros, relu=False)(y, train)
        if self.always_project or residual.shape != y.shape:
            residual = conv(out_features, (1, 1), strides=self.strides,
                            name="proj")(residual)
            residual = bn(relu=False)(residual, train)
        # see BasicBlock on the join dtype (f32 parity default vs the lowp
        # experiments' compute-dtype epilogue)
        return nn.relu(y.astype(join) + residual.astype(join)) \
            .astype(self.dtype)


class ResNet(nn.Module):
    """V1 ResNet: 7x7/2 stem → maxpool → 4 stages → GAP → Dense."""
    stage_sizes: Sequence[int]
    block: type = BottleneckBlock
    num_classes: int = 1000
    width: int = 64
    dtype: jnp.dtype = jnp.bfloat16
    stride_on_first: bool = False  # reference stride placement, for imported
                                   # torch checkpoints (utils/torch_convert.py)
    project_first_blocks: bool = False  # reference BasicBlock policy: project
                                        # block 0 of every stage (import compat)
    stem_space_to_depth: bool = False  # MLPerf-style TPU stem: 2x2
    # space-to-depth then a 4x4/1 conv on (H/2, W/2, 4C). The C=3 7x7/2 stem
    # conv tiles poorly onto the MXU (channel dim far below the 128 lane
    # width); the blocked form feeds 12 channels and strides 1. The function
    # class contains the original exactly: an 8x8/2 conv whose first row/col
    # of taps is zero equals the 7x7/2 conv, and the 4x4x(4C) kernel is that
    # 8x8 kernel's phase decomposition (tests/test_models_classification.py).
    # The 4x4 kernel / (2,1) padding geometry is derived for block size 2,
    # which is the only block the 7x7/2 stem decomposes into — not a knob.
    lowp_residual: bool = False  # HBM-traffic experiment A: compute-dtype
    # residual join (the f32 relu(y+residual) loop fusion was 10.4% of the
    # r04 step). Measured + numerics-gated in docs/TUNING.md; off for import
    # parity.
    lowp_bn: bool = False  # HBM-traffic experiment B: compute-dtype BN
    # normalize output (stats/params/running-averages stay f32, so
    # checkpoints are identical either way).

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        if self.stem_space_to_depth:
            b = 2
            n, h, w, c = x.shape
            x = x.reshape(n, h // b, b, w // b, b, c)
            x = x.transpose(0, 1, 3, 2, 4, 5).reshape(n, h // b, w // b,
                                                      b * b * c)
            x = nn.Conv(self.width, (4, 4), strides=(1, 1),
                        padding=[(2, 1), (2, 1)], use_bias=False,
                        kernel_init=he_normal_fanout, dtype=self.dtype,
                        name="stem_conv_s2d")(x)
        else:
            x = nn.Conv(self.width, (7, 7), strides=(2, 2),
                        padding=[(3, 3), (3, 3)],
                        use_bias=False, kernel_init=he_normal_fanout,
                        dtype=self.dtype, name="stem_conv")(x)
        x = _BN(dtype=self.dtype if self.lowp_bn else jnp.float32)(
            x, train).astype(self.dtype)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])
        block_kwargs = {"stride_on_first": True} if self.stride_on_first else {}
        if self.lowp_residual:
            block_kwargs["lowp_residual"] = True
        if self.lowp_bn:
            block_kwargs["lowp_bn"] = True
        for i, num_blocks in enumerate(self.stage_sizes):
            for j in range(num_blocks):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                kw = dict(block_kwargs)
                if self.project_first_blocks and j == 0:
                    kw["always_project"] = True
                x = self.block(self.width * 2 ** i, strides=strides,
                               dtype=self.dtype, **kw)(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32,
                     kernel_init=nn.initializers.normal(0.01), name="head")(x)
        return x.astype(jnp.float32)


MODELS.register("resnet34", partial(ResNet, stage_sizes=(3, 4, 6, 3), block=BasicBlock))
MODELS.register("resnet50", partial(ResNet, stage_sizes=(3, 4, 6, 3), block=BottleneckBlock))
MODELS.register("resnet101", partial(ResNet, stage_sizes=(3, 4, 23, 3), block=BottleneckBlock))
MODELS.register("resnet152", partial(ResNet, stage_sizes=(3, 8, 36, 3), block=BottleneckBlock))
# HBM-lean flagship: same parameters/checkpoints as resnet50 (all f32 state),
# bf16 BN-normalize outputs + bf16 residual joins — the measured traffic
# experiments of docs/TUNING.md, addressable by name for bench/recipe use
MODELS.register("resnet50_lean", partial(ResNet, stage_sizes=(3, 4, 6, 3),
                                         block=BottleneckBlock,
                                         lowp_residual=True, lowp_bn=True))


class PreActBottleneck(nn.Module):
    """Pre-activation bottleneck (`ResNet/tensorflow/models/resnet50v2.py:18+`)."""
    features: int
    strides: Tuple[int, int] = (1, 1)
    expansion: int = 4
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(nn.Conv, use_bias=False, kernel_init=he_normal_fanout,
                       dtype=self.dtype)
        out_features = self.features * self.expansion
        pre = _BN()(x, train).astype(self.dtype)
        if x.shape[-1] != out_features or self.strides != (1, 1):
            residual = conv(out_features, (1, 1), strides=self.strides, name="proj")(pre)
        else:
            residual = x
        y = conv(self.features, (1, 1))(pre)
        y = _BN()(y, train).astype(self.dtype)
        y = conv(self.features, (3, 3), strides=self.strides)(y)
        y = _BN()(y, train).astype(self.dtype)
        y = conv(out_features, (1, 1))(y)
        return (y + residual).astype(self.dtype)


class ResNetV2(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int = 1000
    width: int = 64
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = nn.Conv(self.width, (7, 7), strides=(2, 2), padding=[(3, 3), (3, 3)],
                    use_bias=False, kernel_init=he_normal_fanout, dtype=self.dtype,
                    name="stem_conv")(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])
        for i, num_blocks in enumerate(self.stage_sizes):
            for j in range(num_blocks):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = PreActBottleneck(self.width * 2 ** i, strides=strides,
                                     dtype=self.dtype)(x, train=train)
        x = _BN()(x, train).astype(self.dtype)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32,
                     kernel_init=nn.initializers.normal(0.01), name="head")(x)
        return x.astype(jnp.float32)


MODELS.register("resnet50v2", partial(ResNetV2, stage_sizes=(3, 4, 6, 3)))
