"""Vision Transformer (Dosovitskiy et al. 2021, "An Image is Worth 16x16
Words"), the first non-ConvNet family in the zoo.

Patchify (strided Conv) → learned cls token + position embedding → pre-LN
transformer encoder → LayerNorm → f32 classification head.  The attention hot
path dispatches through `ops.attention.attention`: `attention_impl="auto"`
picks the Pallas flash kernel on TPU and the naive einsum lowering elsewhere;
"fused"/"interpret"/"naive" pin it (tests trace both lowerings — see
docs/ATTENTION.md for the fallback matrix).

QKV/out/MLP projections are explicit `nn.Dense` layers so the int8 PTQ
planner's weight provenance survives (per-out-channel scales cover per-head:
the out axis is heads × head_dim).  The head runs in f32 like every other
family (`serving_head_dims` keys off num_classes — internal dims must not
collide, so embed/mlp/seq dims avoid 10 and 1000).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from ..ops.attention import attention
from ..utils.registry import MODELS


class MultiHeadAttention(nn.Module):
    """Self-attention with explicit Q/K/V/out Dense projections."""

    num_heads: int
    attention_impl: str = "auto"
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        b, n, c = x.shape
        h = self.num_heads
        d = c // h

        def split(y):
            return y.reshape(b, n, h, d).transpose(0, 2, 1, 3)

        q = split(nn.Dense(c, dtype=self.dtype, name="query")(x))
        k = split(nn.Dense(c, dtype=self.dtype, name="key")(x))
        v = split(nn.Dense(c, dtype=self.dtype, name="value")(x))
        out = attention(q, k, v, impl=self.attention_impl)
        out = out.transpose(0, 2, 1, 3).reshape(b, n, c)
        return nn.Dense(c, dtype=self.dtype, name="out")(out)


class EncoderBlock(nn.Module):
    """Pre-LN transformer block: x + MHA(LN(x)); x + MLP(LN(x))."""

    num_heads: int
    mlp_dim: int
    dropout_rate: float = 0.0
    attention_impl: str = "auto"
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        y = nn.LayerNorm(dtype=self.dtype, name="ln_attn")(x)
        y = MultiHeadAttention(self.num_heads, self.attention_impl,
                               self.dtype, name="attn")(y)
        y = nn.Dropout(self.dropout_rate)(y, deterministic=not train)
        x = x + y
        y = nn.LayerNorm(dtype=self.dtype, name="ln_mlp")(x)
        y = nn.Dense(self.mlp_dim, dtype=self.dtype, name="mlp_in")(y)
        y = nn.gelu(y)
        y = nn.Dense(x.shape[-1], dtype=self.dtype, name="mlp_out")(y)
        y = nn.Dropout(self.dropout_rate)(y, deterministic=not train)
        return x + y


@MODELS.register("vit")
class ViT(nn.Module):
    num_classes: int = 10
    patch_size: int = 8
    embed_dim: int = 192
    depth: int = 4
    num_heads: int = 3
    mlp_dim: int = 768
    dropout_rate: float = 0.0
    attention_impl: str = "auto"
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        b = x.shape[0]
        p = self.patch_size
        x = nn.Conv(self.embed_dim, (p, p), strides=(p, p), padding="VALID",
                    dtype=self.dtype, name="patch_embed")(x)
        x = x.reshape(b, -1, self.embed_dim)

        cls = self.param("cls_token", nn.initializers.zeros,
                         (1, 1, self.embed_dim))
        x = jnp.concatenate(
            [jnp.broadcast_to(cls.astype(self.dtype),
                              (b, 1, self.embed_dim)), x], axis=1)
        pos = self.param("pos_embed", nn.initializers.normal(stddev=0.02),
                         (1, x.shape[1], self.embed_dim))
        x = x + pos.astype(self.dtype)
        x = nn.Dropout(self.dropout_rate)(x, deterministic=not train)

        for i in range(self.depth):
            x = EncoderBlock(self.num_heads, self.mlp_dim, self.dropout_rate,
                             self.attention_impl, self.dtype,
                             name=f"block{i}")(x, train=train)

        x = nn.LayerNorm(dtype=self.dtype, name="norm")(x)
        x = x[:, 0].astype(jnp.float32)
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
