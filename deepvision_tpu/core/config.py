"""Typed training configs.

The reference's UX is ``python train.py -m <model> [-c <checkpoint>]`` with an in-file
config registry holding batch size / optimizer / scheduler / epochs per model name
(`ResNet/pytorch/train.py:26-215`, `ResNet/tensorflow/train.py:21-62`). We keep that
exact surface but as dataclasses, with hyperparameters paper-cited in the per-model
config modules.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

# Canonical ImageNet channel statistics in [0,1] units (torchvision
# convention) — the single source of truth for both host-side normalization
# (data/imagenet.py) and the on-device path (DataConfig.mean/std → the jitted
# step's input_norm).
IMAGENET_MEAN = (0.485, 0.456, 0.406)
IMAGENET_STD = (0.229, 0.224, 0.225)

# The detection/pose pipelines normalize to [-1,1] (x/127.5 - 1, the
# reference's convention `YOLO/tensorflow/preprocess.py:25`) — as mean/std in
# [0,1] units that is (0.5, 0.5): the on-device input_norm their steps use
# when the pipeline ships raw uint8 (`--device-normalize`).
UNIT_RANGE_NORM = ((0.5, 0.5, 0.5), (0.5, 0.5, 0.5))


def decode_image_size(image_size: int) -> int:
    """Host decode/resize target for the device-augment path
    (`data/device_augment.py`): the reference's Rescale(256) -> crop(224)
    headroom ratio, floored to at least one spare pixel so RandomCrop has
    offsets to draw. 224 -> 256; the single source of truth shared by the
    host decode-only loaders, the trainer's calibration batch, the synthetic
    uint8 generator, and bench_input.py — mismatched sizes would surface as
    an in-step crop shape error."""
    return max(image_size + 1, (image_size * 256) // 224)


@dataclasses.dataclass
class OptimizerConfig:
    name: str = "sgd"               # sgd | momentum | rmsprop | adam | adamw
    learning_rate: float = 0.1
    momentum: float = 0.9
    nesterov: bool = False
    weight_decay: float = 0.0       # decoupled (adamw) or L2-coupled (sgd) per optimizer
    beta1: float = 0.9
    beta2: float = 0.999
    eps: float = 1e-8
    rmsprop_decay: float = 0.9
    grad_clip_norm: Optional[float] = None
    # Linear LR scaling (Goyal et al. 2017): when set, the effective LR is
    # learning_rate * global_batch / base_batch_size — the large-batch recipe
    # the 75.3% north star needs at pod batch sizes (BASELINE.md). None keeps
    # the configured LR verbatim (reference semantics at batch 256).
    base_batch_size: Optional[int] = None
    # Gradient accumulation: average grads over k micro-batches before one
    # optimizer update, making effective global batch = batch_size * k. Lets a
    # single chip reproduce the reference's multi-GPU global batches (e.g. the
    # 8-GPU batch-512 ResNet-34 run, `ResNet/pytorch/README.md:47`) — a
    # capability absent from the reference itself (SURVEY.md §2.8). The LR
    # schedule ticks once per applied update, and linear LR scaling uses the
    # effective batch. Note BN statistics remain per-micro-batch.
    accum_steps: int = 1
    # Skip weight decay on 1-D params (BatchNorm scale/bias, conv/dense
    # biases) — the "no bias decay" rule of the large-batch recipe (Goyal et
    # al. 2017 §5.3; He et al. 2019 bag-of-tricks), part of closing the gap
    # to the 75.3% north star. False keeps the reference's torch.optim.SGD
    # semantics, which decay every parameter (ResNet/pytorch/train.py:141-164).
    no_decay_bn_bias: bool = False


@dataclasses.dataclass
class ScheduleConfig:
    name: str = "constant"          # constant | step | cosine | plateau | linear_decay
    warmup_epochs: float = 0.0
    # step schedule (reference MultiStepLR / StepLR, ResNet/pytorch/train.py:141-164)
    boundaries_epochs: Tuple[float, ...] = ()
    decay_factor: float = 0.1
    # plateau (reference ReduceLROnPlateau, ResNet/pytorch/train.py:171-176 and
    # the hand-rolled YOLO variant YOLO/tensorflow/train.py:56-68) — host-driven.
    plateau_patience: int = 2
    plateau_factor: float = 0.1
    plateau_mode: str = "max"       # watch val top-1 ('max') or val loss ('min')
    min_lr: float = 0.0
    # linear_decay (CycleGAN/tensorflow/utils.py:5-28)
    decay_start_epoch: int = 100


@dataclasses.dataclass
class DataConfig:
    dataset: str = "synthetic"
    data_dir: str = ""
    image_size: int = 224
    channels: int = 3               # input channels (1 for MNIST-family)
    num_classes: int = 1000
    train_examples: int = 1281167   # hard-coded in the reference: ResNet/tensorflow/train.py:223
    val_examples: int = 50000
    shuffle_buffer: int = 10000
    num_parallel_calls: int = 16    # reference num_workers=16, ResNet/pytorch/train.py:229
    cache_val: bool = False
    # Ship raw uint8 pixels to the device and normalize ((x/255-mean)/std)
    # inside the jitted step instead of on the host: 4x less host->device
    # traffic — the bandwidth lever for input-bound pods (SURVEY.md §7.2.1).
    # Supported by the TFRecord ImageNet pipeline (`--device-normalize`).
    normalize_on_device: bool = False
    # channel mean/std in [0,1] units; both the host pipeline and the
    # on-device normalization read these, so overriding them affects the two
    # modes identically
    mean: Tuple[float, ...] = IMAGENET_MEAN
    std: Tuple[float, ...] = IMAGENET_STD


@dataclasses.dataclass
class TrainConfig:
    name: str = "model"
    model: str = "resnet50"
    # Trainer family this config trains under: classification | detection |
    # pose | centernet | gan. Carried on the config itself so generic tools
    # (preflight, verify_mesh) resolve the right train step without a
    # hand-maintained name→trainer map that can drift from the registry.
    family: str = "classification"
    model_kwargs: Dict[str, Any] = dataclasses.field(default_factory=dict)
    batch_size: int = 256           # global batch
    eval_batch_size: Optional[int] = None
    total_epochs: int = 100
    optimizer: OptimizerConfig = dataclasses.field(default_factory=OptimizerConfig)
    schedule: ScheduleConfig = dataclasses.field(default_factory=ScheduleConfig)
    data: DataConfig = dataclasses.field(default_factory=DataConfig)
    loss: str = "softmax_xent"
    label_smoothing: float = 0.0    # absent from the reference; needed for the 75.3% bar
    aux_loss_weight: float = 0.3    # GoogLeNet aux heads (fixes reference's latent gap,
                                    # Inception/pytorch/models/inception_v1.py:112-113)
    dtype: str = "bfloat16"         # compute dtype on MXU; params stay f32
    seed: int = 0
    log_every_steps: int = 10       # reference prints every 10 batches, train.py:472
    checkpoint_dir: str = "checkpoints"
    keep_checkpoints: int = 3
    keep_best: bool = True          # save-best policy, YOLO/tensorflow/train.py:244-246
    model_parallel: int = 1
    spatial_parallel: int = 1       # shard activations along H over a 'spatial'
                                    # mesh axis (context parallelism for big
                                    # resolutions; GSPMD halo-exchanges convs)
    # Who owns the spatial-partitioning semantics when spatial_parallel > 1:
    # "gspmd" (default) lets the XLA partitioner insert the halo exchanges —
    # exact on (data, spatial) meshes, but combined spatial x model meshes
    # need the measured grad calibration and some models are refused;
    # "shard_map" uses explicit collectives (parallel/spatial_shard.py):
    # ppermute halos, synced BN, one controlled psum — exact on combined
    # meshes with NO calibration step (supported: ResNet family, CenterNet).
    spatial_backend: str = "gspmd"
    remat: bool = False             # jax.checkpoint the forward: recompute
                                    # activations in backward, trading ~1/3 more
                                    # FLOPs for HBM (big batches / deep stacks)
    # Exponential moving average of params (Polyak averaging): validation and
    # best-model selection use ema = d*ema + (1-d)*params instead of the raw
    # weights. Absent from the reference — part of the modern large-batch
    # recipe (typical d: 0.999-0.9999). None disables (reference semantics).
    ema_decay: Optional[float] = None
    # Mixup (Zhang et al. 2018, classification only, absent from the
    # reference): per-step lam ~ Beta(a, a) blends the batch with a
    # permutation of itself on device. 0 disables (reference semantics);
    # typical a: 0.1-0.4.
    mixup_alpha: float = 0.0
    # CutMix (Yun et al. 2019, classification only): paste a random box from
    # the permuted batch instead of blending pixels; lam = exact kept-pixel
    # fraction. Mutually exclusive with mixup_alpha. Typical a: 1.0.
    cutmix_alpha: float = 0.0
    # Device-side augmentation (data/device_augment.py, classification only):
    # the host pipeline decodes + resizes to decode_image_size(image_size)
    # and ships RAW uint8 NHWC (~4x less host->device traffic than the f32
    # path); RandomCrop/flip/ColorJitter/normalize run batched INSIDE the
    # jitted train step, driven by per-step PRNG keys folded from
    # TrainState.step (seed-reproducible like mixup). Eval center-crops +
    # normalizes on device, matching the host eval_transform exactly.
    # Subsumes data.normalize_on_device (the augment normalizes; the step's
    # input_norm is disabled so the two never double-normalize). CLI:
    # --device-augment / --no-device-augment; docs/INPUT_PIPELINE.md.
    device_augment: bool = False
    # Log the global L2 gradient norm as a per-step metric (`grad_norm` in
    # JSONL/TensorBoard) — divergence forensics to pair with the halt below
    # and the data for choosing grad_clip_norm. Off by default: it's one
    # fused reduction per step, but also one more scalar in every log line.
    # Under gradient accumulation this is the PER-MICRO-BATCH norm (larger
    # and noisier than the k-step-averaged gradient the optimizer — and
    # clip_by_global_norm — actually consumes); scale thresholds accordingly.
    log_grad_norm: bool = False
    # Halt with TrainingDivergedError when an epoch's mean train loss comes
    # back non-finite (NaN/inf): the optimizer state is poisoned and further
    # steps waste pod-hours. The error names the last committed checkpoint to
    # resume from. False trains on regardless (the reference's behavior).
    halt_on_nonfinite: bool = True
    # Host->device staging depth for training batches: a producer thread
    # device_puts up to this many batches ahead so the transfer of batch i+1
    # overlaps compute of batch i (parallel/prefetch.py). 1 disables the
    # thread (inline staging). HBM cost: up to this many extra batches.
    prefetch_batches: int = 2
    # Divergence auto-recovery (core/resilience.py): when an epoch's mean
    # loss goes non-finite, instead of ONLY halting, roll back to the last
    # committed checkpoint, scale the LR down by recovery_lr_factor, and
    # retry — up to this many times per run, after which the existing
    # TrainingDivergedError halt (with its resume hint) fires. 0 keeps the
    # halt-only behavior. Requires halt_on_nonfinite (detection is the
    # trigger) and at least one committed checkpoint to roll back to.
    recover_on_divergence: int = 0
    # Multiplied into the host-side LR scale on every divergence rollback
    # (composes with the plateau schedule's scale; persists for the rest of
    # the run — a blown-up run that needed a lower LR keeps it).
    recovery_lr_factor: float = 0.5
    # Checkpoint-integrity mode when restoring (-c / --auto-resume /
    # divergence rollback): "fallback" (default) verifies the epoch's
    # integrity manifest and, on corruption, quarantines it
    # (corrupt-<epoch>/) and resumes from the next-newest generation that
    # verifies; "strict" raises CheckpointCorruptionError instead of
    # falling back; "off" restores blindly (pre-integrity behavior).
    # Legacy run dirs with no manifests restore with a warning in every
    # mode. The CLI exposes --resume {strict,fallback}; docs/FAILURES.md.
    resume_verify: str = "fallback"
    # In-process step watchdog (resilience.StepWatchdog): abort with
    # diagnostics (last step, last checkpoint epoch, prefetch queue depth +
    # all-thread stacks) when no train step completes for this many seconds.
    # None = off (the default — pytest's CPU compiles would trip any useful
    # threshold); the CLI exposes --watchdog-secs / DEEPVISION_WATCHDOG_SECS.
    watchdog_secs: Optional[float] = None
    # Install SIGTERM/SIGINT handlers for the duration of fit(): finish the
    # in-flight step, commit a synchronous checkpoint, exit 0 with the
    # resume hint (resilience.GracefulShutdown). Complements — never
    # replaces — the SIGKILL atomicity guarantee (tests/test_preemption.py).
    graceful_shutdown: bool = True
    # Device-side step batching: run k train steps per host dispatch via
    # lax.scan (steps.make_multistep_train_step). Amortizes per-step
    # dispatch/launch latency — the lever for dispatch-bound setups (relayed
    # TPUs, tiny models, very fast chips); MaxText-style. Metrics surface
    # once per dispatch as the k-step mean; EMA advances per scanned step
    # (same cadence as k=1); incompatible with accum_steps > 1 (the scan
    # would desync the EMA/accumulation alignment). HBM cost: k staged
    # batches per dispatch.
    steps_per_dispatch: int = 1
    # Whole-epoch on-device training (data/device_cache.py +
    # steps.make_epoch_train_step): stage the full epoch device-resident
    # once and run ONE lax.scan dispatch per epoch — zero host round-trips,
    # the endpoint of the dispatch-amortization axis steps_per_dispatch
    # starts (r05 showed dispatch, not FLOPs, is the off-chip lever).
    # Requires epoch-stationary data (the cache replays the first epoch's
    # stream; per-epoch variety comes from epoch_shuffle + the per-(seed,
    # step) augment draws); datasets that don't fit the HBM budget fall
    # back to the staged path with a named EpochCacheOverflowWarning.
    # Checkpoint/metrics flushes happen at the scan boundary (one host sync
    # per epoch). Incompatible with steps_per_dispatch > 1 (pick one lever)
    # and accum_steps > 1. CLI: --epoch-on-device; docs/INPUT_PIPELINE.md.
    epoch_on_device: bool = False
    # Per-epoch reshuffle for the on-device epoch: a device-side permutation
    # of the example axis folded from (seed, epoch) — the deterministic
    # replacement for the host pipelines' reshuffle, reproducible across
    # resumes. Off = replay the cached order every epoch (parity testing).
    epoch_shuffle: bool = True

    def donate_step(self) -> bool:
        """Whether a family's single train step may donate its state: only
        when the step IS the dispatch unit. Under steps_per_dispatch > 1 or
        the whole-epoch scan the wrapper donates at the outer jit instead —
        inner donation cannot apply inside the scanned trace."""
        return self.steps_per_dispatch == 1 and not self.epoch_on_device

    def replace(self, **kw) -> "TrainConfig":
        return dataclasses.replace(self, **kw)
