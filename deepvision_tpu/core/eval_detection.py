"""Detection accuracy evaluation: per-class AP, VOC mAP@0.5, COCO mAP@[.5:.95].

The reference never shipped a mAP evaluator — YOLO's README lists it as "work in
progress" (`YOLO/tensorflow/README.md:29`) and verification was visual via
`demo_mscoco.ipynb`. This module closes that gap with the standard protocols:

- greedy score-ordered matching of detections to ground truth at an IoU threshold,
  each GT matched at most once (PASCAL VOC devkit semantics);
- AP as either the interpolated 11-point mean (VOC2007) or the area under the
  monotone precision envelope (VOC2010+/COCO, "all-point");
- COCO-style mAP averaged over IoU thresholds 0.50:0.05:0.95.

Evaluation is offline/host-side, so this is plain numpy — accumulation streams
per-image without holding images in memory. Device work (the model forward + NMS)
stays in `ops/nms.py`; this consumes its fixed-shape padded outputs directly via
`add_batch`.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

COCO_IOU_THRESHOLDS = tuple(np.arange(0.5, 1.0, 0.05).round(2).tolist())


def np_iou_matrix(boxes_a: np.ndarray, boxes_b: np.ndarray,
                  crowd_b: Optional[np.ndarray] = None) -> np.ndarray:
    """Pairwise IoU of corner boxes: (N,4) x (M,4) -> (N,M).

    Columns of `boxes_b` flagged in `crowd_b` use intersection-over-DET-area
    instead of intersection-over-union — pycocotools' iscrowd convention
    (a detection fully inside a crowd region scores 1 regardless of the
    crowd's extent)."""
    if boxes_a.size == 0 or boxes_b.size == 0:
        return np.zeros((boxes_a.shape[0], boxes_b.shape[0]), np.float64)
    a = boxes_a[:, None, :]  # (N,1,4)
    b = boxes_b[None, :, :]  # (1,M,4)
    ix1 = np.maximum(a[..., 0], b[..., 0])
    iy1 = np.maximum(a[..., 1], b[..., 1])
    ix2 = np.minimum(a[..., 2], b[..., 2])
    iy2 = np.minimum(a[..., 3], b[..., 3])
    inter = np.clip(ix2 - ix1, 0, None) * np.clip(iy2 - iy1, 0, None)
    area_a = np.clip(a[..., 2] - a[..., 0], 0, None) * np.clip(a[..., 3] - a[..., 1], 0, None)
    area_b = np.clip(b[..., 2] - b[..., 0], 0, None) * np.clip(b[..., 3] - b[..., 1], 0, None)
    union = area_a + area_b - inter
    if crowd_b is not None and np.any(crowd_b):
        union = np.where(np.asarray(crowd_b, bool)[None, :],
                         np.broadcast_to(area_a, union.shape), union)
    return np.where(union > 0, inter / np.maximum(union, 1e-12), 0.0)


def average_precision(recall: np.ndarray, precision: np.ndarray,
                      mode: str = "area") -> float:
    """AP from a recall/precision curve (already sorted by ascending recall).

    mode="11point": VOC2007 interpolated mean of max-precision at r=0,0.1,...,1.
    mode="area": area under the monotonically-decreasing precision envelope
    (VOC2010+).
    mode="101point": the COCO protocol — the precision envelope sampled at
    recall thresholds 0:.01:1 (first curve point with recall >= threshold)
    and averaged; what pycocotools' accumulate() computes, and slightly
    different from the exact envelope area.
    """
    if recall.size == 0:
        return 0.0
    if mode == "11point":
        ap = 0.0
        for t in np.linspace(0.0, 1.0, 11):
            mask = recall >= t
            ap += (np.max(precision[mask]) if mask.any() else 0.0) / 11.0
        return float(ap)
    if mode == "101point":
        p = np.maximum.accumulate(precision[::-1])[::-1]
        inds = np.searchsorted(recall, np.linspace(0.0, 1.0, 101),
                               side="left")
        q = np.zeros(101)
        valid = inds < p.size
        q[valid] = p[inds[valid]]
        return float(q.mean())
    if mode != "area":
        raise ValueError(f"unknown AP mode {mode!r}")
    # envelope with sentinels, then sum rectangle areas where recall steps
    r = np.concatenate([[0.0], recall, [1.0]])
    p = np.concatenate([[0.0], precision, [0.0]])
    p = np.maximum.accumulate(p[::-1])[::-1]
    idx = np.where(r[1:] != r[:-1])[0]
    return float(np.sum((r[idx + 1] - r[idx]) * p[idx + 1]))


class DetectionEvaluator:
    """Streaming mAP accumulator.

    Feed per-image detections (any order) and ground truth; `summarize()` computes
    per-class AP at each IoU threshold and the VOC/COCO summary metrics. Boxes are
    corner-format (x1, y1, x2, y2) in any consistent coordinate space.
    """

    def __init__(self, num_classes: int,
                 iou_thresholds: Sequence[float] = (0.5,),
                 ap_mode: str = "area", match_mode: str = "voc",
                 max_dets: Optional[int] = None):
        if match_mode not in ("voc", "coco"):
            raise ValueError(f"unknown match_mode {match_mode!r}")
        self.num_classes = num_classes
        self.iou_thresholds = tuple(iou_thresholds)
        self.ap_mode = ap_mode
        self.match_mode = match_mode
        # top-k score cap per image per class before matching (pycocotools'
        # maxDets, 100 for the headline COCO metric); None = unlimited
        self.max_dets = max_dets
        # per image: dict with det boxes/scores/classes + gt boxes/classes/difficult
        self._images: List[dict] = []

    def add_image(self, det_boxes: np.ndarray, det_scores: np.ndarray,
                  det_classes: np.ndarray, gt_boxes: np.ndarray,
                  gt_classes: np.ndarray,
                  gt_difficult: Optional[np.ndarray] = None) -> None:
        det_boxes = np.asarray(det_boxes, np.float64).reshape(-1, 4)
        det_scores = np.asarray(det_scores, np.float64).reshape(-1)
        det_classes = np.asarray(det_classes, np.int64).reshape(-1)
        gt_boxes = np.asarray(gt_boxes, np.float64).reshape(-1, 4)
        gt_classes = np.asarray(gt_classes, np.int64).reshape(-1)
        if gt_difficult is None:
            gt_difficult = np.zeros(gt_boxes.shape[0], bool)
        self._images.append(dict(
            det_boxes=det_boxes, det_scores=det_scores, det_classes=det_classes,
            gt_boxes=gt_boxes, gt_classes=gt_classes,
            gt_difficult=np.asarray(gt_difficult, bool).reshape(-1)))

    def add_batch(self, nms_boxes, nms_scores, nms_classes, valid_counts,
                  gt_boxes, gt_classes, gt_valid, gt_difficult=None) -> None:
        """Consume one batch of padded fixed-shape arrays straight from
        `ops.nms.batched_nms` output + the padded GT the pipeline carries.

        nms_classes may be (B,D,C) per-class probs (argmax taken) or (B,D) ids;
        gt_difficult is an optional (B,N) padded 0/1 array.
        """
        nms_boxes = np.asarray(nms_boxes)
        nms_scores = np.asarray(nms_scores)
        nms_classes = np.asarray(nms_classes)
        valid_counts = np.asarray(valid_counts).astype(int)
        gt_boxes = np.asarray(gt_boxes)
        gt_classes = np.asarray(gt_classes)
        gt_valid = np.asarray(gt_valid).astype(bool)
        if gt_difficult is not None:
            gt_difficult = np.asarray(gt_difficult).astype(bool)
        if nms_classes.ndim == 3:
            nms_classes = np.argmax(nms_classes, axis=-1)
        for i in range(nms_boxes.shape[0]):
            n = valid_counts[i]
            m = gt_valid[i]
            self.add_image(nms_boxes[i, :n], nms_scores[i, :n],
                           nms_classes[i, :n], gt_boxes[i][m], gt_classes[i][m],
                           None if gt_difficult is None else gt_difficult[i][m])

    def _gather_class(self, cls: int):
        """Per-image detections/GT for one class, with score-sorted detections
        and the (threshold-independent) IoU matrix computed ONCE — matching at
        each IoU threshold then reuses these.

        Returns (per_image list of (scores_sorted, iou_sorted, difficult),
        n_positives).
        """
        per_image = []
        n_pos = 0
        for img in self._images:
            det_mask = img["det_classes"] == cls
            gt_mask = img["gt_classes"] == cls
            gt = img["gt_boxes"][gt_mask]
            difficult = img["gt_difficult"][gt_mask]
            n_pos += int((~difficult).sum())
            det = img["det_boxes"][det_mask]
            sc = img["det_scores"][det_mask]
            if det.shape[0] == 0 and gt.shape[0] == 0:
                continue
            order = np.argsort(-sc, kind="stable")
            if self.max_dets is not None:
                order = order[:self.max_dets]
            # coco mode scores crowd GT by intersection/det-area (iscrowd)
            crowd = difficult if self.match_mode == "coco" else None
            per_image.append((sc[order],
                              np_iou_matrix(det[order], gt, crowd_b=crowd),
                              difficult))
        return per_image, n_pos

    def _match_at_iou(self, per_image, n_pos: int, iou_thresh: float):
        """Greedy matching at one threshold → (ap, n_pos).

        match_mode="voc" — PASCAL devkit semantics: each detection (descending
        score) takes the argmax-IoU ground truth over ALL GT of its class; if
        IoU ≥ threshold and that GT is difficult → ignored, taken → FP, else
        TP. No reassignment to the next-best GT.

        match_mode="coco" — pycocotools `evaluateImg` semantics: each
        detection (descending score) takes the best-IoU ground truth among
        the still-unmatched REAL GT; only if none clears the threshold may
        it fall back to a crowd/ignore GT (detection then ignored, and the
        crowd stays matchable by later detections — `gtm[gind]>0 and not
        iscrowd[gind]` is pycocotools' skip rule). Crowd IoU is
        intersection-over-det-area (`_gather_class`).
        """
        scores, matches = [], []
        for sc, iou, difficult in per_image:
            taken = np.zeros(iou.shape[1], bool)
            for d in range(sc.shape[0]):
                scores.append(sc[d])
                if iou.shape[1] == 0:
                    matches.append(0)
                    continue
                if self.match_mode == "voc":
                    best = int(np.argmax(iou[d]))
                    if iou[d, best] >= iou_thresh:
                        if difficult[best]:
                            matches.append(-1)  # neither TP nor FP
                        elif not taken[best]:
                            taken[best] = True
                            matches.append(1)
                        else:
                            matches.append(0)  # GT already claimed → FP
                    else:
                        matches.append(0)
                else:  # coco: best still-unmatched real GT, crowd fallback
                    real = np.where(difficult | taken, -1.0, iou[d])
                    best = int(np.argmax(real))
                    if real[best] >= iou_thresh:
                        taken[best] = True
                        matches.append(1)
                        continue
                    ign = np.where(difficult, iou[d], -1.0)  # never 'taken'
                    if ign[int(np.argmax(ign))] >= iou_thresh:
                        matches.append(-1)  # matched crowd GT → ignored
                    else:
                        matches.append(0)
        if n_pos == 0:
            return float("nan"), 0
        matches = np.asarray(matches)[np.argsort(-np.asarray(scores),
                                                 kind="stable")]
        matches = matches[matches != -1]
        tp = np.cumsum(matches == 1)
        fp = np.cumsum(matches == 0)
        recall = tp / n_pos
        precision = tp / np.maximum(tp + fp, 1)
        return average_precision(recall, precision, self.ap_mode), n_pos

    def summarize(self) -> Dict[str, float]:
        """Compute summary metrics.

        Returns {"mAP@<t>": ..., "mAP": mean over thresholds, plus
        "AP@<t>/class<i>" per class with ground truth}. Classes absent from the
        ground truth are excluded from the means (NaN AP).
        """
        out: Dict[str, float] = {}
        thresh_aps: Dict[float, list] = {t: [] for t in self.iou_thresholds}
        for c in range(self.num_classes):
            per_image, n_pos = self._gather_class(c)
            if n_pos == 0:
                continue
            for t in self.iou_thresholds:
                ap, _ = self._match_at_iou(per_image, n_pos, t)
                out[f"AP@{t:g}/class{c}"] = ap
                thresh_aps[t].append(ap)
        per_thresh = []
        for t in self.iou_thresholds:
            m = float(np.mean(thresh_aps[t])) if thresh_aps[t] else 0.0
            out[f"mAP@{t:g}"] = m
            per_thresh.append(m)
        out["mAP"] = float(np.mean(per_thresh)) if per_thresh else 0.0
        return out


def make_evaluator(metric: str, num_classes: int) -> "DetectionEvaluator":
    """Dispatch on the metric name shared by every detector family's eval CLI:
    "coco" → mAP@[.5:.95], "voc" → all-point mAP@0.5, "voc07" → 11-point."""
    if metric == "coco":
        return coco_evaluator(num_classes)
    if metric in ("voc", "voc07"):
        return voc_evaluator(num_classes, use_07_metric=(metric == "voc07"))
    raise ValueError(f"unknown metric {metric!r}")


def coco_evaluator(num_classes: int) -> DetectionEvaluator:
    """mAP@[.5:.95] evaluator reproducing pycocotools' headline metric
    exactly: its matching (crowd fallback + reassignment), its 101-point
    interpolated AP, and its maxDets=100 cap. Fuzz-verified against the real
    library in tests/test_eval_detection.py (importorskip) and against an
    independent loop-transcription oracle offline."""
    return DetectionEvaluator(num_classes, COCO_IOU_THRESHOLDS,
                              ap_mode="101point", match_mode="coco",
                              max_dets=100)


def voc_evaluator(num_classes: int, use_07_metric: bool = False) -> DetectionEvaluator:
    """mAP@0.5 evaluator (PASCAL VOC devkit matching; 11-point interpolation if
    use_07_metric)."""
    return DetectionEvaluator(num_classes, (0.5,),
                              ap_mode="11point" if use_07_metric else "area",
                              match_mode="voc")
