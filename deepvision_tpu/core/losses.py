"""Shared classification losses & metrics.

Semantics mirror the reference: CrossEntropyLoss (`ResNet/pytorch/train.py:358-360`),
top-1/top-5 accuracy (`ResNet/pytorch/train.py:524-538`,
`ResNet/tensorflow/train.py:217`), plus label smoothing (absent from the reference —
part of the modern recipe required to hit BASELINE.md's 75.3% bar) and properly
weighted GoogLeNet auxiliary losses (the reference never combined them — SURVEY.md §2.1).
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax.numpy as jnp
import optax


def per_example_xent(logits: jnp.ndarray, labels: jnp.ndarray,
                     label_smoothing: float = 0.0) -> jnp.ndarray:
    """Per-example softmax cross-entropy over integer labels, shape (batch,)."""
    num_classes = logits.shape[-1]
    onehot = optax.smooth_labels(
        jnp.eye(num_classes, dtype=jnp.float32)[labels], label_smoothing)
    return optax.softmax_cross_entropy(logits.astype(jnp.float32), onehot)


def softmax_xent(logits: jnp.ndarray, labels: jnp.ndarray,
                 label_smoothing: float = 0.0) -> jnp.ndarray:
    """Mean softmax cross-entropy over integer labels."""
    return per_example_xent(logits, labels, label_smoothing).mean()


def classification_loss(outputs, labels, label_smoothing: float = 0.0,
                        aux_weight: float = 0.3) -> jnp.ndarray:
    """Main + weighted auxiliary-head loss.

    `outputs` is either logits or a (main, aux1, aux2, ...) tuple as produced by
    Inception V1 in train mode (reference returns the tuple but never sums it:
    `Inception/pytorch/models/inception_v1.py:112-113`; GoogLeNet paper weights the
    aux classifiers by 0.3).
    """
    if isinstance(outputs, (tuple, list)):
        main, *aux = outputs
        loss = softmax_xent(main, labels, label_smoothing)
        for a in aux:
            loss = loss + aux_weight * softmax_xent(a, labels, label_smoothing)
        return loss
    return softmax_xent(outputs, labels, label_smoothing)


def topk_accuracies(logits: jnp.ndarray, labels: jnp.ndarray,
                    ks: Sequence[int] = (1, 5)) -> dict:
    """Top-k accuracy fractions (reference `accuracy()`,
    ResNet/pytorch/train.py:524-538)."""
    if isinstance(logits, (tuple, list)):
        logits = logits[0]
    k_max = min(max(ks), logits.shape[-1])
    top = jnp.argsort(logits, axis=-1)[..., ::-1][..., :k_max]
    correct = top == labels[..., None]
    out = {}
    for k in ks:
        kk = min(k, logits.shape[-1])
        out[f"top{k}"] = correct[..., :kk].any(axis=-1).mean()
    return out


def topk_correct(logits: jnp.ndarray, labels: jnp.ndarray,
                 ks: Sequence[int] = (1, 5)) -> dict:
    """Per-example top-k correctness indicators (batch,) — for masked eval sums."""
    if isinstance(logits, (tuple, list)):
        logits = logits[0]
    k_max = min(max(ks), logits.shape[-1])
    top = jnp.argsort(logits, axis=-1)[..., ::-1][..., :k_max]
    correct = top == labels[..., None]
    return {f"top{k}": correct[..., :min(k, logits.shape[-1])].any(axis=-1)
            .astype(jnp.float32) for k in ks}
