"""Pose accuracy evaluation: PCKh (percentage of correct keypoints).

The reference never shipped a pose metric — Hourglass verification was visual
(`Hourglass/tensorflow/demo_hourglass_pose.ipynb`, SURVEY.md §4). This module
adds the MPII standard: a predicted joint is correct when its distance to the
ground truth is below `threshold` × a per-person reference length.

MPII PCKh normalizes by the head-rectangle size; our TFRecords
(`Datasets/MPII/tfrecords_mpii.py:59-70`) carry joints but no head box, so the
reference length is the ground-truth head SEGMENT ‖head_top − upper_neck‖
(MPII joints 9 and 8) — the standard derivable approximation. Persons whose
head joints are missing are skipped. All coordinates normalized [0, 1]; pass
`aspect` if width ≠ height so distances are isotropic.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np

MPII_UPPER_NECK = 8
MPII_HEAD_TOP = 9

MPII_JOINT_NAMES = ["r_ankle", "r_knee", "r_hip", "l_hip", "l_knee", "l_ankle",
                    "pelvis", "thorax", "upper_neck", "head_top", "r_wrist",
                    "r_elbow", "r_shoulder", "l_shoulder", "l_elbow", "l_wrist"]


class PoseEvaluator:
    """Streaming PCKh accumulator over (pred, gt, visibility) keypoint sets."""

    def __init__(self, num_joints: int = 16,
                 thresholds: Sequence[float] = (0.5,),
                 head_joints: tuple = (MPII_UPPER_NECK, MPII_HEAD_TOP)):
        self.num_joints = num_joints
        self.thresholds = tuple(thresholds)
        self.head_joints = head_joints
        # per threshold: per-joint correct counts; shared per-joint totals
        self._correct = {t: np.zeros(num_joints) for t in self.thresholds}
        self._total = np.zeros(num_joints)

    def add_batch(self, pred_x, pred_y, gt_x, gt_y, visibility,
                  aspect: float = 1.0) -> None:
        """All arrays (B, K), coordinates in [0, 1]; visibility > 0 marks
        joints that exist (converter writes 0/2). `aspect` = width/height."""
        pred_x, pred_y, gt_x, gt_y = (np.asarray(a, np.float64)
                                      for a in (pred_x, pred_y, gt_x, gt_y))
        vis = np.asarray(visibility) > 0
        a, b = self.head_joints
        head = np.sqrt(((gt_x[:, a] - gt_x[:, b]) * aspect) ** 2 +
                       (gt_y[:, a] - gt_y[:, b]) ** 2)       # (B,)
        ok_person = vis[:, a] & vis[:, b] & (head > 1e-6)
        dist = np.sqrt(((pred_x - gt_x) * aspect) ** 2 + (pred_y - gt_y) ** 2)
        # joints counted only when the joint AND the head reference exist
        counted = vis & ok_person[:, None] & (gt_x >= 0) & (gt_y >= 0)
        self._total += counted.sum(axis=0)
        for t in self.thresholds:
            hit = counted & (dist <= t * head[:, None])
            self._correct[t] += hit.sum(axis=0)

    def summarize(self, joint_names: Optional[Sequence[str]] = None
                  ) -> Dict[str, float]:
        """{"PCKh@<t>": mean over joints with data, "PCKh@<t>/<joint>": ...}."""
        names = joint_names or MPII_JOINT_NAMES
        out: Dict[str, float] = {}
        for t in self.thresholds:
            per_joint = []
            for j in range(self.num_joints):
                if self._total[j] == 0:
                    continue
                v = float(self._correct[t][j] / self._total[j])
                label = names[j] if j < len(names) else f"joint{j}"
                out[f"PCKh@{t:g}/{label}"] = v
                per_joint.append(v)
            out[f"PCKh@{t:g}"] = float(np.mean(per_joint)) if per_joint else 0.0
        return out


def evaluate_pckh(state, batches, *, num_joints: int = 16,
                  thresholds: Sequence[float] = (0.5,)) -> Dict[str, float]:
    """Run the pose model over (images, kp_x, kp_y, visibility) batches and
    return PCKh metrics. Predictions come from the LAST stack's heatmaps
    (intermediate heads are train-time supervision only)."""
    import jax.numpy as jnp

    from ..ops.heatmap import decode_keypoints

    ev = PoseEvaluator(num_joints=num_joints, thresholds=thresholds)
    for images, kp_x, kp_y, vis in batches:
        outputs = state.apply_fn(
            {"params": state.params, "batch_stats": state.batch_stats},
            jnp.asarray(images), train=False)
        px, py, _ = decode_keypoints(outputs[-1])
        ev.add_batch(np.asarray(px), np.asarray(py), kp_x, kp_y, vis)
    return ev.summarize()
