"""Shared classification Trainer.

Replaces the reference's md5-copied per-model training loops
(`ResNet/pytorch/train.py:310-520` and its 5 copies; `ResNet/tensorflow/train.py:221-297`)
with one implementation: epoch loop → jitted SPMD train step over the mesh →
validation with top-1/top-5 → plateau/step/cosine LR → Orbax checkpoint with
keep-latest + keep-best → metrics logging. The per-model `train.py` entrypoints are
thin wrappers that build a TrainConfig and call `Trainer.fit()`.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import sys
import time
from typing import Any, Callable, Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import steps
from .checkpoint import CheckpointManager
from .config import TrainConfig
from .metrics import MeanAccumulator, MetricsLogger
from .optim import build_optimizer, set_lr_scale
from .resilience import (GracefulShutdown, PreemptionExit, RetryPolicy,
                         StepWatchdog, resilient_batches)
from .schedules import PlateauState
from .train_state import TrainState, init_model, make_ema_update, param_count
from ..parallel import mesh as mesh_lib
from ..parallel.prefetch import prefetch_to_device
from ..utils.faults import FaultInjector
from ..models import MODELS  # importing ..models registers the whole zoo


def _is_main_process() -> bool:
    return jax.process_index() == 0


def _timed_pulls(batches, tacc):
    """Iterate `batches` accumulating the host-blocking pull time into
    `tacc[0]` (ns) — the data-wait share of the per-window tracing spans
    (Trainer.arm_tracing). Only installed when tracing is armed."""
    it = iter(batches)
    while True:
        t = time.monotonic_ns()
        try:
            batch = next(it)
        except StopIteration:
            return
        tacc[0] += time.monotonic_ns() - t
        yield batch


class TrainingDivergedError(RuntimeError):
    """Raised when an epoch's mean train loss is non-finite (NaN/inf): the
    optimizer state is poisoned, so training on would only burn pod-hours.
    The reference's only gesture at this was skipping NaN val batches with a
    TODO (`Hourglass/tensorflow/train.py:126-130`). Here a divergent epoch
    first takes the auto-recovery path when it is enabled
    (`--recover-on-divergence N` / `TrainConfig.recover_on_divergence`):
    fit() rolls back to the last committed checkpoint, scales the LR down by
    `recovery_lr_factor`, and retries the epoch up to N times, logging each
    rollback to the `resilience_` stream (docs/FAILURES.md). Only when
    recovery is off — or its budget is exhausted — does this error halt the
    run loudly with the last committed checkpoint to resume from; and on the
    serving side the promotion gate (serve/promote.py) keeps any epoch such
    a run still managed to commit away from traffic."""


def divergence_halt(config, ckpt, epoch: int, what: str,
                    resume_cmd: str = "-c {last}"):
    """Raise TrainingDivergedError with the actionable remedy — shared by the
    supervised and adversarial trainers so the hint text can't drift.
    `resume_cmd` is the trainer family's resume UX ('{last}' substituted)."""
    last = ckpt.latest_epoch()
    resume = (f"resume from epoch {last} with `{resume_cmd.format(last=last)}`"
              if last is not None else "no checkpoint committed yet")
    raise TrainingDivergedError(
        f"[{config.name}] epoch {epoch} {what} — training diverged. "
        f"{resume}; consider a lower learning rate, warmup_epochs, or "
        f"grad_clip_norm. (Set halt_on_nonfinite=False to keep going anyway.)")


def fit_and_close(trainer, *args, **kwargs):
    """`trainer.fit(...)` then `close()`, with the entry-point divergence UX:
    a TrainingDivergedError becomes a one-line remedy + nonzero exit instead
    of a traceback. close() runs in a finally so buffered JSONL/TB metrics
    survive EVERY mid-fit exception (Ctrl-C, an OSError, a step failure) —
    those are exactly the runs whose forensics matter. Shared by the CLI and
    the GAN mains so the UX can't drift.

    A PreemptionExit (SIGTERM/SIGINT observed, checkpoint committed —
    resilience.GracefulShutdown) becomes the resume hint + exit 0: the
    platform asked the process to leave and it left cleanly."""
    try:
        return trainer.fit(*args, **kwargs)
    except TrainingDivergedError as e:
        raise SystemExit(f"error: {e}")
    except PreemptionExit as e:
        print(str(e), flush=True)
        raise SystemExit(0)
    finally:
        trainer.close()


def _accepts_kwarg(ctor, name: str) -> bool:
    import functools
    import inspect
    if isinstance(ctor, functools.partial):
        if name in ctor.keywords:
            return False  # already bound
        ctor = ctor.func
    try:
        params = inspect.signature(ctor).parameters
    except (TypeError, ValueError):
        return False
    return name in params or any(p.kind == inspect.Parameter.VAR_KEYWORD
                                 for p in params.values())


def build_model_from_config(config, *, num_classes_kwarg: str = "num_classes",
                            workdir: Optional[str] = None, verbose: bool = False):
    """Construct the Flax module a config describes — single source of truth
    for ctor-kwarg plumbing (model_kwargs + class-count + dtype injection),
    shared by Trainer and tools/summarize.py.

    A workdir can pin model kwargs (model_kwargs.json, written by
    tools/import_torch_checkpoint.py) so every later run builds the
    architecture the imported weights expect. Returns (model, config) with
    any pinned kwargs folded into the returned config."""
    pinned = os.path.join(workdir, "model_kwargs.json") if workdir else None
    if pinned and os.path.exists(pinned):
        with open(pinned) as fp:
            extra = json.load(fp)
        if extra:
            if verbose:
                print(f"[{config.name}] applying pinned model kwargs {extra}",
                      flush=True)
            config = config.replace(
                model_kwargs={**config.model_kwargs, **extra})
    model_ctor = MODELS.get(config.model)
    kwargs = dict(config.model_kwargs)
    # Guarded injection: some configs carry a class count their model ctor
    # doesn't take (e.g. dcgan's data.num_classes=10 labels MNIST, but the
    # generator is class-unconditional) — inject only when accepted.
    if config.data.num_classes and _accepts_kwarg(model_ctor, num_classes_kwarg):
        kwargs.setdefault(num_classes_kwarg, config.data.num_classes)
    if config.dtype and "dtype" not in kwargs and _accepts_kwarg(model_ctor, "dtype"):
        kwargs["dtype"] = jnp.dtype(config.dtype)
    return model_ctor(**kwargs), config


class Trainer:
    """Classification trainer: `fit(train_data, val_data)` where each dataset is an
    iterable of (images NHWC float32, labels int32) numpy batches per epoch."""

    # subclass override for the watched metric, e.g. ("loss", "min");
    # None → derived from the plateau config (top-1 max by default)
    default_watch = None
    # constructor kwarg that receives config.data.num_classes when the base
    # builds the model (pose models take num_heatmap instead) — subclasses
    # override the NAME rather than pre-building the model, so the workdir's
    # pinned model_kwargs.json applies to every family
    num_classes_kwarg = "num_classes"

    def __init__(self, config: TrainConfig, model=None,
                 mesh: Optional[Any] = None, workdir: Optional[str] = None):
        self.config = config
        self.workdir = workdir or config.checkpoint_dir
        self.mesh = mesh if mesh is not None else mesh_lib.make_mesh(
            model_parallel=config.model_parallel,
            spatial_parallel=config.spatial_parallel)

        if model is None:
            model, config = build_model_from_config(
                config, num_classes_kwarg=self.num_classes_kwarg,
                workdir=self.workdir, verbose=True)
            self.config = config
        self.model = model

        mesh_lib.check_batch_divisible(config.batch_size, self.mesh)
        if config.eval_batch_size:
            # classification eval pads partial batches, but the loss-watched
            # evaluate (detection/pose/centernet) shards without padding —
            # validate up front either way so the failure isn't a post-epoch
            # device_put error
            mesh_lib.check_batch_divisible(config.eval_batch_size, self.mesh,
                                           what="eval_batch_size")

        self.steps_per_epoch = max(
            1, config.data.train_examples // config.batch_size)
        opt_cfg = config.optimizer
        # effective global batch includes gradient accumulation (one optimizer
        # update sees batch_size * accum_steps examples); build_optimizer
        # rejects accum_steps < 1
        accum = opt_cfg.accum_steps
        effective_batch = config.batch_size * accum
        if accum > 1 and _is_main_process():
            print(f"[{config.name}] gradient accumulation: {accum} micro-steps "
                  f"-> effective batch {effective_batch}", flush=True)
        if opt_cfg.base_batch_size and effective_batch != opt_cfg.base_batch_size:
            scaled = opt_cfg.learning_rate * effective_batch / opt_cfg.base_batch_size
            if _is_main_process():
                print(f"[{config.name}] linear LR scaling: "
                      f"{opt_cfg.learning_rate} -> {scaled:g} "
                      f"(batch {effective_batch}/{opt_cfg.base_batch_size})",
                      flush=True)
            opt_cfg = dataclasses.replace(opt_cfg, learning_rate=scaled)
        self.tx = build_optimizer(opt_cfg, config.schedule,
                                  self.steps_per_epoch, config.total_epochs)

        if config.steps_per_dispatch > 1 and accum > 1:
            raise ValueError(
                "steps_per_dispatch > 1 is incompatible with accum_steps > 1 "
                "(the device-side scan would desync the EMA/accumulation "
                "cadence) — pick one lever")
        if config.epoch_on_device:
            if config.steps_per_dispatch > 1:
                raise ValueError(
                    "epoch_on_device and steps_per_dispatch > 1 are both "
                    "dispatch-amortization levers over the same scan — the "
                    "epoch scan already runs every step in one dispatch; "
                    "pick one")
            if accum > 1:
                raise ValueError(
                    "epoch_on_device is incompatible with accum_steps > 1 "
                    "(the epoch scan would desync the EMA/accumulation "
                    "cadence, same as steps_per_dispatch)")
            if config.spatial_backend == "shard_map":
                raise ValueError(
                    "epoch_on_device does not support "
                    "spatial_backend='shard_map' yet (scanning the manual-"
                    "collective step is untested on this jax); use the "
                    "gspmd backend")
        compute_dtype = jnp.dtype(config.dtype) if config.dtype else jnp.bfloat16
        input_norm = ((config.data.mean, config.data.std)
                      if config.data.normalize_on_device else None)
        # Device-side augmentation (data/device_augment.py): the pipeline
        # ships uint8 at decode_image_size and crop/flip/jitter/normalize run
        # inside the jitted step. Subsumes input_norm — the augment
        # normalizes, so input_norm is dropped here and the two can never
        # double-normalize (the step factories also reject the combination).
        self._train_augment = self._eval_augment = None
        if config.device_augment:
            # per-family capability policy lives with the augment code
            # (data/device_augment.py): families whose steps fuse the crop
            # inside the H-sharded forward are refused on spatial meshes;
            # segmentation augments BEFORE the H-shard and passes
            from ..data import device_augment as daug
            daug.check_spatial_capability(config.family,
                                          config.spatial_parallel)
            self._build_device_augment(compute_dtype)
            input_norm = None
        # A FACTORY, not just a step: on combined spatial×model meshes the
        # step must be rebuilt with the measured per-leaf grad correction
        # (mesh_lib.calibrate_grad_correction, run in init_state) — and the
        # calibration itself needs throwaway steps on other meshes.
        # Subclasses that install their own train_step must also install
        # the matching _step_factory (+ _calibration_batch).
        if config.spatial_backend not in ("gspmd", "shard_map"):
            raise ValueError(
                f"unknown spatial_backend {config.spatial_backend!r}; "
                f"expected 'gspmd' or 'shard_map'")
        if type(self) is Trainer and self._use_shardmap_spatial():
            # owned-semantics spatial path: explicit halo/psum collectives,
            # exact on combined spatial x model meshes with NO calibration
            # (parallel/spatial_shard.py; VERDICT r3 item 7). The explicit
            # type check keeps subclasses that OVERRIDE _use_shardmap_spatial
            # (CenterNetTrainer) from running this classification-specific
            # branch during base __init__ — they install their own factory.
            from ..parallel import spatial_shard
            if config.mixup_alpha > 0 or config.cutmix_alpha > 0:
                # mixup's pixel blend is row-local, but CutMix's pasted box
                # (and both variants' permutation of the batch axis) crosses
                # the spatial shards — keep these on the gspmd backend
                raise ValueError(
                    "spatial_backend='shard_map' does not support "
                    "mixup/cutmix yet; use the gspmd backend for those")
            transition = spatial_shard.default_transition(self.model)
            self._step_factory = (
                lambda m, corr: spatial_shard
                .make_shardmap_classification_train_step(
                    mesh=m, transition=transition,
                    label_smoothing=config.label_smoothing,
                    aux_weight=config.aux_loss_weight,
                    compute_dtype=compute_dtype, input_norm=input_norm,
                    log_grad_norm=config.log_grad_norm,
                    remat=config.remat,
                    donate=config.donate_step()))
        else:
            self._step_factory = lambda m, corr: steps.make_classification_train_step(
                label_smoothing=config.label_smoothing, aux_weight=config.aux_loss_weight,
                compute_dtype=compute_dtype, mesh=m,
                remat=config.remat, mixup_alpha=config.mixup_alpha,
                cutmix_alpha=config.cutmix_alpha, input_norm=input_norm,
                device_augment=self._train_augment,
                log_grad_norm=config.log_grad_norm,
                donate=config.donate_step(), grad_correction=corr)
        self.train_step = self._step_factory(self.mesh, None)
        # steps_per_dispatch > 1: built lazily on first epoch (train_epoch),
        # AFTER subclasses have installed their family's train_step
        self._multi_step = None
        # whole-epoch on-device path (config.epoch_on_device): the staged
        # cache, the scanned epoch step (built lazily like _multi_step), and
        # the sticky HBM-overflow fallback flag — once build_epoch_cache
        # refuses an epoch, the rest of the run stays on the staged path
        self._epoch_cache = None
        self._epoch_step = None
        self._epoch_fallback = False
        # host-side count of train dispatches (single steps, k-step scans,
        # and epoch scans each count 1): surfaces as train_dispatches_total
        # in the log_every flush so dispatch amortization is visible in
        # logs without a profiler, and bench_epoch.py reads it
        self._dispatches_total = 0
        # snapshot of the prefetcher's transfer ledger at the last staged
        # epoch's end (the live prefetcher is gone by then) — bench_epoch.py
        # reads the overlapped fraction from here
        self.last_prefetch_ledger: dict = {}
        self.eval_step = steps.make_classification_eval_step(
            compute_dtype=compute_dtype, mesh=self.mesh, input_norm=input_norm,
            device_augment=self._eval_augment)

        # Polyak averaging: eval/best-model use the EMA weights (config.ema_decay).
        # Under gradient accumulation the average must advance once per APPLIED
        # optimizer update, not per micro-batch (decay^k would shorten the
        # configured horizon k-fold) — _micro_count tracks MultiSteps' cycle.
        self.ema_update = (make_ema_update(config.ema_decay)
                           if config.ema_decay else None)
        self._micro_count = 0

        self.plateau = PlateauState(
            patience=config.schedule.plateau_patience,
            factor=config.schedule.plateau_factor,
            mode=config.schedule.plateau_mode,
        ) if config.schedule.name == "plateau" else None

        self.logger = MetricsLogger(self.workdir, name=config.name)

        # -- resilience state (core/resilience.py) --
        # env-driven deterministic fault injection (utils/faults.py; inert
        # when no DEEPVISION_FAULT_* is set) + transient-I/O retry policy
        # shared by checkpoint save/restore and host data iteration
        self.faults = FaultInjector.from_env()
        self.retry_policy = RetryPolicy.from_env()
        self._recovery_scale = 1.0   # product of recovery_lr_factor rollbacks
        self._recoveries = 0
        self._host_step = 0          # host-side step count (no device sync)
        self._last_saved_epoch: Optional[int] = None
        self._prefetcher = None      # live DevicePrefetcher during an epoch
        self._watchdog: Optional[StepWatchdog] = None
        self._shutdown: Optional[GracefulShutdown] = None
        # span tracing (obs/trace.py; armed by arm_tracing / --trace-out):
        # per log-window spans splitting host data wait vs device dispatch,
        # plus per-epoch checkpoint-commit spans. None = off — the step
        # loop pays one branch.
        self.tracer = None
        self._trace_out: Optional[str] = None

        self.rng = jax.random.PRNGKey(config.seed)
        self.state: Optional[TrainState] = None
        self.start_epoch = 1
        self.best_metric: Optional[float] = None
        # what fit() watches for best-model tracking and plateau decisions;
        # loss-watching subclasses declare `default_watch = ("loss", "min")`
        if self.default_watch is not None:
            self._set_watch(*self.default_watch)
        elif self.plateau and config.schedule.plateau_mode == "min":
            self._set_watch("loss", "min")
        else:
            self._set_watch("top1", "max")

    def _build_device_augment(self, compute_dtype) -> None:
        """Install this family's jitted device-augment stages on
        self._train_augment / self._eval_augment (called only when
        config.device_augment is set, AFTER the capability check). The base
        builds the classification single-tensor stages; SegmentationTrainer
        overrides with the paired image/mask factories
        (data/device_augment.make_paired_train_augment)."""
        from ..data import device_augment as daug
        config = self.config
        mean = daug.channel_stats(config.data.mean, config.data.channels)
        std = daug.channel_stats(config.data.std, config.data.channels)
        self._train_augment = daug.make_train_augment(
            config.data.image_size, mean=mean, std=std,
            compute_dtype=compute_dtype)
        self._eval_augment = daug.make_eval_augment(
            config.data.image_size, mean=mean, std=std,
            compute_dtype=compute_dtype)

    # Families with their own owned-collectives step set this True
    # (CenterNetTrainer, PoseTrainer, DetectionTrainer) instead of
    # re-implementing the opt-in predicate; a family WITHOUT one must
    # refuse the backend loudly at config-validation time with a
    # ValueError (the adversarial trainers' _validate_config is the
    # pattern) rather than training with silently wrong spatial semantics.
    has_own_shardmap_step = False

    def _use_shardmap_spatial(self) -> bool:
        """True when this trainer's spatial semantics are owned by
        parallel/spatial_shard.py instead of GSPMD (config.spatial_backend).
        The classification step lives on Trainer itself, hence the exact
        type check; subclasses opt in via has_own_shardmap_step."""
        return (self.config.spatial_backend == "shard_map"
                and mesh_lib.has_spatial(self.mesh)
                and (type(self) is Trainer or self.has_own_shardmap_step))

    def _set_watch(self, key: str, mode: str):
        """Set the watched metric + direction and (re)build the checkpoint
        manager's keep-best policy to match."""
        self.watch_key, self.watch_mode = key, mode
        if getattr(self, "ckpt", None) is not None:
            self.ckpt.close()
        self.ckpt = CheckpointManager(
            self.workdir + "/ckpt", keep=self.config.keep_checkpoints,
            keep_best=self.config.keep_best, best_mode=mode,
            retry_policy=self.retry_policy, on_retry=self._log_retry,
            fault_injector=self.faults if self.faults.active else None,
            # elastic resume (core/reshard.py): saves stamp this mesh into
            # the manifest, restores reshard checkpoints saved on another
            mesh=self.mesh)

    def _log_retry(self, what: str, attempt: int, exc: BaseException,
                   delay: float) -> None:
        """Retry hook for transient-I/O backoff (checkpoint save/restore and
        data iteration): every retry reaches stderr on every host and the
        metrics stream on process 0 — a flaky-storage epoch must leave
        forensics, not vanish into a silent sleep. May fire from the
        prefetch producer thread; MetricsLogger's append+flush is safe for
        that."""
        print(f"[{self.config.name}] transient {what} failure "
              f"(attempt {attempt}/{self.retry_policy.max_retries}): {exc} — "
              f"retrying in {delay:.2f}s", file=sys.stderr, flush=True)
        if _is_main_process() and getattr(self, "logger", None) is not None:
            self.logger.log(self._host_step,
                            {f"{what}_retries": float(attempt)},
                            prefix="resilience_", echo=False)

    # -- state ------------------------------------------------------------
    def init_state(self, sample_shape) -> TrainState:
        init_rng, self.rng = jax.random.split(self.rng)
        sample = jnp.zeros((2, *sample_shape), jnp.float32)
        params, batch_stats = init_model(self.model, init_rng, sample)
        state = TrainState.create(self.model.apply, params, self.tx, batch_stats,
                                  ema=self.ema_update is not None)
        # Replicate (or model-shard large tensors) across the mesh.
        rules = mesh_lib.param_sharding_rules(self.mesh, state.params)
        repl = mesh_lib.replicated(self.mesh)
        state = state.replace(
            params=jax.device_put(state.params, rules),
            batch_stats=jax.device_put(state.batch_stats, repl),
            opt_state=jax.device_put(state.opt_state, repl),
            ema_params=jax.device_put(
                state.ema_params,
                mesh_lib.param_sharding_rules(self.mesh, state.ema_params)),
            step=jax.device_put(state.step, repl),
        )
        self.state = state
        if _is_main_process():
            print(f"[{self.config.name}] model={self.config.model} "
                  f"params={param_count(params):,} "
                  f"mesh={dict(self.mesh.shape)} "
                  f"steps/epoch={self.steps_per_epoch}", flush=True)
        self._calibrate_grad_correction(sample_shape)
        return state

    _calibration_batch_size_override: Optional[int] = None

    def _calibration_batch_size(self) -> int:
        """Calibration batches shard on BOTH the target mesh and the
        all-device DP oracle mesh — pad the configured batch up to the total
        device count (a combined mesh's data axis is smaller than the device
        count, so small valid batch sizes need not divide it). The padded
        shape can differ from production; `_calibrate_grad_correction`
        re-verifies at the real batch via the override."""
        if self._calibration_batch_size_override is not None:
            return self._calibration_batch_size_override
        return mesh_lib.pad_to_multiple(self.config.batch_size,
                                        len(self.mesh.devices.flat))

    def _calibration_batch(self, sample_shape, seed: int = 0):
        """Synthetic batch matching this family's train_step contract, used
        to calibrate the combined-mesh grad correction (seed 0) and, with a
        DIFFERENT seed, as independent data for tools/verify_mesh.py's
        parity check. Subclasses with different batch tuples override."""
        rs = np.random.RandomState(seed)
        b = self._calibration_batch_size()
        if self.config.device_augment:
            # the step's input contract is uint8 at the decode (padded) size;
            # the jitted augment crops it down to sample_shape
            from .config import decode_image_size
            d = decode_image_size(sample_shape[0])
            images = rs.randint(
                0, 256, (b, d, d, sample_shape[-1])).astype(np.uint8)
        elif self.config.data.normalize_on_device:
            images = rs.randint(0, 256, (b, *sample_shape)).astype(np.uint8)
        else:
            images = rs.randn(b, *sample_shape).astype(np.float32)
        labels = rs.randint(0, self.config.data.num_classes,
                            size=(b,)).astype(np.int32)
        return (images, labels)

    def _calibrate_grad_correction(self, sample_shape) -> None:
        """On combined spatial×model meshes: measure the per-leaf gradient
        over-reduction of THIS model at THIS resolution/batch (GSPMD's
        spurious model-axis psum is per-op and context-dependent — see
        mesh_lib.calibrate_grad_correction) and rebuild train_step with the
        correction. Costs two extra compiles + two steps, once per init."""
        if self._use_shardmap_spatial():
            return  # owned collectives: grads exact by construction, no
                    # GSPMD spatial partitioning to calibrate around
        if not mesh_lib.needs_conv_grad_fix(self.mesh):
            return
        batch = self._calibration_batch(sample_shape)
        params0 = jax.device_get(self.state.params)
        bs0 = jax.device_get(self.state.batch_stats)

        def run(m, correction=None):
            return self._run_calibration_step(m, batch, params0, bs0,
                                              correction)

        correction = mesh_lib.calibrate_grad_correction(run, self.mesh)
        if correction is not None:
            self.train_step = self._step_factory(self.mesh, correction)
            self._multi_step = None  # rebuilt lazily from the corrected step
            if _is_main_process():
                n = sum(1 for f in jax.tree_util.tree_leaves(correction)
                        if f != 1.0)
                print(f"[{self.config.name}] combined-mesh grad calibration: "
                      f"{n} param leaves corrected", flush=True)
            self._verify_correction_at_production_batch(
                sample_shape, params0, bs0, correction)

    def _run_calibration_step(self, m, batch, params0, bs0, correction=None):
        """One seeded train step on mesh `m` from the given init with a fresh
        sgd(1.0) state: update == -grad, so per-leaf update norms measure
        grad norms (the real optimizer may be adam, whose first step is
        gradient-scale-invariant and would hide a rescale bug). Returns
        `(init_params, updated_params)` host pytrees."""
        import optax
        st = TrainState.create(self.model.apply, params0, optax.sgd(1.0), bs0)
        repl = mesh_lib.replicated(m)
        st = st.replace(
            params=jax.device_put(
                st.params, mesh_lib.param_sharding_rules(m, st.params)),
            batch_stats=jax.device_put(st.batch_stats, repl),
            opt_state=jax.device_put(st.opt_state, repl),
            step=jax.device_put(st.step, repl))
        step = self._step_factory(m, correction)
        sharded = mesh_lib.shard_batch_pytree(m, batch)
        new_state, _ = step(st, *sharded, jax.random.PRNGKey(0))
        return params0, jax.device_get(new_state.params)

    def _verify_correction_at_production_batch(self, sample_shape, params0,
                                               bs0, correction) -> None:
        """Calibration runs at a batch padded up to the total device count,
        which can differ from the production batch when batch_size is only
        divisible by the data axis. GSPMD's spurious psum is context-
        dependent ('THIS resolution/batch'), so measured factors might not
        transfer: run one CORRECTED step at the real batch shape on the
        target mesh and cross-check per-leaf update norms against a
        same-batch DP oracle restricted to data-axis-many devices. Costs two
        extra compiles, only when the padded shape differs."""
        b_real = self.config.batch_size
        data_axis = dict(self.mesh.shape)[mesh_lib.DATA_AXIS]
        if (b_real == self._calibration_batch_size()
                or b_real % data_axis != 0):
            return  # calibration already at production shape / unshardable
        n_proc = jax.process_count()
        if n_proc > 1 and (b_real % n_proc != 0
                           or data_axis % n_proc != 0):
            # b % n_proc: no per-host pipeline could feed that batch either.
            # data_axis % n_proc: the per-process slice below assumes the
            # data axis spans processes evenly — data_axis < n_proc means
            # some hosts hold the batch replicated and
            # make_array_from_process_local_data expects FULL rows from
            # them, not a slice.
            if _is_main_process():
                print(f"[{self.config.name}] grad correction: production-"
                      f"batch verify skipped — batch {b_real} / data axis "
                      f"{data_axis} do not shard evenly over {n_proc} "
                      f"processes", flush=True)
            return
        self._calibration_batch_size_override = b_real
        try:
            batch = self._calibration_batch(sample_shape)
        finally:
            self._calibration_batch_size_override = None
        # the TARGET step is collective — every process must enter it,
        # feeding its per-host slice of the seeded batch exactly like the
        # production pipelines do (shard_batch_pytree assembles the global
        # array in process order). The DP ORACLE's update is device-count
        # invariant — that is what data parallelism means — so on
        # multi-process runs the main process then runs it ALONE on its own
        # devices with the full batch (VERDICT r4 item 8: this used to be
        # skipped on pods, leaving the config class most exposed to the
        # padded-vs-production gap the one that couldn't verify).
        if n_proc > 1:
            rows = b_real // n_proc
            lo = jax.process_index() * rows
            pbatch = jax.tree_util.tree_map(
                lambda a: a[lo:lo + rows], batch)
        else:
            pbatch = batch
        target = self._run_calibration_step(self.mesh, pbatch, params0, bs0,
                                            correction)
        context = (f" (corrected step at production batch {b_real} on "
                   f"mesh {dict(self.mesh.shape)})")
        if n_proc > 1:
            verdict_err = None
            if _is_main_process():
                local = jax.local_devices()
                n_oracle = next(k for k in range(min(data_axis, len(local)),
                                                 0, -1) if b_real % k == 0)
                try:
                    oracle = self._run_calibration_step(
                        mesh_lib.make_mesh(local[:n_oracle]), batch,
                        params0, bs0)
                    mesh_lib.verify_update_parity(oracle, target,
                                                  context=context)
                except Exception as e:  # noqa: BLE001 — must reach the
                    verdict_err = e     # rendezvous below, whatever failed
            # every process rendezvouses on the verdict: without this a
            # main-process raise would leave the other hosts entering the
            # first train-step collective against a dead peer — a
            # distributed-timeout hang instead of a clean abort
            from jax.experimental import multihost_utils
            ok = bool(multihost_utils.broadcast_one_to_all(
                np.array(verdict_err is None)))
            if not ok:
                if verdict_err is not None:
                    raise verdict_err
                raise RuntimeError(
                    "grad-correction production-batch verify failed on the "
                    "main process (see its log); aborting this process too")
            if not _is_main_process():
                return
        else:
            oracle_mesh = mesh_lib.make_mesh(
                list(self.mesh.devices.flat)[:data_axis])
            oracle = self._run_calibration_step(oracle_mesh, batch, params0,
                                                bs0)
            mesh_lib.verify_update_parity(oracle, target, context=context)
        if _is_main_process():
            print(f"[{self.config.name}] grad correction verified at "
                  f"production batch {b_real}", flush=True)

    def resume(self, epoch: Optional[int] = None,
               verify: Optional[str] = None) -> Optional[int]:
        """Restore latest (or given) checkpoint — the `-c` / auto-resume UX
        (`ResNet/pytorch/train.py:552-557`, `YOLO/tensorflow/train.py:300-304`).

        `verify` overrides `config.resume_verify` (fallback/strict/off —
        core/checkpoint.py): by default a corrupt latest checkpoint is
        quarantined and the run resumes from the next-newest epoch that
        verifies instead of dying on an opaque deserialization error."""
        assert self.state is not None, "call init_state first"
        state, host, got = self.ckpt.restore(
            self.state, epoch,
            verify=verify if verify is not None else self.config.resume_verify)
        if got is None:
            return None
        self.state = state
        self.start_epoch = got + 1
        self.best_metric = host.get("best_metric")
        if self.plateau and "plateau" in host:
            p = host["plateau"]
            self.plateau.best = p.get("best")
            self.plateau.num_bad_epochs = p.get("num_bad_epochs", 0)
            self.plateau.scale = p.get("scale", 1.0)
            self.state = self.state.replace(
                opt_state=set_lr_scale(self.state.opt_state, self.plateau.scale))
        if self.ema_update is not None and hasattr(self.state.opt_state,
                                                   "mini_step"):
            # re-align the EMA cadence with MultiSteps' restored accumulation
            # cycle (a run can stop mid-cycle when accum doesn't divide
            # steps_per_epoch)
            self._micro_count = int(self.state.opt_state.mini_step)
        info = self.ckpt.last_restore_info or {}
        if _is_main_process() and (info.get("fallback_skipped")
                                   or not info.get("verified", False)):
            # corruption fallback / unverified (legacy) restore: forensics
            # belong in the metrics stream, not only on stderr
            self.logger.log(self._host_step,
                            {"ckpt_fallback_generations":
                                float(info.get("fallback_skipped") or 0),
                             "ckpt_verified":
                                1.0 if info.get("verified") else 0.0},
                            prefix="resilience_", echo=False)
        if _is_main_process() and info.get("resharded"):
            # elastic resume took the resharding path: the next save
            # re-stamps the CURRENT mesh, so later restores are native —
            # leave the one-time event in the metrics stream for forensics
            self.logger.log(self._host_step, {"ckpt_resharded": 1.0},
                            prefix="resilience_", echo=False)
        if _is_main_process():
            note = ""
            if info.get("resharded"):
                saved = info.get("saved_mesh") or {}
                note = (" (resharded from mesh "
                        f"{saved or 'unknown'} to {dict(self.mesh.shape)})")
            print(f"[{self.config.name}] resumed from epoch {got}{note}",
                  flush=True)
        return got

    # -- loops ------------------------------------------------------------
    def train_epoch(self, epoch: int, data: Iterable) -> dict:
        """One training epoch. Routes to the whole-epoch on-device scan when
        `config.epoch_on_device` is set (and the epoch fits HBM — the cache
        build falls back here with a named warning otherwise); every other
        configuration runs the staged per-batch loop."""
        if self.config.epoch_on_device and not self._epoch_fallback:
            return self._train_epoch_on_device(epoch, data)
        return self._train_epoch_staged(epoch, data)

    def _train_epoch_on_device(self, epoch: int, data: Iterable) -> dict:
        """The zero-round-trip epoch (ROADMAP item 2): stage the epoch
        device-resident once (`data/device_cache.py`), then ONE scanned
        dispatch per epoch (`steps.make_epoch_train_step`). The metrics
        fetch and the log flush are pinned to the scan boundary — a single
        host sync per epoch while the device is idle anyway, so the
        SYNC001 discipline (no sync in the hot loop) holds trivially: there
        is no hot host loop left."""
        from ..data import device_cache
        cfg = self.config
        if self._epoch_cache is None:
            # the first trained epoch's stream IS the cache (the mode's
            # epoch-stationarity contract); retry/fault wrapping matches
            # the staged path so flaky storage backs off identically
            src = resilient_batches(
                data, self.retry_policy,
                injector=self.faults if self.faults.active else None,
                on_retry=self._log_retry)
            cache, fallback = device_cache.build_epoch_cache(
                self.mesh, src, shuffle=cfg.epoch_shuffle, name=cfg.name)
            if cache is None:
                # named EpochCacheOverflowWarning already emitted; sticky —
                # the rest of the run trains through the staged path
                self._epoch_fallback = True
                return self._train_epoch_staged(epoch, fallback,
                                                wrapped=True)
            self._epoch_cache = cache
            if _is_main_process():
                print(f"[{cfg.name}] epoch cache: {cache.steps} steps x "
                      f"{cache.examples_per_step} examples device-resident "
                      f"({cache.nbytes / 1e6:.1f} MB staged once in "
                      f"{cache.stage_secs:.2f}s) — 1 dispatch/epoch"
                      + (", device shuffle per (seed, epoch)"
                         if cfg.epoch_shuffle else ""), flush=True)
        cache = self._epoch_cache
        if self._epoch_step is None:
            # lazily, like _multi_step: subclasses installed their family's
            # train_step after the base __init__ ran
            self._epoch_step = steps.make_epoch_train_step(
                self.train_step, cache.n_batch_args, mesh=self.mesh,
                ema_decay=cfg.ema_decay, shuffle=cfg.epoch_shuffle)
        t0 = time.time()
        step0 = int(self.state.step)  # device idle between epochs: cheap
        step_rng = jax.random.fold_in(self.rng, epoch)
        t_d = time.monotonic_ns()
        self.state, metrics = self._epoch_step(self.state, *cache.arrays,
                                               step_rng)
        jax.block_until_ready(self.state.params)
        dispatch_ns = time.monotonic_ns() - t_d
        self._dispatches_total += 1
        self._host_step = step0 + cache.steps
        if self._watchdog is not None:
            self._watchdog.beat()
        # scan-boundary flush: per-step metrics come back stacked (steps,)
        host = jax.device_get(metrics)
        out = {k: float(np.mean(v)) for k, v in host.items()}
        dt = time.time() - t0
        n_img = cache.steps * cache.examples_per_step
        out["images_per_sec"] = n_img / dt if dt > 0 else 0.0
        if self.tracer is not None:
            wid = self.tracer.add(
                "train_window", "train", t_d, dispatch_ns,
                args={"epoch": epoch, "steps": cache.steps,
                      **self._prefetch_stats()})
            self.tracer.add("train_dispatch", "train", t_d, dispatch_ns,
                            args={"window": wid, "aggregate": True})
        if _is_main_process():
            self.logger.log(self._host_step,
                            {**{k: v for k, v in out.items()
                                if k != "images_per_sec"},
                             **self._prefetch_stats()},
                            epoch=epoch, prefix="train_", echo=True)
        if cfg.halt_on_nonfinite and not np.isfinite(out.get("loss", 0.0)):
            if _is_main_process():
                self.logger.log(self._host_step, out, epoch=epoch,
                                prefix="epoch_train_")
            divergence_halt(cfg, self.ckpt, epoch,
                            f"mean train loss is {out['loss']}")
        return out

    def _train_epoch_staged(self, epoch: int, data: Iterable,
                            wrapped: bool = False) -> dict:
        """The staged per-batch loop: host batches -> double-buffered
        DevicePrefetcher -> per-step (or k-step scanned) dispatches.
        `wrapped=True` means `data` already passed through
        resilient_batches (the epoch-cache overflow fallback hands back a
        wrapped stream — wrapping twice would double-fire injected
        faults)."""
        t0 = time.time()
        n_img = 0
        step_rng = jax.random.fold_in(self.rng, epoch)
        device_metrics = []  # device arrays; fetched once at epoch end (no per-step sync)
        # Per-interval logging must not stall the dispatch pipeline: fetching
        # the CURRENT step's metrics would block until the device catches up
        # (expensive through a relayed TPU). Instead each interval enqueues
        # (host-side step number, device metrics) and logs the PREVIOUS
        # interval's entry — by then that step has long finished, so the
        # device_get costs only transfer latency. The tail flushes after the
        # epoch-end barrier. Step numbers are tracked on host (one sync here,
        # while the device is idle between epochs).
        step0 = int(self.state.step)
        pending: list = []
        weights: list = []  # steps behind each device_metrics entry (k or 1)
        consumed = 0        # host-side count of steps dispatched this epoch
        k = self.config.steps_per_dispatch
        group: list = []    # staged batches awaiting a k-step dispatch
        # tracing accumulators (arm_tracing / --trace-out): [host data-wait
        # ns, dispatch ns, window start ns, steps at window start] — None
        # keeps the untraced step loop at exactly one branch per step
        tacc = ([0, 0, time.monotonic_ns(), 0]
                if self.tracer is not None else None)

        def record(metrics, n_steps, n_examples):
            nonlocal consumed, n_img
            prev = consumed
            consumed += n_steps
            n_img += n_examples
            self._dispatches_total += 1  # one host dispatch, whatever its k
            self._host_step = step0 + consumed
            if self._watchdog is not None:
                self._watchdog.beat()
            device_metrics.append(metrics)
            weights.append(n_steps)
            log_every = self.config.log_every_steps
            if tacc is not None and consumed // log_every > prev // log_every:
                self._emit_window_spans(tacc, epoch, consumed)
            if (consumed // log_every > prev // log_every
                    and _is_main_process()):
                # JSONL/TB writes are process-0-only, like checkpoints
                # (SURVEY.md §5.8) — other hosts skip the device_get too.
                # The prefetch stats are sampled NOW (host-side ints, no
                # sync — queue depth is the same value the watchdog dumps on
                # a stall): depth 0 at the flush cadence means the input
                # pipeline is starving the step loop, and the staged-bytes
                # ledger makes the uint8-vs-f32 transfer savings visible in
                # logs, not just in bench runs.
                pending.append((step0 + consumed, metrics,
                                self._prefetch_stats()))
                if len(pending) > 1:
                    s, m, pf_stats = pending.pop(0)
                    self.logger.log(
                        s, {**jax.device_get(m), **pf_stats},
                        epoch=epoch, prefix="train_", echo=True)

        def run_single(batch):
            if tacc is None:
                self.state, metrics = self.train_step(self.state, *batch,
                                                      step_rng)
            else:
                t_d = time.monotonic_ns()
                self.state, metrics = self.train_step(self.state, *batch,
                                                      step_rng)
                tacc[1] += time.monotonic_ns() - t_d
            if self.ema_update is not None:
                self._micro_count += 1
                if self._micro_count % self.config.optimizer.accum_steps == 0:
                    self.state = self.ema_update(self.state)
            record(metrics, 1, len(jax.tree_util.tree_leaves(batch)[0]))

        # each batch is any tuple of arrays with a leading batch dim —
        # (images, labels) for classification, (images, boxes, classes,
        # valid) for detection — forwarded positionally to the task's train
        # step. Staged to device ahead of consumption by a producer thread
        # (prefetch_batches > 1) so host->device transfer overlaps compute.
        # With steps_per_dispatch > 1, k staged batches go to the device in
        # ONE dispatch (lax.scan wrapper); a sub-k tail runs as single steps.
        # The host pull is retry-wrapped (transient OSError from flaky
        # storage backs off instead of killing the epoch) and carries the
        # fault injector's deterministic failures when armed.
        if not wrapped:
            data = resilient_batches(
                data, self.retry_policy,
                injector=self.faults if self.faults.active else None,
                on_retry=self._log_retry)
        staged = prefetch_to_device(self.mesh, data,
                                    self.config.prefetch_batches)
        self._prefetcher = staged
        if self._watchdog is not None:
            self._watchdog.beat()
        batches_iter = staged if tacc is None else _timed_pulls(staged, tacc)

        def _preempted() -> bool:
            return self._shutdown is not None and self._shutdown.requested

        try:
            for batch in batches_iter:
                if _preempted():
                    # finish-the-in-flight-step contract: the last dispatched
                    # step completes on device; we just stop feeding new ones
                    # and let fit() commit the checkpoint
                    break
                if k > 1:
                    group.append(batch)
                    if len(group) == k:
                        if self._multi_step is None:
                            # built here, not __init__: subclasses install
                            # their family's train_step after the base ran
                            self._multi_step = steps.make_multistep_train_step(
                                self.train_step, k, len(batch),
                                mesh=self.mesh,
                                ema_decay=self.config.ema_decay)
                        n_ex = sum(len(jax.tree_util.tree_leaves(b)[0])
                                   for b in group)
                        flat = [a for b in group for a in b]
                        group = []
                        try:
                            t_d = (time.monotonic_ns() if tacc is not None
                                   else 0)
                            self.state, metrics = self._multi_step(
                                self.state, *flat, step_rng)
                            if tacc is not None:
                                tacc[1] += time.monotonic_ns() - t_d
                        finally:
                            # a failing dispatch must not pin k staged
                            # batches in the retained traceback frame
                            flat = None
                        record(metrics, k, n_ex)
                else:
                    run_single(batch)
            if not _preempted():
                for batch in group:  # tail shorter than k
                    run_single(batch)
            group = []
        finally:
            # a step exception must release the producer's staged device
            # batches NOW (a retained traceback would otherwise pin them
            # exactly when a recovering driver needs the HBM back)
            group = None
            self._prefetcher = None
            # final ledger snapshot (the live prefetcher is about to close):
            # the overlap fraction is the double-buffering proof
            # bench_epoch.py reports (docs/INPUT_PIPELINE.md)
            self.last_prefetch_ledger = {
                "bytes_staged_total": staged.bytes_staged_total,
                "last_stage_secs": staged.last_stage_secs,
                "wait_secs_total": staged.wait_secs_total,
                "first_wait_secs": staged.first_wait_secs,
                "overlapped_fraction": staged.overlapped_fraction,
            }
            staged.close()
        jax.block_until_ready(self.state.params)
        if tacc is not None and consumed > tacc[3]:
            # epoch tail below the log_every boundary: flush the partial
            # window so short runs (and every epoch's tail) still trace
            self._emit_window_spans(tacc, epoch, consumed)
        for s, m, pf_stats in pending:  # main process only
            self.logger.log(s, {**jax.device_get(m), **pf_stats},
                            epoch=epoch, prefix="train_", echo=True)
        dt = time.time() - t0
        if device_metrics:
            # step-weighted mean: a k-step dispatch's entry is already the
            # mean of k steps, a tail single's the mean of 1
            w = np.asarray(weights, np.float32)
            w = w / w.sum()
            stacked = jax.tree_util.tree_map(
                lambda *xs: (jnp.stack(xs) * w).sum(), *device_metrics)
            out = {key: float(v) for key, v in jax.device_get(stacked).items()}
        else:
            out = {}
        out["images_per_sec"] = n_img / dt if dt > 0 else 0.0
        if self.config.halt_on_nonfinite and not np.isfinite(
                out.get("loss", 0.0)):
            # Every process computes the same epoch mean from the same SPMD
            # program, so all hosts raise together (no straggler stuck in a
            # collective). One diverged batch poisons momentum/Adam state —
            # later "recovery" steps train the wrong weights.
            if _is_main_process():
                # the diverged epoch's metrics (which loss went non-finite,
                # throughput) must reach JSONL/TB before the raise aborts
                # fit's normal epoch_train_ record — forensics belong in the
                # metrics stream, not only the exception text
                self.logger.log(int(self.state.step), out, epoch=epoch,
                                prefix="epoch_train_")
            divergence_halt(self.config, self.ckpt, epoch,
                            f"mean train loss is {out['loss']}")
        return out

    def eval_state(self) -> TrainState:
        """State whose params are the eval weights — the EMA whenever present
        (enabled for this run, or restored from an EMA-trained checkpoint)."""
        if jax.tree_util.tree_leaves(self.state.ema_params):
            return self.state.replace(params=self.state.ema_params)
        return self.state

    def evaluate(self, data: Iterable) -> dict:
        """Masked eval: partial batches are zero-padded up to the LARGEST
        padded batch seen so far in this pass (a running max, so the usual
        full-then-final-partial stream compiles exactly one shape) — a
        varying final batch would otherwise cost one extra XLA compile per
        distinct shape. Padded rows carry mask 0 and don't affect the metric
        sums. Shape-stability is pinned by
        tests/test_real_data.py::test_eval_partial_batch_single_compile."""
        eval_state = self.eval_state()
        data_axis = self.mesh.shape[mesh_lib.DATA_AXIS]
        sums: dict = {}
        target = 0
        for images, labels in data:
            n = len(labels)
            target = max(target, mesh_lib.pad_to_multiple(n, data_axis))
            padded = target
            mask = np.zeros((padded,), np.float32)
            mask[:n] = 1.0
            if padded != n:
                pad = [(0, padded - n)]
                images = np.pad(np.asarray(images), pad + [(0, 0)] * (images.ndim - 1))
                labels = np.pad(np.asarray(labels), pad)
            batch = mesh_lib.shard_batch_pytree(self.mesh, (images, labels, mask))
            m = jax.device_get(self.eval_step(eval_state, *batch))
            for k, v in m.items():
                sums[k] = sums.get(k, 0.0) + float(v)
        count = sums.pop("count", 0.0)
        if count == 0:
            return {}
        out = {k: v / count for k, v in sums.items()}
        out["count"] = count
        return out

    def fit(self, train_data_fn: Callable[[int], Iterable],
            val_data_fn: Optional[Callable[[int], Iterable]] = None,
            sample_shape=None, resume: bool = False,
            total_epochs: Optional[int] = None,
            profile_dir: Optional[str] = None) -> dict:
        """`train_data_fn(epoch)` returns that epoch's batch iterable (re-shuffled).

        Mirrors run_epochs (`ResNet/pytorch/train.py:310-428`): optional sanity
        validate at epoch 0, then train/validate/schedule/checkpoint per epoch.
        `profile_dir` captures a jax.profiler trace of the first trained epoch
        (viewable in TensorBoard/XProf) — the first-class profiling hook the
        reference lacked (SURVEY.md §5.1).
        """
        cfg = self.config
        total_epochs = total_epochs or cfg.total_epochs
        if self.state is None:
            if sample_shape is None:
                s = cfg.data.image_size
                sample_shape = (s, s, 3)
            self.init_state(sample_shape)
        if resume:
            self.resume()
        if self.ema_update is None and jax.tree_util.tree_leaves(
                self.state.ema_params):
            # restored from an EMA-trained checkpoint but this run won't
            # update the average — training on while re-saving a frozen EMA
            # would be silently stale, so drop it loudly
            from flax.core import FrozenDict
            if _is_main_process():
                print(f"[{cfg.name}] checkpoint carries EMA weights but "
                      f"ema_decay is unset: discarding them for this training "
                      f"run (pass --ema-decay to keep updating the average)",
                      flush=True)
            self.state = self.state.replace(ema_params=FrozenDict({}))

        watch_key, watch_mode = self.watch_key, self.watch_mode
        last_val = {}
        recoveries_left = cfg.recover_on_divergence
        first_epoch = self.start_epoch
        with contextlib.ExitStack() as stack:
            if cfg.graceful_shutdown:
                # SIGTERM/SIGINT → finish the in-flight step, commit, exit 0
                # (handlers restored when fit unwinds; inert off-main-thread)
                self._shutdown = stack.enter_context(GracefulShutdown())
            if cfg.watchdog_secs:
                self._watchdog = stack.enter_context(StepWatchdog(
                    cfg.watchdog_secs, diagnostics=self._watchdog_diagnostics,
                    name=cfg.name))
            stack.callback(self._clear_resilience_handles)

            epoch = self.start_epoch
            while epoch <= total_epochs:
                if (self._shutdown is not None and self._shutdown.requested
                        and self._last_saved_epoch is not None):
                    # signal landed between epochs (eval/save window): the
                    # last save already covers everything trained
                    self._commit_preemption(self._last_saved_epoch)
                profiling = profile_dir and epoch == first_epoch
                if profiling:
                    jax.profiler.start_trace(profile_dir)
                try:
                    # a live epoch cache replays on device — don't make the
                    # host pipeline build an epoch nobody will read
                    train_metrics = self.train_epoch(
                        epoch, () if self._epoch_cache is not None
                        else train_data_fn(epoch))
                except TrainingDivergedError:
                    # bounded auto-recovery: roll back to the last committed
                    # checkpoint, scale the LR down, retry the epoch — the
                    # halt (with its resume hint) fires once the budget is
                    # spent or there is nothing committed to roll back to
                    if recoveries_left <= 0:
                        raise
                    rolled = self._recover_from_divergence(epoch)
                    if rolled is None:
                        raise
                    recoveries_left -= 1
                    epoch = rolled + 1
                    continue
                finally:
                    # train_epoch blocks on params → trace is complete;
                    # finally so a divergence halt (or any step failure)
                    # still writes the trace of the epoch the user most
                    # wants to inspect
                    if profiling:
                        jax.profiler.stop_trace()
                if _is_main_process():
                    self.logger.log(int(self.state.step), train_metrics,
                                    epoch=epoch, prefix="epoch_train_")
                if self._shutdown is not None and self._shutdown.requested:
                    # preempted mid-epoch: skip eval, commit what we have as
                    # this epoch (partial — resume continues at epoch+1;
                    # under a grace window every step kept beats a redo)
                    self._save_epoch(epoch, metric=None)
                    self._commit_preemption(epoch)
                if val_data_fn is not None:
                    last_val = self.evaluate(val_data_fn(epoch))
                    if _is_main_process():
                        self.logger.log(int(self.state.step), last_val,
                                        epoch=epoch, prefix="val_")
                    # empty eval (e.g. all val batches dropped/skipped) must
                    # not register as a perfect 0.0 loss in min-mode
                    metric = last_val.get(
                        watch_key, 0.0 if watch_mode == "max" else float("inf"))
                else:
                    # no val set: watch the same key on train metrics so
                    # min-mode (loss-watching) plateau semantics stay correct
                    metric = train_metrics.get(
                        watch_key, 0.0 if watch_mode == "max" else float("inf"))

                if self.best_metric is None or (
                        metric > self.best_metric if watch_mode == "max"
                        else metric < self.best_metric):
                    self.best_metric = metric

                if self.plateau:
                    scale = self.plateau.update(metric)
                    self.state = self.state.replace(
                        opt_state=set_lr_scale(
                            self.state.opt_state,
                            scale * self._recovery_scale))

                self._save_epoch(epoch, metric=metric)
                epoch += 1
        # fit returning means "training done": the last async save must be
        # committed, or a fresh Trainer on this workdir (library UX — the CLI
        # also calls close()) would resume from the previous epoch
        self.ckpt.flush()
        return {"best_metric": self.best_metric, **last_val}

    def _clear_resilience_handles(self) -> None:
        self._shutdown = None
        self._watchdog = None

    def _save_epoch(self, epoch: int, metric: Optional[float]) -> None:
        # NOTE: Orbax save is a collective — every process must enter it
        # (process 0 writes; the rest participate in the barrier).
        host = {"best_metric": self.best_metric}
        if self.plateau:
            host["plateau"] = {"best": self.plateau.best,
                               "num_bad_epochs": self.plateau.num_bad_epochs,
                               "scale": self.plateau.scale}
        t_ck = time.monotonic_ns() if self.tracer is not None else 0
        self.ckpt.save(epoch, self.state, host_state=host, metric=metric)
        if self.tracer is not None:
            # the host-blocking share of the commit (async saves: snapshot
            # + enqueue; sync saves: the full write) — the third split of
            # the training trace next to data wait and dispatch
            self.tracer.add("ckpt_commit", "train", t_ck,
                            time.monotonic_ns() - t_ck,
                            args={"epoch": epoch})
        self._last_saved_epoch = epoch

    def _commit_preemption(self, epoch: int) -> None:
        """Graceful-preemption tail: barrier until the checkpoint at `epoch`
        is COMMITTED (synchronous — a SIGKILL follow-up must find it
        restorable), then raise PreemptionExit; fit_and_close turns it into
        the resume hint + exit 0."""
        self.ckpt.flush()
        if _is_main_process():
            self.logger.log(self._host_step,
                            {"preempted_at_epoch": float(epoch)},
                            epoch=epoch, prefix="resilience_", echo=False)
        raise PreemptionExit(
            epoch,
            f"[{self.config.name}] graceful preemption: checkpoint "
            f"committed at epoch {epoch} — relaunch with --auto-resume "
            f"(or -c latest) to continue")

    def _recover_from_divergence(self, epoch: int) -> Optional[int]:
        """Roll back to the last committed checkpoint and scale the LR down
        by config.recovery_lr_factor (the scale persists for the rest of the
        run and composes with the plateau schedule's own scale). Returns the
        restored epoch, or None when nothing is committed yet."""
        if self.ckpt.latest_epoch() is None:
            return None
        got = self.resume()  # restores state/plateau/best + prints the line
        if got is None:
            return None
        self._recoveries += 1
        self._recovery_scale *= self.config.recovery_lr_factor
        base = self.plateau.scale if self.plateau else 1.0
        self.state = self.state.replace(opt_state=set_lr_scale(
            self.state.opt_state, base * self._recovery_scale))
        if _is_main_process():
            print(f"[{self.config.name}] divergence recovery "
                  f"{self._recoveries}: epoch {epoch} diverged — rolled back "
                  f"to epoch {got}, LR scale now {self._recovery_scale:g}",
                  flush=True)
            self.logger.log(
                self._host_step,
                {"divergence_recoveries": float(self._recoveries),
                 "lr_scale": self._recovery_scale},
                epoch=epoch, prefix="resilience_", echo=False)
        return got

    def arm_tracing(self, trace_out: Optional[str] = None, tracer=None):
        """Arm span tracing (`--trace-out`, docs/OBSERVABILITY.md): each
        log_every window emits a `train_window` span split into aggregate
        `host_data_wait` (time blocked on the input pipeline) and
        `train_dispatch` (host time dispatching steps) child spans, tagged
        with the prefetcher's transfer ledger (queue depth, bytes staged,
        stage latency); each checkpoint save emits a `ckpt_commit` span.
        The Chrome trace JSON lands at `trace_out` when the trainer closes
        — load it in Perfetto. Returns the tracer (tests read it live)."""
        from ..obs.trace import Tracer
        # no sampling for training: windows are log_every-rate, not
        # request-rate — every one matters in a trace
        self.tracer = tracer if tracer is not None else Tracer(sample=1.0)
        self._trace_out = trace_out
        return self.tracer

    def _emit_window_spans(self, tacc, epoch: int, consumed: int) -> None:
        """One window's spans at the log_every boundary: wall window +
        aggregate data-wait/dispatch splits (tacc accumulators, reset
        here). The split is host-observed — data wait is time blocked on
        the prefetcher, dispatch is host time in the (async) step calls —
        so window_wall - (wait + dispatch) is host-side everything-else."""
        now_ns = time.monotonic_ns()
        w0 = tacc[2]
        wid = self.tracer.add(
            "train_window", "train", w0, now_ns - w0,
            args={"epoch": epoch, "steps": consumed - tacc[3],
                  **self._prefetch_stats()})
        self.tracer.add("host_data_wait", "train", w0, tacc[0],
                        args={"window": wid, "aggregate": True})
        self.tracer.add("train_dispatch", "train", w0, tacc[1],
                        args={"window": wid, "aggregate": True})
        tacc[0] = tacc[1] = 0
        tacc[2] = now_ns
        tacc[3] = consumed

    def _prefetch_stats(self) -> dict:
        """Host-side snapshot of the live prefetcher's transfer ledger (no
        device sync): queue depth plus the staged-bytes total and the last
        single-batch staging latency — logged at the log_every cadence so a
        starving pipeline AND the uint8-vs-f32 transfer savings both show up
        in the metrics stream (parallel/prefetch.py). `dispatches_total`
        (logged as train_dispatches_total) counts host train dispatches —
        per-step, k-step-scanned, or one-per-epoch — so dispatch
        amortization is visible in logs and the bench without a profiler."""
        pf = self._prefetcher
        out = {"dispatches_total": float(self._dispatches_total)}
        if pf is None:
            out["prefetch_queue_depth"] = 0
            return out
        out.update(prefetch_queue_depth=pf.queue_depth,
                   prefetch_bytes_staged=float(pf.bytes_staged_total),
                   prefetch_stage_ms=round(pf.last_stage_secs * 1e3, 3))
        return out

    def _watchdog_diagnostics(self) -> dict:
        pf = self._prefetcher
        return {
            "last_step": self._host_step,
            "last_checkpoint_epoch": self._last_saved_epoch,
            "prefetch_queue_depth": pf.queue_depth if pf is not None else None,
        }

    def close(self):
        if self.tracer is not None and self._trace_out:
            from ..obs.export import write_chrome_trace
            path, self._trace_out = self._trace_out, None  # idempotent
            n = write_chrome_trace(self.tracer, path)
            print(f"[{self.config.name}] wrote {n} trace span(s) to "
                  f"{path} (open in https://ui.perfetto.dev)", flush=True)
        self.logger.close()
        self.ckpt.close()


class LossWatchedTrainer(Trainer):
    """Base for tasks that validate on loss only (detection / pose / centernet):
    watches ("loss", "min") for best-model + plateau decisions and averages
    per-batch val losses, skipping non-finite batches — the NaN-batch guard of
    `Hourglass/tensorflow/train.py:126-130`, applied uniformly."""

    default_watch = ("loss", "min")

    def __init__(self, config: TrainConfig, *args, **kwargs):
        if config.mixup_alpha or config.cutmix_alpha:
            # the subclasses replace train_step with task-specific steps that
            # never see mixup/cutmix — erroring beats a silent no-op
            raise ValueError(
                "mixup_alpha/cutmix_alpha are classification-only; the "
                f"{type(self).__name__} ignores them — use the task's own "
                "augmentations (flip/crop in the data pipeline) instead")
        if config.device_augment:
            # same shape of latent bug: the task steps would never call the
            # augment, silently training on raw padded uint8
            raise ValueError(
                "device_augment is classification-only; the "
                f"{type(self).__name__} steps don't fuse it — use "
                "--device-normalize (uint8 transfer + on-device normalize) "
                "for this family instead")
        super().__init__(config, *args, **kwargs)

    def evaluate(self, data: Iterable) -> dict:
        """Mean of per-batch val losses (`distributed_val_epoch`,
        `YOLO/tensorflow/train.py:182-193,228-233`)."""
        eval_state = self.eval_state()
        total, n = 0.0, 0
        for batch in data:
            sharded = mesh_lib.shard_batch_pytree(self.mesh, tuple(batch))
            m = jax.device_get(self.eval_step(eval_state, *sharded))
            loss = float(m["loss"])
            if np.isfinite(loss):
                total += loss
                n += 1
        return {"loss": total / n, "count": float(n)} if n else {}
