"""Checkpoint-restore + single-image prediction helpers for the classification
zoo — the programmatic core of what the reference does inside its per-model
visualization notebooks (`ResNet/pytorch/notebooks/ResNet50.ipynb`: load
checkpoint, plot the saved loggers, `predict()` top-5 on test images).

Used by the per-family `<Family>/jax/notebooks/*.ipynb` demos and usable from
scripts:

    from deepvision_tpu.core.classify import Classifier
    clf = Classifier("resnet50", workdir="runs/resnet50")
    for name, prob in clf.predict("cat.jpg"):
        print(f"{prob:6.2%}  {name}")
"""

from __future__ import annotations

import json
import os
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def load_class_names(path: Optional[str] = None,
                     num_classes: int = 1000) -> List[str]:
    """Human-readable class names.

    `path` may be a JSON mapping of index → name (the reference's
    `Datasets/ILSVRC2012/indices.json` format, values like ["n01440764",
    "tench"]) or a text file with one name per line. Falls back to
    "class <i>" placeholders when no file is given.
    """
    if path is None:
        return [f"class {i}" for i in range(num_classes)]
    if path.endswith(".json"):
        with open(path) as fp:
            raw = json.load(fp)
        names = [f"class {i}" for i in range(num_classes)]
        for k, v in raw.items():
            names[int(k)] = v[-1] if isinstance(v, (list, tuple)) else str(v)
        return names
    with open(path) as fp:
        return [line.strip() for line in fp if line.strip()]


def load_metrics(workdir: str) -> dict:
    """Read the trainer's JSONL metric logs into {metric: {"epochs": [...],
    "value": [...]}} — same shape as the reference's pickled `loggers` dicts
    (`ResNet/pytorch/train.py:260-285`), so notebook plotting code is 1:1."""
    out: dict = {}
    if not os.path.isdir(workdir):
        return out
    for fname in sorted(os.listdir(workdir)):
        if not fname.endswith(".jsonl"):
            continue
        with open(os.path.join(workdir, fname)) as fp:
            for line in fp:
                rec = json.loads(line)
                step = rec.get("epoch", rec.get("step", 0))
                for key, val in rec.items():
                    if isinstance(val, str):
                        # MetricsLogger serializes non-finite values as
                        # strings ("nan"/"inf") to keep the JSONL strict —
                        # surface them as the floats they were, so diverged
                        # epochs appear in plots instead of silently dropping
                        try:
                            val = float(val)
                        except ValueError:
                            continue
                    if key in ("epoch", "step", "t") or not isinstance(
                            val, (int, float)):
                        continue
                    slot = out.setdefault(key, {"epochs": [], "value": []})
                    slot["epochs"].append(step)
                    slot["value"].append(val)
    return out


class Classifier:
    """Restore a trained classification checkpoint and predict top-k classes."""

    def __init__(self, model_name: str, workdir: Optional[str] = None,
                 checkpoint: Optional[int] = None,
                 image_size: Optional[int] = None,
                 class_names: Optional[Sequence[str]] = None,
                 class_names_file: Optional[str] = None):
        from ..configs import get_config
        from .trainer import Trainer

        cfg = get_config(model_name)
        self.image_size = image_size or cfg.data.image_size
        self.grayscale = cfg.data.dataset == "mnist"
        self.trainer = Trainer(cfg, workdir=workdir or os.path.join(
            "runs", cfg.name))
        self.trainer.init_state(
            (self.image_size, self.image_size, cfg.data.channels))
        restored = self.trainer.resume(epoch=checkpoint)
        if restored is None:
            print("WARNING: no checkpoint found — predictions use random "
                  "weights")
        self.epoch = restored
        self.class_names = list(class_names) if class_names else \
            load_class_names(class_names_file, cfg.data.num_classes)

        state = self.trainer.state
        apply_fn = state.apply_fn

        @jax.jit
        def _logits(params, batch_stats, images):
            variables = {"params": params}
            # dict-emptiness of the batch_stats PYTREE, not a tracer bool —
            # static at trace time  # jaxlint: disable=TRC001
            if batch_stats:
                variables["batch_stats"] = batch_stats
            return apply_fn(variables, images, train=False)

        self._logits = _logits

    def preprocess(self, image) -> np.ndarray:
        """PIL image / path / HWC uint8 array → normalized NHWC float32 [1,...]."""
        if isinstance(image, str):
            from PIL import Image
            image = Image.open(image)
            image = np.asarray(image.convert("L" if self.grayscale else "RGB"))
        image = np.asarray(image)
        if self.grayscale:
            from ..data import mnist
            if image.ndim == 3:  # HWC with a trailing channel axis
                image = image[..., 0]
            if image.shape[:2] != (28, 28):
                from PIL import Image
                image = np.asarray(
                    Image.fromarray(image.astype(np.uint8)).resize((28, 28)))
            return mnist.preprocess(image[None])
        from ..data import transforms as T
        tf = T.eval_transform(self.image_size)
        return tf(image.astype(np.float32))[None]

    def predict(self, image, top: int = 5) -> List[Tuple[str, float]]:
        """Top-k (class name, probability), like the reference notebooks'
        `predict()` (softmax → topk over `indices.json` names). Uses the EMA
        weights when the checkpoint carries them."""
        state = self.trainer.eval_state()
        logits = self._logits(state.params, state.batch_stats,
                              jnp.asarray(self.preprocess(image)))
        if isinstance(logits, (tuple, list)):  # inception aux heads
            logits = logits[0]
        probs = np.asarray(jax.nn.softmax(logits[0]))
        idx = np.argsort(probs)[::-1][:top]
        return [(self.class_names[i], float(probs[i])) for i in idx]
