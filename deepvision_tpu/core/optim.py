"""Optimizer factory.

Covers the reference's optimizer set: SGD+momentum (+weight decay) for classification
(`ResNet/pytorch/train.py:141-215`), Adam for YOLO/Hourglass/GANs
(`YOLO/tensorflow/train.py:287`, `DCGAN/tensorflow/main.py:42-43`), RMSprop for
Inception-style configs. Built as optax chains with an injectable LR so the host-side
plateau scale (schedules.PlateauState) can rescale without recompiling.
"""

from __future__ import annotations

import optax

from .config import OptimizerConfig, ScheduleConfig
from .schedules import build_schedule


def build_optimizer(opt_cfg: OptimizerConfig, sched_cfg: ScheduleConfig,
                    steps_per_epoch: int, total_epochs: int) -> optax.GradientTransformation:
    accum = opt_cfg.accum_steps
    if accum < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum}")
    # Under accumulation the inner chain (and thus the schedule counter) ticks
    # once per APPLIED update, not per micro-batch — epoch boundaries in the
    # schedule must be expressed in updates/epoch. Kept fractional: flooring
    # would compress warmup/boundaries/total whenever accum doesn't divide
    # steps_per_epoch (MultiSteps' buffer carries across epoch edges).
    schedule = build_schedule(sched_cfg, opt_cfg.learning_rate,
                              steps_per_epoch / accum, total_epochs)

    parts = []
    if opt_cfg.grad_clip_norm:
        parts.append(optax.clip_by_global_norm(opt_cfg.grad_clip_norm))

    # no_decay_bn_bias: decay only rank>1 tensors (conv HWIO / dense kernels);
    # 1-D leaves are exactly the BN scales/biases and layer biases. The mask
    # is a callable so it adapts to whatever param tree the optimizer is
    # init'd with.
    decay_mask = None
    if opt_cfg.no_decay_bn_bias:
        import jax
        decay_mask = (lambda params: jax.tree_util.tree_map(
            lambda x: x.ndim > 1, params))

    def decayed_weights():
        return optax.add_decayed_weights(opt_cfg.weight_decay, mask=decay_mask)

    name = opt_cfg.name
    if name in ("sgd", "momentum"):
        # L2-coupled weight decay, matching torch.optim.SGD(weight_decay=...) used by
        # the reference configs (e.g. resnet50: lr .1, momentum .9, wd 1e-4,
        # ResNet/pytorch/train.py:141-164).
        if opt_cfg.weight_decay:
            parts.append(decayed_weights())
        if opt_cfg.momentum:
            parts.append(optax.trace(decay=opt_cfg.momentum, nesterov=opt_cfg.nesterov))
    elif name == "rmsprop":
        parts.append(optax.scale_by_rms(decay=opt_cfg.rmsprop_decay, eps=opt_cfg.eps))
        if opt_cfg.weight_decay:
            parts.append(decayed_weights())
    elif name == "adam":
        parts.append(optax.scale_by_adam(b1=opt_cfg.beta1, b2=opt_cfg.beta2, eps=opt_cfg.eps))
    elif name == "adamw":
        parts.append(optax.scale_by_adam(b1=opt_cfg.beta1, b2=opt_cfg.beta2, eps=opt_cfg.eps))
        if opt_cfg.weight_decay:
            parts.append(decayed_weights())
    else:
        raise ValueError(f"unknown optimizer {name!r}")

    # inject_hyperparams exposes opt_state.hyperparams['lr_scale'] so the host-side
    # plateau schedule can rescale the LR between epochs without retracing the step.
    def _lr(lr_scale: float):
        chain = optax.chain(*parts, optax.scale_by_schedule(schedule),
                            optax.scale(-1.0), optax.scale(lr_scale))
        return chain

    tx = optax.inject_hyperparams(lambda lr_scale: _lr(lr_scale))(lr_scale=1.0)
    if accum > 1:
        # MultiSteps buffers the running mean of the micro-batch grads and
        # emits zero updates until the k-th call, when the inner chain
        # (weight decay, momentum, schedule) sees the averaged gradient —
        # identical semantics to one large-batch step for everything except
        # BatchNorm statistics.
        tx = optax.MultiSteps(tx, every_k_schedule=accum)
    return tx


def set_lr_scale(opt_state, scale: float):
    """Write the plateau scale into an inject_hyperparams state (host side).

    With gradient accumulation the inject_hyperparams state lives inside
    MultiStepsState.inner_opt_state — walk down to it."""
    import jax.numpy as jnp
    inner = opt_state
    while not hasattr(inner, "hyperparams"):
        if hasattr(inner, "inner_opt_state"):
            inner = inner.inner_opt_state
        else:
            raise ValueError("opt_state has no inject_hyperparams layer")
    inner.hyperparams["lr_scale"] = jnp.asarray(scale, dtype=jnp.float32)
    return opt_state
