"""Optimizer factory.

Covers the reference's optimizer set: SGD+momentum (+weight decay) for classification
(`ResNet/pytorch/train.py:141-215`), Adam for YOLO/Hourglass/GANs
(`YOLO/tensorflow/train.py:287`, `DCGAN/tensorflow/main.py:42-43`), RMSprop for
Inception-style configs. Built as optax chains with an injectable LR so the host-side
plateau scale (schedules.PlateauState) can rescale without recompiling.
"""

from __future__ import annotations

import optax

from .config import OptimizerConfig, ScheduleConfig
from .schedules import build_schedule


def build_optimizer(opt_cfg: OptimizerConfig, sched_cfg: ScheduleConfig,
                    steps_per_epoch: int, total_epochs: int) -> optax.GradientTransformation:
    schedule = build_schedule(sched_cfg, opt_cfg.learning_rate, steps_per_epoch, total_epochs)

    parts = []
    if opt_cfg.grad_clip_norm:
        parts.append(optax.clip_by_global_norm(opt_cfg.grad_clip_norm))

    name = opt_cfg.name
    if name in ("sgd", "momentum"):
        # L2-coupled weight decay, matching torch.optim.SGD(weight_decay=...) used by
        # the reference configs (e.g. resnet50: lr .1, momentum .9, wd 1e-4,
        # ResNet/pytorch/train.py:141-164).
        if opt_cfg.weight_decay:
            parts.append(optax.add_decayed_weights(opt_cfg.weight_decay))
        if opt_cfg.momentum:
            parts.append(optax.trace(decay=opt_cfg.momentum, nesterov=opt_cfg.nesterov))
    elif name == "rmsprop":
        parts.append(optax.scale_by_rms(decay=opt_cfg.rmsprop_decay, eps=opt_cfg.eps))
        if opt_cfg.weight_decay:
            parts.append(optax.add_decayed_weights(opt_cfg.weight_decay))
    elif name == "adam":
        parts.append(optax.scale_by_adam(b1=opt_cfg.beta1, b2=opt_cfg.beta2, eps=opt_cfg.eps))
    elif name == "adamw":
        parts.append(optax.scale_by_adam(b1=opt_cfg.beta1, b2=opt_cfg.beta2, eps=opt_cfg.eps))
        if opt_cfg.weight_decay:
            parts.append(optax.add_decayed_weights(opt_cfg.weight_decay))
    else:
        raise ValueError(f"unknown optimizer {name!r}")

    # inject_hyperparams exposes opt_state.hyperparams['lr_scale'] so the host-side
    # plateau schedule can rescale the LR between epochs without retracing the step.
    def _lr(lr_scale: float):
        chain = optax.chain(*parts, optax.scale_by_schedule(schedule),
                            optax.scale(-1.0), optax.scale(lr_scale))
        return chain

    return optax.inject_hyperparams(lambda lr_scale: _lr(lr_scale))(lr_scale=1.0)


def set_lr_scale(opt_state, scale: float):
    """Write the plateau scale into an inject_hyperparams state (host side)."""
    import jax.numpy as jnp
    opt_state.hyperparams["lr_scale"] = jnp.asarray(scale, dtype=jnp.float32)
    return opt_state
