"""Metrics logging.

One `MetricsLogger` replaces the reference's three stores (hand-rolled `loggers` dict
`ResNet/pytorch/train.py:260-285`, per-epoch pickles `ResNet/tensorflow/train.py:140-144`,
TensorBoard writers `YOLO/tensorflow/train.py:159-179`): console prints every N steps,
JSONL persistence, and an in-memory history dict with the reference's
`{epochs: [], value: []}` shape for checkpoint round-tripping.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, Optional

import jax
import numpy as np


class MeanAccumulator:
    """Running mean of scalar metrics (the tf.keras.metrics.Mean role,
    CycleGAN/tensorflow/train.py:33-52), weighted by example count."""

    def __init__(self):
        self.totals: Dict[str, float] = {}
        self.weight = 0.0

    def update(self, metrics: Dict[str, float], weight: float = 1.0):
        for k, v in metrics.items():
            if k == "count":
                continue
            self.totals[k] = self.totals.get(k, 0.0) + float(v) * weight
        self.weight += weight

    def result(self) -> Dict[str, float]:
        if self.weight == 0:
            return {}
        return {k: v / self.weight for k, v in self.totals.items()}


class MetricsLogger:
    def __init__(self, log_dir: Optional[str] = None, name: str = "train",
                 tensorboard: bool = True):
        self.log_dir = log_dir
        self.name = name
        self.history: Dict[str, Dict[str, list]] = {}
        # one lock keeps interleaved JSONL lines whole: the trainers log from
        # the fit thread (plus retry hooks off the prefetch producer), and
        # the serving stack (serve/) flushes from its lifecycle thread while
        # request threads read history
        self._lock = threading.Lock()
        self._jsonl = None
        self._tb = None
        self._tb_pending = bool(log_dir) and tensorboard  # created on first log
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
            self._jsonl = open(os.path.join(log_dir, f"{name}.jsonl"), "a")
            # provenance header: a committed run log must say what hardware
            # produced it (the role the reference's training logs fill with
            # their console preamble, `ResNet/pytorch/logs/*.log`). Written
            # only when the file is new/empty so auto-resumed runs keep the
            # "first line is the meta header" contract (runs/README.md).
            if self._jsonl.tell() == 0:
                dev = jax.devices()[0]
                self._write_meta_header(dev)
        self._t0 = time.time()

    def _write_meta_header(self, dev):
        self._jsonl.write(json.dumps({"meta": {
            "platform": dev.platform,
            "device_kind": dev.device_kind,
            "n_devices": jax.device_count(),
            "process": f"{jax.process_index()}/{jax.process_count()}",
            "jax_version": jax.__version__,
            "started_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        }}) + "\n")
        self._jsonl.flush()

    def log(self, step: int, metrics: Dict[str, float], epoch: Optional[int] = None,
            prefix: str = "", echo: bool = True):
        metrics = {k: float(np.asarray(v)) for k, v in metrics.items()}
        with self._lock:
            for k, v in metrics.items():
                h = self.history.setdefault(prefix + k, {"epochs": [], "value": []})
                h["epochs"].append(epoch if epoch is not None else step)
                h["value"].append(v)
            rec = {"step": step, "epoch": epoch, "t": round(time.time() - self._t0, 3),
                   **{prefix + k: round(v, 6) for k, v in metrics.items()}}
            if self._jsonl:
                # json.dumps would emit bare NaN/Infinity tokens for non-finite
                # values (invalid JSON — jq/pandas choke on exactly the diverged-
                # epoch forensics lines); serialize them as strings instead
                safe = {k: (v if not isinstance(v, float) or np.isfinite(v)
                            else str(v))
                        for k, v in rec.items()}
                self._jsonl.write(json.dumps(safe, allow_nan=False) + "\n")
                self._jsonl.flush()
            if self._tb_pending:  # lazy: inference-only runs never pay the TF cost
                self._tb_pending = False
                self._tb = _make_tb_writer(os.path.join(self.log_dir, "tb",
                                                        self.name))
            if self._tb is not None:
                with self._tb.as_default():
                    import tensorflow as tf
                    for k, v in metrics.items():
                        tf.summary.scalar(prefix + k, v, step=step)
        if echo:
            body = " ".join(f"{prefix + k}={v:.4f}" for k, v in metrics.items())
            ep = f"epoch {epoch} " if epoch is not None else ""
            print(f"[{self.name}] {ep}step {step}: {body}", flush=True)

    def close(self):
        with self._lock:
            if self._jsonl:
                self._jsonl.close()
                self._jsonl = None
            if self._tb is not None:
                self._tb.close()
                self._tb = None


def _make_tb_writer(path: str):
    """TensorBoard scalar writer (`tf.summary.create_file_writer` role of
    `YOLO/tensorflow/train.py:196-199`); None if tensorflow is unavailable."""
    try:
        import tensorflow as tf
    except ImportError:  # TF genuinely optional; any other failure surfaces
        return None
    try:
        tf.config.set_visible_devices([], "GPU")
    except RuntimeError:  # devices already initialized elsewhere — benign
        pass
    return tf.summary.create_file_writer(path)


def device_get_metrics(metrics) -> Dict[str, float]:
    return {k: float(v) for k, v in jax.device_get(metrics).items()}
