"""Metrics logging.

One `MetricsLogger` replaces the reference's three stores (hand-rolled `loggers` dict
`ResNet/pytorch/train.py:260-285`, per-epoch pickles `ResNet/tensorflow/train.py:140-144`,
TensorBoard writers `YOLO/tensorflow/train.py:159-179`): console prints every N steps,
JSONL persistence, and an in-memory history dict with the reference's
`{epochs: [], value: []}` shape for checkpoint round-tripping.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, Optional

import jax
import numpy as np


class MeanAccumulator:
    """Running mean of scalar metrics (the tf.keras.metrics.Mean role,
    CycleGAN/tensorflow/train.py:33-52), weighted by example count."""

    def __init__(self):
        self.totals: Dict[str, float] = {}
        self.weight = 0.0

    def update(self, metrics: Dict[str, float], weight: float = 1.0):
        for k, v in metrics.items():
            if k == "count":
                continue
            self.totals[k] = self.totals.get(k, 0.0) + float(v) * weight
        self.weight += weight

    def result(self) -> Dict[str, float]:
        if self.weight == 0:
            return {}
        return {k: v / self.weight for k, v in self.totals.items()}


class MetricsLogger:
    def __init__(self, log_dir: Optional[str] = None, name: str = "train",
                 tensorboard: bool = True):
        self.log_dir = log_dir
        self.name = name
        self.history: Dict[str, Dict[str, list]] = {}
        # one lock keeps interleaved JSONL lines whole: the trainers log from
        # the fit thread (plus retry hooks off the prefetch producer), and
        # the serving stack (serve/) flushes from its lifecycle thread while
        # request threads read history
        self._lock = threading.Lock()
        self._jsonl = None
        self._tb = None
        self._tb_pending = bool(log_dir) and tensorboard  # created on first log
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
            self._jsonl = open(os.path.join(log_dir, f"{name}.jsonl"), "a")
            # provenance header: a committed run log must say what hardware
            # produced it (the role the reference's training logs fill with
            # their console preamble, `ResNet/pytorch/logs/*.log`). Written
            # only when the file is new/empty so auto-resumed runs keep the
            # "first line is the meta header" contract (runs/README.md).
            if self._jsonl.tell() == 0:
                dev = jax.devices()[0]
                self._write_meta_header(dev)
        self._t0 = time.time()

    def _write_meta_header(self, dev):
        self._jsonl.write(json.dumps({"meta": {
            "platform": dev.platform,
            "device_kind": dev.device_kind,
            "n_devices": jax.device_count(),
            "process": f"{jax.process_index()}/{jax.process_count()}",
            "jax_version": jax.__version__,
            "started_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        }}) + "\n")
        self._jsonl.flush()

    def log(self, step: int, metrics: Dict[str, float], epoch: Optional[int] = None,
            prefix: str = "", echo: bool = True,
            extra: Optional[Dict[str, str]] = None):
        """`extra` carries non-numeric correlation fields (request_id,
        trace_ref — core/resilience.log_resilience_event) onto the JSONL
        line only: history and TensorBoard are scalar stores."""
        metrics = {k: float(np.asarray(v)) for k, v in metrics.items()}
        with self._lock:
            for k, v in metrics.items():
                h = self.history.setdefault(prefix + k, {"epochs": [], "value": []})
                h["epochs"].append(epoch if epoch is not None else step)
                h["value"].append(v)
            rec = {"step": step, "epoch": epoch, "t": round(time.time() - self._t0, 3),
                   **(extra or {}),
                   **{prefix + k: round(v, 6) for k, v in metrics.items()}}
            if self._jsonl:
                # json.dumps would emit bare NaN/Infinity tokens for non-finite
                # values (invalid JSON — jq/pandas choke on exactly the diverged-
                # epoch forensics lines); serialize them as strings instead
                safe = {k: (v if not isinstance(v, float) or np.isfinite(v)
                            else str(v))
                        for k, v in rec.items()}
                self._jsonl.write(json.dumps(safe, allow_nan=False) + "\n")
                self._jsonl.flush()
            if self._tb_pending:  # lazy: inference-only runs never pay the TF cost
                self._tb_pending = False
                self._tb = _make_tb_writer(os.path.join(self.log_dir, "tb",
                                                        self.name))
            if self._tb is not None:
                with self._tb.as_default():
                    import tensorflow as tf
                    for k, v in metrics.items():
                        tf.summary.scalar(prefix + k, v, step=step)
        if echo:
            body = " ".join(f"{prefix + k}={v:.4f}" for k, v in metrics.items())
            ep = f"epoch {epoch} " if epoch is not None else ""
            print(f"[{self.name}] {ep}step {step}: {body}", flush=True)

    def close(self):
        with self._lock:
            if self._jsonl:
                self._jsonl.close()
                self._jsonl = None
            if self._tb is not None:
                self._tb.close()
                self._tb = None


def _make_tb_writer(path: str):
    """TensorBoard scalar writer (`tf.summary.create_file_writer` role of
    `YOLO/tensorflow/train.py:196-199`); None if tensorflow is unavailable."""
    try:
        import tensorflow as tf
    except ImportError:  # TF genuinely optional; any other failure surfaces
        return None
    try:
        tf.config.set_visible_devices([], "GPU")
    except RuntimeError:  # devices already initialized elsewhere — benign
        pass
    return tf.summary.create_file_writer(path)


def device_get_metrics(metrics) -> Dict[str, float]:
    return {k: float(v) for k, v in jax.device_get(metrics).items()}


# -- dense-prediction metrics (segmentation family, core/segment.py) -----------

def confusion_matrix(preds, labels, num_classes: int, weights=None):
    """jit-safe (num_classes, num_classes) confusion COUNTS: rows are true
    classes, columns predicted. Pure jnp scatter-add over the flattened
    pixels, so it traces inside the segmentation eval step (one fused
    program, no host round trip per batch); `weights` (same shape as labels,
    0/1 float) drops padded pixels from the counts. Sums across batches add
    elementwise — the streaming accumulator below (and serve's /stats) just
    keeps adding returned matrices."""
    import jax.numpy as jnp

    preds = jnp.reshape(preds, (-1,)).astype(jnp.int32)
    labels = jnp.reshape(labels, (-1,)).astype(jnp.int32)
    idx = labels * num_classes + preds
    w = (jnp.ones(idx.shape, jnp.float32) if weights is None
         else jnp.reshape(weights, (-1,)).astype(jnp.float32))
    flat = jnp.zeros((num_classes * num_classes,), jnp.float32).at[idx].add(w)
    return flat.reshape(num_classes, num_classes)


def segmentation_scores(cm) -> Dict[str, np.ndarray]:
    """Derive {pixel_acc, miou, per_class_iou, present} from a summed
    confusion matrix (host-side numpy — runs on accumulated sums, once per
    eval pass, not per batch). IoU_c = TP_c / (row_c + col_c - TP_c); mIoU
    averages over classes PRESENT in the ground truth (absent classes carry
    IoU nan in `per_class_iou` and are excluded — the standard convention,
    so a 3-class val shard doesn't deflate a 21-class model's mIoU)."""
    cm = np.asarray(cm, np.float64)
    tp = np.diag(cm)
    gt = cm.sum(axis=1)           # true-class pixel counts
    pred = cm.sum(axis=0)         # predicted-class pixel counts
    union = gt + pred - tp
    with np.errstate(divide="ignore", invalid="ignore"):
        per_class = np.where(union > 0, tp / np.maximum(union, 1), np.nan)
    present = gt > 0
    total = cm.sum()
    return {
        "pixel_acc": float(tp.sum() / total) if total else 0.0,
        "miou": float(np.nanmean(np.where(present, per_class, np.nan)))
                if present.any() else 0.0,
        "per_class_iou": per_class,
        "present": present,
    }


class StreamingConfusion:
    """Host-side streaming confusion-matrix accumulator: feed per-batch
    (C, C) count matrices (from `confusion_matrix`) or raw pred/label
    arrays; `result()` derives pixel-accuracy / mIoU / per-class IoU from
    the running sums. Used by the segmentation trainer's evaluate and
    available to serving's /stats; cheap enough to keep per-model."""

    def __init__(self, num_classes: int):
        self.num_classes = num_classes
        self.cm = np.zeros((num_classes, num_classes), np.float64)

    def update(self, cm) -> None:
        cm = np.asarray(cm, np.float64)
        if cm.shape != self.cm.shape:
            raise ValueError(f"confusion matrix shape {cm.shape} != "
                             f"({self.num_classes}, {self.num_classes})")
        self.cm += cm

    def update_preds(self, preds, labels, weights=None) -> None:
        self.update(np.asarray(confusion_matrix(
            jax.numpy.asarray(preds), jax.numpy.asarray(labels),
            self.num_classes,
            None if weights is None else jax.numpy.asarray(weights))))

    def result(self) -> Dict[str, np.ndarray]:
        return segmentation_scores(self.cm)

    def reset(self) -> None:
        self.cm[:] = 0.0
