"""Jitted SPMD train/eval steps for classification.

TPU-native translation of the reference's three training-loop generations
(SURVEY.md §0): the per-batch body of `train()` (`ResNet/pytorch/train.py:438-485`) and
the MirroredStrategy per-replica step + SUM-reduce (`YOLO/tensorflow/train.py:70-103,
131-151`) collapse into one pure function `train_step(state, batch, rng)` jitted over a
`Mesh`. The batch is sharded over the 'data' axis; GSPMD inserts the gradient
all-reduce (the NCCL `strategy.reduce` equivalent) over ICI. BatchNorm statistics are
computed over the full global batch (sync-BN), unlike the reference's per-replica BN.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import losses
from .train_state import TrainState
from ..parallel import mesh as mesh_lib
from ..parallel.mesh import DATA_AXIS


def annotate_step(fn, **meta):
    """Attach the factory's own declaration — donation, compute dtype, step
    kind — to the jitted step it returns. This is the claim side of jaxvet's
    IR audit (`deepvision_tpu/check`): the checker traces the step and
    verifies the lowered jaxpr against exactly what the factory that built
    it declared, so the claim can never drift from the construction site.
    Plain attribute assignment; inert everywhere else."""
    fn._jaxvet = meta
    return fn


def _normalize_input(images, input_norm, compute_dtype):
    """Cast to compute dtype; with `input_norm=(mean, std)` the images are raw
    [0,255] pixels (uint8 transfer) normalized here on device instead of on
    the host. Division/subtraction happen in f32 so uint8 pixel values stay
    exact, then the result drops to the compute dtype once."""
    if input_norm is None:
        return images.astype(compute_dtype)
    mean, std = input_norm
    mean = jnp.asarray(mean, jnp.float32)
    std = jnp.asarray(std, jnp.float32)
    images = images.astype(jnp.float32) / 255.0
    return ((images - mean) / std).astype(compute_dtype)


def maybe_grad_norm(enabled: bool, grads) -> dict:
    """{'grad_norm': global L2 of grads} when enabled, else {} — the one
    definition of the metric, shared by every task's train step. One tree of
    square-sums + a sqrt, fused by XLA: divergence forensics ("what was the
    norm when it went NaN") at negligible step cost."""
    return {"grad_norm": optax.global_norm(grads)} if enabled else {}


def make_classification_train_step(
    *,
    label_smoothing: float = 0.0,
    aux_weight: float = 0.3,
    compute_dtype: jnp.dtype = jnp.bfloat16,
    donate: bool = True,
    mesh: Optional[Mesh] = None,
    remat: bool = False,
    mixup_alpha: float = 0.0,
    cutmix_alpha: float = 0.0,
    input_norm: Optional[tuple] = None,
    device_augment: Optional[Callable] = None,
    log_grad_norm: bool = False,
    grad_correction=None,
) -> Callable:
    """Build a jitted `(state, images, labels, rng) -> (state, metrics)` step.

    `grad_correction`: per-leaf divisor pytree from
    `mesh_lib.calibrate_grad_correction` — required for correct training on
    combined spatial×model meshes (the Trainer calibrates and rebuilds the
    step automatically; direct users of this function on such meshes must do
    the same, see tools/verify_mesh.py).

    `remat=True` wraps the forward in `jax.checkpoint`: activations are
    recomputed during the backward pass instead of living in HBM — the standard
    TPU lever for batch sizes / model depths that don't otherwise fit
    (dot-products still saved via the dots_with_no_batch_dims policy).

    `mixup_alpha>0` enables mixup (Zhang et al. 2018) and `cutmix_alpha>0`
    CutMix (Yun et al. 2019) — both absent from the reference: each step
    draws lam ~ Beta(a, a) and blends the batch with a permutation of itself
    (pixel blend for mixup; a pasted random box for CutMix, lam corrected to
    the exact pasted-pixel fraction), then mixes the two losses — all on
    device, so the host pipeline is untouched. Mutually exclusive; reported
    top-k is against the primary labels.

    `input_norm=(mean, std)` (each length-C, in [0,1] units) declares that
    images arrive as RAW [0,255] pixels (typically uint8 from a
    `normalize_on_host=False` pipeline) and normalizes them ON DEVICE:
    (x/255 - mean)/std. uint8 transfer is 4x smaller than normalized f32 —
    the host->device bandwidth lever for input-bound pods (SURVEY.md §7.2.1).

    `device_augment` (data/device_augment.make_train_augment) goes further:
    images arrive as uint8 at `config.decode_image_size` and the whole
    train-time augmentation stack — RandomCrop/flip/ColorJitter/normalize —
    runs here, fused into this step's XLA program, driven by a per-step key
    folded from `state.step` (seed-reproducible like mixup). It REPLACES
    `input_norm` (the augment normalizes; passing both is an error — the
    Trainer guarantees they never double-normalize).
    """
    if mixup_alpha > 0.0 and cutmix_alpha > 0.0:
        raise ValueError("mixup_alpha and cutmix_alpha are mutually exclusive")
    if device_augment is not None and input_norm is not None:
        raise ValueError("device_augment already normalizes; passing "
                         "input_norm too would double-normalize")
    mixing = mixup_alpha > 0.0 or cutmix_alpha > 0.0

    def step(state: TrainState, images, labels, rng):
        step_rng = jax.random.fold_in(rng, state.step)
        if device_augment is not None:
            # fold tag 2 (mixup owns tag 1 below): crop/flip/jitter draws are
            # a pure function of (seed, step), independent of host threading
            images = device_augment(images,
                                    jax.random.fold_in(step_rng, 2))
        else:
            images = _normalize_input(images, input_norm, compute_dtype)
        if mesh is not None:
            # batch over 'data'; on a spatial mesh also H over 'spatial' —
            # GSPMD partitions every conv with halo exchange (context
            # parallelism for activations, SURVEY.md §5.7)
            images = jax.lax.with_sharding_constraint(
                images, mesh_lib.batch_sharding(mesh, images.ndim,
                                                dim1=images.shape[1]))
        if mixing:
            mix_rng, perm_rng, box_rng = jax.random.split(
                jax.random.fold_in(step_rng, 1), 3)
            perm = jax.random.permutation(perm_rng, images.shape[0])
            labels_b = labels[perm]
        if mixup_alpha > 0.0:
            lam = jax.random.beta(mix_rng, mixup_alpha, mixup_alpha,
                                  dtype=jnp.float32).astype(compute_dtype)
            images = lam * images + (1.0 - lam) * images[perm]
        elif cutmix_alpha > 0.0:
            # one box per step (canonical CutMix): area fraction 1-lam0,
            # center uniform, clipped to the image; lam re-derived as the
            # exact kept-pixel fraction after clipping
            h, w = images.shape[1], images.shape[2]
            lam0 = jax.random.beta(mix_rng, cutmix_alpha, cutmix_alpha,
                                   dtype=jnp.float32)
            r = jnp.sqrt(1.0 - lam0)
            cy, cx = jax.random.uniform(box_rng, (2,), dtype=jnp.float32)
            y1 = jnp.clip((cy - r / 2) * h, 0, h)
            y2 = jnp.clip((cy + r / 2) * h, 0, h)
            x1 = jnp.clip((cx - r / 2) * w, 0, w)
            x2 = jnp.clip((cx + r / 2) * w, 0, w)
            rows = jnp.arange(h, dtype=jnp.float32)
            cols = jnp.arange(w, dtype=jnp.float32)
            in_box = (((rows >= y1) & (rows < y2))[:, None]
                      & ((cols >= x1) & (cols < x2))[None, :])  # (H, W)
            images = jnp.where(in_box[None, :, :, None], images[perm], images)
            lam = 1.0 - in_box.mean()  # exact fraction, kept f32

        def forward(params, images):
            with mesh_lib.spatial_activation_constraints(mesh):
                return state.apply_fn(
                    {"params": params, "batch_stats": state.batch_stats},
                    images, train=True, mutable=["batch_stats"],
                    rngs={"dropout": step_rng},
                )

        if remat:
            forward = jax.checkpoint(
                forward,
                policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable)

        def loss_fn(params):
            outputs, mutated = forward(params, images)
            loss = losses.classification_loss(
                outputs, labels, label_smoothing=label_smoothing, aux_weight=aux_weight)
            if mixing:
                loss_b = losses.classification_loss(
                    outputs, labels_b, label_smoothing=label_smoothing,
                    aux_weight=aux_weight)
                lam32 = lam.astype(jnp.float32)
                loss = lam32 * loss + (1.0 - lam32) * loss_b
            return loss, (outputs, mutated)

        (loss, (outputs, mutated)), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params)
        grads = mesh_lib.apply_grad_correction(grads, grad_correction)
        new_state = state.apply_gradients(grads).replace(
            batch_stats=mutated.get("batch_stats", state.batch_stats))
        metrics = {"loss": loss, **losses.topk_accuracies(outputs, labels),
                   **maybe_grad_norm(log_grad_norm, grads)}
        return new_state, metrics

    jit_kwargs = {}
    if donate:
        jit_kwargs["donate_argnums"] = (0,)
    if mesh is not None:
        repl = NamedSharding(mesh, P())
        data = NamedSharding(mesh, P(DATA_AXIS))
        jit_kwargs["out_shardings"] = (None, repl)
    return annotate_step(jax.jit(step, **jit_kwargs), donate=donate,
                         compute_dtype=jnp.dtype(compute_dtype), kind="train")


def make_multistep_train_step(step_fn: Callable, k: int, n_batch_args: int,
                              *, mesh: Optional[Mesh] = None,
                              ema_decay: Optional[float] = None) -> Callable:
    """Wrap any family's `(state, *batch, rng) -> (state, metrics)` step into
    `(state, *k_batches_flat, rng)` running k steps per host dispatch via
    `lax.scan` — one XLA launch instead of k (config.steps_per_dispatch).

    Per-step dispatch latency is pure overhead the chip idles through; over
    a relayed TPU it's the dominant cost of small steps (docs/TUNING.md
    "How to time through a tunneled TPU"). The k host batches arrive as
    flat args (k × n_batch_args arrays, already sharded like single
    batches), are stacked on device — a layout-only concat, no resharding —
    and scanned. Inner per-step RNG stays correct because every task step
    folds `rng` with `state.step`, which advances inside the scan.

    `ema_decay`: the Polyak update runs INSIDE the scan after each step, so
    the averaging cadence is identical to k=1 (the trainer's external
    per-dispatch EMA would decay k× too slowly). Returned metrics are the
    mean over the k steps. Build the wrapped `step_fn` with donate=False —
    its own donation cannot apply inside this trace; the wrapper donates
    the state at the outer jit instead. The staged batches are NOT donated:
    jax donation is output aliasing, and no output matches a batch buffer —
    donating them buys nothing and makes every dispatch warn 'donated
    buffers were not usable'."""
    if k < 2:
        raise ValueError(f"steps_per_dispatch wrapper needs k >= 2, got {k}")

    def multi(state, *args):
        flat, rng = args[:-1], args[-1]
        assert len(flat) == k * n_batch_args, (len(flat), k, n_batch_args)
        stacked = tuple(
            jnp.stack([flat[i * n_batch_args + j] for i in range(k)])
            for j in range(n_batch_args))

        from flax.core import FrozenDict, freeze
        frozen_bs = isinstance(state.batch_stats, FrozenDict)

        def body(st, xs):
            st, metrics = step_fn(st, *xs, rng)
            if frozen_bs and not isinstance(st.batch_stats, FrozenDict):
                # flax's mutable apply hands batch_stats back as a plain
                # dict; harmless under jit, but scan demands the carry
                # keep the input's pytree TYPE
                st = st.replace(batch_stats=freeze(st.batch_stats))
            if ema_decay is not None:
                from .train_state import ema_tree_update
                st = st.replace(ema_params=ema_tree_update(
                    ema_decay, st.ema_params, st.params))
            return st, metrics

        state, metrics = jax.lax.scan(body, state, stacked)
        return state, jax.tree_util.tree_map(lambda m: m.mean(axis=0), metrics)

    jit_kwargs = {"donate_argnums": (0,)}
    if mesh is not None:
        jit_kwargs["out_shardings"] = (None, NamedSharding(mesh, P()))
    inner = getattr(step_fn, "_jaxvet", {})
    return annotate_step(jax.jit(multi, **jit_kwargs), donate=True,
                         compute_dtype=inner.get("compute_dtype"),
                         kind="train")


# fold_in tag for the per-epoch device-side shuffle permutation. The inner
# step folds the SAME rng with state.step (and then tags 1/2 for mixup /
# augment), so any small constant could collide with a real step number —
# this one is outside any reachable step count.
EPOCH_SHUFFLE_TAG = 2**31 - 1


def make_epoch_train_step(step_fn: Callable, n_batch_args: int,
                          *, mesh: Optional[Mesh] = None,
                          ema_decay: Optional[float] = None,
                          shuffle: bool = False) -> Callable:
    """Wrap any family's `(state, *batch, rng) -> (state, metrics)` step into
    `(state, *epoch_arrays, rng)` running a WHOLE EPOCH per host dispatch —
    `lax.scan` over device-resident data (`data/device_cache.py`), one XLA
    launch and zero host round-trips per epoch (config.epoch_on_device).

    Each of the `n_batch_args` epoch arrays is `(steps, batch, ...)` —
    already staged on device, step slices sharded like single batches (the
    cache's `(None, 'data', ...)` layout). The r05 dispatch grid showed
    per-dispatch RPC latency collapsing off-chip throughput to 46–66 img/s
    vs ~2400 on-chip; `steps_per_dispatch` amortizes a handful of steps,
    this wrapper amortizes all of them.

    `shuffle=True` re-permutes the EXAMPLE axis on device before the scan:
    `jax.random.permutation` keyed by `fold_in(rng, EPOCH_SHUFFLE_TAG)`.
    The trainer passes `rng = fold_in(seed_key, epoch)`, so the permutation
    is a pure function of (seed, epoch) — the device-side replacement for
    the host pipelines' per-epoch reshuffle, reproducible across resumes.
    Costs one transient shuffled copy of the epoch in HBM.

    Inner per-step RNG stays correct exactly as in
    `make_multistep_train_step`: every task step folds `rng` with
    `state.step`, which advances inside the scan — so augment/mixup draws
    per (seed, step) are bit-identical to the per-step path (the paired-
    augment segmentation contract rides along unchanged). Same construction
    rules too: build `step_fn` with donate=False (its donation cannot apply
    inside this trace; the wrapper donates the state at the outer jit), and
    the EMA update runs inside the scan so the averaging cadence matches
    k=1. The epoch arrays are NOT donated — they are reused every epoch.

    Returns per-step metrics STACKED along a leading `steps` axis (not the
    mean): the trainer derives the epoch mean from them, and parity tests /
    bench_epoch.py read the full per-step trajectory."""

    def epoch(state, *args):
        arrays, rng = args[:-1], args[-1]
        assert len(arrays) == n_batch_args, (len(arrays), n_batch_args)
        if shuffle:
            n_steps, batch = arrays[0].shape[0], arrays[0].shape[1]
            perm = jax.random.permutation(
                jax.random.fold_in(rng, EPOCH_SHUFFLE_TAG), n_steps * batch)
            arrays = tuple(
                a.reshape(n_steps * batch, *a.shape[2:])[perm]
                .reshape(a.shape) for a in arrays)

        from flax.core import FrozenDict, freeze
        frozen_bs = isinstance(state.batch_stats, FrozenDict)

        def body(st, xs):
            st, metrics = step_fn(st, *xs, rng)
            if frozen_bs and not isinstance(st.batch_stats, FrozenDict):
                # same carry-type normalization as the multistep wrapper
                st = st.replace(batch_stats=freeze(st.batch_stats))
            if ema_decay is not None:
                from .train_state import ema_tree_update
                st = st.replace(ema_params=ema_tree_update(
                    ema_decay, st.ema_params, st.params))
            return st, metrics

        state, metrics = jax.lax.scan(body, state, arrays)
        return state, metrics

    jit_kwargs = {"donate_argnums": (0,)}
    if mesh is not None:
        jit_kwargs["out_shardings"] = (None, NamedSharding(mesh, P()))
    inner = getattr(step_fn, "_jaxvet", {})
    return annotate_step(jax.jit(epoch, **jit_kwargs), donate=True,
                         compute_dtype=inner.get("compute_dtype"),
                         kind="train")


def make_classification_eval_step(*, compute_dtype: jnp.dtype = jnp.bfloat16,
                                  mesh: Optional[Mesh] = None,
                                  input_norm: Optional[tuple] = None,
                                  device_augment: Optional[Callable] = None,
                                  ) -> Callable:
    """Build a jitted `(state, images, labels, mask) -> sums` step (no_grad validate
    loop, reference `validate()` ResNet/pytorch/train.py:488-520).

    `mask` is a (batch,) 0/1 float marking real examples: partial final batches are
    padded up to a multiple of the data axis on the host, and padded rows contribute
    nothing to the returned SUMS. The host divides by `count` to get means.

    `device_augment` here is the EVAL stage (make_eval_augment: deterministic
    center crop + normalize on uint8 input) — it replaces `input_norm`, same
    no-double-normalize contract as the train step.
    """
    if device_augment is not None and input_norm is not None:
        raise ValueError("device_augment already normalizes; passing "
                         "input_norm too would double-normalize")

    def step(state: TrainState, images, labels, mask):
        if device_augment is not None:
            images = device_augment(images)
        else:
            images = _normalize_input(images, input_norm, compute_dtype)
        if mesh is not None:
            images = jax.lax.with_sharding_constraint(
                images, mesh_lib.batch_sharding(mesh, images.ndim,
                                                dim1=images.shape[1]))
        with mesh_lib.spatial_activation_constraints(mesh):
            outputs = state.apply_fn(
                {"params": state.params, "batch_stats": state.batch_stats},
                images, train=False)
        xent = losses.per_example_xent(outputs if not isinstance(outputs, (tuple, list))
                                       else outputs[0], labels)
        correct = losses.topk_correct(outputs, labels)
        m = {"loss": jnp.sum(xent * mask),
             **{k: jnp.sum(v * mask) for k, v in correct.items()},
             "count": jnp.sum(mask)}
        return m

    jit_kwargs = {}
    if mesh is not None:
        jit_kwargs["out_shardings"] = NamedSharding(mesh, P())
    return annotate_step(jax.jit(step, **jit_kwargs), donate=False,
                         compute_dtype=jnp.dtype(compute_dtype), kind="eval")
