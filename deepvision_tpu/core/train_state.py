"""Train state pytree.

One struct covers every model family: `params` + optional `batch_stats` (BatchNorm
running stats — under jit+GSPMD the BN reduction spans the full global batch, i.e.
cross-replica sync-BN for free, unlike the reference's per-replica stats under
MirroredStrategy), optax `opt_state`, and the global `step`. This replaces the
reference's four checkpoint payload shapes (SURVEY.md §5.4) with one.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax
from flax import struct
from flax.core import FrozenDict


@struct.dataclass
class TrainState:
    step: jnp.ndarray
    params: Any
    batch_stats: Any
    opt_state: Any
    # static (not part of the pytree):
    apply_fn: Callable = struct.field(pytree_node=False)
    tx: optax.GradientTransformation = struct.field(pytree_node=False)
    # EMA of params for eval/best-model (empty pytree when disabled — keeps
    # the checkpoint template structure static either way)
    ema_params: Any = FrozenDict({})

    def apply_gradients(self, grads) -> "TrainState":
        updates, new_opt_state = self.tx.update(grads, self.opt_state, self.params)
        new_params = optax.apply_updates(self.params, updates)
        return self.replace(step=self.step + 1, params=new_params, opt_state=new_opt_state)

    @classmethod
    def create(cls, apply_fn, params, tx, batch_stats=None, ema=False) -> "TrainState":
        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            batch_stats=batch_stats if batch_stats is not None else FrozenDict({}),
            opt_state=tx.init(params),
            ema_params=jax.tree_util.tree_map(jnp.array, params) if ema
            else FrozenDict({}),
            apply_fn=apply_fn,
            tx=tx,
        )


def ema_tree_update(decay: float, ema_params, params):
    """ema = d*ema + (1-d)*params — the ONE Polyak formula, shared by the
    per-dispatch jitted update below and the in-scan update of
    steps.make_multistep_train_step (so the k>1 path can never drift from
    the k=1 semantics)."""
    return jax.tree_util.tree_map(
        lambda e, p: e * decay + (1.0 - decay) * p, ema_params, params)


def make_ema_update(decay: float):
    """Jitted `state -> state` Polyak update.

    Kept OUTSIDE the per-task train steps so every trainer (classification,
    detection, pose, centernet) gets EMA with no per-task wiring; the
    elementwise tree op is negligible next to a train step."""
    def f(state: TrainState) -> TrainState:
        return state.replace(
            ema_params=ema_tree_update(decay, state.ema_params, state.params))
    return jax.jit(f, donate_argnums=0)


def init_model(model, rng: jax.Array, sample_input):
    """Initialize a Flax module, splitting out batch_stats if present."""
    # init in train mode so every branch's params materialize (e.g. Inception aux
    # heads exist only when train=True)
    variables = model.init({"params": rng, "dropout": jax.random.fold_in(rng, 1)},
                           sample_input, train=True)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", FrozenDict({}))
    return params, batch_stats


def param_count(params) -> int:
    return sum(x.size for x in jax.tree_util.tree_leaves(params))
