"""Train state pytree.

One struct covers every model family: `params` + optional `batch_stats` (BatchNorm
running stats — under jit+GSPMD the BN reduction spans the full global batch, i.e.
cross-replica sync-BN for free, unlike the reference's per-replica stats under
MirroredStrategy), optax `opt_state`, and the global `step`. This replaces the
reference's four checkpoint payload shapes (SURVEY.md §5.4) with one.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax
from flax import struct
from flax.core import FrozenDict


@struct.dataclass
class TrainState:
    step: jnp.ndarray
    params: Any
    batch_stats: Any
    opt_state: Any
    # static (not part of the pytree):
    apply_fn: Callable = struct.field(pytree_node=False)
    tx: optax.GradientTransformation = struct.field(pytree_node=False)

    def apply_gradients(self, grads) -> "TrainState":
        updates, new_opt_state = self.tx.update(grads, self.opt_state, self.params)
        new_params = optax.apply_updates(self.params, updates)
        return self.replace(step=self.step + 1, params=new_params, opt_state=new_opt_state)

    @classmethod
    def create(cls, apply_fn, params, tx, batch_stats=None) -> "TrainState":
        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            batch_stats=batch_stats if batch_stats is not None else FrozenDict({}),
            opt_state=tx.init(params),
            apply_fn=apply_fn,
            tx=tx,
        )


def init_model(model, rng: jax.Array, sample_input):
    """Initialize a Flax module, splitting out batch_stats if present."""
    # init in train mode so every branch's params materialize (e.g. Inception aux
    # heads exist only when train=True)
    variables = model.init({"params": rng, "dropout": jax.random.fold_in(rng, 1)},
                           sample_input, train=True)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", FrozenDict({}))
    return params, batch_stats


def param_count(params) -> int:
    return sum(x.size for x in jax.tree_util.tree_leaves(params))
