"""Semantic segmentation SPMD steps + trainer — the zoo's first
dense-prediction family.

The reference covers classification/detection/pose/GANs (PAPER.md §0);
segmentation is the workload the spatial mesh machinery was built for
(ROADMAP open item 4): dense per-pixel targets are row-sliceable exactly like
CenterNet's heatmaps, so the same halo/synced-BN/row-sliced-target recipe
carries a U-Net end to end under H-sharding (`parallel/spatial_shard.py::
make_shardmap_segmentation_train_step` for combined meshes; the GSPMD
`spatial_activation_constraints` path for plain (data, spatial) meshes).

Same shape as core/centernet.py: one jitted step over the mesh, pixel-wise
cross-entropy (+ optional soft-dice) computed on device, a streaming
confusion-matrix eval (mIoU / per-class IoU / pixel accuracy via
core/metrics.py), and a predict step returning int32 class-id masks — the
contract the serving engine exposes over POST /predict/<model>.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..parallel import mesh as mesh_lib
from . import metrics as metrics_lib
from .config import TrainConfig
from .steps import _normalize_input, annotate_step, maybe_grad_norm
from .trainer import Trainer

# TrainConfig.loss values this family understands; "xent_dice" adds the soft
# dice term at this weight (the boundary-sensitive complement of pixel CE)
DICE_WEIGHT = 0.5
DICE_EPS = 1.0


def dice_weight_for(config: TrainConfig) -> float:
    """Map the config's `loss` field to the dice weight: "softmax_xent"
    (the zoo default) is pure CE; "xent_dice" blends in the soft-dice term.
    Unknown values raise at trainer construction, not mid-epoch."""
    if config.loss in ("softmax_xent", "xent"):
        return 0.0
    if config.loss == "xent_dice":
        return DICE_WEIGHT
    raise ValueError(
        f"segmentation config {config.name!r} declares unknown loss "
        f"{config.loss!r}; expected 'softmax_xent' or 'xent_dice'")


def soft_dice_loss(logits: jnp.ndarray, masks: jnp.ndarray) -> jnp.ndarray:
    """Mean (1 - dice) over classes and batch: dice_c = (2·Σ p_c·y_c + eps)
    / (Σ p_c + Σ y_c + eps) with softmax probabilities p and one-hot ground
    truth y, pixel sums per example. The eps makes absent classes score
    dice 1 (no gradient pressure), the standard smooth-dice convention."""
    num_classes = logits.shape[-1]
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    onehot = jax.nn.one_hot(masks, num_classes, dtype=jnp.float32)
    inter = jnp.sum(probs * onehot, axis=(1, 2))          # (B, C)
    denom = jnp.sum(probs, axis=(1, 2)) + jnp.sum(onehot, axis=(1, 2))
    dice = (2.0 * inter + DICE_EPS) / (denom + DICE_EPS)
    return jnp.mean(1.0 - dice)


def segmentation_loss(logits: jnp.ndarray, masks: jnp.ndarray,
                      dice_weight: float = 0.0) -> dict:
    """{'total', 'ce'[, 'dice']}: mean pixel-wise softmax cross-entropy over
    the whole (batch × H × W) slab, plus `dice_weight` × soft dice. Logits
    (B, H, W, C) — f32 by the model's head contract; masks (B, H, W) int32
    class ids."""
    masks = masks.astype(jnp.int32)
    ce = optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), masks).mean()
    comp = {"ce": ce, "total": ce}
    if dice_weight > 0.0:
        dice = soft_dice_loss(logits, masks)
        comp["dice"] = dice
        comp["total"] = ce + dice_weight * dice
    return comp


def pixel_accuracy(logits: jnp.ndarray, masks: jnp.ndarray) -> jnp.ndarray:
    return jnp.mean((jnp.argmax(logits, axis=-1)
                     == masks.astype(jnp.int32)).astype(jnp.float32))


def make_segmentation_train_step(*, num_classes: int,
                                 compute_dtype=jnp.bfloat16,
                                 donate: bool = True, mesh=None,
                                 remat: bool = False, input_norm=None,
                                 device_augment: Optional[Callable] = None,
                                 dice_weight: float = 0.0,
                                 log_grad_norm: bool = False,
                                 grad_correction=None) -> Callable:
    """(state, images, masks, rng) -> (state, metrics).

    `device_augment` is the PAIRED stage (data/device_augment.
    make_paired_train_augment): images arrive as uint8 at the padded
    decode size WITH a same-size uint8 mask, and one folded per-step key
    drives the crop/flip draw applied to BOTH tensors — it replaces
    `input_norm` (the augment normalizes the image; passing both is an
    error). On spatial meshes the augment runs BEFORE the H-shard
    constraint, which is why this family passes the per-family capability
    check that refuses classification there. `remat=True` recomputes
    forward activations in backward (cf. steps.py)."""
    del num_classes  # the loss derives C from the logits' last dim
    if device_augment is not None and input_norm is not None:
        raise ValueError("device_augment already normalizes; passing "
                         "input_norm too would double-normalize")

    def step(state, images, masks, rng):
        step_rng = jax.random.fold_in(rng, state.step)
        if device_augment is not None:
            # fold tag 2, the classification step's convention: the paired
            # crop/flip draw is a pure function of (seed, step)
            images, masks = device_augment(
                images, masks, jax.random.fold_in(step_rng, 2))
        else:
            images = _normalize_input(images, input_norm, compute_dtype)
        masks = masks.astype(jnp.int32)
        if mesh is not None:
            images = jax.lax.with_sharding_constraint(
                images, mesh_lib.batch_sharding(mesh, images.ndim,
                                                dim1=images.shape[1]))

        def forward(params, images):
            with mesh_lib.spatial_activation_constraints(mesh):
                return state.apply_fn(
                    {"params": params, "batch_stats": state.batch_stats},
                    images, train=True, mutable=["batch_stats"])

        if remat:
            forward = jax.checkpoint(
                forward,
                policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable)

        def loss_fn(params):
            logits, mutated = forward(params, images)
            comp = segmentation_loss(logits, masks, dice_weight)
            return comp["total"], (logits, comp, mutated)

        (loss, (logits, comp, mutated)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)
        grads = mesh_lib.apply_grad_correction(grads, grad_correction)
        new_state = state.apply_gradients(grads).replace(
            batch_stats=mutated.get("batch_stats", state.batch_stats))
        metrics = {"loss": loss,
                   "pixel_acc": pixel_accuracy(logits, masks),
                   **{f"{k}_loss": v for k, v in comp.items()
                      if k != "total"},
                   **maybe_grad_norm(log_grad_norm, grads)}
        return new_state, metrics

    jit_kwargs = {}
    if donate:
        jit_kwargs["donate_argnums"] = (0,)
    if mesh is not None:
        jit_kwargs["out_shardings"] = (None, NamedSharding(mesh, P()))
    return annotate_step(jax.jit(step, **jit_kwargs), donate=donate,
                         compute_dtype=jnp.dtype(compute_dtype), kind="train")


def make_segmentation_eval_step(*, num_classes: int,
                                compute_dtype=jnp.bfloat16, mesh=None,
                                input_norm=None,
                                device_augment: Optional[Callable] = None,
                                dice_weight: float = 0.0) -> Callable:
    """(state, images, masks) -> {'loss', 'confusion'}: batch-mean loss plus
    the jit-safe (C, C) confusion COUNT matrix (core/metrics.py) — the host
    accumulates matrices across batches and derives mIoU / per-class IoU /
    pixel accuracy once per eval pass. `device_augment` here is the paired
    EVAL stage (deterministic center crop on both tensors)."""
    if device_augment is not None and input_norm is not None:
        raise ValueError("device_augment already normalizes; passing "
                         "input_norm too would double-normalize")

    def step(state, images, masks):
        if device_augment is not None:
            images, masks = device_augment(images, masks)
        else:
            images = _normalize_input(images, input_norm, compute_dtype)
        masks = masks.astype(jnp.int32)
        if mesh is not None:
            images = jax.lax.with_sharding_constraint(
                images, mesh_lib.batch_sharding(mesh, images.ndim,
                                                dim1=images.shape[1]))
        with mesh_lib.spatial_activation_constraints(mesh):
            logits = state.apply_fn(
                {"params": state.params, "batch_stats": state.batch_stats},
                images, train=False)
        comp = segmentation_loss(logits, masks, dice_weight)
        preds = jnp.argmax(logits, axis=-1)
        return {"loss": comp["total"],
                "confusion": metrics_lib.confusion_matrix(
                    preds, masks, num_classes)}

    jit_kwargs = {}
    if mesh is not None:
        jit_kwargs["out_shardings"] = NamedSharding(mesh, P())
    return annotate_step(jax.jit(step, **jit_kwargs), donate=False,
                         compute_dtype=jnp.dtype(compute_dtype), kind="eval")


def make_segmentation_predict_step(*, compute_dtype=jnp.bfloat16,
                                   input_norm=None) -> Callable:
    """(state, images) -> int32 (B, H, W) class-id masks — argmax over the
    f32 logits, the exact payload serving returns (serve/engine.py applies
    the same argmax transform so the two can't drift in spirit; this step
    is the library/eval-tool surface)."""

    def step(state, images):
        x = _normalize_input(images, input_norm, compute_dtype)
        logits = state.apply_fn(
            {"params": state.params, "batch_stats": state.batch_stats},
            x, train=False)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    return annotate_step(jax.jit(step), donate=False,
                         compute_dtype=jnp.dtype(compute_dtype),
                         kind="predict")


class SegmentationTrainer(Trainer):
    """U-Net family trainer: shared epoch/checkpoint/plateau machinery with
    segmentation steps, a confusion-matrix evaluate (mIoU watched for
    best-model selection), and paired device augmentation."""

    default_watch = ("miou", "max")
    has_own_shardmap_step = True  # make_shardmap_segmentation_train_step

    def __init__(self, config: TrainConfig, model=None, mesh=None,
                 workdir: Optional[str] = None):
        if config.mixup_alpha or config.cutmix_alpha:
            # blending class-id masks is meaningless; erroring beats a
            # silent no-op (the LossWatchedTrainer convention)
            raise ValueError(
                "mixup_alpha/cutmix_alpha are classification-only; "
                "SegmentationTrainer trains on per-pixel class ids — use "
                "the paired device augmentation (--device-augment) instead")
        super().__init__(config, model=model, mesh=mesh, workdir=workdir)
        compute_dtype = (jnp.dtype(config.dtype) if config.dtype
                         else jnp.bfloat16)
        input_norm = ((config.data.mean, config.data.std)
                      if config.data.normalize_on_device else None)
        if config.device_augment:
            input_norm = None  # the paired augment normalizes
        dice_weight = dice_weight_for(config)
        if self._use_shardmap_spatial():
            # owned collectives: fully convolutional, H sharded end to end
            # with row-sliced masks (transition None — the CenterNet recipe)
            from ..parallel import spatial_shard
            transition = spatial_shard.default_transition(self.model)
            assert transition is None, type(self.model).__name__
            self._step_factory = (
                lambda m, corr: spatial_shard
                .make_shardmap_segmentation_train_step(
                    num_classes=config.data.num_classes,
                    image_size=config.data.image_size,
                    compute_dtype=compute_dtype, mesh=m,
                    input_norm=input_norm,
                    device_augment=self._train_augment,
                    dice_weight=dice_weight,
                    log_grad_norm=config.log_grad_norm,
                    remat=config.remat,
                    donate=config.donate_step()))
        else:
            self._step_factory = (
                lambda m, corr: make_segmentation_train_step(
                    num_classes=config.data.num_classes,
                    compute_dtype=compute_dtype, mesh=m, remat=config.remat,
                    input_norm=input_norm,
                    device_augment=self._train_augment,
                    dice_weight=dice_weight,
                    log_grad_norm=config.log_grad_norm,
                    donate=config.donate_step(),
                    grad_correction=corr))
        self.train_step = self._step_factory(self.mesh, None)
        self.eval_step = make_segmentation_eval_step(
            num_classes=config.data.num_classes, compute_dtype=compute_dtype,
            mesh=self.mesh, input_norm=input_norm,
            device_augment=self._eval_augment, dice_weight=dice_weight)

    def _build_device_augment(self, compute_dtype) -> None:
        """Paired image/mask stages (data/device_augment.py): one crop/flip
        draw per example applied to both tensors."""
        from ..data import device_augment as daug
        config = self.config
        mean = daug.channel_stats(config.data.mean, config.data.channels)
        std = daug.channel_stats(config.data.std, config.data.channels)
        self._train_augment = daug.make_paired_train_augment(
            config.data.image_size, mean=mean, std=std,
            compute_dtype=compute_dtype)
        self._eval_augment = daug.make_paired_eval_augment(
            config.data.image_size, mean=mean, std=std,
            compute_dtype=compute_dtype)

    def _calibration_batch(self, sample_shape, seed: int = 0):
        rs = np.random.RandomState(seed)
        b = self._calibration_batch_size()
        s = sample_shape[0]
        ch = sample_shape[-1]
        num_classes = self.config.data.num_classes
        if self.config.device_augment:
            # the step's contract is PAIRED uint8 at the decode size; the
            # jitted augment crops both down to sample_shape
            from .config import decode_image_size
            d = decode_image_size(s)
            images = rs.randint(0, 256, (b, d, d, ch)).astype(np.uint8)
            masks = rs.randint(0, num_classes, (b, d, d)).astype(np.uint8)
            return (images, masks)
        masks = rs.randint(0, num_classes, (b, s, s)).astype(np.int32)
        if self.config.data.normalize_on_device:
            images = rs.randint(0, 256, (b, *sample_shape)).astype(np.uint8)
        else:
            images = rs.rand(b, *sample_shape).astype(np.float32) * 2.0 - 1.0
        return (images, masks)

    def evaluate(self, data) -> dict:
        """Streaming-confusion eval: per-batch (C, C) count matrices sum on
        the host (core/metrics.StreamingConfusion) and mIoU / pixel accuracy
        derive from the totals — the loss is the mean of finite per-batch
        losses (the NaN-batch guard, like LossWatchedTrainer). Batches are
        fixed-shape (drop-remainder pipelines), no padding."""
        eval_state = self.eval_state()
        stream = metrics_lib.StreamingConfusion(self.config.data.num_classes)
        total, n = 0.0, 0
        for batch in data:
            sharded = mesh_lib.shard_batch_pytree(self.mesh, tuple(batch))
            out = jax.device_get(self.eval_step(eval_state, *sharded))
            loss = float(out["loss"])
            if np.isfinite(loss):
                total += loss
                n += 1
            stream.update(out["confusion"])
        if n == 0:
            return {}
        scores = stream.result()
        return {"loss": total / n, "count": float(n),
                "miou": float(scores["miou"]),
                "pixel_acc": float(scores["pixel_acc"])}
