"""Mesh-aware resharding restore: save on N chips, restore on M.

The checkpoint layer (core/checkpoint.py + core/integrity.py) made saves
verified and restores fall back through good generations — but every restore
still assumed the mesh shape the checkpoint was saved under. Production pods
are elastic: a run preempted on a v5e-8 relaunches on a v5e-4, a serving
host restores a pod-trained checkpoint on one chip, an operator flips
`--model-parallel` between attempts (ROADMAP item 3). This module makes the
mesh a recorded, checkable property of every checkpoint instead of a silent
assumption:

- `sharding_section(payload, mesh)` is stamped into the PR 4 integrity
  manifest at save time: the mesh topology (axis names/sizes, device and
  process counts) plus the per-leaf PartitionSpec of every payload leaf,
  self-digested so tampering reads as corruption (`integrity.verify_files`
  recomputes the digest);
- on restore, the manager compares the manifest's saved topology against
  its target mesh. A match restores natively (today's path, zero overhead).
  A MISMATCH takes the resharding path: the payload is restored **host-
  side** (numpy template — no device-layout assumptions for Orbax to trip
  over), deep-verified against the manifest's shape/dtype/hash source of
  truth, and every leaf is `device_put` under the sharding the restore
  template carries for it — params under the target mesh's
  `param_sharding_rules`, optimizer/EMA/batch-stats trees placed exactly
  like the trainer's `init_state` would, because the template IS the
  trainer's initialized state;
- a mismatch that cannot be resolved (no manifest to trust, or the native
  path failing on a legacy dir) raises a typed `MeshMismatch` naming the
  saved and target topologies instead of an opaque Orbax shape error.

Everything here is single-dispatch host logic — no collectives. On
multi-process runs the placement uses `make_array_from_callback` for
non-fully-addressable shardings, so each host materializes only the shards
it owns and no hidden DCN collective is introduced on the restore path.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from . import integrity


class MeshMismatch(RuntimeError):
    """A checkpoint's saved mesh topology differs from the restore target in
    a way the resharding path cannot bridge (typically: no integrity
    manifest to reshard against). Carries both topologies so the report
    names the actual shapes instead of an opaque deserialization error."""

    def __init__(self, saved: Optional[dict], target: Optional[dict],
                 detail: str = ""):
        self.saved = saved
        self.target = target
        super().__init__(
            f"mesh mismatch: checkpoint saved on {describe_topology(saved)}, "
            f"restore target is {describe_topology(target)}"
            + (f" — {detail}" if detail else ""))


# -- topology ------------------------------------------------------------------

def mesh_topology(mesh) -> dict:
    """JSON-able topology record of a jax Mesh: axis names/sizes in mesh
    order plus device/process counts — what save stamps and restore
    compares."""
    import jax
    return {
        "axes": {str(k): int(v) for k, v in mesh.shape.items()},
        "device_count": int(mesh.devices.size),
        "process_count": int(jax.process_count()),
    }


def topology_from_leaves(payload) -> Optional[dict]:
    """Derive the topology from the first NamedSharding leaf — the fallback
    for managers constructed without an explicit mesh."""
    import jax
    from jax.sharding import NamedSharding
    for leaf in jax.tree_util.tree_leaves(payload):
        sh = getattr(leaf, "sharding", None)
        if isinstance(sh, NamedSharding):
            return mesh_topology(sh.mesh)
    return None


def describe_topology(topo: Optional[dict]) -> str:
    """'data=4 x model=2 (8 devices, 1 process)' — the human form used by
    MeshMismatch reports, restore logs, and fsck."""
    if not topo:
        return "unknown (no recorded topology)"
    axes = " x ".join(f"{k}={v}" for k, v in (topo.get("axes") or {}).items())
    return (f"{axes or 'unnamed axes'} ({topo.get('device_count')} devices, "
            f"{topo.get('process_count')} process"
            f"{'es' if topo.get('process_count') != 1 else ''})")


def topologies_differ(saved: dict, target: dict) -> bool:
    """True when a restore under `target` needs resharding. Size-1 axes are
    normalized away (a (data=8, model=1) mesh and a (data=8) mesh place
    every array identically), so only real shape changes pay the reshard."""
    def norm(t):
        return {k: v for k, v in (t.get("axes") or {}).items() if v > 1}
    return (norm(saved) != norm(target)
            or saved.get("device_count") != target.get("device_count")
            or saved.get("process_count") != target.get("process_count"))


def manifest_topology(manifest: Optional[dict]) -> Optional[dict]:
    if not manifest:
        return None
    return (manifest.get("sharding") or {}).get("mesh")


# -- per-leaf specs ------------------------------------------------------------

def leaf_spec(leaf) -> Optional[list]:
    """JSON-able PartitionSpec of a NamedSharding leaf (None | axis name |
    list of axis names per dim); None for host arrays / single-device
    placements — those carry no mesh layout to record."""
    from jax.sharding import NamedSharding
    sh = getattr(leaf, "sharding", None)
    if not isinstance(sh, NamedSharding):
        return None
    out = []
    for entry in sh.spec:
        if entry is None:
            out.append(None)
        elif isinstance(entry, (tuple, list)):
            out.append([str(e) for e in entry])
        else:
            out.append(str(entry))
    return out


def sharding_section(payload, mesh=None) -> dict:
    """The manifest's `sharding` section: saved topology + per-leaf specs,
    keyed exactly like the integrity manifest's `leaves` (jax keystr), and
    self-digested (`integrity.sharding_digest`) so a tampered section is
    detected as corruption rather than silently steering a reshard."""
    import jax
    specs: Dict[str, Any] = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(payload)[0]:
        specs[jax.tree_util.keystr(path)] = leaf_spec(leaf)
    topo = mesh_topology(mesh) if mesh is not None \
        else topology_from_leaves(payload)
    section = {"mesh": topo, "leaves": specs}
    section["digest"] = integrity.sharding_digest(section)
    return section


# -- host-side restore + replacement ------------------------------------------

def host_template(template):
    """Numpy restore template mirroring a (possibly device-resident) payload
    template: same tree, same shapes/dtypes, zero device state — Orbax
    restores into it entirely host-side, with no saved-vs-target sharding
    for the deserializer to reconcile."""
    import jax

    def leaf(x):
        # np.asarray fallback: a rare non-array host leaf (python scalar)
        # must keep its real dtype or Orbax refuses the template
        return np.empty(np.shape(x),
                        getattr(x, "dtype", None) or np.asarray(x).dtype)
    return jax.tree_util.tree_map(leaf, template)


def put_like(host_payload, template):
    """Place a host-restored payload under the shardings the restore
    template carries — params under the target mesh's rules, the rest
    replicated, because the template is the trainer's initialized state.

    Structure may differ from the template by exactly the EMA slot
    (checkpoint.py's flip contract): an `ema_params` subtree present on
    disk but absent from the template is placed like `params` (same tree,
    same rules). Leaves whose template counterpart has no sharding (plain
    host payloads) stay host-side, matching the native restore's behavior
    for numpy templates."""
    import jax

    flat_t = {jax.tree_util.keystr(p): leaf for p, leaf
              in jax.tree_util.tree_flatten_with_path(template)[0]}

    def target_sharding(key: str):
        leaf = flat_t.get(key)
        if leaf is None and key.startswith("['ema_params']"):
            leaf = flat_t.get("['params']" + key[len("['ema_params']"):])
        return getattr(leaf, "sharding", None)

    flat_h, treedef = jax.tree_util.tree_flatten_with_path(host_payload)
    placed = []
    for path, leaf in flat_h:
        sharding = target_sharding(jax.tree_util.keystr(path))
        placed.append(leaf if sharding is None
                      else _put_global(np.asarray(leaf), sharding))
    return jax.tree_util.tree_unflatten(treedef, placed)


def _put_global(arr: np.ndarray, sharding):
    """device_put a host-global value under `sharding`. On multi-process
    meshes `jax.device_put` would treat the host value as global and
    assert equality across hosts with a hidden DCN collective;
    `make_array_from_callback` instead hands each process exactly the
    shards it owns — every host restored the same bytes (hash-verified),
    so the assembled global array is consistent by construction."""
    import jax
    if getattr(sharding, "is_fully_addressable", True):
        return jax.device_put(arr, sharding)
    return jax.make_array_from_callback(arr.shape, sharding,
                                        lambda idx: arr[idx])
