"""Predict-side watch metrics: score a model family from its SERVING outputs.

Two serving-side gates need the same primitive — "replay a pinned shard
through the engine and reduce the outputs to one scalar the family watches":

- the accuracy-gated promotion pipeline (serve/promote.py) comparing two
  WEIGHT generations, and
- the int8 quantization gate (serve/quantize.py) comparing two PRECISIONS
  of one generation.

Until this module, only classification (top-1 from logits) and segmentation
(mIoU from class-id masks) could be scored, so detection/pose/centernet
took the integrity-only path. This closes the ROADMAP item-3 follow-up with
predict-side PROXY scores for the remaining families, computed from exactly
the payloads clients get:

- detection (YOLO serves decoded (boxes, objectness, class_probs) triples
  per scale): a box-count agreement score — per image, the number of
  anchors with objectness > 0.5 against the ground-truth box count,
  reduced as mean(1 / (1 + |pred - true|)). Coarser than mAP, but it is
  monotone in the right thing (a generation or precision that moves
  objectness across the decision threshold moves the score) and needs no
  NMS replay on the host.
- pose (hourglass serves per-stack heatmaps): PCK@0.2 on the LAST stack —
  the fraction of visible keypoints whose heatmap argmax lands within 0.2
  (normalized) of the ground truth.
- centernet (per-stack {heatmap, wh, offset} head dicts): the same
  box-count agreement score over sigmoid(heatmap) peaks > 0.3 on the last
  stack.

All three are deltas-not-absolutes metrics: the gates only ever compare
score(A) - score(B) on IDENTICAL pinned inputs, so a proxy that tracks
prediction movement is sufficient — docs/SERVING.md "Promotion" states the
contract.

`pinned_shard` is the one source of those pinned inputs: deterministic per
(config, seed) down to the byte (tests/test_quant.py pins equality across
processes), shaped/dtyped for the engine, built from each family's own
synthetic generator / calibration-batch recipe.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

# families whose watched metric is computable from serving outputs — with
# the detection/pose/centernet proxies, every servable (non-GAN) family
GATED_FAMILIES = ("classification", "segmentation", "detection", "pose",
                  "centernet")

DEFAULT_SHARD_SEED = 12345

# decision thresholds of the count proxies (objectness sigmoid for YOLO,
# peak sigmoid for CenterNet's -2.19-biased heatmap head) and the PCK
# radius, in normalized coordinates
DETECTION_OBJ_THRESH = 0.5
CENTERNET_PEAK_THRESH = 0.3
PCK_RADIUS = 0.2


def watch_metric_name(cfg) -> str:
    """The scalar `score_serving_outputs` reduces to, for logs/healthz."""
    return {"classification": "top1", "segmentation": "miou",
            "detection": "box_count", "centernet": "box_count",
            "pose": "pck"}[cfg.family]


def serving_head_dims(cfg) -> frozenset:
    """Dimensions that identify the DELIBERATE f32 output heads of a
    declared-bf16 model (`models/*.py`: `nn.Dense(num_classes,
    dtype=jnp.float32)`, the f32 detection/pose head convs). Shared by
    jaxvet's DTYPE rule (an f32 conv/dot is policy-conformant iff it
    touches one of these dims) and the int8 quantization plan (the same
    equations stay in float — the head keeps full precision)."""
    nc = cfg.data.num_classes
    dims = {nc}
    if cfg.family == "detection":        # YOLO: 3 anchors x (5 + nc) head
        dims.add(3 * (5 + nc))
    if cfg.family == "centernet":        # heatmap nc + wh/offset pairs, and
        dims.update({nc, 2, 64})         # the shared 64-wide f32 head conv
    if cfg.family == "pose":             # per-stack heatmap heads
        dims.add(nc)
    if cfg.family == "segmentation":     # the f32 1x1 class-logit head
        dims.add(nc)
    return frozenset(d for d in dims if d)


def pinned_shard(cfg, *, image_size: int, input_dtype,
                 examples: int = 64,
                 seed: int = DEFAULT_SHARD_SEED) -> Tuple[np.ndarray, tuple]:
    """One deterministic labeled batch for shadow eval / quantization
    calibration: `(images, targets)` where `targets` is the family's
    ground-truth tuple. Byte-identical per (config, image_size, dtype,
    examples, seed) — both gates score live-vs-candidate (or bf16-vs-int8)
    on IDENTICAL inputs, so the delta is pure weight/precision difference.
    Production deployments pass a real held-out shard instead; the
    synthetic default keeps the gates closed-loop testable with no data on
    disk."""
    h = int(image_size)
    ch = cfg.data.channels
    input_dtype = np.dtype(input_dtype)
    emit_uint8 = input_dtype == np.dtype(np.uint8)
    if cfg.family == "classification":
        from ..data.synthetic import SyntheticClassification
        gen = SyntheticClassification(
            examples, image_size=h, channels=ch,
            num_classes=cfg.data.num_classes, num_batches=1, seed=seed,
            emit_uint8=emit_uint8)
        images, labels = next(iter(gen))
        return images.astype(input_dtype), (np.asarray(labels, np.int64),)
    if cfg.family == "segmentation":
        from ..data.segmentation import SyntheticSegmentation
        gen = SyntheticSegmentation(
            examples, image_size=h, channels=ch,
            num_classes=cfg.data.num_classes, num_batches=1, seed=seed,
            emit_uint8=emit_uint8)
        images, masks = next(iter(gen))
        return images.astype(input_dtype), (np.asarray(masks, np.int64),)
    rs = np.random.RandomState(seed)
    if cfg.family in ("detection", "centernet"):
        from ..ops.yolo import MAX_BOXES
        images = (rs.randint(0, 256, (examples, h, h, ch))
                  if emit_uint8
                  else rs.rand(examples, h, h, ch) * 2.0 - 1.0)
        # varying per-example box counts: the count-agreement proxy needs
        # per-image diversity, unlike the uniform one-box grad-calibration
        # batch (core/detection.boxes_calibration_batch)
        boxes = np.zeros((examples, MAX_BOXES, 4), np.float32)
        classes = np.zeros((examples, MAX_BOXES), np.int32)
        valid = np.zeros((examples, MAX_BOXES), np.float32)
        counts = rs.randint(0, 4, size=(examples,))
        for i, n in enumerate(counts):
            for j in range(n):
                cx, cy = rs.uniform(0.2, 0.8, size=2)
                w = rs.uniform(0.1, 0.3)
                boxes[i, j] = [cx, cy, w, w]
                classes[i, j] = rs.randint(0, cfg.data.num_classes)
                valid[i, j] = 1.0
        return images.astype(input_dtype), (boxes, classes, valid)
    if cfg.family == "pose":
        k = cfg.data.num_classes           # keypoint count (MPII: 16)
        images = (rs.randint(0, 256, (examples, h, h, ch))
                  if emit_uint8 else rs.rand(examples, h, h, ch))
        kp_x = rs.rand(examples, k).astype(np.float32)
        kp_y = rs.rand(examples, k).astype(np.float32)
        visibility = (rs.rand(examples, k) > 0.2).astype(np.float32)
        return images.astype(input_dtype), (kp_x, kp_y, visibility)
    raise ValueError(
        f"config {cfg.name!r} (family {cfg.family!r}) has no predict-side "
        f"watch metric — gated families: {GATED_FAMILIES}")


def input_moments(images: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Per-channel (mean, std) of one `(n, h, w, c)` image batch, float64.
    The single moment recipe both sides of the flywheel drift comparison
    use (flywheel/drift.py): the pinned calibration shard's reference
    moments and every live reservoir window are reduced HERE, so the two
    can never disagree on normalization, dtype, or axis order."""
    x = np.asarray(images, np.float64)
    if x.ndim != 4:
        raise ValueError(f"expected a (n, h, w, c) image batch, got shape "
                         f"{x.shape}")
    mean = x.mean(axis=(0, 1, 2))
    std = x.std(axis=(0, 1, 2))
    return mean, std


def moment_shift(ref_mean: np.ndarray, ref_std: np.ndarray,
                 mean: np.ndarray, std: np.ndarray) -> float:
    """Scalar drift score between two per-channel moment sets: the worst
    channel's |Δmean| in reference-std units, plus the worst relative std
    change — dimensionless, 0.0 for identical distributions, ~1.0 when a
    channel's mean moved one reference-σ (the flywheel gate's unit)."""
    ref_mean = np.asarray(ref_mean, np.float64)
    ref_std = np.asarray(ref_std, np.float64)
    eps = 1e-6
    dmean = float(np.max(np.abs(np.asarray(mean, np.float64) - ref_mean)
                         / (ref_std + eps)))
    dstd = float(np.max(np.abs(np.asarray(std, np.float64) - ref_std)
                        / (ref_std + eps)))
    return dmean + 0.5 * dstd


def _sigmoid(x: np.ndarray) -> np.ndarray:
    return 1.0 / (1.0 + np.exp(-np.clip(x, -30.0, 30.0)))


def _count_agreement(pred_counts: np.ndarray,
                     true_counts: np.ndarray) -> float:
    """mean(1 / (1 + |pred - true|)) in (0, 1] — 1.0 iff every image's
    predicted count matches; smooth in the miss size, so threshold-crossing
    perturbations (a regressed generation, a bad quantization) move it."""
    return float(np.mean(1.0 / (1.0 + np.abs(
        pred_counts.astype(np.float64) - true_counts.astype(np.float64)))))


def score_serving_outputs(cfg, outputs, targets) -> float:
    """Reduce one engine `predict()` output pytree + the pinned targets to
    the family's watched scalar. `outputs` is exactly what serving clients
    get (f32 logits / int32 masks / decoded triples / heatmaps)."""
    import jax

    if cfg.family == "classification":
        (labels,) = targets
        logits = np.asarray(jax.tree_util.tree_leaves(outputs)[0])
        pred = np.argmax(logits, axis=-1).astype(np.int64)
        return float(np.mean(pred == np.asarray(labels)))
    if cfg.family == "segmentation":
        (masks,) = targets
        from .metrics import StreamingConfusion
        sc = StreamingConfusion(cfg.data.num_classes)
        out = np.asarray(jax.tree_util.tree_leaves(outputs)[0])
        sc.update_preds(out.astype(np.int64), np.asarray(masks))
        return float(sc.result()["miou"])
    if cfg.family == "detection":
        # decoded per-scale triples (boxes, objectness, class_probs); the
        # objectness leaves are every 2nd-of-3 leaf (models/yolo.py decode)
        _, _, valid = targets
        leaves = jax.tree_util.tree_leaves(outputs)
        assert len(leaves) % 3 == 0, "expected per-scale decoded triples"
        b = np.asarray(leaves[0]).shape[0]
        pred = np.zeros((b,), np.int64)
        for i in range(1, len(leaves), 3):      # the objectness leaves
            obj = np.asarray(leaves[i]).reshape(b, -1)
            pred += np.sum(obj > DETECTION_OBJ_THRESH, axis=-1)
        true = np.sum(np.asarray(valid) > 0, axis=-1)
        return _count_agreement(pred, true)
    if cfg.family == "centernet":
        # per-stack {heatmap, wh, offset} dicts: peaks of the LAST stack's
        # pre-sigmoid heatmap (bias -2.19 — models/centernet.py)
        _, _, valid = targets
        hm = np.asarray(outputs[-1]["heatmap"])
        b = hm.shape[0]
        peaks = _sigmoid(hm).reshape(b, -1) > CENTERNET_PEAK_THRESH
        true = np.sum(np.asarray(valid) > 0, axis=-1)
        return _count_agreement(np.sum(peaks, axis=-1), true)
    if cfg.family == "pose":
        kp_x, kp_y, vis = targets
        hm = np.asarray(outputs[-1] if isinstance(outputs, (tuple, list))
                        else outputs)           # last stack (B, h, w, K)
        b, hh, ww, k = hm.shape
        flat = hm.reshape(b, hh * ww, k)
        idx = np.argmax(flat, axis=1)            # (B, K)
        py = (idx // ww) / max(hh - 1, 1)
        px = (idx % ww) / max(ww - 1, 1)
        d = np.sqrt((px - np.asarray(kp_x)) ** 2
                    + (py - np.asarray(kp_y)) ** 2)
        v = np.asarray(vis) > 0
        hits = (d < PCK_RADIUS) & v
        n_vis = max(int(np.sum(v)), 1)
        return float(np.sum(hits) / n_vis)
    raise ValueError(
        f"config {cfg.name!r} (family {cfg.family!r}) has no predict-side "
        f"watch metric — gated families: {GATED_FAMILIES}")
