"""Checkpoint save/restore via Orbax.

One mechanism replacing the reference's four (SURVEY.md §5.4): torch dict-per-epoch
(`ResNet/pytorch/train.py:417-428`), Keras hdf5 callback, save-best weights with the
metric in the filename (`YOLO/tensorflow/train.py:244-257`), and
`tf.train.Checkpoint`+Manager (`CycleGAN/tensorflow/train.py:134-148`). Payload is
`{params, batch_stats, opt_state, step}` plus host metadata (epoch, plateau state,
metric history), with keep-latest and keep-best policies and atomic writes (safe for
preemption — a gap called out in SURVEY.md §5.3).
"""

from __future__ import annotations

import json
import os
import shutil
from typing import Any, Dict, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

from .train_state import TrainState


class CheckpointManager:
    def __init__(self, directory: str, keep: int = 3, keep_best: bool = True,
                 best_mode: str = "max", async_save: bool = True):
        """`async_save=True` (SURVEY.md §5.4's async-save goal): `save()`
        kicks off the write in a background thread and training continues on
        device; `restore()`/`close()` barrier on any in-flight save."""
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.keep = keep
        self.keep_best = keep_best
        self.best_mode = best_mode
        self.async_save = async_save
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep,
                best_fn=(lambda m: m.get("best_metric", 0.0)) if keep_best else None,
                best_mode=best_mode if keep_best else "max",
                keep_checkpoints_without_metrics=True,
                create=True,
                enable_async_checkpointing=async_save,
            ),
        )

    @staticmethod
    def _payload(state):
        """TrainState → dict payload; any other pytree (e.g. the GAN trainers'
        {gen, disc} dicts) is saved as-is."""
        if isinstance(state, TrainState):
            return {
                "step": state.step,
                "params": state.params,
                "batch_stats": state.batch_stats,
                "opt_state": state.opt_state,
            }
        return state

    def save(self, epoch: int, state, host_state: Optional[Dict[str, Any]] = None,
             metric: Optional[float] = None):
        """Save at `epoch` (reference saves per-epoch with epoch in the payload,
        ResNet/pytorch/train.py:417-428)."""
        payload = self._payload(state)
        metrics = {"best_metric": float(metric)} if metric is not None else None
        self._mgr.save(
            epoch,
            args=ocp.args.Composite(
                state=ocp.args.StandardSave(payload),
                host=ocp.args.JsonSave(host_state or {}),
            ),
            metrics=metrics,
        )
        if not self.async_save:
            self._mgr.wait_until_finished()

    def latest_epoch(self) -> Optional[int]:
        self._mgr.wait_until_finished()
        return self._mgr.latest_step()

    def best_epoch(self) -> Optional[int]:
        self._mgr.wait_until_finished()
        return self._mgr.best_step()

    def restore(self, state, epoch: Optional[int] = None):
        """Restore into an abstract/concrete template (TrainState or pytree);
        returns (state, host_state, epoch). `epoch=None` → latest
        (auto-resume-from-latest)."""
        self._mgr.wait_until_finished()  # barrier on any in-flight async save
        if epoch is None:
            epoch = self._mgr.latest_step()
        if epoch is None:
            return state, {}, None
        template = self._payload(state)
        restored = self._mgr.restore(
            epoch,
            args=ocp.args.Composite(
                state=ocp.args.StandardRestore(template),
                host=ocp.args.JsonRestore(),
            ),
        )
        payload = restored["state"]
        if isinstance(state, TrainState):
            new_state = state.replace(
                step=payload["step"], params=payload["params"],
                batch_stats=payload["batch_stats"], opt_state=payload["opt_state"])
        else:
            new_state = payload
        return new_state, dict(restored["host"] or {}), epoch

    def close(self):
        self._mgr.wait_until_finished()
        self._mgr.close()
