"""Checkpoint save/restore via Orbax, with an integrity layer.

One mechanism replacing the reference's four (SURVEY.md §5.4): torch dict-per-epoch
(`ResNet/pytorch/train.py:417-428`), Keras hdf5 callback, save-best weights with the
metric in the filename (`YOLO/tensorflow/train.py:244-257`), and
`tf.train.Checkpoint`+Manager (`CycleGAN/tensorflow/train.py:134-148`). Payload is
`{params, batch_stats, opt_state, step}` plus host metadata (epoch, plateau state,
metric history), with keep-latest and keep-best policies and atomic writes (safe for
preemption — a gap called out in SURVEY.md §5.3).

Integrity (core/integrity.py): every save also commits an
`integrity_manifest.json` into the epoch dir — per-leaf shapes/dtypes/content
hashes plus a per-file size+sha256 inventory — written by a finalizer thread
AFTER the Orbax commit, so training never blocks on hashing and a manifest's
presence certifies the save finished. `restore()` verifies by default and, in
fallback mode, quarantines a corrupt epoch (`corrupt-<epoch>/`) and lands on
the next-newest generation that verifies — a run resumes from epoch N-1
instead of dying on an opaque deserialization error. Failures inside the
async background write (previously lost until `close()`) are captured by the
finalizer and re-raised through the `what="ckpt_save"` retry path at the
next `save()`/`flush()` barrier.

Elastic restore (core/reshard.py): every save also stamps the mesh topology
and per-leaf sharding specs into that manifest, and `restore()` accepts a
checkpoint saved under a DIFFERENT mesh than the manager's target mesh:
the payload is restored host-side, deep-verified against the manifest's
shape/dtype/hash source of truth, and re-placed under the template's target
shardings — so a run preempted on N chips resumes on M (or with
--model-parallel/--spatial-parallel flipped), and the next save re-stamps
the current mesh so later restores are native again. A mismatch that cannot
be resolved (e.g. a legacy dir with no manifest whose native restore fails)
raises a typed `MeshMismatch` naming both topologies.
"""

from __future__ import annotations

import os
import queue
import sys
import threading
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import orbax.checkpoint as ocp

from . import integrity, reshard
from .integrity import CheckpointCorruptionError  # noqa: F401 — re-export:
# callers catch it from the module that raised it
from .reshard import MeshMismatch  # noqa: F401 — re-export, same contract
from .resilience import RetryPolicy, call_with_retry
from .train_state import TrainState

# restore() verification modes: "fallback" verifies and walks back to the
# next-newest generation that passes (quarantining what didn't), "strict"
# raises on the first unverified checkpoint, "off" is the pre-integrity
# behavior. True/False/None are accepted aliases for CLI/bool callers.
VERIFY_MODES = ("fallback", "strict", "off")


class CheckpointManager:
    def __init__(self, directory: str, keep: int = 3, keep_best: bool = True,
                 best_mode: str = "max", async_save: bool = True,
                 retry_policy: Optional[RetryPolicy] = None,
                 on_retry=None, fault_injector=None, mesh=None):
        """`async_save=True` (SURVEY.md §5.4's async-save goal): `save()`
        kicks off the write in a background thread and training continues on
        device; `restore()`/`close()` barrier on any in-flight save.

        `retry_policy` arms transient-I/O retry with backoff around save and
        restore (flaky storage must cost a logged retry, not the run);
        `on_retry(what, attempt, exc, delay)` is the trainers' logging hook,
        and `fault_injector` (utils/faults.py) provides the deterministic
        checkpoint-write failures AND post-commit corruption the resilience
        and integrity tests inject.

        `mesh` is the owner's device mesh — the RESTORE TARGET for elastic
        resume (core/reshard.py) and the topology every save stamps into
        its manifest. None (plain payload callers) keeps the pre-elastic
        behavior: topology is still derived from NamedSharding leaves when
        present, and restore is native-only."""
        self.mesh = mesh
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.keep = keep
        self.keep_best = keep_best
        self.best_mode = best_mode
        self.async_save = async_save
        self.retry_policy = retry_policy or RetryPolicy()
        self.on_retry = on_retry
        self.fault_injector = fault_injector
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep,
                best_fn=(lambda m: m.get("best_metric", 0.0)) if keep_best else None,
                best_mode=best_mode if keep_best else "max",
                keep_checkpoints_without_metrics=True,
                create=True,
                enable_async_checkpointing=async_save,
            ),
        )
        # provenance of the last successful restore: {"epoch", "verified",
        # "manifest_sha256", "fallback_skipped"/"legacy"/"mode"} — serving
        # reports it on /healthz so replicas can be audited for weight skew
        self.last_restore_info: Optional[Dict[str, Any]] = None
        # per-restore elastic record ({"resharded", "saved_mesh"}), written
        # by _restore_epoch and merged into last_restore_info by restore()
        self._last_reshard_info: Dict[str, Any] = {"resharded": False,
                                                   "saved_mesh": None}
        # Integrity finalizer: one worker thread waits for each Orbax commit
        # off the training thread, writes the manifest into the committed
        # epoch dir, and CAPTURES background-write failures (previously those
        # surfaced only from wait_until_finished at close — i.e. silently
        # after the run had moved on). A captured error re-raises through the
        # ckpt_save retry path at the next save()/flush() barrier.
        self._finalize_q: "queue.Queue" = queue.Queue()
        self._async_error: Optional[BaseException] = None
        self._error_lock = threading.Lock()
        self._finalizer = threading.Thread(
            target=self._finalize_loop, daemon=True, name="ckpt-finalizer")
        self._finalizer.start()

    @staticmethod
    def _payload(state):
        """TrainState → dict payload; any other pytree (e.g. the GAN trainers'
        {gen, disc} dicts) is saved as-is. `ema_params` is included only when
        EMA is enabled (non-empty), so non-EMA checkpoints keep their layout."""
        if isinstance(state, TrainState):
            p = {
                "step": state.step,
                "params": state.params,
                "batch_stats": state.batch_stats,
                "opt_state": state.opt_state,
            }
            if jax.tree_util.tree_leaves(state.ema_params):
                p["ema_params"] = state.ema_params
            return p
        return state

    def _step_dir(self, epoch: int) -> str:
        return os.path.join(self.directory, str(epoch))

    @staticmethod
    def _log(msg: str) -> None:
        print(f"[ckpt] {msg}", file=sys.stderr, flush=True)

    # -- async-failure surfacing -------------------------------------------

    def _record_async_failure(self, exc: BaseException) -> None:
        with self._error_lock:
            if self._async_error is None:  # first failure wins — it names
                self._async_error = exc    # the epoch that actually broke

    def _reraise_async_failure(self) -> None:
        with self._error_lock:
            err, self._async_error = self._async_error, None
        if err is not None:
            raise err

    def _finalize_loop(self) -> None:
        """Worker: per save, barrier on the Orbax commit, hash the committed
        files + the payload leaves (host buffers — off the step loop's
        critical path), write the manifest atomically, then run any armed
        post-commit corruption injection. Failures (including Orbax's own
        async-write errors, which surface from wait_until_finished) are
        captured for the next save/flush barrier, never swallowed."""
        while True:
            item = self._finalize_q.get()
            try:
                if item is None:
                    return
                epoch, payload, host_state = item
                if self.fault_injector is not None:
                    self.fault_injector.during_async_save()
                self._mgr.wait_until_finished()
                step_dir = self._step_dir(epoch)
                if not os.path.isdir(step_dir):
                    # committed then already garbage-collected (keep=N churn
                    # faster than the finalizer) — nothing left to stamp
                    continue
                manifest = integrity.build_manifest(
                    epoch=epoch,
                    leaves=integrity.leaf_entries(payload),
                    files=integrity.hash_tree_files(step_dir),
                    writer={"async_save": self.async_save,
                            "process_index": jax.process_index(),
                            "host_state_keys": sorted(host_state)},
                    # mesh topology + per-leaf specs: what elastic restore
                    # reshards against when the pod size changes (the leaves
                    # here are the save-time device arrays — the async
                    # snapshot copy preserves their shardings)
                    sharding=reshard.sharding_section(payload, self.mesh))
                integrity.write_manifest(step_dir, manifest)
                if self.fault_injector is not None:
                    self.fault_injector.corrupt_checkpoint(
                        epoch, step_dir,
                        manifest_name=integrity.MANIFEST_NAME)
            except BaseException as e:  # noqa: BLE001 — captured, re-raised
                self._record_async_failure(e)  # at the next barrier
            finally:
                self._finalize_q.task_done()

    def _barrier(self) -> None:
        """Wait for every enqueued finalization (which itself barriers on
        the Orbax async write) — after this, all committed epochs carry
        their manifests. Does NOT re-raise captured failures; that is
        save()/flush()'s contract."""
        self._finalize_q.join()
        self._mgr.wait_until_finished()

    # -- save ---------------------------------------------------------------

    def save(self, epoch: int, state, host_state: Optional[Dict[str, Any]] = None,
             metric: Optional[float] = None):
        """Save at `epoch` (reference saves per-epoch with epoch in the payload,
        ResNet/pytorch/train.py:417-428). A transient OSError (real, or the
        injector's) is retried with backoff under `retry_policy` before it is
        allowed to kill the run — and a failure captured from a PREVIOUS
        save's async background write re-raises here first, through the same
        retry path, instead of surfacing silently at close()."""
        payload = self._payload(state)
        if self.async_save:
            # Snapshot before backgrounding: the async writer keeps
            # REFERENCES to these arrays while training continues, and the
            # very next train step DONATES the live state's buffers — on
            # backends where the host transfer is zero-copy (CPU) the write
            # then serializes overwritten memory, i.e. a silently corrupt
            # checkpoint (measured: a diverged epoch's NaNs landing in the
            # PREVIOUS epoch's payload). One device-side, sharding-
            # preserving copy per save severs the aliasing; the copy is
            # owned by the writer alone and freed when the write commits.
            payload = jax.tree_util.tree_map(
                lambda x: jnp.copy(x) if isinstance(x, jax.Array) else x,
                payload)
        metrics = {"best_metric": float(metric)} if metric is not None else None
        result = {}

        def _save():
            self._reraise_async_failure()
            if self.fault_injector is not None:
                self.fault_injector.before_checkpoint_save()
            result["saved"] = self._mgr.save(
                epoch,
                args=ocp.args.Composite(
                    state=ocp.args.StandardSave(payload),
                    host=ocp.args.JsonSave(host_state or {}),
                ),
                metrics=metrics,
            )

        call_with_retry(_save, self.retry_policy, what="ckpt_save",
                        on_retry=self.on_retry)
        if result.get("saved", True):
            self._finalize_q.put((epoch, payload, dict(host_state or {})))
        else:
            # orbax skips (returns False) when the step already exists —
            # stamping a manifest from the NEW payload over the OLD bytes
            # would read as corruption forever after, so don't
            self._log(f"save skipped: epoch {epoch} already exists on disk "
                      f"(orbax keeps the existing bytes)")
        if not self.async_save:
            self.flush()

    # -- queries ------------------------------------------------------------

    def latest_epoch(self) -> Optional[int]:
        self._barrier()
        return self._mgr.latest_step()

    def best_epoch(self) -> Optional[int]:
        self._barrier()
        return self._mgr.best_step()

    # -- restore ------------------------------------------------------------

    def restore(self, state, epoch: Optional[int] = None,
                verify: Any = "fallback"):
        """Restore into an abstract/concrete template (TrainState or pytree);
        returns (state, host_state, epoch). `epoch=None` → latest
        (auto-resume-from-latest).

        `verify` (default "fallback"): check the epoch's integrity manifest
        (file sizes/hashes before deserializing, restored leaf hashes after)
        and on corruption QUARANTINE the epoch (`corrupt-<epoch>/`, logged
        loudly) and fall back to the next-newest generation that verifies.
        "strict" raises CheckpointCorruptionError instead of falling back;
        "off" (or False) restores blindly. A fully-legacy dir — no manifest
        anywhere, written before this layer existed — restores with a
        one-line warning in every mode (not a breaking change)."""
        mode = {True: "fallback", False: "off", None: "fallback"}.get(
            verify, verify)
        if mode not in VERIFY_MODES:
            raise ValueError(f"verify must be one of {VERIFY_MODES} (or a "
                             f"bool), got {verify!r}")
        self._barrier()  # commits + manifests of any in-flight save
        epochs = integrity.committed_epochs(self.directory)
        if epoch is not None and epoch not in epochs:
            raise FileNotFoundError(
                f"no committed checkpoint at epoch {epoch} in "
                f"{self.directory} (committed: {epochs or 'none'})")
        candidates = [s for s in reversed(epochs)
                      if epoch is None or s <= epoch]
        if not candidates:
            return state, {}, None
        if mode == "off":
            new_state, host, got, _ = self._restore_epoch(state, candidates[0])
            self.last_restore_info = {"epoch": got, "verified": False,
                                      "mode": mode, "manifest_sha256": None,
                                      **self._last_reshard_info}
            return new_state, host, got
        any_manifest = any(
            os.path.exists(integrity.manifest_path(self._step_dir(s)))
            for s in epochs)
        attempts = []
        for skipped, s in enumerate(candidates):
            step_dir = self._step_dir(s)
            status, detail = integrity.verify_files(step_dir)
            if status == integrity.MISSING_MANIFEST and not any_manifest:
                # legacy run dir predating the integrity layer: warn, don't
                # break (pinned by tests — existing run dirs keep restoring)
                self._log(f"epoch {s}: no integrity manifest (legacy "
                          f"checkpoint, predates verification) — restoring "
                          f"unverified")
                new_state, host, got, _ = self._restore_epoch(state, s)
                self.last_restore_info = {
                    "epoch": got, "verified": False, "mode": mode,
                    "legacy": True, "manifest_sha256": None,
                    **self._last_reshard_info}
                return new_state, host, got
            problem = None
            if status == integrity.MISSING_MANIFEST:
                problem = (f"epoch {s}: manifest missing while sibling "
                           f"epochs carry one — save interrupted before "
                           f"the manifest committed?")
            elif status == integrity.CORRUPT:
                problem = f"epoch {s}: {detail}"
            else:
                new_state, host, got, payload = self._restore_epoch(state, s)
                manifest = integrity.load_manifest(step_dir)
                mismatches = integrity.verify_leaves(payload, manifest)
                if mismatches:
                    problem = (f"epoch {s}: restored arrays disagree with "
                               f"the manifest: " + "; ".join(mismatches[:3]))
                else:
                    self.last_restore_info = {
                        "epoch": got, "verified": True, "mode": mode,
                        "manifest_sha256": integrity.manifest_digest(manifest),
                        "fallback_skipped": skipped,
                        **self._last_reshard_info}
                    if skipped:
                        self._log(f"restored epoch {got} after skipping "
                                  f"{skipped} bad generation(s)")
                    return new_state, host, got
            if mode == "strict":
                raise CheckpointCorruptionError(
                    f"{problem} — refusing to restore (verify='strict'). "
                    f"Audit with `python -m deepvision_tpu fsck "
                    f"{self.directory}`, or restore with fallback/--no-verify "
                    f"semantics to use an older generation.")
            dest = integrity.quarantine_epoch(self.directory, s)
            self._mgr.reload()  # orbax's step cache must drop the renamed dir
            self._log(f"QUARANTINED {problem} -> {os.path.basename(dest)}; "
                      f"falling back to the next-newest checkpoint")
            attempts.append(problem)
        raise CheckpointCorruptionError(
            f"no checkpoint in {self.directory} passed verification: "
            + " | ".join(attempts))

    def _restore_epoch(self, state, epoch: int):
        """One epoch's raw restore (retry-wrapped, EMA-slot tolerant,
        donation-safe, mesh-aware): returns (new_state, host_state, epoch,
        payload) where `payload` is the copied on-disk tree for deep
        verification. When the manifest records a mesh topology that differs
        from this manager's target mesh, the restore takes the RESHARDING
        path (core/reshard.py): host-side deserialization against a numpy
        template, then device_put under the template's target shardings —
        the elastic save-on-N/restore-on-M contract."""
        template = self._payload(state)
        step_dir = self._step_dir(epoch)
        try:
            manifest = integrity.load_manifest(step_dir)
        except (OSError, ValueError):
            manifest = None  # torn manifest: verified modes already refused
            # it upstream; verify='off' proceeds natively, as before
        saved_topo = reshard.manifest_topology(manifest)
        target_topo = (reshard.mesh_topology(self.mesh)
                       if self.mesh is not None else None)
        resharding = (saved_topo is not None and target_topo is not None
                      and reshard.topologies_differ(saved_topo, target_topo))
        self._last_reshard_info = {
            "resharded": resharding,
            "saved_mesh": (saved_topo or {}).get("axes")
            if saved_topo else None}

        if resharding:
            self._log(f"epoch {epoch}: mesh changed since save — resharding "
                      f"{reshard.describe_topology(saved_topo)} -> "
                      f"{reshard.describe_topology(target_topo)}")
            host = self._restore_composite(
                epoch, reshard.host_template(template), state)
            # host-side numpy leaves, re-placed under the template's target
            # shardings (params rules / replication / EMA-like-params); the
            # caller deep-verifies this payload against the manifest before
            # trusting it — the hashes were taken over host buffers, so the
            # check is layout-independent
            payload = reshard.put_like(host["state"], template)
            # Donation safety, reshard flavor: on CPU `device_put` of a host
            # array is zero-copy — the placed jax.Array ALIASES the numpy
            # buffer Orbax restored into, and the first post-resume train
            # step DONATES the state, freeing that shared memory out from
            # under the host reference (measured: segfault in the first
            # `run_single` after an 8->1-device resume, the same class the
            # native path's copy fixes). One device-side sharding-preserving
            # copy severs the aliasing.
            payload = jax.tree_util.tree_map(
                lambda x: jnp.copy(x) if isinstance(x, jax.Array) else x,
                payload)
            return (self._payload_to_state(state, payload),
                    dict(host["host"] or {}), epoch, payload)

        if manifest is None and self.mesh is not None:
            # legacy epoch dir (or pre-elastic manifest): nothing to reshard
            # against — the PR 4 legacy-restore contract extends to elastic
            # resume as a same-mesh-only attempt, loudly
            self._log(f"epoch {epoch}: cannot reshard without an integrity "
                      f"manifest — restoring same-mesh only (target "
                      f"{reshard.describe_topology(target_topo)}); if this "
                      f"checkpoint was saved on a different mesh, restore "
                      f"it on a matching device count once and re-save")
        try:
            restored = self._restore_composite(epoch, template, state)
        except (ValueError, TypeError, RuntimeError) as e:
            if isinstance(e, reshard.MeshMismatch):
                raise
            if manifest is None and self.mesh is not None:
                # the opaque-deserialization-error case elastic resume
                # exists to kill: name the topologies instead
                raise reshard.MeshMismatch(
                    saved_topo, target_topo,
                    f"native restore of epoch {epoch} failed ({e}) and no "
                    f"manifest records the saved topology to reshard "
                    f"against") from e
            raise
        # Donation safety: the arrays Orbax hands back can share buffers with
        # its own deserialization machinery (and with the restore template);
        # feeding them straight into a train step that DONATES its state
        # frees those buffers out from under the other owner — measured on
        # this repo's 8-virtual-device CPU mesh as heap corruption
        # (malloc "corrupted double-linked list" / segfault) on the first
        # post-restore step, the crash that made in-process resume-then-train
        # flaky. One defensive sharding-preserving copy per restore (a rare
        # path) severs the aliasing for every consumer.
        payload = jax.tree_util.tree_map(
            lambda x: jnp.copy(x) if isinstance(x, jax.Array) else x,
            restored["state"])
        return (self._payload_to_state(state, payload),
                dict(restored["host"] or {}), epoch, payload)

    def _restore_composite(self, epoch: int, template, state):
        """Retry-wrapped Orbax composite restore with the EMA-flip fallback,
        shared by the native and resharding paths so the two can never
        diverge on the structure contract."""
        def _restore(tmpl):
            return self._mgr.restore(
                epoch,
                args=ocp.args.Composite(
                    state=ocp.args.StandardRestore(tmpl),
                    host=ocp.args.JsonRestore(),
                ),
            )

        try:
            return call_with_retry(
                lambda: _restore(template), self.retry_policy,
                what="ckpt_restore", on_retry=self.on_retry)
        except ValueError as e:
            # Orbax requires template == on-disk structure; the EMA slot is
            # the one legitimately run-dependent key. Retry with it toggled:
            # a checkpoint WITHOUT EMA restored into an EMA run (ema then
            # seeds from params in _payload_to_state), or a checkpoint WITH
            # EMA restored into a non-EMA run (eval-only / classify of an
            # EMA-trained model — restored alongside and dropped later). Any
            # other structure mismatch (wrong architecture, num_classes...)
            # must surface as-is, not as a confusing ema-flipped diff.
            if not isinstance(state, TrainState) or "ema_params" not in str(e):
                raise
            flipped = dict(template)
            if "ema_params" in flipped:
                flipped.pop("ema_params")
            else:
                flipped["ema_params"] = flipped["params"]
            try:
                return _restore(flipped)
            except ValueError:
                # the mismatch wasn't (only) the EMA slot — e.g. a genuinely
                # different architecture; the ORIGINAL error describes the
                # user's real template, not the flipped one
                raise e

    def _payload_to_state(self, state, payload):
        """Rebuild the caller's state object from a restored payload tree —
        shared tail of the native and resharding paths (EMA seeding/keeping
        semantics live here exactly once)."""
        if isinstance(state, TrainState):
            ema = payload.get("ema_params")
            if ema is None:
                if jax.tree_util.tree_leaves(state.ema_params):
                    # EMA enabled but the checkpoint predates it: start the
                    # average at a COPY of the restored params (aliasing them
                    # would make the train step donate the same buffer twice)
                    ema = jax.tree_util.tree_map(jnp.copy, payload["params"])
                else:
                    ema = state.ema_params
            # else: checkpoint carries EMA weights — keep them even when this
            # run didn't ask for EMA, so eval-only/classify of an EMA-trained
            # model scores the same weights training validated (Trainer.fit
            # discards them with a note before training without --ema-decay)
            new_state = state.replace(
                step=payload["step"], params=payload["params"],
                batch_stats=payload["batch_stats"], opt_state=payload["opt_state"],
                ema_params=ema)
        else:
            new_state = payload
        return new_state

    # -- lifecycle ----------------------------------------------------------

    def flush(self):
        """Barrier on any in-flight async save AND its manifest
        finalization (the manager stays usable) — then re-raise a failure
        captured from the background write, so a broken save surfaces at a
        well-defined point in the epoch loop instead of at close()."""
        self._barrier()
        self._reraise_async_failure()

    def close(self):
        self._finalize_q.join()
        self._finalize_q.put(None)  # sentinel: finalizer exits
        self._finalizer.join(timeout=60)
        self._mgr.wait_until_finished()
        self._mgr.close()
        # last resort for a failure no later save()/flush() ever observed
        # (fit() flushes on every normal path, so reaching here means the
        # caller is already unwinding — still better loud than silent)
        self._reraise_async_failure()
