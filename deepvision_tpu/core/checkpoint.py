"""Checkpoint save/restore via Orbax.

One mechanism replacing the reference's four (SURVEY.md §5.4): torch dict-per-epoch
(`ResNet/pytorch/train.py:417-428`), Keras hdf5 callback, save-best weights with the
metric in the filename (`YOLO/tensorflow/train.py:244-257`), and
`tf.train.Checkpoint`+Manager (`CycleGAN/tensorflow/train.py:134-148`). Payload is
`{params, batch_stats, opt_state, step}` plus host metadata (epoch, plateau state,
metric history), with keep-latest and keep-best policies and atomic writes (safe for
preemption — a gap called out in SURVEY.md §5.3).
"""

from __future__ import annotations

import json
import os
import shutil
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import orbax.checkpoint as ocp

from .resilience import RetryPolicy, call_with_retry
from .train_state import TrainState


class CheckpointManager:
    def __init__(self, directory: str, keep: int = 3, keep_best: bool = True,
                 best_mode: str = "max", async_save: bool = True,
                 retry_policy: Optional[RetryPolicy] = None,
                 on_retry=None, fault_injector=None):
        """`async_save=True` (SURVEY.md §5.4's async-save goal): `save()`
        kicks off the write in a background thread and training continues on
        device; `restore()`/`close()` barrier on any in-flight save.

        `retry_policy` arms transient-I/O retry with backoff around save and
        restore (flaky storage must cost a logged retry, not the run);
        `on_retry(what, attempt, exc, delay)` is the trainers' logging hook,
        and `fault_injector` (utils/faults.py) provides the deterministic
        checkpoint-write failures the resilience tests inject."""
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.keep = keep
        self.keep_best = keep_best
        self.best_mode = best_mode
        self.async_save = async_save
        self.retry_policy = retry_policy or RetryPolicy()
        self.on_retry = on_retry
        self.fault_injector = fault_injector
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep,
                best_fn=(lambda m: m.get("best_metric", 0.0)) if keep_best else None,
                best_mode=best_mode if keep_best else "max",
                keep_checkpoints_without_metrics=True,
                create=True,
                enable_async_checkpointing=async_save,
            ),
        )

    @staticmethod
    def _payload(state):
        """TrainState → dict payload; any other pytree (e.g. the GAN trainers'
        {gen, disc} dicts) is saved as-is. `ema_params` is included only when
        EMA is enabled (non-empty), so non-EMA checkpoints keep their layout."""
        if isinstance(state, TrainState):
            p = {
                "step": state.step,
                "params": state.params,
                "batch_stats": state.batch_stats,
                "opt_state": state.opt_state,
            }
            if jax.tree_util.tree_leaves(state.ema_params):
                p["ema_params"] = state.ema_params
            return p
        return state

    def save(self, epoch: int, state, host_state: Optional[Dict[str, Any]] = None,
             metric: Optional[float] = None):
        """Save at `epoch` (reference saves per-epoch with epoch in the payload,
        ResNet/pytorch/train.py:417-428). A transient OSError (real, or the
        injector's) is retried with backoff under `retry_policy` before it is
        allowed to kill the run."""
        payload = self._payload(state)
        if self.async_save:
            # Snapshot before backgrounding: the async writer keeps
            # REFERENCES to these arrays while training continues, and the
            # very next train step DONATES the live state's buffers — on
            # backends where the host transfer is zero-copy (CPU) the write
            # then serializes overwritten memory, i.e. a silently corrupt
            # checkpoint (measured: a diverged epoch's NaNs landing in the
            # PREVIOUS epoch's payload). One device-side, sharding-
            # preserving copy per save severs the aliasing; the copy is
            # owned by the writer alone and freed when the write commits.
            payload = jax.tree_util.tree_map(
                lambda x: jnp.copy(x) if isinstance(x, jax.Array) else x,
                payload)
        metrics = {"best_metric": float(metric)} if metric is not None else None

        def _save():
            if self.fault_injector is not None:
                self.fault_injector.before_checkpoint_save()
            self._mgr.save(
                epoch,
                args=ocp.args.Composite(
                    state=ocp.args.StandardSave(payload),
                    host=ocp.args.JsonSave(host_state or {}),
                ),
                metrics=metrics,
            )

        call_with_retry(_save, self.retry_policy, what="ckpt_save",
                        on_retry=self.on_retry)
        if not self.async_save:
            self._mgr.wait_until_finished()

    def latest_epoch(self) -> Optional[int]:
        self._mgr.wait_until_finished()
        return self._mgr.latest_step()

    def best_epoch(self) -> Optional[int]:
        self._mgr.wait_until_finished()
        return self._mgr.best_step()

    def restore(self, state, epoch: Optional[int] = None):
        """Restore into an abstract/concrete template (TrainState or pytree);
        returns (state, host_state, epoch). `epoch=None` → latest
        (auto-resume-from-latest)."""
        self._mgr.wait_until_finished()  # barrier on any in-flight async save
        if epoch is None:
            epoch = self._mgr.latest_step()
        if epoch is None:
            return state, {}, None
        template = self._payload(state)

        def _restore(tmpl):
            return self._mgr.restore(
                epoch,
                args=ocp.args.Composite(
                    state=ocp.args.StandardRestore(tmpl),
                    host=ocp.args.JsonRestore(),
                ),
            )

        try:
            restored = call_with_retry(
                lambda: _restore(template), self.retry_policy,
                what="ckpt_restore", on_retry=self.on_retry)
        except ValueError as e:
            # Orbax requires template == on-disk structure; the EMA slot is
            # the one legitimately run-dependent key. Retry with it toggled:
            # a checkpoint WITHOUT EMA restored into an EMA run (ema then
            # seeds from params below), or a checkpoint WITH EMA restored
            # into a non-EMA run (eval-only / classify of an EMA-trained
            # model — restored alongside and dropped below). Any other
            # structure mismatch (wrong architecture, num_classes...) must
            # surface as-is, not as a confusing ema-flipped diff.
            if not isinstance(state, TrainState) or "ema_params" not in str(e):
                raise
            flipped = dict(template)
            if "ema_params" in flipped:
                flipped.pop("ema_params")
            else:
                flipped["ema_params"] = flipped["params"]
            try:
                restored = _restore(flipped)
            except ValueError:
                # the mismatch wasn't (only) the EMA slot — e.g. a genuinely
                # different architecture; the ORIGINAL error describes the
                # user's real template, not the flipped one
                raise e
        # Donation safety: the arrays Orbax hands back can share buffers with
        # its own deserialization machinery (and with the restore template);
        # feeding them straight into a train step that DONATES its state
        # frees those buffers out from under the other owner — measured on
        # this repo's 8-virtual-device CPU mesh as heap corruption
        # (malloc "corrupted double-linked list" / segfault) on the first
        # post-restore step, the crash that made in-process resume-then-train
        # flaky. One defensive sharding-preserving copy per restore (a rare
        # path) severs the aliasing for every consumer.
        payload = jax.tree_util.tree_map(
            lambda x: jnp.copy(x) if isinstance(x, jax.Array) else x,
            restored["state"])
        if isinstance(state, TrainState):
            ema = payload.get("ema_params")
            if ema is None:
                if jax.tree_util.tree_leaves(state.ema_params):
                    # EMA enabled but the checkpoint predates it: start the
                    # average at a COPY of the restored params (aliasing them
                    # would make the train step donate the same buffer twice)
                    ema = jax.tree_util.tree_map(jnp.copy, payload["params"])
                else:
                    ema = state.ema_params
            # else: checkpoint carries EMA weights — keep them even when this
            # run didn't ask for EMA, so eval-only/classify of an EMA-trained
            # model scores the same weights training validated (Trainer.fit
            # discards them with a note before training without --ema-decay)
            new_state = state.replace(
                step=payload["step"], params=payload["params"],
                batch_stats=payload["batch_stats"], opt_state=payload["opt_state"],
                ema_params=ema)
        else:
            new_state = payload
        return new_state, dict(restored["host"] or {}), epoch

    def flush(self):
        """Barrier on any in-flight async save (the manager stays usable)."""
        self._mgr.wait_until_finished()

    def close(self):
        self._mgr.wait_until_finished()
        self._mgr.close()
