"""Model export: JAX → TensorFlow SavedModel → TFLite.

Parity target: the reference ships a TFLite converter for CycleGAN generators
(`CycleGAN/tensorflow/convert.py:8-14`: `TFLiteConverter.from_saved_model` with
`OPTIMIZE_FOR_SIZE`). Its models are already Keras, so export is one call; ours
are Flax, so the bridge is `jax2tf.convert` — the function (with the trained
variables closed over as constants) becomes a `tf.function`, saved as a
SavedModel, and optionally converted to TFLite. Works for any `(variables, x) ->
y` apply function, so every model in the zoo can be exported, not just CycleGAN.
"""

from __future__ import annotations

import os
from typing import Callable, Optional, Sequence


def _tf():
    import tensorflow as tf
    tf.config.set_visible_devices([], "GPU")
    return tf


def export_saved_model(apply_fn: Callable, variables, input_shape: Sequence[int],
                       path: str, *, batch_size: int = 1) -> str:
    """Write a TF SavedModel wrapping `apply_fn(variables, images)`.

    `input_shape` is per-example (H, W, C); the exported signature takes
    (batch_size, H, W, C) float32. Variables are baked in as constants — the
    export is inference-only (`with_gradient=False`).
    """
    tf = _tf()
    from jax.experimental import jax2tf

    tf_fn = jax2tf.convert(lambda x: apply_fn(variables, x),
                           with_gradient=False)
    module = tf.Module()
    module.serve = tf.function(
        tf_fn,
        input_signature=[tf.TensorSpec([batch_size, *input_shape], tf.float32,
                                       name="images")])
    # materialize the concrete function so save() embeds it
    module.serve.get_concrete_function()
    tf.saved_model.save(module, path,
                        signatures={"serving_default": module.serve})
    return path


def convert_tflite(saved_model_dir: str, output_path: str,
                   optimize: bool = True) -> str:
    """SavedModel → .tflite flatbuffer (`CycleGAN/tensorflow/convert.py:8-14`).

    `optimize` applies the default size/latency optimization, the successor of
    the reference's deprecated `OPTIMIZE_FOR_SIZE`.
    """
    tf = _tf()
    converter = tf.lite.TFLiteConverter.from_saved_model(saved_model_dir)
    if optimize:
        converter.optimizations = [tf.lite.Optimize.DEFAULT]
    # jax2tf output may contain ops outside the builtin TFLite set
    converter.target_spec.supported_ops = [
        tf.lite.OpsSet.TFLITE_BUILTINS, tf.lite.OpsSet.SELECT_TF_OPS]
    tflite_model = converter.convert()
    os.makedirs(os.path.dirname(output_path) or ".", exist_ok=True)
    with open(output_path, "wb") as f:
        f.write(tflite_model)
    return output_path


def export_tflite(apply_fn: Callable, variables, input_shape: Sequence[int],
                  output_path: str, *, batch_size: int = 1,
                  optimize: bool = True,
                  saved_model_dir: Optional[str] = None) -> str:
    """One-call JAX → TFLite: SavedModel roundtrip in a temp (or given) dir."""
    import tempfile
    if saved_model_dir is None:
        with tempfile.TemporaryDirectory() as tmp:
            export_saved_model(apply_fn, variables, input_shape, tmp,
                               batch_size=batch_size)
            return convert_tflite(tmp, output_path, optimize=optimize)
    export_saved_model(apply_fn, variables, input_shape, saved_model_dir,
                       batch_size=batch_size)
    return convert_tflite(saved_model_dir, output_path, optimize=optimize)
