"""Model export: JAX → TensorFlow SavedModel → TFLite.

Parity target: the reference ships a TFLite converter for CycleGAN generators
(`CycleGAN/tensorflow/convert.py:8-14`: `TFLiteConverter.from_saved_model` with
`OPTIMIZE_FOR_SIZE`). Its models are already Keras, so export is one call; ours
are Flax, so the bridge is `jax2tf.convert` — the function (with the trained
variables closed over as constants) becomes a `tf.function`, saved as a
SavedModel, and optionally converted to TFLite. Works for any `(variables, x) ->
y` apply function, so every model in the zoo can be exported, not just CycleGAN.
"""

from __future__ import annotations

import os
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp


def _tf():
    import tensorflow as tf
    tf.config.set_visible_devices([], "GPU")
    return tf


def _zero_stuff(x, dilation, lhs_spec):
    """Insert `d-1` zeros between elements along each spatial dim — the
    explicit form of `lhs_dilation` (expand→concat-zeros→reshape→slice, all
    ops TFLite converts natively)."""
    spatial_dims = lhs_spec[2:]
    for dim, d in zip(spatial_dims, dilation):
        if d <= 1:
            continue
        n = x.shape[dim]
        xe = jnp.expand_dims(x, dim + 1)
        zeros = jnp.zeros_like(xe)
        y = jnp.concatenate([xe] + [zeros] * (d - 1), axis=dim + 1)
        new_shape = list(x.shape)
        new_shape[dim] = n * d
        y = y.reshape(new_shape)
        idx = [slice(None)] * y.ndim
        idx[dim] = slice(0, n * d - (d - 1))
        x = y[tuple(idx)]
    return x


def rewrite_transposed_convs(fn: Callable) -> Callable:
    """Re-express lhs-dilated convolutions (ConvTranspose / fractional stride)
    as explicit zero-insertion + plain convolution before export.

    TFLite's converter mis-lowers lhs-dilated convs — it emits TRANSPOSE_CONV
    without the SAME-padding crop, so outputs come back the wrong shape/values
    (verified: (1,8,8,3)→(1,18,18,4) instead of (1,16,16,4)). Zero-stuffing is
    the *definition* of lhs_dilation, and the conv's explicit padding numbers
    carry over verbatim, so this rewrite is exact (float round-off only) and a
    no-op for models without transposed convs.
    """

    def _eval(jaxpr, consts, *args):
        from jax.extend.core import Literal
        env = {}

        def read(v):
            return v.val if isinstance(v, Literal) else env[v]

        for var, val in zip(jaxpr.invars, args):
            env[var] = val
        for cv, cval in zip(jaxpr.constvars, consts):
            env[cv] = cval
        for eqn in jaxpr.eqns:
            vals = [read(v) for v in eqn.invars]
            params = dict(eqn.params)
            name = eqn.primitive.name
            if (name == "conv_general_dilated"
                    and any(d > 1 for d in params["lhs_dilation"])):
                dn = params["dimension_numbers"]
                x = _zero_stuff(vals[0], params["lhs_dilation"], dn.lhs_spec)
                params["lhs_dilation"] = (1,) * len(params["lhs_dilation"])
                outs = [eqn.primitive.bind(x, vals[1], **params)]
            elif name in ("custom_jvp_call", "custom_vjp_call"):
                # can't re-bind (expects live callables); recurse into the
                # primal jaxpr — export is inference-only, no grads needed
                sub = params["call_jaxpr"]
                outs = _eval(sub.jaxpr, sub.consts, *vals)
            elif name in ("jit", "pjit", "closed_call"):
                sub = params["jaxpr"]  # ClosedJaxpr
                outs = _eval(sub.jaxpr, sub.consts, *vals)
            elif name in ("remat2", "remat", "checkpoint"):
                # remat carries an OPEN Jaxpr (consts hoisted into invars)
                outs = _eval(params["jaxpr"], [], *vals)
            else:
                out = eqn.primitive.bind(*vals, **params)
                outs = out if eqn.primitive.multiple_results else [out]
            for v, o in zip(eqn.outvars, outs):
                env[v] = o
        return [read(v) for v in jaxpr.outvars]

    def wrapped(*args):
        flat, in_tree = jax.tree_util.tree_flatten(args)

        def flat_fn(*flat_args):
            return fn(*jax.tree_util.tree_unflatten(in_tree, flat_args))

        closed, out_shape = jax.make_jaxpr(flat_fn, return_shape=True)(*flat)
        out_tree = jax.tree_util.tree_structure(out_shape)
        outs = _eval(closed.jaxpr, closed.consts, *flat)
        return jax.tree_util.tree_unflatten(out_tree, outs)

    return wrapped


def export_saved_model(apply_fn: Callable, variables, input_shape: Sequence[int],
                       path: str, *, batch_size: int = 1) -> str:
    """Write a TF SavedModel wrapping `apply_fn(variables, images)`.

    `input_shape` is per-example (H, W, C); the exported signature takes
    (batch_size, H, W, C) float32. Variables are baked in as constants — the
    export is inference-only (`with_gradient=False`).
    """
    tf = _tf()
    from jax.experimental import jax2tf

    tf_fn = jax2tf.convert(
        rewrite_transposed_convs(lambda x: apply_fn(variables, x)),
        with_gradient=False)
    module = tf.Module()
    module.serve = tf.function(
        tf_fn,
        input_signature=[tf.TensorSpec([batch_size, *input_shape], tf.float32,
                                       name="images")])
    # materialize the concrete function so save() embeds it
    module.serve.get_concrete_function()
    tf.saved_model.save(module, path,
                        signatures={"serving_default": module.serve})
    return path


def convert_tflite(saved_model_dir: str, output_path: str,
                   optimize: bool = True) -> str:
    """SavedModel → .tflite flatbuffer (`CycleGAN/tensorflow/convert.py:8-14`).

    `optimize` applies the default size/latency optimization, the successor of
    the reference's deprecated `OPTIMIZE_FOR_SIZE`.
    """
    tf = _tf()
    converter = tf.lite.TFLiteConverter.from_saved_model(saved_model_dir)
    if optimize:
        converter.optimizations = [tf.lite.Optimize.DEFAULT]
    # jax2tf output may contain ops outside the builtin TFLite set
    converter.target_spec.supported_ops = [
        tf.lite.OpsSet.TFLITE_BUILTINS, tf.lite.OpsSet.SELECT_TF_OPS]
    tflite_model = converter.convert()
    os.makedirs(os.path.dirname(output_path) or ".", exist_ok=True)
    with open(output_path, "wb") as f:
        f.write(tflite_model)
    return output_path


def export_tflite(apply_fn: Callable, variables, input_shape: Sequence[int],
                  output_path: str, *, batch_size: int = 1,
                  optimize: bool = True,
                  saved_model_dir: Optional[str] = None) -> str:
    """One-call JAX → TFLite: SavedModel roundtrip in a temp (or given) dir."""
    import tempfile
    if saved_model_dir is None:
        with tempfile.TemporaryDirectory() as tmp:
            export_saved_model(apply_fn, variables, input_shape, tmp,
                               batch_size=batch_size)
            return convert_tflite(tmp, output_path, optimize=optimize)
    export_saved_model(apply_fn, variables, input_shape, saved_model_dir,
                       batch_size=batch_size)
    return convert_tflite(saved_model_dir, output_path, optimize=optimize)
