"""Pose (Stacked Hourglass) SPMD steps + trainer.

Parity target: `Hourglass/tensorflow/train.py:15-226` — MirroredStrategy trainer
with foreground-weighted MSE summed over stacks (`compute_loss`, `:65-76`: weights
= 81×[label>0] + 1, i.e. 82 on gaussian pixels), Adam, hand-rolled plateau LR /10
after 10 bad epochs watching val loss (`:46-58`), NaN-val-batch skip (`:126-130`),
and save-best checkpoints (`:160-163`).

TPU-native shape: heatmap rendering happens ON DEVICE inside the jitted step from
the raw (keypoints, visibility) batch (ops/heatmap.py) — the reference renders on
the host with per-keypoint autograph loops. Loss is the plain global-batch mean of
the weighted squared error per stack (the reference additionally multiplies by
1/global_batch after an already-mean reduction, `:73-75` — a pure LR rescale we
don't replicate).
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..ops.heatmap import render_gaussian_heatmaps
from ..parallel import mesh as mesh_lib
from .config import TrainConfig, UNIT_RANGE_NORM
from .steps import _normalize_input, annotate_step, maybe_grad_norm
from .trainer import LossWatchedTrainer

FOREGROUND_WEIGHT = 81.0  # `Hourglass/tensorflow/train.py:69`


def weighted_mse_loss(labels: jnp.ndarray, outputs) -> jnp.ndarray:
    """Σ_stacks mean((pred - label)² · (81·[label>0] + 1)) (`train.py:65-76`)."""
    labels = labels.astype(jnp.float32)
    weights = (labels > 0).astype(jnp.float32) * FOREGROUND_WEIGHT + 1.0
    loss = 0.0
    for out in outputs:
        loss = loss + jnp.mean(jnp.square(labels - out.astype(jnp.float32))
                               * weights)
    return loss


def make_pose_train_step(*, heatmap_size: Tuple[int, int],
                         compute_dtype=jnp.bfloat16, donate: bool = True,
                         mesh=None, remat: bool = False,
                         input_norm=None, log_grad_norm: bool = False,
                         grad_correction=None) -> Callable:
    """(state, images, kp_x, kp_y, visibility, rng) -> (state, metrics).

    kp_x/kp_y: (B, K) normalized keypoints; visibility: (B, K). `remat=True`
    recomputes forward activations in the backward pass — hourglass stacks are
    activation-heavy, so this is the main big-batch lever (cf. steps.py).
    """
    h, w = heatmap_size

    def step(state, images, kp_x, kp_y, visibility, rng):
        del rng
        images = _normalize_input(images, input_norm, compute_dtype)
        labels = jax.vmap(
            lambda x, y, v: render_gaussian_heatmaps(x, y, v, h, w))(
                kp_x, kp_y, visibility)

        def forward(params, images):
            with mesh_lib.spatial_activation_constraints(mesh):
                return state.apply_fn(
                    {"params": params, "batch_stats": state.batch_stats},
                    images, train=True, mutable=["batch_stats"])

        if remat:
            forward = jax.checkpoint(
                forward,
                policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable)

        def loss_fn(params):
            outputs, mutated = forward(params, images)
            return weighted_mse_loss(labels, outputs), mutated

        (loss, mutated), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)
        grads = mesh_lib.apply_grad_correction(grads, grad_correction)
        new_state = state.apply_gradients(grads).replace(
            batch_stats=mutated.get("batch_stats", state.batch_stats))
        metrics = {"loss": loss, **maybe_grad_norm(log_grad_norm, grads)}
        return new_state, metrics

    jit_kwargs = {}
    if donate:
        jit_kwargs["donate_argnums"] = (0,)
    if mesh is not None:
        jit_kwargs["out_shardings"] = (None, NamedSharding(mesh, P()))
    return annotate_step(jax.jit(step, **jit_kwargs), donate=donate,
                         compute_dtype=jnp.dtype(compute_dtype), kind="train")


def make_pose_eval_step(*, heatmap_size: Tuple[int, int],
                        compute_dtype=jnp.bfloat16, mesh=None,
                        input_norm=None) -> Callable:
    h, w = heatmap_size

    def step(state, images, kp_x, kp_y, visibility):
        images = _normalize_input(images, input_norm, compute_dtype)
        labels = jax.vmap(
            lambda x, y, v: render_gaussian_heatmaps(x, y, v, h, w))(
                kp_x, kp_y, visibility)
        with mesh_lib.spatial_activation_constraints(mesh):
            outputs = state.apply_fn(
                {"params": state.params, "batch_stats": state.batch_stats},
                images, train=False)
        return {"loss": weighted_mse_loss(labels, outputs)}

    jit_kwargs = {}
    if mesh is not None:
        jit_kwargs["out_shardings"] = NamedSharding(mesh, P())
    return annotate_step(jax.jit(step, **jit_kwargs), donate=False,
                         compute_dtype=jnp.dtype(compute_dtype), kind="eval")


class PoseTrainer(LossWatchedTrainer):
    """Hourglass trainer: shared epoch/checkpoint/plateau machinery with pose
    steps; loss-watched validation with NaN-batch skip comes from the base.
    Model construction stays in the base (via `num_classes_kwarg`) so the
    workdir's pinned model_kwargs.json applies here like everywhere else."""

    num_classes_kwarg = "num_heatmap"  # pose models take num_heatmap
    has_own_shardmap_step = True       # make_shardmap_pose_train_step

    def __init__(self, config: TrainConfig, model=None, mesh=None,
                 workdir: Optional[str] = None):
        super().__init__(config, model=model, mesh=mesh, workdir=workdir)
        hm = (config.data.image_size // 4, config.data.image_size // 4)
        compute_dtype = jnp.dtype(config.dtype) if config.dtype else jnp.bfloat16
        input_norm = UNIT_RANGE_NORM if config.data.normalize_on_device else None
        if self._use_shardmap_spatial():
            # StackedHourglass is fully convolutional, so the owned-
            # collectives path keeps H sharded end to end (transition=None,
            # parallel/spatial_shard.py) — exact on combined meshes with no
            # calibration, same recipe as CenterNet. default_transition
            # validates the model class: an arbitrary model= with
            # non-row-local ops would otherwise train with silently wrong
            # gradients, and a model needing an all_to_all handoff is not
            # something the pose step implements.
            from ..parallel import spatial_shard
            transition = spatial_shard.default_transition(self.model)
            if transition is not None:
                raise NotImplementedError(
                    f"spatial_backend='shard_map' pose training requires a "
                    f"fully convolutional model (transition plan None); "
                    f"{type(self.model).__name__} plans a handoff at "
                    f"{transition!r}, which make_shardmap_pose_train_step "
                    f"does not implement — use the gspmd backend")
            self._step_factory = (
                lambda m, corr: spatial_shard.make_shardmap_pose_train_step(
                    heatmap_size=hm, compute_dtype=compute_dtype, mesh=m,
                    input_norm=input_norm,
                    log_grad_norm=config.log_grad_norm,
                    remat=config.remat,
                    donate=config.donate_step()))
        else:
            self._step_factory = lambda m, corr: make_pose_train_step(
                heatmap_size=hm, compute_dtype=compute_dtype, mesh=m,
                remat=config.remat, input_norm=input_norm,
                log_grad_norm=config.log_grad_norm,
                donate=config.donate_step(), grad_correction=corr)
        self.train_step = self._step_factory(self.mesh, None)
        self.eval_step = make_pose_eval_step(
            heatmap_size=hm, compute_dtype=compute_dtype, mesh=self.mesh,
            input_norm=input_norm)

    def _calibration_batch(self, sample_shape, seed: int = 0):
        import numpy as np
        rs = np.random.RandomState(seed)
        b, k = self._calibration_batch_size(), self.config.data.num_classes
        images = (rs.randint(0, 256, (b, *sample_shape)).astype(np.uint8)
                  if self.config.data.normalize_on_device
                  else rs.rand(b, *sample_shape).astype(np.float32))
        kp_x = rs.rand(b, k).astype(np.float32)
        kp_y = rs.rand(b, k).astype(np.float32)
        visibility = np.ones((b, k), np.float32)
        return (images, kp_x, kp_y, visibility)
