"""Checkpoint integrity: manifests, verification, quarantine, fsck.

PR 1 made the trainer survive preemption and transient I/O, but every
recovery path still trusted the newest checkpoint blindly: a host killed
mid-async-save, a truncated write, or bit rot on flaky storage turns both
auto-resume and serve-side weight loading into an opaque Orbax error and a
dead run. Production checkpoint managers treat checkpoints as a verified,
multi-generation lineage (Orbax/t5x-style management, PAPERS.md); this
module is that proof layer:

- every `CheckpointManager.save` commits a small **integrity manifest**
  (`integrity_manifest.json` inside the committed epoch dir) recording the
  per-leaf tree structure (shapes/dtypes + content hashes streamed over the
  host buffers) and a per-file size+sha256 inventory of everything Orbax
  wrote, plus writer metadata — written atomically AFTER the Orbax commit,
  so a manifest's presence certifies the save finished;
- `verify_files` / `verify_leaves` prove an epoch intact before anything
  consumes it (file level without deserializing — fsck's path — and leaf
  level against the restored arrays — restore's deep check);
- `quarantine_epoch` renames a bad epoch to `corrupt-<epoch>` so fallback
  restore can land on the next-newest generation that verifies and a later
  re-save of the same epoch number cannot collide with the bad bytes;
- `audit` drives the `python -m deepvision_tpu fsck` subcommand and
  preflight's fsck check.

Committed Orbax step dirs are immutable (the atomic tmp->digit rename is
the commit marker, and later saves/GC never touch older steps — probed in
tests), so file hashes taken right after the commit stay valid for the
checkpoint's lifetime. Everything here is stdlib+numpy on the host; jax is
imported lazily only for leaf hashing so the fsck CLI starts fast.
"""

from __future__ import annotations

import hashlib
import json
import os
import socket
import sys
import time
from typing import Dict, List, Optional, Tuple

MANIFEST_NAME = "integrity_manifest.json"
MANIFEST_VERSION = 1
QUARANTINE_PREFIX = "corrupt-"

# verification statuses (audit/verify_files contract; fsck prints them)
OK = "ok"
CORRUPT = "corrupt"
MISSING_MANIFEST = "missing-manifest"
QUARANTINED = "quarantined"


class CheckpointCorruptionError(RuntimeError):
    """A checkpoint failed integrity verification: strict mode refused it,
    or fallback mode exhausted every generation without one verifying."""


def _log(msg: str) -> None:
    # stderr like the trainers' retry hook: corruption events must be loud
    # on every host, not buried in a return value
    print(f"[ckpt-integrity] {msg}", file=sys.stderr, flush=True)


# -- hashing -------------------------------------------------------------------

def file_sha256(path: str, chunk: int = 1 << 20) -> Tuple[int, str]:
    """(size, sha256) of a file, streamed — checkpoint shards can be GBs."""
    h = hashlib.sha256()
    size = 0
    with open(path, "rb") as fp:
        while True:
            block = fp.read(chunk)
            if not block:
                break
            size += len(block)
            h.update(block)
    return size, h.hexdigest()


def leaf_entries(payload) -> Dict[str, dict]:
    """Per-leaf {keypath: {shape, dtype, sha256}} over a payload pytree.
    Hashes are over the host buffer bytes (device_get then tobytes), so the
    same values always hash the same regardless of sharding; a leaf that
    cannot become an array (rare host metadata) hashes its repr instead."""
    import jax  # lazy: fsck's file-level path never needs it
    import numpy as np

    out: Dict[str, dict] = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(payload)[0]:
        key = jax.tree_util.keystr(path)
        try:
            arr = np.asarray(jax.device_get(leaf))
            out[key] = {
                "shape": list(arr.shape),
                "dtype": str(arr.dtype),
                "sha256": hashlib.sha256(
                    np.ascontiguousarray(arr).tobytes()).hexdigest(),
            }
        except Exception:  # noqa: BLE001 — non-array host leaf
            out[key] = {"repr_sha256": hashlib.sha256(
                repr(leaf).encode()).hexdigest()}
    return out


def hash_tree_files(step_dir: str) -> Dict[str, dict]:
    """{relpath: {bytes, sha256}} for every file under a committed epoch dir
    (the manifest itself excluded — it describes, it isn't described)."""
    out: Dict[str, dict] = {}
    for root, dirs, files in os.walk(step_dir):
        dirs.sort()
        for f in sorted(files):
            if root == step_dir and f == MANIFEST_NAME:
                continue
            path = os.path.join(root, f)
            rel = os.path.relpath(path, step_dir).replace(os.sep, "/")
            size, digest = file_sha256(path)
            out[rel] = {"bytes": size, "sha256": digest}
    return out


# -- manifest ------------------------------------------------------------------

def build_manifest(*, epoch: int, leaves: Dict[str, dict],
                   files: Dict[str, dict],
                   writer: Optional[dict] = None,
                   sharding: Optional[dict] = None) -> dict:
    """`sharding` (core/reshard.sharding_section) records the mesh topology
    and per-leaf PartitionSpecs the payload was saved under — the metadata
    elastic restore reshards against. Optional: plain host payloads (and
    manifests written before this field existed) simply omit it and restore
    same-mesh only."""
    manifest = {
        "format_version": MANIFEST_VERSION,
        "epoch": int(epoch),
        "created_unix": time.time(),
        "writer": {"hostname": socket.gethostname(), "pid": os.getpid(),
                   **(writer or {})},
        "total_bytes": sum(f["bytes"] for f in files.values()),
        "files": files,
        "leaves": leaves,
    }
    if sharding is not None:
        manifest["sharding"] = sharding
    return manifest


def sharding_digest(section: dict) -> str:
    """Self-digest of a manifest's sharding section (the `digest` key
    excluded): the section steers how restored bytes are laid out across a
    DIFFERENT mesh than they were saved on, so it must not be silently
    editable — `verify_files` recomputes this and reports a mismatch as
    corruption. stdlib-only (fsck's no-jax constraint)."""
    blob = json.dumps({k: v for k, v in section.items() if k != "digest"},
                      sort_keys=True, separators=(",", ":")).encode()
    return hashlib.sha256(blob).hexdigest()


def manifest_digest(manifest: dict) -> str:
    """Canonical sha256 of a manifest — the provenance fingerprint serving
    replicas report (/healthz) so a fleet can be audited for weight skew."""
    blob = json.dumps(manifest, sort_keys=True,
                      separators=(",", ":")).encode()
    return hashlib.sha256(blob).hexdigest()


def manifest_path(step_dir: str) -> str:
    return os.path.join(step_dir, MANIFEST_NAME)


def write_manifest(step_dir: str, manifest: dict) -> str:
    """Atomic commit: tmp + fsync + rename, so a kill mid-write leaves NO
    manifest (the epoch then reads as missing-manifest, never as a torn
    manifest that happens to parse)."""
    path = manifest_path(step_dir)
    tmp = path + ".tmp"
    with open(tmp, "w") as fp:
        json.dump(manifest, fp, sort_keys=True, indent=1)
        fp.flush()
        os.fsync(fp.fileno())
    os.replace(tmp, path)
    return path


def load_manifest(step_dir: str) -> Optional[dict]:
    path = manifest_path(step_dir)
    if not os.path.exists(path):
        return None
    with open(path) as fp:
        return json.load(fp)


# -- verification --------------------------------------------------------------

def verify_files(step_dir: str) -> Tuple[str, str]:
    """File-level check of one committed epoch against its manifest,
    without deserializing anything: (status, detail) where status is OK /
    CORRUPT / MISSING_MANIFEST. Catches exactly the boring production
    corruption classes — truncation (size), bit rot (hash), deleted or
    torn files (missing / unreadable manifest)."""
    if not os.path.isdir(step_dir):
        return CORRUPT, "checkpoint directory missing"
    if not os.path.exists(manifest_path(step_dir)):
        return MISSING_MANIFEST, "no integrity manifest"
    try:
        manifest = load_manifest(step_dir)
    except (OSError, ValueError) as e:
        return CORRUPT, f"unreadable manifest: {e}"
    problems: List[str] = []
    files = manifest.get("files", {})
    for rel, rec in sorted(files.items()):
        path = os.path.join(step_dir, rel.replace("/", os.sep))
        if not os.path.isfile(path):
            problems.append(f"{rel}: missing")
            continue
        size = os.path.getsize(path)
        if size != rec["bytes"]:
            problems.append(f"{rel}: {size} bytes, manifest says "
                            f"{rec['bytes']} (truncated write?)")
            continue
        if file_sha256(path)[1] != rec["sha256"]:
            problems.append(f"{rel}: content hash mismatch (bit rot?)")
    section = manifest.get("sharding")
    if section is not None and section.get("digest") != \
            sharding_digest(section):
        problems.append("sharding section tampered (self-digest mismatch — "
                        "mesh topology / per-leaf specs not trustworthy for "
                        "an elastic restore)")
    if problems:
        head = "; ".join(problems[:4])
        more = f" (+{len(problems) - 4} more)" if len(problems) > 4 else ""
        return CORRUPT, head + more
    return OK, f"{len(files)} files verified"


def verify_epoch(ckpt_dir: str, epoch: int) -> Tuple[str, str, Optional[str]]:
    """File-level verdict on ONE committed epoch of a checkpoint dir:
    `(status, detail, manifest_sha256)` with the digest only when the epoch
    verifies OK. This is the cheap gate hot reload (serve/reload.py) runs
    on every candidate BEFORE deserializing anything: a corrupt candidate
    costs a hash pass and a log line, never a swap — and MISSING_MANIFEST
    doubles as the "save still committing" signal, because the manifest is
    written by the finalizer strictly AFTER the Orbax commit."""
    step_dir = os.path.join(ckpt_dir, str(epoch))
    status, detail = verify_files(step_dir)
    if status != OK:
        return status, detail, None
    return status, detail, manifest_digest(load_manifest(step_dir))


def verify_leaves(payload, manifest: dict) -> List[str]:
    """Deep check: restored payload leaves vs the manifest's save-time
    hashes. Compares the intersection of keypaths only — the EMA slot is
    legitimately template-dependent (checkpoint.py's flip logic), so a
    missing/extra leaf is a structure difference, not corruption."""
    got = leaf_entries(payload)
    want = manifest.get("leaves", {})
    mismatches: List[str] = []
    for key in sorted(set(got) & set(want)):
        for field in ("shape", "dtype", "sha256", "repr_sha256"):
            if field in want[key] and want[key][field] != got[key].get(field):
                mismatches.append(
                    f"{key}: {field} {got[key].get(field)!r} != manifest "
                    f"{want[key][field]!r}")
                break
    return mismatches


# -- run-dir layout ------------------------------------------------------------

def committed_epochs(ckpt_dir: str) -> List[int]:
    """Ascending committed epochs: orbax finalizes by atomically renaming
    the tmp dir to `<epoch>`, so a pure-digit directory name IS the commit
    marker (same predicate as tests/test_preemption.py)."""
    if not os.path.isdir(ckpt_dir):
        return []
    return sorted(int(d) for d in os.listdir(ckpt_dir)
                  if d.isdigit() and os.path.isdir(os.path.join(ckpt_dir, d)))


def quarantined_dirs(ckpt_dir: str) -> List[str]:
    if not os.path.isdir(ckpt_dir):
        return []
    return sorted(d for d in os.listdir(ckpt_dir)
                  if d.startswith(QUARANTINE_PREFIX)
                  and os.path.isdir(os.path.join(ckpt_dir, d)))


def quarantine_epoch(ckpt_dir: str, epoch: int) -> str:
    """Rename `<epoch>` -> `corrupt-<epoch>` (collision appends `.2`,
    `.3`, ...): the bad bytes stay on disk for forensics, stop shadowing
    older verified generations, and can never collide with a re-save of
    the same epoch number after the fallback resume retrains it."""
    src = os.path.join(ckpt_dir, str(epoch))
    dest = os.path.join(ckpt_dir, f"{QUARANTINE_PREFIX}{epoch}")
    n = 1
    while os.path.exists(dest):
        n += 1
        dest = os.path.join(ckpt_dir, f"{QUARANTINE_PREFIX}{epoch}.{n}")
    os.rename(src, dest)
    return dest


def audit(ckpt_dir: str, quarantine: bool = False) -> List[dict]:
    """fsck one checkpoint dir: a record per committed epoch (OK / CORRUPT /
    MISSING_MANIFEST + detail) plus one per already-quarantined dir. With
    `quarantine=True`, CORRUPT epochs — and missing-manifest epochs in a
    dir whose other epochs DO carry manifests (an interrupted save, by this
    writer's contract) — are renamed aside; a fully-legacy dir (no
    manifests anywhere) is never touched, only reported."""
    epochs = committed_epochs(ckpt_dir)
    any_manifest = any(
        os.path.exists(manifest_path(os.path.join(ckpt_dir, str(e))))
        for e in epochs)
    records: List[dict] = []
    for epoch in epochs:
        step_dir = os.path.join(ckpt_dir, str(epoch))
        status, detail = verify_files(step_dir)
        rec = {"epoch": epoch, "status": status, "detail": detail}
        if status == OK:
            manifest = load_manifest(step_dir)
            rec["manifest_sha256"] = manifest_digest(manifest)
            rec["total_bytes"] = manifest.get("total_bytes")
            # saved mesh topology (core/reshard.py): fsck reports what shape
            # each epoch expects so an operator planning an elastic resume
            # can see which epochs need resharding — None for pre-elastic
            # manifests and plain host payloads
            rec["mesh"] = (manifest.get("sharding") or {}).get("mesh")
        suspect = status == CORRUPT or (status == MISSING_MANIFEST
                                        and any_manifest)
        if quarantine and suspect:
            rec["quarantined_to"] = os.path.basename(
                quarantine_epoch(ckpt_dir, epoch))
            _log(f"fsck: quarantined epoch {epoch} -> "
                 f"{rec['quarantined_to']} ({detail})")
        records.append(rec)
    for d in quarantined_dirs(ckpt_dir):
        records.append({"epoch": None, "status": QUARANTINED, "detail": d})
    return records


def find_checkpoint_dirs(path: str) -> List[str]:
    """Checkpoint dirs under `path` for the fsck CLI: `path` itself when it
    holds committed epochs (or quarantined ones), its `ckpt/` child (a run
    workdir), else every `<child>/ckpt` one level down (a runs/ root)."""
    def is_ckpt_dir(p: str) -> bool:
        return bool(committed_epochs(p) or quarantined_dirs(p)
                    or os.path.basename(p.rstrip(os.sep)) == "ckpt")

    if is_ckpt_dir(path):
        return [path]
    child = os.path.join(path, "ckpt")
    if os.path.isdir(child):
        return [child]
    found = []
    for name in sorted(os.listdir(path)):
        sub = os.path.join(path, name, "ckpt")
        if os.path.isdir(sub):
            found.append(sub)
    return found
