"""LR schedules.

Covers every schedule the reference uses: StepLR / MultiStepLR
(`ResNet/pytorch/train.py:141-215`), ReduceLROnPlateau (`:171-176` and the hand-rolled
plateau in `YOLO/tensorflow/train.py:56-68`), CycleGAN's LinearDecay
(`CycleGAN/tensorflow/utils.py:5-28`), plus warmup+cosine (not in the reference — needed
for the large-batch ResNet recipe per BASELINE.md).

Step-based schedules are optax functions of the global step (traceable under jit).
Plateau is inherently host-driven (it reacts to val metrics), so it is a small host-side
state machine whose output multiplies a base schedule via a dynamic scale carried in the
optimizer state.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import optax

from .config import ScheduleConfig


def build_schedule(cfg: ScheduleConfig, base_lr: float, steps_per_epoch: float,
                   total_epochs: int) -> optax.Schedule:
    # steps_per_epoch may be fractional (updates/epoch under gradient
    # accumulation); every use below multiplies first, then truncates.
    warmup_steps = int(cfg.warmup_epochs * steps_per_epoch)
    total_steps = max(1, int(total_epochs * steps_per_epoch))

    if cfg.name == "constant" or cfg.name == "plateau":
        # plateau: base schedule is constant; the host-side PlateauState scales it.
        base = optax.constant_schedule(base_lr)
    elif cfg.name == "step":
        # compound factors when distinct boundary epochs land on the same
        # update index (possible when updates/epoch < 1 under accumulation —
        # a plain dict comprehension would silently drop all but one decay)
        boundaries: dict = {}
        for e in cfg.boundaries_epochs:
            k = int(e * steps_per_epoch)
            boundaries[k] = boundaries.get(k, 1.0) * cfg.decay_factor
        base = optax.piecewise_constant_schedule(base_lr, boundaries)
    elif cfg.name == "cosine":
        base = optax.cosine_decay_schedule(base_lr, total_steps,
                                           alpha=cfg.min_lr / base_lr if base_lr else 0.0)
    elif cfg.name == "linear_decay":
        # constant until decay_start_epoch, then linear to ~0 (CycleGAN LinearDecay).
        decay_start = int(cfg.decay_start_epoch * steps_per_epoch)
        base = optax.join_schedules(
            [optax.constant_schedule(base_lr),
             optax.linear_schedule(base_lr, 0.0, max(1, total_steps - decay_start))],
            [decay_start],
        )
    else:
        raise ValueError(f"unknown schedule {cfg.name!r}")

    if warmup_steps > 0:
        # Multiplicative linear warmup: keeps the base schedule's boundaries at their
        # ABSOLUTE steps (optax.join_schedules would shift the inner schedule by
        # -warmup_steps, silently moving step-decay epochs late).
        import jax.numpy as jnp

        def sched(count):
            warm = jnp.minimum(1.0, (count + 1) / warmup_steps)
            return base(count) * warm

        return sched
    return base


@dataclasses.dataclass
class PlateauState:
    """Host-side ReduceLROnPlateau (semantics of torch's, used at
    `ResNet/pytorch/train.py:412-415`): if the watched val metric hasn't improved for
    `patience` epochs, multiply LR by `factor`. The resulting scale is injected into the
    optimizer via optax's `scale_by_learning_rate` wrapper (see optim.build_optimizer).
    """
    patience: int = 2
    factor: float = 0.1
    mode: str = "max"
    min_scale: float = 0.0
    best: Optional[float] = None
    num_bad_epochs: int = 0
    scale: float = 1.0

    def update(self, metric: float) -> float:
        improved = (
            self.best is None
            or (self.mode == "max" and metric > self.best)
            or (self.mode == "min" and metric < self.best)
        )
        if improved:
            self.best = metric
            self.num_bad_epochs = 0
        else:
            self.num_bad_epochs += 1
            if self.num_bad_epochs > self.patience:
                self.scale = max(self.scale * self.factor, self.min_scale)
                self.num_bad_epochs = 0
        return self.scale
