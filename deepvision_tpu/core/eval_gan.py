"""Quantitative generator evaluation: Fréchet distance on classifier features.

The reference judges its GANs with no metric at all — its training loops
emit only checkpoint saves and epoch-time prints
(`DCGAN/tensorflow/main.py:75-85`, `CycleGAN/tensorflow/train.py:331`),
with sample inspection left to the separate inference scripts — so a
silently degraded generator is invisible to it.
This module gives the GAN family a number the way classification has top-1:
the Fréchet distance (Heusel et al. 2017) between Gaussian fits of real and
generated feature activations, with the feature extractor a parameter (the
production gate uses the repo's own LeNet-5 penultimate layer on
MNIST-shaped data; any classifier's embedding works).

All math is numpy + eigendecompositions — no scipy.sqrtm, whose Schur-based
result can go complex on near-singular products; the eigh route stays real,
deterministic, and exact for the PSD inputs covariance matrices are.

Scale caveat, measured (tests/test_gan_quality.py pins the evaluator, not a
quality bar, on the offline digits set): on the 1797-scan UCI digits proxy
a DCGAN cannot beat untrained-noise feature statistics — the set is ~33x
smaller than the MNIST the reference's recipe assumes, and the trained
generator's tight off-manifold cluster scores *worse* than broad random
noise (measured round 4: trained ≈215-240 vs untrained ≈171, real-vs-real
floor ≈2). Quality-bar assertions therefore live behind the real-MNIST
fetch gate; offline CI pins trainer *behavior* (no collapse, no NaNs,
moved-from-init) instead of sample quality.
"""

from __future__ import annotations

from typing import Callable, Tuple

import numpy as np


def gaussian_stats(features: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Mean vector and covariance of an (N, D) feature matrix, f64."""
    f = np.asarray(features, dtype=np.float64)
    if f.ndim != 2:
        raise ValueError(f"features must be (N, D), got {f.shape}")
    if f.shape[0] < 2:
        raise ValueError("need at least 2 samples for a covariance")
    return f.mean(axis=0), np.cov(f, rowvar=False)


def _psd_sqrt(mat: np.ndarray) -> np.ndarray:
    """Symmetric PSD square root via eigh; negative eigenvalues from
    floating-point noise are clipped to zero."""
    vals, vecs = np.linalg.eigh((mat + mat.T) / 2.0)
    return (vecs * np.sqrt(np.clip(vals, 0.0, None))) @ vecs.T


def frechet_distance(mu1: np.ndarray, cov1: np.ndarray,
                     mu2: np.ndarray, cov2: np.ndarray) -> float:
    """d² = |μ1-μ2|² + tr(C1 + C2 - 2·(C1^½ C2 C1^½)^½).

    The symmetrized trace form equals the textbook tr·sqrt(C1·C2) for PSD
    inputs but keeps every intermediate real and symmetric.
    """
    diff = np.asarray(mu1, np.float64) - np.asarray(mu2, np.float64)
    s1 = _psd_sqrt(np.asarray(cov1, np.float64))
    inner = _psd_sqrt(s1 @ np.asarray(cov2, np.float64) @ s1)
    return float(diff @ diff + np.trace(cov1) + np.trace(cov2)
                 - 2.0 * np.trace(inner))


def frechet_from_features(real: np.ndarray, generated: np.ndarray) -> float:
    """Fréchet distance between two (N, D) feature sets."""
    return frechet_distance(*gaussian_stats(real), *gaussian_stats(generated))


def lenet_feature_fn(params, image_size: int = 32) -> Callable[[np.ndarray],
                                                               np.ndarray]:
    """Penultimate-layer (f6, 84-dim) embedding of the repo's LeNet-5 —
    the production feature extractor for MNIST-shaped GAN evaluation.
    `params` is a trained LeNet-5 params pytree; images smaller than
    `image_size` are symmetrically padded with -1 (the normalized
    background the classifier was trained with)."""
    from ..models.lenet import LeNet5

    model = LeNet5(num_classes=10)

    def features(images: np.ndarray) -> np.ndarray:
        x = np.asarray(images, np.float32)
        pad = image_size - x.shape[1]
        if pad < 0:
            raise ValueError(
                f"lenet_feature_fn: images are {x.shape[1]}px but the "
                f"feature extractor was built for {image_size}px — larger "
                "inputs would hit LeNet with a receptive field it was never "
                "trained on; resize the images or rebuild with a matching "
                "image_size")
        if pad > 0:
            lo, hi = pad // 2, pad - pad // 2
            x = np.pad(x, ((0, 0), (lo, hi), (lo, hi), (0, 0)),
                       constant_values=-1.0)
        _, state = model.apply(
            {"params": params}, x,
            capture_intermediates=lambda mdl, _: mdl.name == "f6")
        return np.asarray(state["intermediates"]["f6"]["__call__"][0])

    return features
