"""CenterNet SPMD steps + trainer — completing the reference's disabled family
(`ObjectsAsPoints/tensorflow/train.py`: a copy of the YOLO trainer with
`self.loss_objects = []` at `:35` and `trainer.run` commented out at `:248`).

Same shape as core/detection.py: one jitted step over the mesh, label encoding
on device from the shared padded ground-truth batches, loss-watched validation.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..ops import centernet as cn_ops
from ..parallel import mesh as mesh_lib
from .config import TrainConfig, UNIT_RANGE_NORM
from .steps import _normalize_input, annotate_step, maybe_grad_norm
from .trainer import LossWatchedTrainer


def make_centernet_train_step(*, num_classes: int, grid: int,
                              compute_dtype=jnp.bfloat16, donate: bool = True,
                              mesh=None, remat: bool = False,
                              input_norm=None,
                              log_grad_norm: bool = False,
                         grad_correction=None) -> Callable:
    """(state, images, boxes, classes, valid, rng) -> (state, metrics).
    `remat=True` recomputes forward activations in backward (cf. steps.py);
    `input_norm=(mean, std)` normalizes raw [0,255] pixels on device."""

    def step(state, images, boxes, classes, valid, rng):
        del rng
        images = _normalize_input(images, input_norm, compute_dtype)
        targets = cn_ops.encode_labels(boxes, classes, valid, grid, num_classes)

        def forward(params, images):
            with mesh_lib.spatial_activation_constraints(mesh):
                return state.apply_fn(
                    {"params": params, "batch_stats": state.batch_stats},
                    images, train=True, mutable=["batch_stats"])

        if remat:
            forward = jax.checkpoint(
                forward,
                policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable)

        def loss_fn(params):
            outputs, mutated = forward(params, images)
            comp = cn_ops.centernet_loss(outputs, targets)
            return jnp.mean(comp["total"]), (comp, mutated)

        (loss, (comp, mutated)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)
        grads = mesh_lib.apply_grad_correction(grads, grad_correction)
        new_state = state.apply_gradients(grads).replace(
            batch_stats=mutated.get("batch_stats", state.batch_stats))
        metrics = {"loss": loss,
                   **{f"{k}_loss": jnp.mean(v) for k, v in comp.items()
                      if k != "total"},
                   **maybe_grad_norm(log_grad_norm, grads)}
        return new_state, metrics

    jit_kwargs = {}
    if donate:
        jit_kwargs["donate_argnums"] = (0,)
    if mesh is not None:
        jit_kwargs["out_shardings"] = (None, NamedSharding(mesh, P()))
    return annotate_step(jax.jit(step, **jit_kwargs), donate=donate,
                         compute_dtype=jnp.dtype(compute_dtype), kind="train")


def make_centernet_eval_step(*, num_classes: int, grid: int,
                             compute_dtype=jnp.bfloat16, mesh=None,
                             input_norm=None) -> Callable:
    def step(state, images, boxes, classes, valid):
        images = _normalize_input(images, input_norm, compute_dtype)
        targets = cn_ops.encode_labels(boxes, classes, valid, grid, num_classes)
        with mesh_lib.spatial_activation_constraints(mesh):
            outputs = state.apply_fn(
                {"params": state.params, "batch_stats": state.batch_stats},
                images, train=False)
        comp = cn_ops.centernet_loss(outputs, targets)
        return {"loss": jnp.mean(comp["total"])}

    jit_kwargs = {}
    if mesh is not None:
        jit_kwargs["out_shardings"] = NamedSharding(mesh, P())
    return annotate_step(jax.jit(step, **jit_kwargs), donate=False,
                         compute_dtype=jnp.dtype(compute_dtype), kind="eval")


class CenterNetTrainer(LossWatchedTrainer):
    """Uses the same padded-GT detection batches as DetectionTrainer; model
    construction and loss-watched eval come from the base."""

    has_own_shardmap_step = True  # make_shardmap_centernet_train_step

    def __init__(self, config: TrainConfig, model=None, mesh=None,
                 workdir: Optional[str] = None):
        super().__init__(config, model=model, mesh=mesh, workdir=workdir)
        grid = config.data.image_size // 4  # output stride 4
        compute_dtype = jnp.dtype(config.dtype) if config.dtype else jnp.bfloat16
        input_norm = UNIT_RANGE_NORM if config.data.normalize_on_device else None
        if self._use_shardmap_spatial():
            # CenterNet is the family whose combined spatial x model mesh the
            # GSPMD path REFUSES (calibration finds ~500x stem-BN grads,
            # PARITY.md §2.8) — the owned-collectives step makes it trainable
            from ..parallel import spatial_shard
            self._step_factory = (
                lambda m, corr: spatial_shard
                .make_shardmap_centernet_train_step(
                    num_classes=config.data.num_classes, grid=grid,
                    compute_dtype=compute_dtype, mesh=m,
                    input_norm=input_norm,
                    log_grad_norm=config.log_grad_norm,
                    remat=config.remat,
                    donate=config.donate_step()))
        else:
            self._step_factory = lambda m, corr: make_centernet_train_step(
                num_classes=config.data.num_classes, grid=grid,
                compute_dtype=compute_dtype, mesh=m, remat=config.remat,
                input_norm=input_norm, log_grad_norm=config.log_grad_norm,
                donate=config.donate_step(), grad_correction=corr)
        self.train_step = self._step_factory(self.mesh, None)
        self.eval_step = make_centernet_eval_step(
            num_classes=config.data.num_classes, grid=grid,
            compute_dtype=compute_dtype, mesh=self.mesh,
            input_norm=input_norm)

    def _calibration_batch(self, sample_shape, seed: int = 0):
        from .detection import boxes_calibration_batch
        return boxes_calibration_batch(self.config, sample_shape,
                                       self._calibration_batch_size(),
                                       seed=seed)


def make_centernet_predict_step(*, compute_dtype=jnp.bfloat16,
                                max_detections: int = 100) -> Callable:
    """(state, images) -> (boxes, scores, classes): decode the LAST stack's
    heads into score-ordered detections (`ops/centernet.py` decode — the
    3×3-maxpool peak NMS of the paper). top-k always returns max_detections
    rows; callers derive valid counts by score threshold."""

    def step(state, images):
        outputs = state.apply_fn(
            {"params": state.params, "batch_stats": state.batch_stats},
            images.astype(compute_dtype), train=False)
        boxes, scores, classes = cn_ops.decode(outputs[-1],
                                               max_detections=max_detections)
        return boxes, scores, classes

    return annotate_step(jax.jit(step), donate=False,
                         compute_dtype=jnp.dtype(compute_dtype),
                         kind="predict")


def evaluate_map(state, batches, *, num_classes: int, metric: str = "coco",
                 score_thresh: float = 0.05,
                 compute_dtype=jnp.bfloat16) -> dict:
    """CenterNet mAP over (images, boxes, classes, valid) batches — the
    evaluation the reference's WIP family never reached
    (`ObjectsAsPoints/tensorflow/train.py:248` disabled runner)."""
    import numpy as np

    from .eval_detection import make_evaluator

    ev = make_evaluator(metric, num_classes)
    predict = make_centernet_predict_step(compute_dtype=compute_dtype)
    for batch in batches:
        images, gt_boxes, gt_classes, gt_valid = batch[:4]
        boxes, scores, classes = map(np.asarray,
                                     predict(state, jnp.asarray(images)))
        counts = (scores >= score_thresh).sum(axis=1)  # scores are descending
        ev.add_batch(boxes, scores, classes, counts,
                     gt_boxes, gt_classes, gt_valid,
                     gt_difficult=batch[4] if len(batch) > 4 else None)
    return ev.summarize()
