"""Adversarial (DCGAN / CycleGAN) SPMD steps + trainers.

Parity targets:
- DCGAN trainer (`DCGAN/tensorflow/main.py:20-88`): one step with TWO GradientTapes
  and two Adam(1e-4) optimizers — generator and discriminator gradients both taken
  against the pre-update parameters, then both applied; `tf.train.Checkpoint` +
  manager saving every 2 epochs, keep 3.
- CycleGAN trainer (`CycleGAN/tensorflow/train.py:150-344`): two-phase step —
  jitted generator phase (one loss over BOTH generators: GAN + 10·cycle +
  5·identity, one Adam(2e-4, β1=.5) over the concatenated generator variables),
  host-side ImagePool query on the fakes, jitted discriminator phase (second Adam
  over both discriminators, each (real+fake)/2 LSGAN-MSE) — with LinearDecay LR
  after epoch 100 and checkpoints every 2 epochs.

TPU-native shape: each phase is one jitted SPMD function over the mesh; the two
optimizers are two optax states over the param pytrees {"a2b": …, "b2a": …} /
{"a": …, "b": …} (the concatenated-variables trick, `train.py:183-185`). The
ImagePool stays on the host BETWEEN the two jitted calls — the same structure the
reference uses and the reason its outer step is eager (`utils.py:31`).
"""

from __future__ import annotations

import time
from typing import Callable, Iterable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..parallel import mesh as mesh_lib
from ..utils.faults import FaultInjector
from ..utils.image_pool import ImagePool
from .checkpoint import CheckpointManager
from .config import TrainConfig
from .metrics import MetricsLogger
from .optim import build_optimizer, set_lr_scale
from .resilience import (GracefulShutdown, PreemptionExit, RetryPolicy,
                         log_resilience_event, resilient_batches)
from .steps import annotate_step
from .train_state import TrainState, init_model


def _bce_logits(logits, target: float) -> jnp.ndarray:
    """BinaryCrossentropy(from_logits=True) vs all-ones/zeros
    (`DCGAN/tensorflow/main.py:42-53`)."""
    t = jnp.full_like(logits, target)
    return optax.sigmoid_binary_cross_entropy(logits, t).mean()


def _mse(pred, target: float) -> jnp.ndarray:
    """LSGAN loss (`CycleGAN/tensorflow/train.py:58-63`)."""
    return jnp.mean(jnp.square(pred - target))


def _mae(a, b) -> jnp.ndarray:
    """Cycle/identity loss (`train.py:65-72`)."""
    return jnp.mean(jnp.abs(a - b))


class AdversarialTrainer:
    """Shared machinery for the two-network trainers: epoch loop with mean
    metric accumulation, checkpoint-every-N-epochs ({gen, disc} payloads), and
    resume — the common shape of `DCGAN/tensorflow/main.py:73-87` and
    `CycleGAN/tensorflow/train.py:314-336`. Subclasses set gen_state/disc_state
    and implement `train_batch(*batch) -> metrics dict`."""

    gen_state: TrainState
    disc_state: TrainState

    @staticmethod
    def _validate_config(config: TrainConfig) -> None:
        """First line of every subclass __init__ — config errors knowable
        without building anything must fail before model init / device_put /
        the conv-grad probes."""
        if (getattr(config, "spatial_backend", "gspmd") == "shard_map"
                and config.spatial_parallel > 1):
            # consistent with the supervised trainers: the backend choice
            # only matters when a spatial axis exists; spatial_parallel==1
            # configs train identically either way and are accepted
            raise ValueError(
                "spatial_backend='shard_map' is not implemented for "
                "adversarial trainers; GAN combined meshes use the measured "
                "grad calibration (gspmd backend)")
        if getattr(config, "steps_per_dispatch", 1) > 1:
            # the shared TrainConfig field reaches library users even though
            # the GAN CLIs never set it — fail loud (like accum_steps'
            # incompatibility guard) instead of silently dispatching 1 step
            raise ValueError(
                "steps_per_dispatch > 1 is not supported by adversarial "
                "trainers: the CycleGAN step round-trips through the host "
                "ImagePool between the two jitted phases, and DCGAN keeps "
                "one dispatch per step for the same two-optimizer shape")

    def _init_logging(self, config: TrainConfig, workdir: str):
        self.config = config
        self.logger = MetricsLogger(workdir, name=config.name)
        # same resilience plumbing as the supervised Trainer: env-driven
        # fault injection, transient-I/O retry on checkpoint writes and the
        # host data pull, graceful SIGTERM/SIGINT, divergence rollback
        self.faults = FaultInjector.from_env()
        self.retry_policy = RetryPolicy.from_env()
        self._recovery_scale = 1.0
        self._recoveries = 0
        self._batch_count = 0
        self._shutdown = None
        self.ckpt = CheckpointManager(workdir + "/ckpt",
                                      keep=config.keep_checkpoints,
                                      keep_best=False,
                                      retry_policy=self.retry_policy,
                                      on_retry=self._log_retry,
                                      fault_injector=(self.faults
                                                      if self.faults.active
                                                      else None),
                                      # elastic resume: both adversarial
                                      # trainers set self.mesh before
                                      # calling _init_logging
                                      mesh=getattr(self, "mesh", None))
        self.start_epoch = 1

    def _log_retry(self, what: str, attempt: int, exc: BaseException,
                   delay: float) -> None:
        import sys
        print(f"[{self.config.name}] transient {what} failure "
              f"(attempt {attempt}/{self.retry_policy.max_retries}): {exc} — "
              f"retrying in {delay:.2f}s", file=sys.stderr, flush=True)
        if jax.process_index() == 0:
            # through the single resilience choke point (the correlation
            # fields land there), not a hand-rolled prefixed write
            log_resilience_event(self.logger, self._batch_count,
                                 {f"{what}_retries": float(attempt)})

    def _payload(self):
        return {"gen": CheckpointManager._payload(self.gen_state),
                "disc": CheckpointManager._payload(self.disc_state)}

    def resume(self) -> Optional[int]:
        payload, _, epoch = self.ckpt.restore(self._payload())
        if epoch is None:
            return None
        self.gen_state = self.gen_state.replace(**payload["gen"])
        self.disc_state = self.disc_state.replace(**payload["disc"])
        self.start_epoch = epoch + 1
        return epoch

    def train_batch(self, *batch) -> dict:
        raise NotImplementedError

    def _train_one_epoch(self, epoch: int, train_data_fn, profiling) -> dict:
        t0 = time.time()
        step_metrics = []  # device arrays; fetched once at epoch end so a
        if profiling:
            jax.profiler.start_trace(profiling)
        try:
            batches = resilient_batches(
                train_data_fn(epoch), self.retry_policy,
                injector=self.faults if self.faults.active else None,
                on_retry=self._log_retry)
            for batch in batches:  # pool-free step stays async
                if self._shutdown is not None and self._shutdown.requested:
                    break  # in-flight step finishes; fit commits + exits 0
                if not isinstance(batch, tuple):
                    batch = (batch,)
                step_metrics.append(self.train_batch(*batch))
                self._batch_count += 1
            if step_metrics:
                stacked = jax.tree_util.tree_map(
                    lambda *xs: float(np.mean(jax.device_get(jnp.stack(
                        [jnp.asarray(x) for x in xs])))), *step_metrics)
                metrics = dict(stacked)
            else:
                metrics = {}
        finally:
            # the metric fetch above synced the device; finally so a step
            # failure still writes the captured trace
            if profiling:
                jax.profiler.stop_trace()
        metrics["epoch_seconds"] = time.time() - t0
        return metrics

    def _recover_from_divergence(self, epoch: int) -> Optional[int]:
        """GAN flavor of Trainer._recover_from_divergence: roll back BOTH
        networks to the last committed {gen, disc} checkpoint and scale both
        optimizers' LR down by recovery_lr_factor (persistently)."""
        got = self.resume()
        if got is None:
            return None
        self._recoveries += 1
        self._recovery_scale *= self.config.recovery_lr_factor
        self.gen_state = self.gen_state.replace(opt_state=set_lr_scale(
            self.gen_state.opt_state, self._recovery_scale))
        self.disc_state = self.disc_state.replace(opt_state=set_lr_scale(
            self.disc_state.opt_state, self._recovery_scale))
        if jax.process_index() == 0:
            print(f"[{self.config.name}] divergence recovery "
                  f"{self._recoveries}: epoch {epoch} diverged — rolled back "
                  f"to epoch {got}, LR scale now {self._recovery_scale:g}",
                  flush=True)
            log_resilience_event(
                self.logger, self._batch_count,
                {"divergence_recoveries": float(self._recoveries),
                 "lr_scale": self._recovery_scale},
                epoch=epoch)
        return got

    def fit(self, train_data_fn: Callable[[int], Iterable],
            total_epochs: Optional[int] = None, save_every: int = 2,
            profile_dir: Optional[str] = None) -> dict:
        """Epoch loop + save every 2 epochs (`DCGAN/tensorflow/main.py:81-83`,
        `CycleGAN/tensorflow/train.py:330-333`). `profile_dir` captures a
        jax.profiler trace of the first trained epoch.

        Resilience (core/resilience.py, same contract as Trainer.fit):
        SIGTERM/SIGINT commits a checkpoint and raises PreemptionExit
        (fit_and_close → resume hint + exit 0); a non-finite epoch rolls
        back and retries under config.recover_on_divergence; host data pulls
        and checkpoint writes retry transient OSError with backoff."""
        total_epochs = total_epochs or self.config.total_epochs
        metrics = {}
        recoveries_left = self.config.recover_on_divergence
        first_epoch = self.start_epoch
        shutdown_cm = (GracefulShutdown() if self.config.graceful_shutdown
                       else None)
        if shutdown_cm is not None:
            self._shutdown = shutdown_cm.__enter__()
        try:
            epoch = self.start_epoch
            while epoch <= total_epochs:
                profiling = (profile_dir if profile_dir
                             and epoch == first_epoch else None)
                metrics = self._train_one_epoch(epoch, train_data_fn,
                                                profiling)
                # log BEFORE the divergence check: the diverged epoch's
                # metrics (which loss went NaN, epoch time) belong in
                # JSONL/TB, not only in the exception text (same ordering as
                # Trainer.train_epoch)
                self.logger.log(epoch, metrics, epoch=epoch, prefix="train_",
                                echo=jax.process_index() == 0)
                if self._shutdown is not None and self._shutdown.requested:
                    self.ckpt.save(epoch, self._payload())
                    self.ckpt.flush()
                    raise PreemptionExit(
                        epoch,
                        f"[{self.config.name}] graceful preemption: "
                        f"checkpoint committed at epoch {epoch} — relaunch "
                        f"with --resume to continue")
                if self.config.halt_on_nonfinite and any(
                        not np.isfinite(v) for v in metrics.values()):
                    # adversarial training collapses to NaN more readily than
                    # supervised (two coupled optimizers); same guard as
                    # Trainer.train_epoch, with this family's --resume UX
                    if recoveries_left > 0:
                        rolled = self._recover_from_divergence(epoch)
                        if rolled is not None:
                            recoveries_left -= 1
                            epoch = rolled + 1
                            continue
                    from .trainer import divergence_halt
                    divergence_halt(self.config, self.ckpt, epoch,
                                    f"mean metrics contain a non-finite "
                                    f"value ({metrics})",
                                    resume_cmd="--resume")
                if epoch % save_every == 0 or epoch == total_epochs:
                    self.ckpt.save(epoch, self._payload())
                epoch += 1
        finally:
            self._shutdown = None
            if shutdown_cm is not None:
                shutdown_cm.__exit__(None, None, None)
        return metrics

    def close(self):
        self.logger.close()
        self.ckpt.close()


# ---------------------------------------------------------------------------
# DCGAN
# ---------------------------------------------------------------------------

def make_dcgan_train_step(gen_apply: Callable, disc_apply: Callable,
                          noise_dim: int, mesh=None, donate: bool = True,
                          gen_grad_correction=None,
                          disc_grad_correction=None) -> Callable:
    """(gen_state, disc_state, images, rng) -> (gen_state, disc_state, metrics).

    Both gradient sets are computed against the pre-update parameters (the
    two-tape semantics of `DCGAN/tensorflow/main.py:59-71`); XLA CSEs the shared
    generator forward.

    Combined spatial×model meshes: each network's gradients are divided by
    its measured per-leaf over-reduction correction
    (`mesh_lib.calibrate_grad_correction`; the trainer calibrates both and
    rebuilds this step) — the same compensation the supervised steps carry.
    """

    def step(gen_state: TrainState, disc_state: TrainState, images, rng):
        rng = jax.random.fold_in(rng, gen_state.step)
        rng_z, rng_d1, rng_d2, rng_d3 = jax.random.split(rng, 4)
        noise = jax.random.normal(rng_z, (images.shape[0], noise_dim))

        def gen_loss_fn(gp):
            with mesh_lib.spatial_activation_constraints(mesh):
                fake, mut = gen_apply(
                    {"params": gp, "batch_stats": gen_state.batch_stats},
                    noise, train=True, mutable=["batch_stats"])
                fake_logits = disc_apply(
                    {"params": disc_state.params}, fake, train=True,
                    rngs={"dropout": rng_d1})
            return _bce_logits(fake_logits, 1.0), (fake, mut)

        (g_loss, (fake, g_mut)), g_grads = jax.value_and_grad(
            gen_loss_fn, has_aux=True)(gen_state.params)
        g_grads = mesh_lib.apply_grad_correction(g_grads, gen_grad_correction)

        def disc_loss_fn(dp):
            with mesh_lib.spatial_activation_constraints(mesh):
                real_logits = disc_apply({"params": dp}, images, train=True,
                                         rngs={"dropout": rng_d2})
                fake_logits = disc_apply({"params": dp},
                                         jax.lax.stop_gradient(fake),
                                         train=True, rngs={"dropout": rng_d3})
            return _bce_logits(real_logits, 1.0) + _bce_logits(fake_logits, 0.0)

        d_loss, d_grads = jax.value_and_grad(disc_loss_fn)(disc_state.params)
        d_grads = mesh_lib.apply_grad_correction(d_grads, disc_grad_correction)

        new_gen = gen_state.apply_gradients(g_grads).replace(
            batch_stats=g_mut.get("batch_stats", gen_state.batch_stats))
        new_disc = disc_state.apply_gradients(d_grads)
        return new_gen, new_disc, {"gen_loss": g_loss, "disc_loss": d_loss}

    jit_kwargs = {}
    if donate:
        jit_kwargs["donate_argnums"] = (0, 1)
    if mesh is not None:
        jit_kwargs["out_shardings"] = (None, None, NamedSharding(mesh, P()))
    return annotate_step(jax.jit(step, **jit_kwargs), donate=donate,
                         compute_dtype=jnp.dtype(jnp.float32), kind="train")


class DCGANTrainer(AdversarialTrainer):
    """Epoch loop + checkpointing for DCGAN (`DCGAN/tensorflow/main.py:73-87`)."""

    def __init__(self, config: TrainConfig, workdir: str = "runs/dcgan",
                 mesh=None, noise_dim: int = 100):
        from ..models.gan import DCGANDiscriminator, DCGANGenerator
        self._validate_config(config)
        self.noise_dim = noise_dim
        self.mesh = mesh if mesh is not None else mesh_lib.make_mesh()
        mesh_lib.check_batch_divisible(config.batch_size, self.mesh)
        self.generator = DCGANGenerator(noise_dim=noise_dim)
        self.discriminator = DCGANDiscriminator()

        steps_per_epoch = max(1, config.data.train_examples // config.batch_size)
        tx_g = build_optimizer(config.optimizer, config.schedule,
                               steps_per_epoch, config.total_epochs)
        tx_d = build_optimizer(config.optimizer, config.schedule,
                               steps_per_epoch, config.total_epochs)

        rng = jax.random.PRNGKey(config.seed)
        g_rng, d_rng, self.rng = jax.random.split(rng, 3)
        g_params, g_bs = init_model(self.generator, g_rng,
                                    jnp.zeros((2, noise_dim)))
        d_params, d_bs = init_model(self.discriminator, d_rng,
                                    jnp.zeros((2, 28, 28, 1)))
        repl = mesh_lib.replicated(self.mesh)
        self.gen_state = jax.device_put(
            TrainState.create(self.generator.apply, g_params, tx_g, g_bs), repl)
        self.disc_state = jax.device_put(
            TrainState.create(self.discriminator.apply, d_params, tx_d, d_bs),
            repl)

        step_factory = lambda m, gc, dc: make_dcgan_train_step(  # noqa: E731
            self.generator.apply, self.discriminator.apply, noise_dim,
            mesh=m, gen_grad_correction=gc, disc_grad_correction=dc)
        self.train_step = step_factory(self.mesh, None, None)
        if mesh_lib.needs_conv_grad_fix(self.mesh):
            # measure both networks' per-leaf grad over-reduction in one
            # paired run (the tuple pytree calibrates gen and disc together)
            import optax

            # pad so the batch also shards on the all-device DP oracle mesh
            cal_b = mesh_lib.pad_to_multiple(
                config.batch_size, len(self.mesh.devices.flat))
            images = np.random.RandomState(0).uniform(
                -1, 1, (cal_b, 28, 28, 1)).astype(np.float32)
            g0 = jax.device_get(self.gen_state.params)
            d0 = jax.device_get(self.disc_state.params)
            gbs = jax.device_get(self.gen_state.batch_stats)
            rng = jax.random.PRNGKey(0)

            def run(m):
                repl = mesh_lib.replicated(m)
                gst = jax.device_put(TrainState.create(
                    self.generator.apply, g0, optax.sgd(1.0), gbs), repl)
                dst = jax.device_put(TrainState.create(
                    self.discriminator.apply, d0, optax.sgd(1.0)), repl)
                step = step_factory(m, None, None)
                batch = mesh_lib.shard_batch_pytree(m, images)
                gst, dst, _ = step(gst, dst, batch, rng)
                return ((g0, d0), (jax.device_get(gst.params),
                                   jax.device_get(dst.params)))

            corr = mesh_lib.calibrate_grad_correction(run, self.mesh)
            if corr is not None:
                self.train_step = step_factory(self.mesh, corr[0], corr[1])
        self._init_logging(config, workdir)

    def train_batch(self, images) -> dict:
        batch = mesh_lib.shard_batch_pytree(self.mesh, np.asarray(images))
        self.gen_state, self.disc_state, m = self.train_step(
            self.gen_state, self.disc_state, batch, self.rng)
        return m  # device arrays — no per-step host sync (DCGAN has no pool)

    def generate(self, num: int, rng: Optional[jax.Array] = None) -> np.ndarray:
        """Sample images (`DCGAN/tensorflow/inference.py:7-29`)."""
        rng = rng if rng is not None else jax.random.PRNGKey(42)
        noise = jax.random.normal(rng, (num, self.noise_dim))
        images = self.generator.apply(
            {"params": self.gen_state.params,
             "batch_stats": self.gen_state.batch_stats}, noise, train=False)
        return np.asarray(images)


# ---------------------------------------------------------------------------
# CycleGAN
# ---------------------------------------------------------------------------

LAMBDA_CYCLE = 10.0  # `CycleGAN/tensorflow/train.py:16-17`
LAMBDA_ID = 5.0


def make_cyclegan_generator_step(gen_apply: Callable, disc_apply: Callable,
                                 mesh=None, grad_correction=None) -> Callable:
    """Generator phase (`train.py:150-205`): one loss over both generators.

    gen_state.params = {"a2b": …, "b2a": …}; disc_state.params = {"a": …, "b": …}.
    Returns (gen_state, disc_batch_stats, fake_a2b, fake_b2a, metrics) — the
    discriminator forward passes run train=True (keras side-effect parity), so
    their mutated batch_stats are threaded back to the caller.

    `grad_correction` matches gen_state.params' {"a2b": …, "b2a": …} nesting
    (calibrated per-leaf by the trainer on combined meshes) — see
    make_dcgan_train_step.
    """

    def step(gen_state: TrainState, disc_state: TrainState, real_a, real_b):

        def loss_fn(gparams):
            bs = dict(gen_state.batch_stats)

            def g(name, x):
                with mesh_lib.spatial_activation_constraints(mesh):
                    y, mut = gen_apply(
                        {"params": gparams[name], "batch_stats": bs[name]},
                        x, train=True, mutable=["batch_stats"])
                bs[name] = mut["batch_stats"]
                return y

            fake_a2b = g("a2b", real_a)          # cycle A→B→A
            recon_b2a = g("b2a", fake_a2b)
            fake_b2a = g("b2a", real_b)          # cycle B→A→B
            recon_a2b = g("a2b", fake_b2a)
            identity_a2b = g("a2b", real_b)      # identity terms
            identity_b2a = g("b2a", real_a)

            dbs = dict(disc_state.batch_stats)

            def d(name, x):
                with mesh_lib.spatial_activation_constraints(mesh):
                    y, mut = disc_apply(
                        {"params": disc_state.params[name],
                         "batch_stats": dbs[name]},
                        x, train=True, mutable=["batch_stats"])
                dbs[name] = mut["batch_stats"]
                return y

            loss_gan_a2b = _mse(d("b", fake_a2b), 1.0)
            loss_gan_b2a = _mse(d("a", fake_b2a), 1.0)
            loss_cycle_a2b2a = _mae(recon_b2a, real_a)
            loss_cycle_b2a2b = _mae(recon_a2b, real_b)
            loss_id_a2b = _mae(identity_a2b, real_b)
            loss_id_b2a = _mae(identity_b2a, real_a)
            total = (loss_gan_a2b + loss_gan_b2a
                     + (loss_cycle_a2b2a + loss_cycle_b2a2b) * LAMBDA_CYCLE
                     + (loss_id_a2b + loss_id_b2a) * LAMBDA_ID)
            aux = (bs, dbs, fake_a2b, fake_b2a,
                   {"loss_gen_a2b": loss_gan_a2b, "loss_gen_b2a": loss_gan_b2a,
                    "loss_cycle_a2b2a": loss_cycle_a2b2a,
                    "loss_cycle_b2a2b": loss_cycle_b2a2b,
                    "loss_id_a2b": loss_id_a2b, "loss_id_b2a": loss_id_b2a,
                    "loss_gen_total": total})
            return total, aux

        (_, (bs, dbs, fake_a2b, fake_b2a, metrics)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(gen_state.params)
        grads = mesh_lib.apply_grad_correction(grads, grad_correction)
        new_gen = gen_state.apply_gradients(grads).replace(batch_stats=bs)
        return new_gen, dbs, fake_a2b, fake_b2a, metrics

    jit_kwargs = {"donate_argnums": (0,)}
    if mesh is not None:
        repl = NamedSharding(mesh, P())
        data = NamedSharding(mesh, P(mesh_lib.DATA_AXIS))
        jit_kwargs["out_shardings"] = (None, repl, data, data, repl)
    return annotate_step(jax.jit(step, **jit_kwargs), donate=True,
                         compute_dtype=jnp.dtype(jnp.float32), kind="train")


def make_cyclegan_discriminator_step(disc_apply: Callable, mesh=None,
                                     grad_correction=None) -> Callable:
    """Discriminator phase (`train.py:207-246`): (real+fake)/2 LSGAN per domain,
    one optimizer over both discriminators. Fakes come from the host ImagePool.
    `grad_correction` matches disc_state.params' {"a": …, "b": …} nesting —
    combined-mesh compensation as in make_cyclegan_generator_step."""

    def step(disc_state: TrainState, real_a, real_b, fake_a2b, fake_b2a):

        def loss_fn(dparams):
            bs = dict(disc_state.batch_stats)

            def d(name, x):
                with mesh_lib.spatial_activation_constraints(mesh):
                    y, mut = disc_apply(
                        {"params": dparams[name], "batch_stats": bs[name]},
                        x, train=True, mutable=["batch_stats"])
                bs[name] = mut["batch_stats"]
                return y

            loss_dis_a = (_mse(d("a", real_a), 1.0) +
                          _mse(d("a", fake_b2a), 0.0)) * 0.5
            loss_dis_b = (_mse(d("b", real_b), 1.0) +
                          _mse(d("b", fake_a2b), 0.0)) * 0.5
            total = loss_dis_a + loss_dis_b
            return total, (bs, {"loss_dis_a": loss_dis_a,
                                "loss_dis_b": loss_dis_b,
                                "loss_dis_total": total})

        (_, (bs, metrics)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(disc_state.params)
        grads = mesh_lib.apply_grad_correction(grads, grad_correction)
        new_disc = disc_state.apply_gradients(grads).replace(batch_stats=bs)
        return new_disc, metrics

    jit_kwargs = {"donate_argnums": (0,)}
    if mesh is not None:
        jit_kwargs["out_shardings"] = (None, NamedSharding(mesh, P()))
    return annotate_step(jax.jit(step, **jit_kwargs), donate=True,
                         compute_dtype=jnp.dtype(jnp.float32), kind="train")


class CycleGANTrainer(AdversarialTrainer):
    """Two-phase adversarial trainer (`CycleGAN/tensorflow/train.py:248-344`)."""

    def __init__(self, config: TrainConfig, workdir: str = "runs/cyclegan",
                 mesh=None, image_size: int = 256, n_blocks: int = 9,
                 pool_size: int = 50, steps_per_epoch: Optional[int] = None):
        """`steps_per_epoch` anchors the LinearDecay schedule to the real epoch
        length — pass the counted dataset size like the reference does
        (`CycleGAN/tensorflow/train.py:108-129` counts total_batches before
        building LinearDecay); defaults to config.data.train_examples / batch."""
        from ..models.gan import CycleGANGenerator, PatchGANDiscriminator
        self._validate_config(config)
        self.mesh = mesh if mesh is not None else mesh_lib.make_mesh()
        mesh_lib.check_batch_divisible(config.batch_size, self.mesh)
        self.generator = CycleGANGenerator(n_blocks=n_blocks)
        self.discriminator = PatchGANDiscriminator()

        steps_per_epoch = steps_per_epoch or max(
            1, config.data.train_examples // config.batch_size)
        tx_g = build_optimizer(config.optimizer, config.schedule,
                               steps_per_epoch, config.total_epochs)
        tx_d = build_optimizer(config.optimizer, config.schedule,
                               steps_per_epoch, config.total_epochs)

        rng = jax.random.PRNGKey(config.seed)
        rngs = jax.random.split(rng, 4)
        sample = jnp.zeros((2, image_size, image_size, 3))
        g_params, g_bs, d_params, d_bs = {}, {}, {}, {}
        for i, name in enumerate(("a2b", "b2a")):
            g_params[name], g_bs[name] = init_model(self.generator, rngs[i],
                                                    sample)
        for i, name in enumerate(("a", "b")):
            d_params[name], d_bs[name] = init_model(self.discriminator,
                                                    rngs[2 + i], sample)
        repl = mesh_lib.replicated(self.mesh)
        self.gen_state = jax.device_put(
            TrainState.create(self.generator.apply, g_params, tx_g, g_bs), repl)
        self.disc_state = jax.device_put(
            TrainState.create(self.discriminator.apply, d_params, tx_d, d_bs),
            repl)

        self.gen_step = make_cyclegan_generator_step(
            self.generator.apply, self.discriminator.apply, mesh=self.mesh)
        self.disc_step = make_cyclegan_discriminator_step(
            self.discriminator.apply, mesh=self.mesh)
        if mesh_lib.needs_conv_grad_fix(self.mesh):
            self._calibrate(config, image_size)
        # one pool per fake stream (`train.py:55-56`)
        self.pool_a2b = ImagePool(pool_size, seed=config.seed)
        self.pool_b2a = ImagePool(pool_size, seed=config.seed + 1)
        self._init_logging(config, workdir)

    def _calibrate(self, config: TrainConfig, image_size: int) -> None:
        """Combined-mesh grad calibration for BOTH phases: each phase's
        gradients live in its own optimizer, so each gets its own measured
        per-leaf correction (mesh_lib.calibrate_grad_correction) and its
        step is rebuilt with it."""
        import optax
        rs = np.random.RandomState(0)
        # pad so the batch also shards on the all-device DP oracle mesh
        cal_b = mesh_lib.pad_to_multiple(config.batch_size,
                                         len(self.mesh.devices.flat))
        shp = (cal_b, image_size, image_size, 3)
        a = rs.uniform(-1, 1, shp).astype(np.float32)
        b = rs.uniform(-1, 1, shp).astype(np.float32)
        fa = rs.uniform(-1, 1, shp).astype(np.float32)
        fb = rs.uniform(-1, 1, shp).astype(np.float32)
        g0 = jax.device_get(self.gen_state.params)
        d0 = jax.device_get(self.disc_state.params)
        gbs = jax.device_get(self.gen_state.batch_stats)
        dbs = jax.device_get(self.disc_state.batch_stats)

        def states(m):
            repl = mesh_lib.replicated(m)
            gst = jax.device_put(TrainState.create(
                self.generator.apply, g0, optax.sgd(1.0), gbs), repl)
            dst = jax.device_put(TrainState.create(
                self.discriminator.apply, d0, optax.sgd(1.0), dbs), repl)
            return gst, dst

        def run_gen(m):
            gst, dst = states(m)
            step = make_cyclegan_generator_step(
                self.generator.apply, self.discriminator.apply, mesh=m)
            ra, rb = mesh_lib.shard_batch_pytree(m, (a, b))
            gst, *_ = step(gst, dst, ra, rb)
            return g0, jax.device_get(gst.params)

        def run_disc(m):
            _, dst = states(m)
            step = make_cyclegan_discriminator_step(
                self.discriminator.apply, mesh=m)
            ra, rb, sfa, sfb = mesh_lib.shard_batch_pytree(m, (a, b, fa, fb))
            dst, _ = step(dst, ra, rb, sfa, sfb)
            return d0, jax.device_get(dst.params)

        gcorr = mesh_lib.calibrate_grad_correction(run_gen, self.mesh)
        if gcorr is not None:
            self.gen_step = make_cyclegan_generator_step(
                self.generator.apply, self.discriminator.apply,
                mesh=self.mesh, grad_correction=gcorr)
        dcorr = mesh_lib.calibrate_grad_correction(run_disc, self.mesh)
        if dcorr is not None:
            self.disc_step = make_cyclegan_discriminator_step(
                self.discriminator.apply, mesh=self.mesh,
                grad_correction=dcorr)

    def train_batch(self, images_a: np.ndarray, images_b: np.ndarray) -> dict:
        """One eager-outer step: jitted gen phase → host pools → jitted disc
        phase (`train.py:248-255`)."""
        real_a, real_b = mesh_lib.shard_batch_pytree(
            self.mesh, (np.asarray(images_a), np.asarray(images_b)))
        self.gen_state, disc_bs, fake_a2b, fake_b2a, gm = self.gen_step(
            self.gen_state, self.disc_state, real_a, real_b)
        self.disc_state = self.disc_state.replace(batch_stats=disc_bs)

        fake_a2b_pool = self.pool_a2b.query(jax.device_get(fake_a2b))
        fake_b2a_pool = self.pool_b2a.query(jax.device_get(fake_b2a))
        fa, fb = mesh_lib.shard_batch_pytree(self.mesh,
                                             (fake_a2b_pool, fake_b2a_pool))
        self.disc_state, dm = self.disc_step(self.disc_state, real_a, real_b,
                                             fa, fb)
        return {**jax.device_get(gm), **jax.device_get(dm)}

    def translate(self, images: np.ndarray, direction: str = "a2b") -> np.ndarray:
        """Run one generator (`CycleGAN/tensorflow/inference.py:34-63`)."""
        out = self.generator.apply(
            {"params": self.gen_state.params[direction],
             "batch_stats": self.gen_state.batch_stats[direction]},
            jnp.asarray(images), train=False)
        return np.asarray(out)
