"""Detection (YOLO V3) SPMD steps + trainer.

Parity target: the reference's distributed YOLO trainer
(`YOLO/tensorflow/train.py:22-257`): per-replica GradientTape step over the 3 scale
losses with SUM cross-replica reduce and 1/global_batch pre-scaling, plateau LR decay
(`:56-68`), loss-watching save-best checkpoints (`:244-257`), and epoch loops
(`:122-250`).

TPU-native shape: one jitted `train_step(state, images, boxes, classes, valid, rng)`
over the mesh — GSPMD inserts the gradient all-reduce; the label encoding runs inside
the step on device (see ops/yolo.py); `jnp.mean` over the data-sharded batch IS the
`strategy.reduce(SUM) × 1/global_batch` of the reference.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..ops import yolo as yolo_ops
from ..parallel import mesh as mesh_lib
from .config import TrainConfig, UNIT_RANGE_NORM
from .steps import _normalize_input, annotate_step, maybe_grad_norm
from .trainer import LossWatchedTrainer


def yolo_grid_sizes(image_size: int) -> Sequence[int]:
    """Grids at strides 8/16/32, finest first — (52, 26, 13) at 416px
    (`YOLO/tensorflow/preprocess.py:27-34`)."""
    return (image_size // 8, image_size // 16, image_size // 32)


def boxes_calibration_batch(config, sample_shape, batch_size: int,
                            seed: int = 0):
    """Synthetic (images, boxes, classes, valid) batch for combined-mesh grad
    calibration — the padded-GT layout shared by the YOLO and CenterNet
    steps (`ops/yolo.py::MAX_BOXES`)."""
    import numpy as np

    from ..ops.yolo import MAX_BOXES
    rs = np.random.RandomState(seed)
    b = batch_size
    images = (rs.randint(0, 256, (b, *sample_shape)).astype(np.uint8)
              if config.data.normalize_on_device
              else rs.rand(b, *sample_shape).astype(np.float32))
    boxes = np.zeros((b, MAX_BOXES, 4), np.float32)
    boxes[:, 0] = [0.2, 0.2, 0.6, 0.6]
    classes = np.zeros((b, MAX_BOXES), np.int32)
    valid = np.zeros((b, MAX_BOXES), np.float32)
    valid[:, 0] = 1.0
    return (images, boxes, classes, valid)


def make_yolo_train_step(*, num_classes: int, grid_sizes: Sequence[int],
                         compute_dtype=jnp.bfloat16, donate: bool = True,
                         mesh=None, remat: bool = False,
                         input_norm=None, log_grad_norm: bool = False,
                         grad_correction=None) -> Callable:
    """(state, images, boxes, classes, valid, rng) -> (state, metrics).

    boxes: (B, N, 4) normalized corner ground truth padded to N=MAX_BOXES;
    classes: (B, N) int32; valid: (B, N) 0/1. `remat=True` recomputes forward
    activations in the backward pass (HBM-for-FLOPs, cf. steps.py).
    `input_norm=(mean, std)`: images arrive as raw [0,255] pixels (uint8
    transfer, `--device-normalize`) and are normalized on device (steps.py).
    """

    def step(state, images, boxes, classes, valid, rng):
        del rng  # YOLO has no dropout; augmentation happens host-side
        images = _normalize_input(images, input_norm, compute_dtype)
        classes_onehot = jax.nn.one_hot(classes, num_classes, dtype=jnp.float32)
        y_trues = yolo_ops.encode_labels(classes_onehot, boxes, valid, grid_sizes)

        def forward(params, images):
            with mesh_lib.spatial_activation_constraints(mesh):
                return state.apply_fn(
                    {"params": params, "batch_stats": state.batch_stats},
                    images, train=True, mutable=["batch_stats"])

        if remat:
            forward = jax.checkpoint(
                forward,
                policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable)

        def loss_fn(params):
            outputs, mutated = forward(params, images)
            comp = yolo_ops.yolo_loss(y_trues, outputs, boxes, valid, num_classes)
            # mean over the global batch == reference's sum × 1/global_batch_size
            # (`YOLO/tensorflow/train.py:85-91,134-151`)
            return jnp.mean(comp["total"]), (comp, mutated)

        (loss, (comp, mutated)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)
        grads = mesh_lib.apply_grad_correction(grads, grad_correction)
        new_state = state.apply_gradients(grads).replace(
            batch_stats=mutated.get("batch_stats", state.batch_stats))
        metrics = {"loss": loss,
                   **{f"{k}_loss": jnp.mean(v) for k, v in comp.items()
                      if k != "total"},
                   **maybe_grad_norm(log_grad_norm, grads)}
        return new_state, metrics

    jit_kwargs = {}
    if donate:
        jit_kwargs["donate_argnums"] = (0,)
    if mesh is not None:
        jit_kwargs["out_shardings"] = (None, NamedSharding(mesh, P()))
    return annotate_step(jax.jit(step, **jit_kwargs), donate=donate,
                         compute_dtype=jnp.dtype(compute_dtype), kind="train")


def make_yolo_eval_step(*, num_classes: int, grid_sizes: Sequence[int],
                        compute_dtype=jnp.bfloat16, mesh=None,
                        input_norm=None) -> Callable:
    """Validation loss step (`val_step`, `YOLO/tensorflow/train.py:105-117`)."""

    def step(state, images, boxes, classes, valid):
        images = _normalize_input(images, input_norm, compute_dtype)
        classes_onehot = jax.nn.one_hot(classes, num_classes, dtype=jnp.float32)
        y_trues = yolo_ops.encode_labels(classes_onehot, boxes, valid, grid_sizes)
        with mesh_lib.spatial_activation_constraints(mesh):
            outputs = state.apply_fn(
                {"params": state.params, "batch_stats": state.batch_stats},
                images, train=False, decode=False)
        comp = yolo_ops.yolo_loss(y_trues, outputs, boxes, valid, num_classes)
        return {"loss": jnp.mean(comp["total"])}

    jit_kwargs = {}
    if mesh is not None:
        jit_kwargs["out_shardings"] = NamedSharding(mesh, P())
    return annotate_step(jax.jit(step, **jit_kwargs), donate=False,
                         compute_dtype=jnp.dtype(compute_dtype), kind="eval")


def make_predict_step(*, compute_dtype=jnp.bfloat16, iou_thresh: float = 0.5,
                      score_thresh: float = 0.5, max_detection: int = 100) -> Callable:
    """(state, images) -> (nms_boxes, nms_scores, nms_class_probs, counts).

    Full device-side inference: decoded multi-scale heads → flatten → fixed-shape
    NMS (ops/nms.py) — the role of the reference's `Postprocessor`
    (`YOLO/tensorflow/postprocess.py:6-36`), but jitted and batched.
    """
    from ..ops.boxes import xywh_to_x1y1x2y2
    from ..ops.nms import batched_nms

    def step(state, images):
        images = images.astype(compute_dtype)
        outputs = state.apply_fn(
            {"params": state.params, "batch_stats": state.batch_stats},
            images, train=False, decode=True)
        b = images.shape[0]
        boxes = jnp.concatenate([o[0].reshape(b, -1, 4) for o in outputs], axis=1)
        obj = jnp.concatenate([o[1].reshape(b, -1) for o in outputs], axis=1)
        cls_probs = jnp.concatenate(
            [o[2].reshape(b, -1, o[2].shape[-1]) for o in outputs], axis=1)
        # detection confidence = objectness × class probability (the standard
        # score both COCO and VOC evaluators rank by); rank/suppress on the best
        # class's confidence, report per-class confidences for the evaluator.
        conf = obj[..., None].astype(jnp.float32) * cls_probs.astype(jnp.float32)
        return batched_nms(xywh_to_x1y1x2y2(boxes.astype(jnp.float32)),
                           jnp.max(conf, axis=-1), conf,
                           iou_thresh=iou_thresh, score_thresh=score_thresh,
                           max_detection=max_detection)

    return annotate_step(jax.jit(step), donate=False,
                         compute_dtype=jnp.dtype(compute_dtype),
                         kind="predict")


def evaluate_map(state, batches, *, num_classes: int, metric: str = "coco",
                 iou_thresh: float = 0.5, score_thresh: float = 0.05,
                 compute_dtype=jnp.bfloat16) -> dict:
    """Run detection inference over `batches` of (images, boxes, classes, valid)
    and return mAP summary metrics.

    metric="coco" → mAP@[.5:.95]; "voc" → all-point mAP@0.5; "voc07" → 11-point.
    The low default score threshold keeps the PR curve's low-confidence tail, as
    standard evaluators do. This is the evaluator the reference never shipped
    (`YOLO/tensorflow/README.md:29`).
    """
    from .eval_detection import make_evaluator

    ev = make_evaluator(metric, num_classes)
    predict = make_predict_step(compute_dtype=compute_dtype,
                                iou_thresh=iou_thresh, score_thresh=score_thresh)
    for batch in batches:
        images, boxes, classes, valid = batch[:4]
        difficult = batch[4] if len(batch) > 4 else None  # VOC devkit flags
        nms_boxes, nms_scores, nms_classes, counts = predict(
            state, jnp.asarray(images))
        ev.add_batch(nms_boxes, nms_scores, nms_classes, counts,
                     boxes, classes, valid, gt_difficult=difficult)
    return ev.summarize()


class DetectionTrainer(LossWatchedTrainer):
    """YOLO trainer: same epoch/checkpoint/plateau machinery as the shared Trainer,
    with detection steps and loss-watched validation (the reference watches val loss
    for both LR decay and save-best, `YOLO/tensorflow/train.py:244-247`). Model
    construction (num_classes/dtype kwargs) is inherited from the base."""

    has_own_shardmap_step = True  # make_shardmap_yolo_train_step

    def __init__(self, config: TrainConfig, model=None, mesh=None,
                 workdir: Optional[str] = None):
        super().__init__(config, model=model, mesh=mesh, workdir=workdir)
        grids = yolo_grid_sizes(config.data.image_size)
        compute_dtype = jnp.dtype(config.dtype) if config.dtype else jnp.bfloat16
        input_norm = UNIT_RANGE_NORM if config.data.normalize_on_device else None
        if self._use_shardmap_spatial():
            # owned collectives through the Darknet/FPN backbone with an
            # all_gather head handoff (the YOLO loss is not row-local) —
            # exact on combined meshes, no calibration
            from ..parallel import spatial_shard
            self._step_factory = (
                lambda m, corr: spatial_shard.make_shardmap_yolo_train_step(
                    num_classes=config.data.num_classes, grid_sizes=grids,
                    compute_dtype=compute_dtype, mesh=m,
                    input_norm=input_norm,
                    log_grad_norm=config.log_grad_norm,
                    remat=config.remat,
                    donate=config.donate_step()))
        else:
            self._step_factory = lambda m, corr: make_yolo_train_step(
                num_classes=config.data.num_classes, grid_sizes=grids,
                compute_dtype=compute_dtype, mesh=m, remat=config.remat,
                input_norm=input_norm, log_grad_norm=config.log_grad_norm,
                donate=config.donate_step(), grad_correction=corr)
        self.train_step = self._step_factory(self.mesh, None)
        self.eval_step = make_yolo_eval_step(
            num_classes=config.data.num_classes, grid_sizes=grids,
            compute_dtype=compute_dtype, mesh=self.mesh,
            input_norm=input_norm)

    def _calibration_batch(self, sample_shape, seed: int = 0):
        return boxes_calibration_batch(self.config, sample_shape,
                                       self._calibration_batch_size(),
                                       seed=seed)
