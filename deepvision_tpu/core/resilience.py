"""Self-healing primitives for the training loops.

The divergence guard in trainer.py detects a poisoned run; this module is
what lets a run RECOVER instead of only halting loudly (the reference's sole
gesture at any of this was skipping NaN val batches with a TODO,
`Hourglass/tensorflow/train.py:126-130`). Four capabilities, shared by the
supervised and adversarial trainers:

- `RetryPolicy` / `call_with_retry`: bounded exponential backoff with jitter
  around transient host I/O (checkpoint save/restore, data iteration) —
  `OSError` is retried, everything else propagates untouched.
- `resilient_batches`: wraps a host batch iterator with the retry policy and
  the fault injector (utils/faults.py), so flaky storage mid-epoch costs a
  logged retry, not the run.
- `GracefulShutdown` + `PreemptionExit`: SIGTERM/SIGINT set a flag the step
  loop polls; the trainer finishes the in-flight step, commits a synchronous
  checkpoint, and exits 0 with the resume hint — complementing the
  SIGKILL-atomicity guarantee (tests/test_preemption.py) with a path that
  loses zero steps when the platform gives notice.
- `StepWatchdog`: in-process monotonic stall detector — the external
  `tools/tpu_window.sh` watchdog's job done from inside `fit`, with
  diagnostics (last step, last checkpoint, prefetch depth) a process-group
  kill could never print.

Divergence auto-recovery itself (rollback + LR scale-down + bounded retry)
lives in the trainers' fit loops — it needs the checkpoint manager and
optimizer state — gated by `TrainConfig.recover_on_divergence`.
"""

from __future__ import annotations

import dataclasses
import os
import random
import signal
import sys
import threading
import time
from typing import Callable, Iterable, Iterator, Optional


@dataclasses.dataclass
class RetryPolicy:
    """Bounded exponential backoff: attempt n sleeps
    min(max_delay, base_delay * 2^(n-1)) * (1 + U[0,jitter]).
    Jitter decorrelates a pod's hosts hammering recovered storage in
    lockstep; the `rng` seed makes test schedules reproducible."""

    max_retries: int = 3
    base_delay: float = 0.5
    max_delay: float = 8.0
    jitter: float = 0.25
    seed: int = 0

    @classmethod
    def from_env(cls, env=None, **overrides) -> "RetryPolicy":
        """DEEPVISION_IO_RETRIES / DEEPVISION_IO_RETRY_DELAY override the
        defaults (tests shrink the schedule; a pod job can raise it)."""
        env = os.environ if env is None else env
        kw = dict(overrides)
        if "DEEPVISION_IO_RETRIES" in env:
            kw["max_retries"] = int(env["DEEPVISION_IO_RETRIES"])
        if "DEEPVISION_IO_RETRY_DELAY" in env:
            kw["base_delay"] = float(env["DEEPVISION_IO_RETRY_DELAY"])
        return cls(**kw)

    def delay(self, attempt: int, rng: random.Random) -> float:
        d = min(self.max_delay, self.base_delay * (2 ** (attempt - 1)))
        return d * (1.0 + rng.random() * self.jitter)


def call_with_retry(fn: Callable, policy: RetryPolicy, *, what: str,
                    on_retry: Optional[Callable] = None):
    """Run `fn()`, retrying transient `OSError` (IOError is its py3 alias)
    up to `policy.max_retries` times with backoff. `on_retry(what, attempt,
    exc, delay)` fires before each sleep — the trainers log it to the
    metrics stream so a flaky-storage epoch leaves forensics. The final
    failure re-raises the last error unchanged."""
    rng = random.Random(policy.seed)
    attempt = 0
    while True:
        try:
            return fn()
        except OSError as e:
            attempt += 1
            if attempt > policy.max_retries:
                raise
            d = policy.delay(attempt, rng)
            if on_retry is not None:
                on_retry(what, attempt, e, d)
            time.sleep(d)


def resilient_batches(batches: Iterable, policy: RetryPolicy,
                      injector=None,
                      on_retry: Optional[Callable] = None) -> Iterator:
    """Yield from a host batch iterator, retrying transient OSError from the
    pull itself (tf.data readers surface flaky remote storage this way and
    stay usable) and applying the fault injector's data hooks. The injected
    fault fires BEFORE the pull, so no batch is ever dropped on retry."""
    it = iter(batches)

    def pull():
        if injector is not None:
            injector.before_batch()
        return next(it)

    while True:
        try:
            batch = call_with_retry(pull, policy, what="data_io",
                                    on_retry=on_retry)
        except StopIteration:
            return
        if injector is not None:
            batch = injector.poison_batch(batch)
        yield batch


def log_resilience_event(logger, step: int, metrics: dict,
                         epoch: Optional[int] = None, *,
                         request_id: Optional[str] = None,
                         trace_ref: Optional[str] = None,
                         flywheel_id: Optional[str] = None) -> None:
    """Write one event onto the `resilience_` metrics stream — the single
    forensics channel every recovery path shares (divergence rollbacks and
    checkpoint fallbacks in the trainers, refused hot reloads in
    serve/reload.py, sheds/breaker transitions in the serving stack):
    prefixed keys, float values, no console echo, same JSONL/TB stream as
    the run's ordinary metrics so incidents line up with the
    training/serving timeline. A None logger is a no-op, so callers
    without a metrics stream (library embedding) need no guard.

    `request_id` / `trace_ref` are the correlation fields
    (docs/OBSERVABILITY.md): the HTTP request id that triggered this event
    and/or the ``span:<id>`` of the span that produced it, written as
    string fields on the JSONL line — a shed, breaker-open, or rollback
    event joins the exact spans (GET /trace) and client log line behind
    it on these keys. `flywheel_id` is the third correlation field: the
    episode id the flywheel controller (flywheel/controller.py) mints at
    a drift event and threads through every decision of one
    drift→retrain→promote episode, so a single grep over the stream
    reconstructs the whole loop (docs/FAILURES.md "Flywheel
    decisions")."""
    if logger is None:
        return
    extra = {}
    if request_id is not None:
        extra["request_id"] = str(request_id)
    if trace_ref is not None:
        extra["trace_ref"] = str(trace_ref)
    if flywheel_id is not None:
        extra["flywheel_id"] = str(flywheel_id)
    logger.log(step, {k: float(v) for k, v in metrics.items()},
               epoch=epoch, prefix="resilience_", echo=False,
               extra=extra or None)


class PreemptionExit(Exception):
    """Raised by fit() after a graceful-shutdown checkpoint is committed;
    `fit_and_close` (and the GAN mains) convert it to a clean exit 0. Carries
    the committed epoch for the resume hint."""

    def __init__(self, epoch: int, message: str):
        super().__init__(message)
        self.epoch = epoch


class GracefulShutdown:
    """SIGTERM/SIGINT → a polled flag, installed for the duration of fit()
    (or a serving lifetime, serve/server.py).

    The step loop checks `requested` between host dispatches: the in-flight
    step finishes, the trainer commits a synchronous checkpoint, and the
    process exits 0 — TPU-pod preemptions send SIGTERM with a grace window,
    and losing an epoch to it is pure waste. A SECOND signal restores the
    previous handlers and re-raises, so a stuck shutdown stays killable with
    plain Ctrl-C Ctrl-C. Signal handlers only exist on the main thread;
    elsewhere (library use under a thread pool) this degrades to an inert
    flag that is never set.

    `on_signal` (optional) fires once, after the flag is set, so loops that
    WAIT rather than poll (the inference server's flush loop) can be woken
    immediately — pass something async-signal-safe like `Event.set`.
    `what` customizes the one-line announcement: the serving drain says
    "finishing in-flight batches, rejecting new work" instead of the
    trainer's checkpoint-commit contract."""

    SIGNALS = (signal.SIGTERM, signal.SIGINT)

    def __init__(self, on_signal: Optional[Callable[[], None]] = None,
                 what: str = "finishing the in-flight step, committing a "
                             "checkpoint, then exiting 0"):
        self.requested = False
        self._signum = None
        self._previous = {}
        self._on_signal = on_signal
        self._what = what

    def _handler(self, signum, frame):
        if self.requested:  # second signal: get out of the way
            self._restore()
            raise KeyboardInterrupt
        self.requested = True
        self._signum = signum
        print(f"[resilience] caught {signal.Signals(signum).name}: "
              f"{self._what} (signal again to abort immediately)",
              file=sys.stderr, flush=True)
        if self._on_signal is not None:
            try:
                self._on_signal()
            except Exception:  # noqa: BLE001 — a handler must never throw
                pass

    def __enter__(self) -> "GracefulShutdown":
        try:
            for s in self.SIGNALS:
                self._previous[s] = signal.signal(s, self._handler)
        except ValueError:  # not the main thread: flag stays inert
            self._previous = {}
        return self

    def _restore(self):
        for s, h in self._previous.items():
            signal.signal(s, h)
        self._previous = {}

    def __exit__(self, *exc):
        self._restore()
        return False


class StepWatchdog:
    """Host-side stall detector: a daemon thread that aborts the process
    when no `beat()` lands within `threshold` seconds (monotonic clock).

    This brings `tools/tpu_window.sh`'s external mtime watchdog in-process:
    the relay's failure mode is a silent wedge inside a dispatch, which no
    epoch-level timeout sees until the window is gone. Before aborting it
    prints the diagnostics an external kill never could — last host-side
    step, last committed checkpoint epoch, prefetch queue depth — plus every
    thread's stack (faulthandler), then `os._exit(EXIT_CODE)` so a wrapping
    retry loop can relaunch with --auto-resume. Off unless a threshold is
    configured (`--watchdog-secs` / DEEPVISION_WATCHDOG_SECS); in particular
    it is NOT armed under pytest's in-process trainer tests, whose CPU
    compile times would trip any useful threshold."""

    EXIT_CODE = 70  # EX_SOFTWARE: distinguishable from the step's own errors

    def __init__(self, threshold_secs: float,
                 diagnostics: Optional[Callable[[], dict]] = None,
                 name: str = "trainer",
                 _abort: Optional[Callable] = None):
        if threshold_secs <= 0:
            raise ValueError(f"watchdog threshold must be > 0, "
                             f"got {threshold_secs}")
        self.threshold = threshold_secs
        self.diagnostics = diagnostics
        self.name = name
        self._abort = _abort if _abort is not None else self._default_abort
        self._last = time.monotonic()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._watch, daemon=True, name=f"step-watchdog-{name}")
        self._thread.start()

    def beat(self) -> None:
        self._last = time.monotonic()

    def _watch(self) -> None:
        poll = min(1.0, self.threshold / 4.0)
        while not self._stop.wait(poll):
            stalled = time.monotonic() - self._last
            if stalled >= self.threshold:
                self._dump(stalled)
                self._abort()
                return

    def _dump(self, stalled: float) -> None:
        info = {}
        if self.diagnostics is not None:
            try:
                info = self.diagnostics()
            except Exception as e:  # noqa: BLE001 — diagnostics must not
                info = {"diagnostics_error": repr(e)}  # mask the stall report
        detail = " ".join(f"{k}={v}" for k, v in info.items())
        print(f"[watchdog:{self.name}] no step progress for {stalled:.0f}s "
              f"(threshold {self.threshold:.0f}s) — aborting. {detail}",
              file=sys.stderr, flush=True)
        try:
            import faulthandler
            faulthandler.dump_traceback(file=sys.stderr)
        except Exception:  # noqa: BLE001 — the abort still proceeds
            pass

    @classmethod
    def _default_abort(cls) -> None:
        # os._exit, not sys.exit: the whole point is that the main thread is
        # wedged inside a dispatch and will never unwind an exception
        os._exit(cls.EXIT_CODE)

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)

    def __enter__(self) -> "StepWatchdog":
        return self

    def __exit__(self, *exc):
        self.stop()
        return False
