"""Import the reference's GAN `tf.train.Checkpoint` weights into Flax params.

The reference saves its GANs with `tf.train.Checkpoint(...)` + CheckpointManager
(DCGAN: `DCGAN/tensorflow/main.py:34-39`, objects `generator`/`discriminator`;
CycleGAN: `CycleGAN/tensorflow/train.py:134-148`, objects `generator_a2b`/
`generator_b2a`/`discriminator_a`/`discriminator_b`), not Keras h5 — a third
checkpoint dialect next to the classification torch dicts and the YOLO h5s.

Variable paths differ across Keras generations (`layer_with_weights-N/...` in
the TF 2.1 era that produced the published checkpoints; `_functional/
_operations/N/...` in current Keras), so parsing keys on the ordered numeric
layer index plus the stable attribute names (kernel/bias/gamma/beta/
moving_mean/moving_variance) and, inside the CycleGAN ResNetBlock, its fixed
sublayer names (`conv1/bn1/conv2/bn2`, `CycleGAN/tensorflow/models.py:17-28`).

Kernel layout notes (verified numerically in tests/test_gan_convert.py):
- Conv2D kernels are HWIO in both frameworks — copied as-is.
- Conv2DTranspose kernels are (kh, kw, out, in) in Keras and compute the
  gradient-of-conv; Flax's `nn.ConvTranspose` applies the kernel as-is, so the
  equivalent Flax kernel is the Keras one transposed to (kh, kw, in, out) AND
  spatially flipped.
"""

from __future__ import annotations

import re
from typing import Dict, List, Tuple

import numpy as np

_ATTRS = ("kernel", "bias", "gamma", "beta", "moving_mean", "moving_variance")
_SUBLAYER_ORDER = {"conv1": 0, "bn1": 1, "conv2": 2, "bn2": 3}


def open_reader(ckpt_path: str):
    """Resolve a checkpoint prefix (`.../ck-5`) or a directory (latest is
    used) into one CheckpointReader — shared across convert_object calls so a
    multi-object import reads the files once."""
    import os

    import tensorflow as tf

    if os.path.isdir(ckpt_path):
        latest = tf.train.latest_checkpoint(ckpt_path)
        if latest is None:
            raise FileNotFoundError(f"no tf.train checkpoint under {ckpt_path}")
        ckpt_path = latest
    return tf.train.load_checkpoint(ckpt_path)


def load_object_groups(ckpt_or_reader, obj: str) -> List[Dict[str, np.ndarray]]:
    """Read one checkpointed object's weight layers, in execution order.

    Returns a list of {attr: array} groups — one per weighted Keras layer —
    ordered by layer index (and sublayer position inside the reference's
    ResNetBlock). Accepts a path (prefix or directory) or an `open_reader`
    result.
    """
    reader = (ckpt_or_reader if hasattr(ckpt_or_reader, "get_tensor")
              else open_reader(ckpt_or_reader))
    pat = re.compile(rf"^{re.escape(obj)}/(?P<body>.+)/\.ATTRIBUTES/VARIABLE_VALUE$")
    groups: Dict[str, Dict[str, np.ndarray]] = {}
    for name in reader.get_variable_to_shape_map():
        m = pat.match(name)
        if not m:
            continue
        parts = m.group("body").split("/")
        attr = parts[-1].lstrip("_")  # Keras 3 writes Conv/Dense as `_kernel`
        if attr not in _ATTRS or "OPTIMIZER" in name:
            continue
        gkey = "/".join(parts[:-1])
        groups.setdefault(gkey, {})[attr] = reader.get_tensor(name)
    if not groups:
        raise KeyError(f"checkpoint has no weights under object {obj!r}")

    def sort_key(gkey: str):
        key = []
        for p in gkey.split("/"):
            if p.isdigit():
                key.append((0, int(p)))
            elif p.startswith("layer_with_weights-"):
                key.append((0, int(p.rsplit("-", 1)[-1])))
            elif p in _SUBLAYER_ORDER:
                key.append((1, _SUBLAYER_ORDER[p]))
        return tuple(key)

    return [groups[k] for k in sorted(groups, key=sort_key)]


def _take_bn(group, params, stats, name):
    params[name] = {"scale": group["gamma"], "bias": group["beta"]}
    stats[name] = {"mean": group["moving_mean"],
                   "var": group["moving_variance"]}


def _conv(group) -> Dict:
    out = {"kernel": group["kernel"]}
    if "bias" in group:
        out["bias"] = group["bias"]
    return out


def _conv_transpose(group) -> Dict:
    k = np.transpose(group["kernel"], (0, 1, 3, 2))[::-1, ::-1]
    out = {"kernel": np.ascontiguousarray(k)}
    if "bias" in group:
        out["bias"] = group["bias"]
    return out


def convert_dcgan_generator(groups: List[Dict]) -> Tuple[Dict, Dict]:
    """Dense → BN → CT128 → BN → CT64 → BN → CT1
    (`DCGAN/tensorflow/models.py:30-65`)."""
    params: Dict = {}
    stats: Dict = {}
    params["Dense_0"] = {"kernel": groups[0]["kernel"]}
    _take_bn(groups[1], params, stats, "BatchNorm_0")
    params["ConvTranspose_0"] = _conv_transpose(groups[2])
    _take_bn(groups[3], params, stats, "BatchNorm_1")
    params["ConvTranspose_1"] = _conv_transpose(groups[4])
    _take_bn(groups[5], params, stats, "BatchNorm_2")
    params["ConvTranspose_2"] = _conv_transpose(groups[6])
    assert len(groups) == 7, len(groups)
    return params, stats


def convert_dcgan_discriminator(groups: List[Dict]) -> Tuple[Dict, Dict]:
    """conv64 → conv128 → dense(1) (`DCGAN/tensorflow/models.py:8-27`)."""
    assert len(groups) == 3, len(groups)
    params = {"Conv_0": _conv(groups[0]), "Conv_1": _conv(groups[1]),
              "Dense_0": {"kernel": groups[2]["kernel"],
                          "bias": groups[2]["bias"]}}
    return params, {}


def convert_cyclegan_generator(groups: List[Dict],
                               n_blocks: int = 9) -> Tuple[Dict, Dict]:
    """c7s1-64, d128, d256, R256×n, u128, u64, c7s1-3
    (`CycleGAN/tensorflow/models.py:41-78`)."""
    expect = 6 + 4 * n_blocks + 2 * 2 + 1
    assert len(groups) == expect, (len(groups), expect)
    params: Dict = {}
    stats: Dict = {}
    it = iter(groups)
    for i in range(3):  # encode: conv + bn
        params[f"Conv_{i}"] = _conv(next(it))
        _take_bn(next(it), params, stats, f"BatchNorm_{i}")
    for b in range(n_blocks):  # transform: conv1 bn1 conv2 bn2
        bp: Dict = {}
        bs: Dict = {}
        bp["Conv_0"] = _conv(next(it))
        _take_bn(next(it), bp, bs, "BatchNorm_0")
        bp["Conv_1"] = _conv(next(it))
        _take_bn(next(it), bp, bs, "BatchNorm_1")
        params[f"CycleGANResBlock_{b}"] = bp
        stats[f"CycleGANResBlock_{b}"] = bs
    for i in range(2):  # decode: convT + bn
        params[f"ConvTranspose_{i}"] = _conv_transpose(next(it))
        _take_bn(next(it), params, stats, f"BatchNorm_{3 + i}")
    params["Conv_3"] = _conv(next(it))  # c7s1-3 (has bias)
    return params, stats


def convert_cyclegan_discriminator(groups: List[Dict]) -> Tuple[Dict, Dict]:
    """C64 → (C128, C256, C512 each + BN) → C1 patch head
    (`CycleGAN/tensorflow/models.py:81-104`)."""
    assert len(groups) == 8, len(groups)
    params: Dict = {}
    stats: Dict = {}
    it = iter(groups)
    params["Conv_0"] = _conv(next(it))
    for i in range(3):
        params[f"Conv_{1 + i}"] = _conv(next(it))
        _take_bn(next(it), params, stats, f"BatchNorm_{i}")
    params["Conv_4"] = _conv(next(it))
    return params, stats


# checkpointed-object name (as the reference constructs it) → converter +
# our registered model name
CONVERTERS = {
    "generator": (convert_dcgan_generator, "dcgan_generator"),
    "discriminator": (convert_dcgan_discriminator, "dcgan_discriminator"),
    "generator_a2b": (convert_cyclegan_generator, "cyclegan_generator"),
    "generator_b2a": (convert_cyclegan_generator, "cyclegan_generator"),
    "discriminator_a": (convert_cyclegan_discriminator, "patchgan_discriminator"),
    "discriminator_b": (convert_cyclegan_discriminator, "patchgan_discriminator"),
}


def convert_object(ckpt_or_reader, obj: str, **kw) -> Tuple[Dict, Dict]:
    """(params, batch_stats) for one checkpointed object by its reference name."""
    if obj not in CONVERTERS:
        raise KeyError(f"unknown GAN checkpoint object {obj!r}; "
                       f"known: {', '.join(sorted(CONVERTERS))}")
    fn, _ = CONVERTERS[obj]
    return fn(load_object_groups(ckpt_or_reader, obj), **kw)
