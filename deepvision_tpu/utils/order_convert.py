"""Generic call-order Keras → Flax weight conversion.

For architectures ported layer-for-layer (the whole zoo: every Flax model here
mirrors its reference builder's layer creation order), the reference model's
weighted Keras layers and our Flax model's weighted submodules correspond 1:1
*per kind, in creation order*: Keras auto-names carry a per-type creation
counter (`conv2d_7`, `batch_normalization_12`), and a Flax
`nn.intercept_methods` interceptor recovers our call order (== creation order
under `nn.compact`) during init. Pairing the per-kind sequences converts any
such checkpoint without a hand-written per-layer name table (the approach
`keras_convert.py` needs for YOLO's explicitly-named layers, and
`gan_convert.py` for checkpoint object paths). Pairing per kind — not over
the single interleaved sequence — matters because Keras `model.layers` is
TOPOLOGICAL order, which permutes parallel branches (a residual projection
lands mid-branch), while the per-type counters are pure creation order.

Used for the Stacked Hourglass h5 import (`tools/import_keras_checkpoint.py
-m hourglass104`), whose ~200 auto-named layers (`conv2d_37`,
`batch_normalization_52`, ...) would make a name table unmaintainable.

Kernel layouts: Keras Conv2D/Dense kernels are HWIO/IO like Flax — copied
as-is; Conv2DTranspose needs (kh, kw, out, in) → (kh, kw, in, out) plus a
spatial flip (verified numerically in tests/test_gan_convert.py).
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

# Keras layer-class / auto-name prefix → Flax module class name
KERAS_TO_FLAX_KIND = {
    "Conv2D": "Conv",
    "Dense": "Dense",
    "BatchNormalization": "BatchNorm",
    "Conv2DTranspose": "ConvTranspose",
}
_NAME_PREFIXES = (  # longest first: conv2d_transpose starts with conv2d
    ("conv2d_transpose", "ConvTranspose"),
    ("batch_normalization", "BatchNorm"),
    ("conv2d", "Conv"),
    ("dense", "Dense"),
)


def flax_modules_in_call_order(model, *init_args, **init_kwargs):
    """Init `model`, recording every weighted submodule in first-call order.

    Returns (ordered [(path_tuple, flax_kind)], init variables)."""
    import flax.linen as nn

    types = (nn.Conv, nn.ConvTranspose, nn.Dense, nn.BatchNorm)
    records: List[Tuple[tuple, str]] = []

    def interceptor(next_fun, args, kwargs, context):
        mod = context.module
        if isinstance(mod, types) and context.method_name == "__call__":
            records.append((mod.path, type(mod).__name__))
        return next_fun(*args, **kwargs)

    with nn.intercept_methods(interceptor):
        variables = model.init(*init_args, **init_kwargs)

    seen, ordered = set(), []
    for path, kind in records:  # shared modules record once, at first call
        if path not in seen:
            seen.add(path)
            ordered.append((path, kind))
    return ordered, variables


def _kind_and_counter(lname: str) -> Tuple[str, int]:
    """('conv2d_7' → ('Conv', 7)); counter 0 for the unsuffixed first layer."""
    for prefix, kind in _NAME_PREFIXES:
        if lname == prefix:
            return kind, 0
        if lname.startswith(prefix + "_"):
            tail = lname[len(prefix) + 1:]
            if tail.isdigit():
                return kind, int(tail)
    raise NotImplementedError(f"unrecognized auto-generated layer name "
                              f"{lname!r}")


def layers_from_keras_model(model) -> List[Tuple[str, Dict[str, np.ndarray]]]:
    """[(flax_kind, {attr: array})] from a built Keras model, in per-type
    CREATION order (the auto-name counters)."""
    rows = []
    for layer in model.layers:
        if not layer.weights:
            continue
        kind, counter = _kind_and_counter(layer.name)
        names = [w.name.split("/")[-1].split(":")[0] for w in layer.weights]
        rows.append((kind, counter, dict(zip(names, layer.get_weights()))))
    rows.sort(key=lambda r: (r[0], r[1]))
    return [(kind, weights) for kind, _, weights in rows]


def layers_from_legacy_h5(path: str) -> List[Tuple[str, Dict[str, np.ndarray]]]:
    """[(flax_kind, {attr: array})] from a TF2.1-era `save_weights` h5
    (per-layer groups named with the auto-name counters), in per-type
    creation order. File walking reuses `keras_convert.load_h5_weights`;
    on-disk order is irrelevant because the auto-name counters carry the
    order."""
    from .keras_convert import load_h5_weights

    rows = []
    for lname, weights in load_h5_weights(path).items():
        kind, counter = _kind_and_counter(lname)
        rows.append((kind, counter, weights))
    rows.sort(key=lambda r: (r[0], r[1]))
    return [(kind, weights) for kind, _, weights in rows]


_BN_PARAMS = {"gamma": "scale", "beta": "bias"}
_BN_STATS = {"moving_mean": "mean", "moving_variance": "var"}


def _set_in(tree: Dict, path: Sequence[str], leaf: str, value, what: str):
    node = tree
    for p in path:
        if p not in node:
            raise KeyError(f"{what}: no module at {'/'.join(path)}")
        node = node[p]
    if leaf not in node:
        raise KeyError(f"{what}: no weight {leaf!r} at {'/'.join(path)}")
    if tuple(node[leaf].shape) != tuple(value.shape):
        raise ValueError(
            f"{what} {'/'.join(path)}/{leaf}: checkpoint shape {value.shape} "
            f"!= model {tuple(node[leaf].shape)}")
    node[leaf] = value.astype(node[leaf].dtype)


def convert_by_call_order(model, keras_layers, *init_args, **init_kwargs):
    """Map ordered Keras weight layers onto `model`'s params/batch_stats.

    Fails loudly on any count, kind, or shape mismatch — a structural
    disagreement between the two models means the order pairing is wrong and
    nothing should be silently imported."""
    import jax

    ordered, variables = flax_modules_in_call_order(model, *init_args,
                                                    **init_kwargs)
    if len(ordered) != len(keras_layers):
        raise ValueError(
            f"layer count mismatch: flax model has {len(ordered)} weighted "
            f"modules, checkpoint has {len(keras_layers)}")
    # both sides sorted by (kind, per-kind order): flax call order within a
    # kind IS its creation order, matching the Keras auto-name counters
    by_kind: Dict[str, List] = {}
    for path, kind in ordered:
        by_kind.setdefault(kind, []).append(path)
    flax_seq = [(kind, path) for kind in sorted(by_kind)
                for path in by_kind[kind]]

    params = jax.tree_util.tree_map(np.asarray, variables["params"])
    params = _to_mutable(params)
    stats = _to_mutable(jax.tree_util.tree_map(
        np.asarray, variables.get("batch_stats", {})))

    for i, ((flax_kind, path), (kind, weights)) in enumerate(
            zip(flax_seq, keras_layers)):
        where = f"layer {i} ({'/'.join(path)})"
        if kind != flax_kind:
            raise ValueError(f"{where}: checkpoint layer is {kind}, "
                             f"model expects {flax_kind} — per-kind layer "
                             f"counts differ between checkpoint and model")
        if kind == "BatchNorm":
            for src, dst in _BN_PARAMS.items():
                _set_in(params, path, dst, weights[src], where)
            for src, dst in _BN_STATS.items():
                _set_in(stats, path, dst, weights[src], where)
            continue
        kernel = weights["kernel"]
        if kind == "ConvTranspose":  # (kh, kw, out, in) → flipped (.., in, out)
            kernel = np.ascontiguousarray(
                np.transpose(kernel, (0, 1, 3, 2))[::-1, ::-1])
        _set_in(params, path, "kernel", kernel, where)
        node = params
        for p in path:
            node = node[p]
        if ("bias" in node) != ("bias" in weights):
            # a silent keep of our random bias (or a dropped checkpoint bias)
            # would "import" a subtly wrong model
            raise ValueError(f"{where}: bias mismatch — model "
                             f"{'has' if 'bias' in node else 'lacks'} one, "
                             f"checkpoint "
                             f"{'has' if 'bias' in weights else 'lacks'} one")
        if "bias" in weights:
            _set_in(params, path, "bias", weights["bias"], where)
    return params, stats


def _to_mutable(tree):
    if isinstance(tree, dict):
        return {k: _to_mutable(v) for k, v in tree.items()}
    try:  # FrozenDict
        items = tree.items()
    except AttributeError:
        return tree
    return {k: _to_mutable(v) for k, v in items}
