"""Tiny name → object registries.

Replaces the reference's per-file ``training_config`` dicts keyed by model name
(`ResNet/pytorch/train.py:26-215`) with one shared registry so configs/models are
declared once and selected via the same ``-m <name>`` CLI surface.
"""

from __future__ import annotations

from typing import Callable, Dict, TypeVar

T = TypeVar("T")


class Registry:
    def __init__(self, kind: str):
        self.kind = kind
        self._entries: Dict[str, object] = {}

    def register(self, name: str, obj: object = None):
        if obj is not None:
            self._add(name, obj)
            return obj

        def deco(o):
            self._add(name, o)
            return o

        return deco

    def _add(self, name: str, obj: object):
        if name in self._entries:
            raise KeyError(f"duplicate {self.kind} registration: {name!r}")
        self._entries[name] = obj

    def get(self, name: str):
        try:
            return self._entries[name]
        except KeyError:
            known = ", ".join(sorted(self._entries))
            raise KeyError(f"unknown {self.kind} {name!r}; known: {known}") from None

    def names(self):
        return sorted(self._entries)

    def items(self):
        """Sorted (name, object) pairs — the enumeration surface for tools
        that list the registry (e.g. `python -m deepvision_tpu.serve
        --list-models`)."""
        return sorted(self._entries.items())

    def __contains__(self, name: str) -> bool:
        return name in self._entries


MODELS = Registry("model")
CONFIGS = Registry("training config")
