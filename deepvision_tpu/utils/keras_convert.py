"""Import the reference's Keras `save_weights` h5 checkpoints into Flax trees.

The reference's TF2 trainers save best-on-val-loss weights as h5
(`YOLO/tensorflow/train.py:244-257`), keyed by the builder's deterministic
layer names (`yolov3.py:23-235`: `conv2d_0_conv2d`, `residual_2_5_1x1_bn`,
`detector_scale_large_3x3_1_conv2d`, ...). Keras Conv2D kernels are already
HWIO, so only BN stat renaming (gamma/beta/moving_* → scale/bias/mean/var)
and tree placement are needed.
"""

from __future__ import annotations

from typing import Dict, Sequence, Tuple

import numpy as np


def load_h5_weights(path: str) -> Dict[str, Dict[str, np.ndarray]]:
    """Flatten a Keras save_weights h5 into {layer_name: {weight: array}}.

    Handles nested submodels (the reference wraps Darknet as an inner
    `darknet_53` model, `yolov3.py:92`) by walking groups down to datasets and
    keying on the dataset's parent group name.
    """
    import h5py

    out: Dict[str, Dict[str, np.ndarray]] = {}

    def visit(name, obj):
        if isinstance(obj, h5py.Dataset):
            parts = name.split("/")
            layer = parts[-2] if len(parts) >= 2 else parts[0]
            weight = parts[-1].split(":")[0]
            out.setdefault(layer, {})[weight] = np.asarray(obj)

    with h5py.File(path, "r") as f:
        f.visititems(visit)
    return out


def _cbl(weights: Dict, name: str) -> Tuple[Dict, Dict]:
    """One DarknetConv (`<name>_conv2d` + `<name>_bn`) → our ConvBNLeaky tree
    ({Conv_0, BatchNorm_0} params + BN stats)."""
    conv = weights[f"{name}_conv2d"]
    bn = weights[f"{name}_bn"]
    p = {"Conv_0": {"kernel": conv["kernel"]},
         "BatchNorm_0": {"scale": bn["gamma"], "bias": bn["beta"]}}
    s = {"BatchNorm_0": {"mean": bn["moving_mean"],
                         "var": bn["moving_variance"]}}
    return p, s


def convert_yolov3(weights: Dict[str, Dict[str, np.ndarray]],
                   stage_blocks: Sequence[int] = (1, 2, 8, 8, 4)
                   ) -> Tuple[Dict, Dict]:
    """Reference YoloV3 h5 weights → (params, batch_stats) for
    `models/yolo.py:YoloV3` (darknet53/tower_*/lateral_* naming)."""
    params: Dict = {}
    stats: Dict = {}

    # -- backbone: conv2d_0 stem, conv2d_{i+1} downsamples, residual_{i}_{j}
    dk_p: Dict = {}
    dk_s: Dict = {}
    dk_p["ConvBNLeaky_0"], dk_s["ConvBNLeaky_0"] = _cbl(weights, "conv2d_0")
    r = 0
    for stage, blocks in enumerate(stage_blocks):
        key = f"ConvBNLeaky_{stage + 1}"
        dk_p[key], dk_s[key] = _cbl(weights, f"conv2d_{stage + 1}")
        for j in range(blocks):
            blk_p: Dict = {}
            blk_s: Dict = {}
            for k, tap in enumerate(("1x1", "3x3")):
                sub = f"ConvBNLeaky_{k}"
                blk_p[sub], blk_s[sub] = _cbl(
                    weights, f"residual_{stage}_{j}_{tap}")
            dk_p[f"DarknetResidual_{r}"] = blk_p
            dk_s[f"DarknetResidual_{r}"] = blk_s
            r += 1
    params["darknet53"] = dk_p
    stats["darknet53"] = dk_s

    # -- detection towers + lateral transitions
    for scale in ("large", "medium", "small"):
        t_p: Dict = {}
        t_s: Dict = {}
        names = [f"detector_scale_{scale}_{tap}"
                 for tap in ("1x1_1", "3x3_1", "1x1_2", "3x3_2", "1x1_3",
                             "3x3_3")]
        for k, name in enumerate(names):
            sub = f"ConvBNLeaky_{k}"
            t_p[sub], t_s[sub] = _cbl(weights, name)
        final = weights[f"detector_scale_{scale}_final_conv2d"]
        t_p["final_conv"] = {"kernel": final["kernel"], "bias": final["bias"]}
        params[f"tower_{scale}"] = t_p
        stats[f"tower_{scale}"] = t_s
    for scale in ("medium", "small"):
        p, s = _cbl(weights, f"detector_scale_{scale}_1x1_0")
        params[f"lateral_{scale}"] = p
        stats[f"lateral_{scale}"] = s
    return params, stats


def convert(model_name: str, weights: Dict) -> Tuple[Dict, Dict]:
    if model_name in ("yolov3", "yolov3_voc"):
        return convert_yolov3(weights)
    raise KeyError(f"no keras-weights converter for {model_name!r} "
                   f"(available: ['yolov3', 'yolov3_voc'])")
