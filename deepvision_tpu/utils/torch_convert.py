"""Import the reference's PyTorch checkpoints into Flax parameter trees.

The reference publishes trained `.pt` checkpoints per model README
(`ResNet/pytorch/README.md:71`: `{epoch, model, optimizer, scheduler,
loggers}` dicts saved by `ResNet/pytorch/train.py:417-428`). This module maps
the `model` state_dict onto our Flax trees so users can switch frameworks
without retraining:

- conv weights OIHW → HWIO;
- linear weights (out, in) → (in, out);
- BatchNorm weight/bias/running_mean/running_var → scale/bias + mean/var
  batch_stats;
- `module.`-prefixed keys (their `nn.DataParallel` wrap,
  `ResNet/pytorch/train.py:352-355`) are stripped.

Architectural caveat, handled: the reference strides bottlenecks on conv1
(`resnet50.py:101-106`), ours default to the 3x3 — build the model with
`model_kwargs={"stride_on_first": True}` (what `tools/import_torch_checkpoint.py`
does) so imported weights compute the same function.
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

RESNET_TORCH_STAGES = ("conv2x", "conv3x", "conv4x", "conv5x")
RESNET_STAGE_SIZES = {
    "resnet50": (3, 4, 6, 3),
    "resnet101": (3, 4, 23, 3),
    "resnet152": (3, 8, 36, 3),
}


def _np(t) -> np.ndarray:
    if hasattr(t, "detach"):
        t = t.detach().cpu().numpy()
    return np.asarray(t)


def _conv_w(sd, key):
    """torch OIHW → flax HWIO."""
    return _np(sd[key]).transpose(2, 3, 1, 0)


def strip_data_parallel(sd: Dict) -> Dict:
    return {(k[7:] if k.startswith("module.") else k): v for k, v in sd.items()}


def _bn(sd, prefix) -> Tuple[Dict, Dict]:
    p = {"BatchNorm_0": {"scale": _np(sd[prefix + ".weight"]),
                         "bias": _np(sd[prefix + ".bias"])}}
    s = {"BatchNorm_0": {"mean": _np(sd[prefix + ".running_mean"]),
                         "var": _np(sd[prefix + ".running_var"])}}
    return p, s


class _RecordingDict(dict):
    """Records key reads so leftover-weight detection can catch a checkpoint
    whose depth doesn't match the requested model (e.g. a resnet152 .pt fed to
    -m resnet101 — every indexed key exists, widths match, output is garbage)."""

    def __init__(self, base):
        super().__init__(base)
        self.used = set()

    def __getitem__(self, k):
        self.used.add(k)
        return super().__getitem__(k)


def convert_resnet_bottleneck(state_dict: Dict, stage_sizes) -> Tuple[Dict, Dict]:
    """Reference bottleneck-ResNet state_dict → (params, batch_stats) matching
    `models/resnet.py` naming (stem_conv/_BN_0/BottleneckBlock_i/head)."""
    sd = _RecordingDict(strip_data_parallel(state_dict))
    params: Dict = {"stem_conv": {"kernel": _conv_w(sd, "conv1.weight")}}
    stats: Dict = {}
    params["_BN_0"], stats["_BN_0"] = _bn(sd, "bn1")
    params["head"] = {"kernel": _np(sd["linear.weight"]).T,
                      "bias": _np(sd["linear.bias"])}

    b = 0
    for stage, n in zip(RESNET_TORCH_STAGES, stage_sizes):
        for i in range(n):
            t = f"{stage}.{i}"
            blk_p: Dict = {}
            blk_s: Dict = {}
            for j in range(3):
                blk_p[f"Conv_{j}"] = {"kernel": _conv_w(sd, f"{t}.conv{j + 1}.weight")}
                blk_p[f"_BN_{j}"], blk_s[f"_BN_{j}"] = _bn(sd, f"{t}.bn{j + 1}")
            if f"{t}.projection.0.weight" in sd:
                blk_p["proj"] = {"kernel": _conv_w(sd, f"{t}.projection.0.weight")}
                blk_p["_BN_3"], blk_s["_BN_3"] = _bn(sd, f"{t}.projection.1")
            params[f"BottleneckBlock_{b}"] = blk_p
            stats[f"BottleneckBlock_{b}"] = blk_s
            b += 1

    leftover = {k for k in sd if k not in sd.used
                and not k.endswith("num_batches_tracked")}
    if leftover:
        raise ValueError(
            f"{len(leftover)} unconsumed weights (e.g. {sorted(leftover)[:3]}) "
            f"— checkpoint depth doesn't match stage_sizes={tuple(stage_sizes)}; "
            f"wrong -m model for this .pt?")
    return params, stats


def convert(model_name: str, state_dict: Dict) -> Tuple[Dict, Dict]:
    """Dispatch by registry model name. Raises KeyError for models without a
    converter yet (extend RESNET_STAGE_SIZES / add a mapper)."""
    if model_name in RESNET_STAGE_SIZES:
        return convert_resnet_bottleneck(state_dict,
                                         RESNET_STAGE_SIZES[model_name])
    raise KeyError(
        f"no torch-checkpoint converter for {model_name!r} "
        f"(available: {sorted(RESNET_STAGE_SIZES)})")
