"""Import the reference's PyTorch checkpoints into Flax parameter trees.

The reference publishes trained `.pt` checkpoints per model README
(`ResNet/pytorch/README.md:71`: `{epoch, model, optimizer, scheduler,
loggers}` dicts saved by `ResNet/pytorch/train.py:417-428`). This module maps
the `model` state_dict onto our Flax trees so users can switch frameworks
without retraining:

- conv weights OIHW → HWIO;
- linear weights (out, in) → (in, out);
- BatchNorm weight/bias/running_mean/running_var → scale/bias + mean/var
  batch_stats;
- `module.`-prefixed keys (their `nn.DataParallel` wrap,
  `ResNet/pytorch/train.py:352-355`) are stripped.

Architectural caveat, handled: the reference strides bottlenecks on conv1
(`resnet50.py:101-106`), ours default to the 3x3 — build the model with
`model_kwargs={"stride_on_first": True}` (what `tools/import_torch_checkpoint.py`
does) so imported weights compute the same function.
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

RESNET_TORCH_STAGES = ("conv2x", "conv3x", "conv4x", "conv5x")
RESNET_STAGE_SIZES = {
    "resnet50": (3, 4, 6, 3),
    "resnet101": (3, 4, 23, 3),
    "resnet152": (3, 8, 36, 3),
}


def _np(t) -> np.ndarray:
    if hasattr(t, "detach"):
        t = t.detach().cpu().numpy()
    return np.asarray(t)


def _conv_w(sd, key):
    """torch OIHW → flax HWIO."""
    return _np(sd[key]).transpose(2, 3, 1, 0)


def strip_data_parallel(sd: Dict) -> Dict:
    return {(k[7:] if k.startswith("module.") else k): v for k, v in sd.items()}


def _bn(sd, prefix) -> Tuple[Dict, Dict]:
    p = {"BatchNorm_0": {"scale": _np(sd[prefix + ".weight"]),
                         "bias": _np(sd[prefix + ".bias"])}}
    s = {"BatchNorm_0": {"mean": _np(sd[prefix + ".running_mean"]),
                         "var": _np(sd[prefix + ".running_var"])}}
    return p, s


class _RecordingDict(dict):
    """Records key reads so leftover-weight detection can catch a checkpoint
    whose depth doesn't match the requested model (e.g. a resnet152 .pt fed to
    -m resnet101 — every indexed key exists, widths match, output is garbage)."""

    def __init__(self, base):
        super().__init__(base)
        self.used = set()

    def __getitem__(self, k):
        self.used.add(k)
        return super().__getitem__(k)


def _convert_resnet(state_dict: Dict, stage_sizes, convs_per_block: int,
                    block_name: str) -> Tuple[Dict, Dict]:
    """Shared reference-ResNet mapper: stem_conv/_BN_0, per-block
    Conv_j/_BN_j (+ proj/_BN_<convs_per_block>), head."""
    sd = _RecordingDict(strip_data_parallel(state_dict))
    params: Dict = {"stem_conv": {"kernel": _conv_w(sd, "conv1.weight")}}
    stats: Dict = {}
    params["_BN_0"], stats["_BN_0"] = _bn(sd, "bn1")
    params["head"] = {"kernel": _np(sd["linear.weight"]).T,
                      "bias": _np(sd["linear.bias"])}

    b = 0
    for stage, n in zip(RESNET_TORCH_STAGES, stage_sizes):
        for i in range(n):
            t = f"{stage}.{i}"
            blk_p: Dict = {}
            blk_s: Dict = {}
            for j in range(convs_per_block):
                blk_p[f"Conv_{j}"] = {"kernel": _conv_w(sd, f"{t}.conv{j + 1}.weight")}
                blk_p[f"_BN_{j}"], blk_s[f"_BN_{j}"] = _bn(sd, f"{t}.bn{j + 1}")
            if f"{t}.projection.0.weight" in sd:
                blk_p["proj"] = {"kernel": _conv_w(sd, f"{t}.projection.0.weight")}
                blk_p[f"_BN_{convs_per_block}"], blk_s[f"_BN_{convs_per_block}"] = \
                    _bn(sd, f"{t}.projection.1")
            params[f"{block_name}_{b}"] = blk_p
            stats[f"{block_name}_{b}"] = blk_s
            b += 1

    leftover = {k for k in sd if k not in sd.used
                and not k.endswith("num_batches_tracked")}
    if leftover:
        raise ValueError(
            f"{len(leftover)} unconsumed weights (e.g. {sorted(leftover)[:3]}) "
            f"— checkpoint depth doesn't match stage_sizes={tuple(stage_sizes)}; "
            f"wrong -m model for this .pt?")
    return params, stats


def convert_resnet_bottleneck(state_dict: Dict, stage_sizes) -> Tuple[Dict, Dict]:
    """Reference bottleneck-ResNet state_dict → (params, batch_stats) matching
    `models/resnet.py` naming (stem_conv/_BN_0/BottleneckBlock_i/head)."""
    return _convert_resnet(state_dict, stage_sizes, 3, "BottleneckBlock")


def _linear_w(sd, key, flatten_hwc: Tuple[int, int, int] = None):
    """torch (out, in) → flax (in, out); `flatten_hwc=(H, W, C)` additionally
    permutes a first-FC weight from torch's CHW flatten order to our NHWC
    flatten order (`x.reshape(n, -1)` of an NHWC tensor)."""
    w = _np(sd[key])
    if flatten_hwc is not None:
        h, wd, c = flatten_hwc
        w = w.reshape(w.shape[0], c, h, wd).transpose(2, 3, 1, 0)
        return w.reshape(h * wd * c, -1)
    return w.T


def convert_sequential_cnn(state_dict: Dict, first_fc_hwc: Tuple[int, int, int]
                           ) -> Tuple[Dict, Dict]:
    """Reference VGG / AlexNet state_dicts → Flax trees.

    Both families are `features` (convs at Sequential indices among
    ReLU/LRN/MaxPool) + `classifier` (Linears at indices among Dropout/ReLU)
    (`VGG/pytorch/models/vgg16.py:25-110`, `AlexNet/pytorch/models/
    alexnet_v2.py:30-64`). Convs map in index order to Conv_0.. and Linears
    to Dense_0..; the first Linear's weight is permuted from the torch CHW
    flatten to our NHWC flatten (`first_fc_hwc` = conv output (H, W, C))."""
    sd = _RecordingDict(strip_data_parallel(state_dict))
    conv_idx = sorted(int(k.split(".")[1]) for k in sd
                      if k.startswith("features.") and k.endswith(".weight"))
    fc_idx = sorted(int(k.split(".")[1]) for k in sd
                    if k.startswith("classifier.") and k.endswith(".weight"))
    params: Dict = {}
    for j, i in enumerate(conv_idx):
        params[f"Conv_{j}"] = {"kernel": _conv_w(sd, f"features.{i}.weight"),
                               "bias": _np(sd[f"features.{i}.bias"])}
    for j, i in enumerate(fc_idx):
        params[f"Dense_{j}"] = {
            "kernel": _linear_w(sd, f"classifier.{i}.weight",
                                first_fc_hwc if j == 0 else None),
            "bias": _np(sd[f"classifier.{i}.bias"])}
    leftover = {k for k in sd if k not in sd.used}
    if leftover:
        raise ValueError(f"unconsumed weights: {sorted(leftover)[:5]}")
    return params, {}


def convert_lenet5(state_dict: Dict) -> Tuple[Dict, Dict]:
    """Reference LeNet-5 state_dict → Flax trees (`LeNet/pytorch/models/
    lenet5.py:24-60`: convs at features indices 0/4/8 among Tanh/AvgPool,
    Linears at classifier 0/2). C5's 1x1 spatial output makes the flatten
    permutation trivial."""
    sd = _RecordingDict(strip_data_parallel(state_dict))
    conv_names = ("c1", "c3", "c5")
    conv_idx = sorted(int(k.split(".")[1]) for k in sd
                      if k.startswith("features.") and k.endswith(".weight"))
    params: Dict = {}
    for name, i in zip(conv_names, conv_idx):
        params[name] = {"kernel": _conv_w(sd, f"features.{i}.weight"),
                        "bias": _np(sd[f"features.{i}.bias"])}
    for name, i in zip(("f6", "output"), (0, 2)):
        params[name] = {"kernel": _linear_w(sd, f"classifier.{i}.weight"),
                        "bias": _np(sd[f"classifier.{i}.bias"])}
    leftover = {k for k in sd if k not in sd.used}
    if leftover:
        raise ValueError(f"unconsumed weights: {sorted(leftover)[:5]}")
    return params, {}


def convert_mobilenet_v1(state_dict: Dict) -> Tuple[Dict, Dict]:
    """Reference MobileNetV1 state_dict → Flax trees: Sequential index 0/1 are
    the stem conv+BN, indices 3..15 the 13 DepthwiseSeparableConv blocks with
    dw.conv/dw.bn/pw.conv/pw.bn children, plus the `linear` head
    (`MobileNet/pytorch/models/mobilenet_v1.py:27-91`)."""
    sd = _RecordingDict(strip_data_parallel(state_dict))
    params: Dict = {"stem": {"kernel": _conv_w(sd, "features.0.weight")}}
    stats: Dict = {}
    stem_bn_p, stem_bn_s = _bn(sd, "features.1")
    params["BatchNorm_0"] = stem_bn_p["BatchNorm_0"]
    stats["BatchNorm_0"] = stem_bn_s["BatchNorm_0"]
    for i in range(13):
        t = f"features.{3 + i}"
        blk_p: Dict = {"dw": {"kernel": _conv_w(sd, f"{t}.dw.conv.weight")},
                       "pw": {"kernel": _conv_w(sd, f"{t}.pw.conv.weight")}}
        blk_s: Dict = {}
        for j, sub in enumerate(("dw", "pw")):
            p, s = _bn(sd, f"{t}.{sub}.bn")
            blk_p[f"BatchNorm_{j}"] = p["BatchNorm_0"]
            blk_s[f"BatchNorm_{j}"] = s["BatchNorm_0"]
        params[f"block{i}"] = blk_p
        stats[f"block{i}"] = blk_s
    params["head"] = {"kernel": _np(sd["linear.weight"]).T,
                      "bias": _np(sd["linear.bias"])}
    leftover = {k for k in sd if k not in sd.used
                and not k.endswith("num_batches_tracked")}
    if leftover:
        raise ValueError(f"unconsumed weights: {sorted(leftover)[:5]}")
    return params, stats


def infer_basic_stage_sizes(state_dict: Dict) -> Tuple[int, ...]:
    """Blocks per stage, counted from the checkpoint keys. The reference's
    `resnet34.py` actually builds 2 blocks per stage (a latent quirk — the
    file cites Table 1's 34-layer column but passes num_blocks=2,
    `resnet34.py:38-41`), so depth must follow the weights, not the name."""
    sd = strip_data_parallel(state_dict)
    sizes = []
    for stage in RESNET_TORCH_STAGES:
        n = 0
        while f"{stage}.{n}.conv1.weight" in sd:
            n += 1
        sizes.append(n)
    return tuple(sizes)


def convert_resnet_basic(state_dict: Dict) -> Tuple[Dict, Dict]:
    """Reference basic-block ResNet state_dict → Flax trees matching
    `models/resnet.py` BasicBlock naming. Build the model with
    `stage_sizes=infer_basic_stage_sizes(sd)` and `project_first_blocks=True`
    (the reference projects block 0 of every stage, `resnet34.py:116-128`)."""
    return _convert_resnet(state_dict, infer_basic_stage_sizes(state_dict),
                           2, "BasicBlock")


_INCEPTION_STEM = {"conv7x7": "stem1", "conv1x1": "stem2a", "conv3x3": "stem2b"}
_INCEPTION_BRANCHES = ("branch1_conv1x1", "branch2_conv1x1", "branch2_conv3x3",
                       "branch3_conv1x1", "branch3_conv5x5", "branch4_conv1x1")
_INCEPTION_MODULES = ("3a", "3b", "4a", "4b", "4c", "4d", "4e", "5a", "5b")


def convert_inception_v1(state_dict: Dict) -> Tuple[Dict, Dict]:
    """Reference GoogLeNet state_dict → Flax trees for
    `InceptionV1(use_bn=False)` (the reference's BN-free BasicConv2d stack,
    `Inception/pytorch/models/inception_v1.py:27-75,133-142,164-190`).

    conv7x7/conv1x1/conv3x3 → stem1/stem2a/stem2b; inception_Xy branches map
    in declaration order onto ConvBN_0..5; aux heads keep their avg-pool conv
    + two Linears (first permuted from CHW flatten); `linear` → head."""
    sd = _RecordingDict(strip_data_parallel(state_dict))

    def basic_conv(prefix):
        return {"Conv_0": {"kernel": _conv_w(sd, f"{prefix}.conv.weight"),
                           "bias": _np(sd[f"{prefix}.conv.bias"])}}

    params: Dict = {}
    for torch_name, ours in _INCEPTION_STEM.items():
        params[ours] = basic_conv(torch_name)
    for m in _INCEPTION_MODULES:
        params[f"mod{m}"] = {
            f"ConvBN_{j}": basic_conv(f"inception_{m}.{branch}")
            for j, branch in enumerate(_INCEPTION_BRANCHES)}
    for aux in ("aux1", "aux2"):
        if f"{aux}.features.1.conv.weight" not in sd:
            continue
        c = _np(sd[f"{aux}.features.1.conv.weight"]).shape[0]
        fc_in = _np(sd[f"{aux}.classifier.0.weight"]).shape[1]
        hw = int(round((fc_in // c) ** 0.5))
        params[aux] = {
            "ConvBN_0": basic_conv(f"{aux}.features.1"),
            "Dense_0": {"kernel": _linear_w(sd, f"{aux}.classifier.0.weight",
                                            (hw, hw, c)),
                        "bias": _np(sd[f"{aux}.classifier.0.bias"])},
            "Dense_1": {"kernel": _linear_w(sd, f"{aux}.classifier.3.weight"),
                        "bias": _np(sd[f"{aux}.classifier.3.bias"])},
        }
    params["head"] = {"kernel": _np(sd["linear.weight"]).T,
                      "bias": _np(sd["linear.bias"])}
    leftover = {k for k in sd if k not in sd.used}
    if leftover:
        raise ValueError(f"unconsumed weights: {sorted(leftover)[:5]}")
    return params, {}


# final conv-output geometry (H, W, C) feeding the first FC at 224px input
SEQUENTIAL_CNN_FC_HWC = {
    "vgg16": (7, 7, 512),
    "vgg19": (7, 7, 512),
    "alexnet1": (6, 6, 256),
    "alexnet2": (6, 6, 256),
}


def convert(model_name: str, state_dict: Dict) -> Tuple[Dict, Dict]:
    """Dispatch by registry model name. Raises KeyError for models without a
    converter yet (extend RESNET_STAGE_SIZES / add a mapper)."""
    if model_name in RESNET_STAGE_SIZES:
        return convert_resnet_bottleneck(state_dict,
                                         RESNET_STAGE_SIZES[model_name])
    if model_name == "resnet34":
        return convert_resnet_basic(state_dict)
    if model_name in SEQUENTIAL_CNN_FC_HWC:
        return convert_sequential_cnn(state_dict,
                                      SEQUENTIAL_CNN_FC_HWC[model_name])
    if model_name == "mobilenet_v1":
        return convert_mobilenet_v1(state_dict)
    if model_name == "lenet5":
        return convert_lenet5(state_dict)
    if model_name in ("inception_v1", "googlenet"):
        return convert_inception_v1(state_dict)
    available = sorted(set(RESNET_STAGE_SIZES) | set(SEQUENTIAL_CNN_FC_HWC)
                       | {"resnet34", "mobilenet_v1", "inception_v1", "lenet5"})
    raise KeyError(
        f"no torch-checkpoint converter for {model_name!r} "
        f"(available: {available})")
