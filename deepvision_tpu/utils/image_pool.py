"""Host-side fake-image history buffer for CycleGAN discriminator updates.

Parity target: `CycleGAN/tensorflow/utils.py:31-61` — a 50-image pool; while
filling, images pass through; once full, each incoming image is 50% swapped with a
random stored one (Shrivastava et al. 2017). The reference notes it "only works in
TF eager mode" — this is inherently stateful host code, which is exactly why the
TPU-native CycleGAN step is split into jitted generator step → host pool query →
jitted discriminator step, mirroring the reference's eager outer step
(`CycleGAN/tensorflow/train.py:248-255`).
"""

from __future__ import annotations

import numpy as np


class ImagePool:
    def __init__(self, pool_size: int = 50, seed: int = 0):
        self.pool_size = pool_size
        self.pool: list = []
        self.rng = np.random.RandomState(seed)

    def query(self, images: np.ndarray) -> np.ndarray:
        """images: (B, H, W, C) host array → same-shape array mixing history."""
        if self.pool_size == 0:
            return images
        out = []
        for image in np.asarray(images):
            if len(self.pool) < self.pool_size:
                self.pool.append(image)
                out.append(image)
            elif self.rng.uniform() > 0.5:
                idx = self.rng.randint(0, self.pool_size)
                out.append(self.pool[idx])
                self.pool[idx] = image
            else:
                out.append(image)
        return np.stack(out, axis=0)
