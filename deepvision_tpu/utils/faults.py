"""Deterministic fault injection for the resilience subsystem.

Every recovery path in core/resilience.py (and the checkpoint-integrity
layer in core/integrity.py) must be testable on CPU without a flaky TPU pod
to provide the faults, so the injector fakes the failure classes the north
star's production runs actually see (ROADMAP.md; TPU-pod preemptions and
flaky storage are routine at scale):

- transient I/O errors from the host data pipeline,
- a loss blow-up (NaN) at a known step,
- checkpoint writes that fail transiently,
- checkpoint writes that fail in the ASYNC background path (after the
  synchronous enqueue already succeeded),
- a checkpoint that COMMITS and then rots on disk (truncated file, flipped
  bit, or a manifest lost to a kill between the data commit and the
  manifest commit).

Configuration is environment-driven so subprocess tests (CLI entrypoints)
and in-process tests configure it the same way:

    DEEPVISION_FAULT_DATA_IO_STEP=k[:count]  raise OSError before yielding
                                             batch k (0-based, counted across
                                             the whole process), `count` times
                                             (default 1) — transient: retries
                                             eventually succeed
    DEEPVISION_FAULT_NAN_STEP=k              overwrite batch k's images with
                                             NaN, so the step's loss goes
                                             non-finite through the real
                                             jitted program (one-shot)
    DEEPVISION_FAULT_CKPT_SAVE_FAILS=M       raise OSError from the first M
                                             checkpoint save() calls
    DEEPVISION_FAULT_CKPT_ASYNC_FAILS=M      raise OSError inside the first M
                                             background finalizations — the
                                             failure class the synchronous
                                             enqueue retry can never see
    DEEPVISION_FAULT_CKPT_CORRUPT=k:mode     after epoch k's save commits
                                             (manifest written), corrupt it on
                                             disk (one-shot). mode: `truncate`
                                             (halve the largest payload file),
                                             `bitflip` (flip one bit in its
                                             middle), `delete_manifest` (what
                                             a kill between data commit and
                                             manifest commit leaves behind),
                                             `tamper_sharding` (edit the
                                             manifest's mesh-topology/sharding
                                             section without refreshing its
                                             self-digest — the metadata an
                                             ELASTIC restore reshards against;
                                             verification must refuse it)
    DEEPVISION_FAULT_SERVE_DISPATCH_FAIL=k[:n]
                                             fail n consecutive serving
                                             dispatches starting at dispatch
                                             k (0-based, counted per
                                             DynamicBatcher across all its
                                             workers; n defaults to 1): the
                                             engine call raises before it
                                             runs, the whole batch's futures
                                             get the error, and the per-model
                                             circuit breaker sees exactly n
                                             consecutive failures — the
                                             deterministic drive for the
                                             breaker's open -> half-open ->
                                             close cycle (tests and the
                                             preflight `autoscale` check),
                                             no flaky dispatch path needed
    DEEPVISION_FAULT_QUANT_REGRESS=1         make the int8 quantization
                                             gate (serve/quantize.py) see a
                                             REGRESSED int8 score: the
                                             shadow comparison's int8 side
                                             is deterministically reduced,
                                             so the gate must refuse int8
                                             and fall back to bf16 serving
                                             (a resilience_quant_refused
                                             event + /healthz decision —
                                             preflight's `quant` check arms
                                             this). Fires on every gate
                                             evaluation while set
    DEEPVISION_FAULT_REPLICA_CRASH=k         the serving replica process
                                             HARD-EXITS (os._exit, no drain,
                                             no atexit) on the predict
                                             request after it has answered k
                                             — the "replica died mid-request"
                                             failure the tier router
                                             (serve/tier.py) must eject on
                                             the spot, retry elsewhere, and
                                             supervise back up
    DEEPVISION_FAULT_REPLICA_WEDGE=k         after k answered predict
                                             requests the replica STOPS
                                             ANSWERING but keeps its socket:
                                             every later request (health
                                             probes included) blocks forever
                                             — the failure mode only a
                                             deadline-bounded probe can
                                             distinguish from "slow", driving
                                             the router's breaker ejection
                                             path rather than the
                                             connection-refused one
    DEEPVISION_FAULT_PROMOTE_REGRESS=k:kind  make candidate epoch k a
                                             REGRESSION when the promotion
                                             controller (serve/promote.py)
                                             evaluates it. kind: `accuracy`
                                             (the candidate's shadow-eval
                                             score is deterministically
                                             reduced — the gate must refuse),
                                             `latency` (the candidate
                                             generation's canary dispatches
                                             pay an injected delay — the
                                             canary p99 comparison must roll
                                             back). Fires for EVERY evaluation
                                             of epoch k (the refusal cache,
                                             not the injector, is what stops
                                             re-evaluation)
    DEEPVISION_FAULT_DRIFT_SHIFT=w:mag       make the flywheel drift monitor
                                             (flywheel/drift.py) see a moved
                                             input distribution: every
                                             serving input SAMPLED at the
                                             batcher observer tap from
                                             reservoir window w onward
                                             (0-based) is shifted by the
                                             constant `mag` before it enters
                                             the live statistics — the
                                             per-channel moment comparison
                                             against the pinned calibration
                                             shard must cross its gate and,
                                             after the hysteresis windows,
                                             trigger a retrain. Deliberately
                                             NOT one-shot and not a single
                                             window: real drift persists,
                                             and the K-consecutive-window
                                             hysteresis only trips on a
                                             shift that stays — a rehearsal
                                             of a transient spike arms a
                                             LATER window than it feeds

An unset environment yields an inert injector (`active` False) whose hooks
are cheap no-ops — production runs pay two integer compares per batch.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Optional, Tuple

import numpy as np

CORRUPT_MODES = ("truncate", "bitflip", "delete_manifest", "tamper_sharding")
PROMOTE_REGRESS_KINDS = ("accuracy", "latency")


def _parse_step_count(raw: Optional[str]) -> Tuple[Optional[int], int]:
    if not raw:
        return None, 0
    step, _, count = raw.partition(":")
    return int(step), int(count) if count else 1


def _parse_epoch_mode(raw: Optional[str]) -> Tuple[Optional[int], Optional[str]]:
    if not raw:
        return None, None
    epoch, _, mode = raw.partition(":")
    mode = mode or "bitflip"
    if mode not in CORRUPT_MODES:
        raise ValueError(f"DEEPVISION_FAULT_CKPT_CORRUPT mode must be one of "
                         f"{CORRUPT_MODES}, got {mode!r}")
    return int(epoch), mode


def _parse_promote_regress(raw: Optional[str]
                           ) -> Tuple[Optional[int], Optional[str]]:
    if not raw:
        return None, None
    epoch, _, kind = raw.partition(":")
    kind = kind or "accuracy"
    if kind not in PROMOTE_REGRESS_KINDS:
        raise ValueError(f"DEEPVISION_FAULT_PROMOTE_REGRESS kind must be one "
                         f"of {PROMOTE_REGRESS_KINDS}, got {kind!r}")
    return int(epoch), kind


def _parse_drift_shift(raw: Optional[str]) -> Tuple[Optional[int], float]:
    if not raw:
        return None, 0.0
    window, _, magnitude = raw.partition(":")
    try:
        w = int(window)
    except ValueError:
        raise ValueError(
            f"DEEPVISION_FAULT_DRIFT_SHIFT window must be an int "
            f"(got {window!r}); expected <window>:<magnitude>")
    if not magnitude:
        raise ValueError(
            "DEEPVISION_FAULT_DRIFT_SHIFT needs an explicit magnitude "
            "(<window>:<magnitude>) — a zero-magnitude shift would arm a "
            "fault that can never fire")
    try:
        m = float(magnitude)
    except ValueError:
        raise ValueError(
            f"DEEPVISION_FAULT_DRIFT_SHIFT magnitude must be a float "
            f"(got {magnitude!r}); expected <window>:<magnitude>")
    if m == 0.0:
        raise ValueError(
            "DEEPVISION_FAULT_DRIFT_SHIFT magnitude must be non-zero — a "
            "zero shift arms a fault that can never fire")
    return w, m


class FaultInjector:
    """Process-local fault state: counters advance as the hooks are called,
    so a fault fires at a deterministic batch/save index and then clears —
    the "transient" in transient error."""

    def __init__(self, data_io_step: Optional[int] = None,
                 data_io_count: int = 1,
                 nan_step: Optional[int] = None,
                 ckpt_save_fails: int = 0,
                 ckpt_async_fails: int = 0,
                 ckpt_corrupt_epoch: Optional[int] = None,
                 ckpt_corrupt_mode: Optional[str] = None,
                 promote_regress_epoch: Optional[int] = None,
                 promote_regress_kind: Optional[str] = None,
                 drift_shift_window: Optional[int] = None,
                 drift_shift_magnitude: float = 0.0,
                 quant_regress: bool = False,
                 serve_dispatch_fail_at: Optional[int] = None,
                 serve_dispatch_fail_count: int = 1,
                 replica_crash_after: Optional[int] = None,
                 replica_wedge_after: Optional[int] = None):
        self.data_io_step = data_io_step
        self.data_io_remaining = data_io_count if data_io_step is not None else 0
        self.nan_step = nan_step
        self.ckpt_save_fails = ckpt_save_fails
        self.ckpt_async_fails = ckpt_async_fails
        self.ckpt_corrupt_epoch = ckpt_corrupt_epoch
        self.ckpt_corrupt_mode = ckpt_corrupt_mode
        self.promote_regress_epoch = promote_regress_epoch
        self.promote_regress_kind = promote_regress_kind
        self.drift_shift_window = drift_shift_window
        self.drift_shift_magnitude = (float(drift_shift_magnitude)
                                      if drift_shift_window is not None
                                      else 0.0)
        self.quant_regress = bool(quant_regress)
        self.serve_dispatch_fail_at = serve_dispatch_fail_at
        self.serve_dispatch_fail_count = (serve_dispatch_fail_count
                                          if serve_dispatch_fail_at is not None
                                          else 0)
        self.replica_crash_after = replica_crash_after
        self.replica_wedge_after = replica_wedge_after
        self._batch_index = 0   # advances once per batch PULLED (post-fault)
        self._save_index = 0
        self._async_index = 0
        self._serve_dispatch_index = 0
        self._replica_requests = 0   # predict requests ANSWERED so far
        self._replica_wedged = False
        # serving dispatches run on N concurrent pool workers; the counter
        # must still be exact or the "n CONSECUTIVE failures" contract
        # flakes — the only multi-threaded hook, so the only locked one
        self._serve_lock = threading.Lock()

    @classmethod
    def from_env(cls, env=None) -> "FaultInjector":
        env = os.environ if env is None else env
        io_step, io_count = _parse_step_count(
            env.get("DEEPVISION_FAULT_DATA_IO_STEP"))
        nan_step, _ = _parse_step_count(env.get("DEEPVISION_FAULT_NAN_STEP"))
        corrupt_epoch, corrupt_mode = _parse_epoch_mode(
            env.get("DEEPVISION_FAULT_CKPT_CORRUPT"))
        regress_epoch, regress_kind = _parse_promote_regress(
            env.get("DEEPVISION_FAULT_PROMOTE_REGRESS"))
        drift_window, drift_magnitude = _parse_drift_shift(
            env.get("DEEPVISION_FAULT_DRIFT_SHIFT"))
        quant_regress = env.get("DEEPVISION_FAULT_QUANT_REGRESS",
                                "") not in ("", "0")
        dispatch_at, dispatch_count = _parse_step_count(
            env.get("DEEPVISION_FAULT_SERVE_DISPATCH_FAIL"))
        crash_raw = env.get("DEEPVISION_FAULT_REPLICA_CRASH")
        wedge_raw = env.get("DEEPVISION_FAULT_REPLICA_WEDGE")
        return cls(data_io_step=io_step, data_io_count=io_count,
                   nan_step=nan_step,
                   ckpt_save_fails=int(
                       env.get("DEEPVISION_FAULT_CKPT_SAVE_FAILS", "0")),
                   ckpt_async_fails=int(
                       env.get("DEEPVISION_FAULT_CKPT_ASYNC_FAILS", "0")),
                   ckpt_corrupt_epoch=corrupt_epoch,
                   ckpt_corrupt_mode=corrupt_mode,
                   promote_regress_epoch=regress_epoch,
                   promote_regress_kind=regress_kind,
                   drift_shift_window=drift_window,
                   drift_shift_magnitude=drift_magnitude,
                   quant_regress=quant_regress,
                   serve_dispatch_fail_at=dispatch_at,
                   serve_dispatch_fail_count=dispatch_count,
                   replica_crash_after=(int(crash_raw) if crash_raw
                                        else None),
                   replica_wedge_after=(int(wedge_raw) if wedge_raw
                                        else None))

    @property
    def active(self) -> bool:
        return (self.data_io_step is not None or self.nan_step is not None
                or self.ckpt_save_fails > 0 or self.ckpt_async_fails > 0
                or self.ckpt_corrupt_epoch is not None
                or self.promote_regress_epoch is not None
                or self.drift_shift_window is not None
                or self.quant_regress
                or self.serve_dispatch_fail_at is not None
                or self.replica_crash_after is not None
                or self.replica_wedge_after is not None)

    # -- hooks -------------------------------------------------------------
    def before_batch(self) -> None:
        """Called before pulling the next batch from the source iterator.
        Raises the configured transient OSError WITHOUT advancing the batch
        index, so a retry faces the remaining fault budget and then pulls
        the batch the source never lost."""
        if (self.data_io_remaining > 0
                and self._batch_index == self.data_io_step):
            self.data_io_remaining -= 1
            raise OSError(
                f"injected transient I/O error at batch {self._batch_index} "
                f"({self.data_io_remaining} more to come)")

    def poison_batch(self, batch):
        """Called with the pulled batch; advances the batch index. At the
        configured step the FIRST array (images, by every family's batch
        contract) is replaced with NaNs — the loss then blows up through the
        real jitted step, exactly like a genuine divergence would."""
        i = self._batch_index
        self._batch_index += 1
        if self.nan_step is None or i != self.nan_step:
            return batch
        self.nan_step = None  # one-shot: the retried epoch trains clean
        batch = tuple(batch)
        poisoned = np.full_like(np.asarray(batch[0], dtype=np.float32),
                                np.nan)
        return (poisoned,) + batch[1:]

    def before_checkpoint_save(self) -> None:
        """Called at the top of every checkpoint save; the first M calls
        raise a transient OSError."""
        i = self._save_index
        self._save_index += 1
        if i < self.ckpt_save_fails:
            raise OSError(
                f"injected transient checkpoint-write failure "
                f"({i + 1}/{self.ckpt_save_fails})")

    def during_async_save(self) -> None:
        """Called from the checkpoint finalizer thread (core/checkpoint.py)
        AFTER the synchronous enqueue succeeded; the first M calls raise —
        the background-writer failure the enqueue-side retry can never see,
        which must surface at the next save/flush barrier rather than at
        close()."""
        i = self._async_index
        self._async_index += 1
        if i < self.ckpt_async_fails:
            raise OSError(
                f"injected async checkpoint-write failure "
                f"({i + 1}/{self.ckpt_async_fails})")

    def before_serve_dispatch(self) -> None:
        """Called by DynamicBatcher._dispatch right before the engine call;
        dispatches [k, k+n) raise, so the batch's futures carry the error
        and the circuit breaker sees exactly n consecutive failures. The
        index counts every dispatch of the owning batcher (all pool
        workers), under a lock — concurrency must not smear the window."""
        if self.serve_dispatch_fail_at is None:
            return
        with self._serve_lock:
            i = self._serve_dispatch_index
            self._serve_dispatch_index += 1
        lo = self.serve_dispatch_fail_at
        if lo <= i < lo + self.serve_dispatch_fail_count:
            raise RuntimeError(
                f"injected serving dispatch failure "
                f"{i - lo + 1}/{self.serve_dispatch_fail_count} "
                f"(dispatch {i})")

    def on_replica_request(self, predict: bool = True) -> None:
        """Called by the HTTP front door (serve/server.py) at the top of
        every request. Predict requests advance the replica request
        counter; once it passes the armed threshold the process either
        HARD-EXITS (`REPLICA_CRASH` — os._exit, so no drain, no flush, the
        client mid-request sees a reset and later connects are refused:
        exactly what a SIGKILLed replica looks like to the tier router) or
        WEDGES (`REPLICA_WEDGE` — this and every later handler thread,
        health probes included, blocks forever while the listener keeps
        accepting: the replica holds its socket but stops answering, the
        failure only a deadline-bounded probe can eject). Non-predict
        requests never advance the counter — a router's health-poll cadence
        must not change WHEN the fault fires — but they do hang once the
        replica is wedged."""
        crash, wedge = self.replica_crash_after, self.replica_wedge_after
        if crash is None and wedge is None:
            return
        hang = False
        with self._serve_lock:
            if predict and not self._replica_wedged:
                n = self._replica_requests   # answered so far
                self._replica_requests += 1
                if crash is not None and n >= crash:
                    print(f"[faults] replica hard-exit after {crash} "
                          f"answered predict requests", file=sys.stderr,
                          flush=True)
                    os._exit(86)
                if wedge is not None and n >= wedge:
                    print(f"[faults] replica wedged after {wedge} answered "
                          f"predict requests — holding the socket, "
                          f"answering nothing", file=sys.stderr, flush=True)
                    self._replica_wedged = True
            hang = self._replica_wedged
        if hang:
            while True:      # hold the connection open, never answer
                time.sleep(3600)

    def quant_regression(self) -> bool:
        """Called by the int8 quantization gate (serve/quantize.py) when it
        compares the bf16 and int8 scores on the pinned shard: True while
        DEEPVISION_FAULT_QUANT_REGRESS is armed — the int8 score is
        deterministically degraded and the gate MUST refuse. Deliberately
        not one-shot: every evaluation under the armed env regresses, so a
        rehearsal can re-run the refusal at will."""
        return self.quant_regress

    def promote_regression(self, epoch: Optional[int]) -> Optional[str]:
        """Called by the promotion controller (serve/promote.py) when a
        candidate epoch enters evaluation: returns the injected regression
        kind (`accuracy` / `latency`) when `epoch` matches the armed one,
        else None. Deliberately NOT one-shot: the same bad epoch regresses
        on every evaluation — the controller's refusal cache, not the
        injector, is what must prevent re-evaluation (and a test can prove
        that by counting evaluations)."""
        if epoch is None or epoch != self.promote_regress_epoch:
            return None
        return self.promote_regress_kind

    def drift_shift(self, window_index: int) -> float:
        """Called by the flywheel drift monitor (flywheel/drift.py) as each
        sampled serving input enters the live reservoir: returns the
        constant to ADD to the sample when reservoir window `window_index`
        has reached the armed window, else 0.0. Deliberately NOT one-shot —
        real drift persists, and the monitor's K-consecutive-window
        hysteresis must see the shift on every window from `w` on to
        trigger (a single-window transient is exactly what hysteresis
        exists to reject, and a test proves that by arming a window the
        rehearsal never reaches again)."""
        if (self.drift_shift_window is None
                or window_index < self.drift_shift_window):
            return 0.0
        return self.drift_shift_magnitude

    def corrupt_checkpoint(self, epoch: int, step_dir: str,
                           manifest_name: str = "integrity_manifest.json"
                           ) -> None:
        """Called after epoch `epoch`'s save fully committed (data + manifest
        on disk): deterministically corrupt it so the verification/fallback
        path is exercised end-to-end against real on-disk damage. One-shot;
        file choice is deterministic (largest payload file, path as the
        tiebreak)."""
        if self.ckpt_corrupt_epoch is None or epoch != self.ckpt_corrupt_epoch:
            return
        mode = self.ckpt_corrupt_mode
        self.ckpt_corrupt_epoch = None
        if mode == "delete_manifest":
            target = os.path.join(step_dir, manifest_name)
            os.remove(target)
        elif mode == "tamper_sharding":
            # rewrite the manifest with its mesh-topology section edited but
            # the self-digest left stale — an elastic restore steered by this
            # section would re-slice wrong, so verification must catch it
            import json
            target = os.path.join(step_dir, manifest_name)
            with open(target) as fp:
                manifest = json.load(fp)
            section = manifest.setdefault(
                "sharding", {"mesh": None, "leaves": {}, "digest": ""})
            mesh = section.get("mesh") or {}
            axes = dict(mesh.get("axes") or {})
            axes["data"] = int(axes.get("data", 1)) * 2  # a plausible lie
            section["mesh"] = {**mesh, "axes": axes}
            with open(target, "w") as fp:
                json.dump(manifest, fp, sort_keys=True, indent=1)
        else:
            candidates = sorted(
                (os.path.join(root, f)
                 for root, _, files in os.walk(step_dir) for f in files
                 if f != manifest_name),
                key=lambda p: (os.path.getsize(p), p))
            target = candidates[-1]
            if mode == "truncate":
                with open(target, "r+b") as fp:
                    fp.truncate(max(1, os.path.getsize(target) // 2))
            else:  # bitflip
                with open(target, "r+b") as fp:
                    fp.seek(os.path.getsize(target) // 2)
                    byte = fp.read(1) or b"\x00"
                    fp.seek(-len(byte), 1)
                    fp.write(bytes([byte[0] ^ 0x80]))
        print(f"[faults] corrupted checkpoint epoch {epoch} ({mode}: "
              f"{os.path.relpath(target, step_dir)})",
              file=sys.stderr, flush=True)
