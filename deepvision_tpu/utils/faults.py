"""Deterministic fault injection for the resilience subsystem.

Every recovery path in core/resilience.py must be testable on CPU without a
flaky TPU pod to provide the faults, so the injector fakes the three failure
classes the north star's production runs actually see (ROADMAP.md; TPU-pod
preemptions and flaky storage are routine at scale):

- transient I/O errors from the host data pipeline,
- a loss blow-up (NaN) at a known step,
- checkpoint writes that fail transiently.

Configuration is environment-driven so subprocess tests (CLI entrypoints)
and in-process tests configure it the same way:

    DEEPVISION_FAULT_DATA_IO_STEP=k[:count]  raise OSError before yielding
                                             batch k (0-based, counted across
                                             the whole process), `count` times
                                             (default 1) — transient: retries
                                             eventually succeed
    DEEPVISION_FAULT_NAN_STEP=k              overwrite batch k's images with
                                             NaN, so the step's loss goes
                                             non-finite through the real
                                             jitted program (one-shot)
    DEEPVISION_FAULT_CKPT_SAVE_FAILS=M       raise OSError from the first M
                                             checkpoint save() calls

An unset environment yields an inert injector (`active` False) whose hooks
are cheap no-ops — production runs pay two integer compares per batch.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np


def _parse_step_count(raw: Optional[str]) -> Tuple[Optional[int], int]:
    if not raw:
        return None, 0
    step, _, count = raw.partition(":")
    return int(step), int(count) if count else 1


class FaultInjector:
    """Process-local fault state: counters advance as the hooks are called,
    so a fault fires at a deterministic batch/save index and then clears —
    the "transient" in transient error."""

    def __init__(self, data_io_step: Optional[int] = None,
                 data_io_count: int = 1,
                 nan_step: Optional[int] = None,
                 ckpt_save_fails: int = 0):
        self.data_io_step = data_io_step
        self.data_io_remaining = data_io_count if data_io_step is not None else 0
        self.nan_step = nan_step
        self.ckpt_save_fails = ckpt_save_fails
        self._batch_index = 0   # advances once per batch PULLED (post-fault)
        self._save_index = 0

    @classmethod
    def from_env(cls, env=None) -> "FaultInjector":
        env = os.environ if env is None else env
        io_step, io_count = _parse_step_count(
            env.get("DEEPVISION_FAULT_DATA_IO_STEP"))
        nan_step, _ = _parse_step_count(env.get("DEEPVISION_FAULT_NAN_STEP"))
        return cls(data_io_step=io_step, data_io_count=io_count,
                   nan_step=nan_step,
                   ckpt_save_fails=int(
                       env.get("DEEPVISION_FAULT_CKPT_SAVE_FAILS", "0")))

    @property
    def active(self) -> bool:
        return (self.data_io_step is not None or self.nan_step is not None
                or self.ckpt_save_fails > 0)

    # -- hooks -------------------------------------------------------------
    def before_batch(self) -> None:
        """Called before pulling the next batch from the source iterator.
        Raises the configured transient OSError WITHOUT advancing the batch
        index, so a retry faces the remaining fault budget and then pulls
        the batch the source never lost."""
        if (self.data_io_remaining > 0
                and self._batch_index == self.data_io_step):
            self.data_io_remaining -= 1
            raise OSError(
                f"injected transient I/O error at batch {self._batch_index} "
                f"({self.data_io_remaining} more to come)")

    def poison_batch(self, batch):
        """Called with the pulled batch; advances the batch index. At the
        configured step the FIRST array (images, by every family's batch
        contract) is replaced with NaNs — the loss then blows up through the
        real jitted step, exactly like a genuine divergence would."""
        i = self._batch_index
        self._batch_index += 1
        if self.nan_step is None or i != self.nan_step:
            return batch
        self.nan_step = None  # one-shot: the retried epoch trains clean
        batch = tuple(batch)
        poisoned = np.full_like(np.asarray(batch[0], dtype=np.float32),
                                np.nan)
        return (poisoned,) + batch[1:]

    def before_checkpoint_save(self) -> None:
        """Called at the top of every checkpoint save; the first M calls
        raise a transient OSError."""
        i = self._save_index
        self._save_index += 1
        if i < self.ckpt_save_fails:
            raise OSError(
                f"injected transient checkpoint-write failure "
                f"({i + 1}/{self.ckpt_save_fails})")
