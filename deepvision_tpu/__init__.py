"""deepvision_tpu — a TPU-native (JAX/Flax/XLA) computer-vision framework.

Re-creation of the capabilities of zackdilan/deep-vision (reference mounted at
/root/reference) designed TPU-first: Flax modules for the networks, optax for
optimization, jit/pjit SPMD steps over a `jax.sharding.Mesh` for scaling, tf.data
host pipelines for input, and Orbax for checkpointing.

Layout (mirrors SURVEY.md layer map):
  core/      — trainer loop, train state, steps, config, checkpoint, metrics, schedules
  parallel/  — mesh construction, sharding rules, collectives helpers
  data/      — dataset parsers + input pipelines (MNIST idx, ImageNet TFRecord, ...)
  models/    — Flax model zoo (LeNet..ResNet..YOLO..CycleGAN)
  ops/       — numerics shared across models (boxes/IoU/NMS/heatmaps, pallas kernels)
  utils/     — registry, logging helpers
"""

__version__ = "0.1.0"
