"""Background host→device batch staging.

`jax.device_put` of a large host batch can block the calling thread while the
buffer is staged (measured on this repo's relay-attached chip: ~1.5s for a
256×224×224×3 f32 batch; ~15ms over real PCIe). Done inline in the step loop,
that stall serializes with compute. A small producer thread device_puts ahead
with the mesh's batch sharding, so the transfer of batch i+1 overlaps the
device executing batch i — the JAX-side counterpart of tf.data's
`prefetch_to_device` (the reference relied on
`experimental_distribute_dataset` + device prefetch inside MirroredStrategy,
`YOLO/tensorflow/train.py:291-294`).

The prefetcher also keeps the transfer ledger: `bytes_staged_total` /
`last_stage_secs` / `bytes_per_sec` quantify what the uint8 device-augment
path (data/device_augment.py, `--device-augment`) saves over f32 batches —
the trainer surfaces them in its periodic `log_every` flush next to
`prefetch_queue_depth`, and bench_input.py reads them for its
bytes-to-device comparison. `wait_secs_total` / `overlapped_fraction`
additionally measure how much of that staging time was HIDDEN under the
consumer's compute — the double-buffering proof `bench_epoch.py` reports
(docs/INPUT_PIPELINE.md "On-device epochs").
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Iterable, Iterator

import jax

from . import mesh as mesh_lib

_SENTINEL = object()


class _ProducerError:
    def __init__(self, exc: BaseException):
        self.exc = exc


def _host_nbytes(batch) -> int:
    """Bytes the host hands to device_put for one batch — dtype-honest (a
    uint8 batch counts 1/4 of the same batch as f32), computed from the host
    arrays so it never syncs the device."""
    return sum(int(getattr(x, "nbytes", 0))
               for x in jax.tree_util.tree_leaves(batch))


class DevicePrefetcher:
    """Iterator of device-staged batches with an inspectable queue.

    Yields `shard_batch_pytree(mesh, tuple(b))` for each host batch `b`,
    staged up to `size` batches ahead by a daemon producer thread.

    Device-memory cost: at most `size` staged batches beyond the one the
    consumer holds (`size-1` queued + 1 the producer stages while the queue
    is full). Producer exceptions re-raise at the consuming `next()`.
    Closing/abandoning the iterator mid-stream (e.g. a train-step error)
    signals the producer to exit and drains the queue, releasing the staged
    device buffers and the underlying data iterator. `size <= 1` degenerates
    to inline staging (no thread).

    `queue_depth` is the count of staged batches currently waiting — the
    stall diagnostic resilience.StepWatchdog dumps: depth `size-1` during a
    stall means the device/dispatch is wedged (producer filled the queue and
    blocked), depth 0 means the host pipeline starved the step loop.

    Transfer accounting (read from any thread; plain-int/float writes are
    atomic under the GIL):

    - `bytes_staged_total`: host bytes handed to device_put so far — the
      number the uint8 staging path (`--device-augment`) divides by ~4.
    - `last_stage_secs`: wall time of the most recent `shard_batch_pytree`
      call (dispatch + transfer of one batch).
    - `bytes_per_sec`: cumulative staged bytes / cumulative staging wall
      time — effective host→device staging bandwidth.
    - `wait_secs_total` / `first_wait_secs` / `overlapped_fraction`: time
      the CONSUMER spent blocked waiting for staged batches, the share of
      it that was the one-time pipeline fill (producer thread spawn + the
      first batch's stage — nothing exists to overlap it with), and the
      share of staging wall time hidden under consumer work in steady
      state: 1 − (wait − first_wait)/stage_total. Double buffering is
      working exactly when the fraction is high: the producer stages batch
      k+1 while the consumer computes on batch k, so after the fill the
      consumer only waits when the host generator — not staging — is the
      bottleneck. Inline mode (`size <= 1`) stages synchronously, so every
      stage is a wait and the fraction is 0 by construction.
    """

    def __init__(self, mesh, batches: Iterable, size: int = 2):
        self._mesh = mesh
        self._size = size
        self._inline = None
        self._stop = threading.Event()
        self._q: "queue.Queue" = None
        self.bytes_staged_total = 0
        self.batches_staged_total = 0
        self.last_stage_secs = 0.0
        self.wait_secs_total = 0.0
        self.first_wait_secs = 0.0
        self._first_wait_seen = False
        self._stage_secs_total = 0.0
        if size <= 1:
            self._inline = iter(batches)
            return
        self._q = queue.Queue(maxsize=size - 1)
        self._batches = batches
        threading.Thread(target=self._producer, daemon=True,
                         name="device-prefetch").start()

    @property
    def queue_depth(self) -> int:
        return self._q.qsize() if self._q is not None else 0

    @property
    def bytes_per_sec(self) -> float:
        if self._stage_secs_total <= 0.0:
            return 0.0
        return self.bytes_staged_total / self._stage_secs_total

    @property
    def overlapped_fraction(self) -> float:
        """Share of staging wall time hidden under consumer compute in
        steady state: max(0, 1 − (wait − first_wait)/stage_total). The
        first wait is the pipeline fill (thread spawn + the first batch's
        stage, nothing to overlap with) — reported via `first_wait_secs`,
        not charged here. Conservative — the steady-state wait also counts
        time blocked on a slow host GENERATOR, so a low number means "the
        consumer waited", not necessarily "transfer was exposed"; a high
        number proves the double buffer hid the staging."""
        if self._stage_secs_total <= 0.0:
            return 0.0
        steady = self.wait_secs_total - self.first_wait_secs
        return max(0.0, 1.0 - steady / self._stage_secs_total)

    def _stage(self, b):
        """shard_batch_pytree with the transfer ledger updated around it."""
        nbytes = _host_nbytes(b)
        t0 = time.perf_counter()
        staged = mesh_lib.shard_batch_pytree(self._mesh, tuple(b))
        dt = time.perf_counter() - t0
        self.bytes_staged_total += nbytes
        self.batches_staged_total += 1
        self.last_stage_secs = dt
        self._stage_secs_total += dt
        return staged

    def _put(self, item) -> bool:
        """Blocking put that still observes stop; True if delivered."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _producer(self):
        try:
            for b in self._batches:
                if self._stop.is_set():
                    return
                if not self._put(self._stage(b)):
                    return
        except BaseException as e:  # propagate into the consumer
            self._put(_ProducerError(e))
            return
        self._put(_SENTINEL)

    def __iter__(self) -> Iterator:
        return self

    def __next__(self):
        if self._inline is not None:
            # inline staging is synchronous: the whole stage is a wait
            staged = self._stage(next(self._inline))
            self.wait_secs_total += self.last_stage_secs
            return staged
        if self._stop.is_set():
            raise StopIteration
        t0 = time.perf_counter()
        item = self._q.get()
        wait = time.perf_counter() - t0
        if not self._first_wait_seen:
            self._first_wait_seen = True
            self.first_wait_secs = wait  # the one-time pipeline fill
        self.wait_secs_total += wait
        if item is _SENTINEL:
            self._stop.set()
            raise StopIteration
        if isinstance(item, _ProducerError):
            self._stop.set()
            raise item.exc
        return item

    def close(self):
        """Reached on exhaustion, error, or abandonment: unblock a producer
        waiting on the full queue so it exits and its staged batches (and
        the source iterator) are released."""
        self._stop.set()
        if self._inline is not None:
            c = getattr(self._inline, "close", None)
            if c is not None:
                c()
            self._inline = None
            return
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass


def prefetch_to_device(mesh, batches: Iterable, size: int = 2) -> DevicePrefetcher:
    """Build a DevicePrefetcher (kept as a function for the existing call
    sites and tests; see the class docstring for the contract)."""
    return DevicePrefetcher(mesh, batches, size)
