"""Background host→device batch staging.

`jax.device_put` of a large host batch can block the calling thread while the
buffer is staged (measured on this repo's relay-attached chip: ~1.5s for a
256×224×224×3 f32 batch; ~15ms over real PCIe). Done inline in the step loop,
that stall serializes with compute. A small producer thread device_puts ahead
with the mesh's batch sharding, so the transfer of batch i+1 overlaps the
device executing batch i — the JAX-side counterpart of tf.data's
`prefetch_to_device` (the reference relied on
`experimental_distribute_dataset` + device prefetch inside MirroredStrategy,
`YOLO/tensorflow/train.py:291-294`).
"""

from __future__ import annotations

import queue
import threading
from typing import Iterable, Iterator

from . import mesh as mesh_lib

_SENTINEL = object()


class _ProducerError:
    def __init__(self, exc: BaseException):
        self.exc = exc


def prefetch_to_device(mesh, batches: Iterable, size: int = 2) -> Iterator:
    """Yield `shard_batch_pytree(mesh, tuple(b))` for each host batch `b`,
    staged up to `size` batches ahead by a daemon producer thread.

    Device-memory cost: at most `size` staged batches beyond the one the
    consumer holds (`size-1` queued + 1 the producer stages while the queue
    is full). Producer exceptions re-raise at the consuming `next()`.
    Closing/abandoning the iterator mid-stream (e.g. a train-step error)
    signals the producer to exit and drains the queue, releasing the staged
    device buffers and the underlying data iterator. `size <= 1` degenerates
    to inline staging (no thread).
    """
    if size <= 1:
        for b in batches:
            yield mesh_lib.shard_batch_pytree(mesh, tuple(b))
        return

    stop = threading.Event()
    q: "queue.Queue" = queue.Queue(maxsize=size - 1)

    def _put(item) -> bool:
        """Blocking put that still observes stop; True if delivered."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def producer():
        try:
            for b in batches:
                if stop.is_set():
                    return
                if not _put(mesh_lib.shard_batch_pytree(mesh, tuple(b))):
                    return
        except BaseException as e:  # propagate into the consumer
            _put(_ProducerError(e))
            return
        _put(_SENTINEL)

    threading.Thread(target=producer, daemon=True,
                     name="device-prefetch").start()
    try:
        while True:
            item = q.get()
            if item is _SENTINEL:
                return
            if isinstance(item, _ProducerError):
                raise item.exc
            yield item
    finally:
        # reached on exhaustion, error, or generator close: unblock a
        # producer waiting on the full queue so it exits and its staged
        # batches (and the source iterator) are released
        stop.set()
        try:
            while True:
                q.get_nowait()
        except queue.Empty:
            pass
