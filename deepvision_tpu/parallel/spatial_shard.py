"""Owned-semantics spatial partitioning: shard_map + explicit collectives.

The GSPMD spatial path (`mesh.py`, `spatial_activation_constraints`) lets the
XLA partitioner insert halo exchanges — exact on (data, spatial) meshes, but
on combined spatial×model meshes GSPMD (jax 0.9.0) inserts a spurious
model-axis psum into SOME conv gradients, forcing the measured
`calibrate_grad_correction` workaround, and CenterNet's combined mesh had to
be refused outright (stem-BN grad ~500x off — PARITY.md §2.8).

This module OWNS the spatial semantics instead, so correctness stops
depending on the partitioner's per-model behavior (VERDICT r3 item 7):

- the train step runs under `jax.shard_map` with MANUAL ('data', 'spatial')
  axes and the 'model' axis left automatic — GSPMD still shards the big
  params (tensor parallelism), but it never sees a spatially-sharded conv,
  which is exactly the context that triggers its mis-partitioning;
- convolutions exchange kernel halos explicitly via `lax.ppermute`
  (zero boundaries = SAME semantics; -inf refill for max_pool);
- BatchNorm statistics psum over ('data', 'spatial') — flax's own
  `_compute_stats(axis_name=...)` math, so numerics match the oracle;
- at a topologically safe block boundary (`transition`), one
  `lax.all_to_all` converts spatial parallelism into extra data parallelism
  (H gathers, the batch splits — the sequence-parallel -> data-parallel
  handoff): no region of the network is ever compute-replicated, so the one
  explicit `psum(grads) / n_ranks` is uniformly exact. No calibration step.

Model code is untouched: a flax method interceptor recognizes `nn.Conv` /
`nn.BatchNorm` calls on spatially-sharded activations and takes them over;
`nn.max_pool` / `nn.avg_pool` (plain functions) are patched for the scope of
the forward. Everything else (residual adds, reshapes, `jax.image.resize`
nearest-x2 upsampling, 1x1 convs) is row-local and runs unchanged.
"""

from __future__ import annotations

import contextlib
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import DATA_AXIS, SPATIAL_AXIS
from ..core.steps import annotate_step

MANUAL_AXES = (DATA_AXIS, SPATIAL_AXIS)

# The collective contract of this module's spatial primitives, keyed by
# probe name — consumed by `deepvision_tpu/check` (jaxvet's COLL family),
# which traces the REAL functions below over a virtual spatial mesh and
# diffs the collectives it finds in the jaxpr against this declaration. A
# mis-axed collective (the `all_to_all(x, "data", ...)` class of typo that
# jaxlint's SHD001 cannot see, because "data" IS a known axis) shows up as
# a declared-vs-traced mismatch. Keys: (primitive name, axis tuple) ->
# occurrence count in one probe trace.
DECLARED_COLLECTIVES = {
    # halo_exchange(x, 1, 1): one ppermute shifting rows forward, one back
    "halo_exchange": {("ppermute", (SPATIAL_AXIS,)): 2},
    # the transition handoff: one tiled all_to_all over 'spatial'
    "transition": {("all_to_all", (SPATIAL_AXIS,)): 1},
    # reduce_grads on a single-leaf tree over both manual axes
    "grad_psum": {("psum", (DATA_AXIS, SPATIAL_AXIS)): 1},
}


def reduce_grads(grads, axes, n_ranks: int):
    """THE controlled cross-rank gradient reduction (VERDICT r3 item 7),
    shared by every shard_map train step in this module: each rank computed
    a disjoint slice of the batch-x-rows work, so sum/n_ranks of the local
    grads of local mean losses is exactly the global-batch gradient — for
    every leaf, in both regimes, on any model."""
    return jax.tree_util.tree_map(
        lambda g: lax.psum(g, axes) / n_ranks, grads)


# -- geometry -------------------------------------------------------------------

def _pair(v, default=1) -> Tuple[int, int]:
    if v is None:
        return (default, default)
    if isinstance(v, int):
        return (v, v)
    return tuple(v)


def _same_pads(size: int, k: int, s: int) -> Tuple[int, int]:
    """XLA SAME padding (jax lax.padtype_to_pads convention: extra on high)."""
    out = -(-size // s)
    total = max((out - 1) * s + k - size, 0)
    return total // 2, total - total // 2


def conv_pads(padding, h: int, w: int, kh: int, kw: int, sh: int, sw: int):
    """Resolve an nn.Conv/pool `padding` attr to explicit ((hl,hh),(wl,wh))
    using the GLOBAL height h (shard-local SAME pads would be wrong)."""
    if padding == "SAME":
        return _same_pads(h, kh, sh), _same_pads(w, kw, sw)
    if padding == "VALID":
        return (0, 0), (0, 0)
    if isinstance(padding, int):
        return (padding, padding), (padding, padding)
    (hl, hh), (wl, wh) = padding
    return (int(hl), int(hh)), (int(wl), int(wh))


def halo_exchange(x, lo: int, hi: int, *, axis_name: str = SPATIAL_AXIS,
                  sp: int, fill: float = 0.0):
    """Concat `lo` rows from the previous spatial shard and `hi` rows from the
    next onto x's H axis (axis 1). Boundary shards receive `fill` (ppermute's
    missing entries are zeros — the SAME-conv zero pad; max_pool refills with
    -inf). Negative lo/hi TRIM rows instead (a strided window that ends
    before the shard does, e.g. 1x1 stride 2)."""
    parts = []
    if lo > 0:
        prev = lax.ppermute(x[:, -lo:], axis_name,
                            [(i, i + 1) for i in range(sp - 1)])
        if fill != 0.0:
            first = lax.axis_index(axis_name) == 0
            prev = jnp.where(first, jnp.full_like(prev, fill), prev)
        parts.append(prev)
    start = -lo if lo < 0 else 0
    stop = x.shape[1] + (hi if hi < 0 else 0)
    parts.append(x[:, start:stop])
    if hi > 0:
        nxt = lax.ppermute(x[:, :hi], axis_name,
                           [(i + 1, i) for i in range(sp - 1)])
        if fill != 0.0:
            last = lax.axis_index(axis_name) == sp - 1
            nxt = jnp.where(last, jnp.full_like(nxt, fill), nxt)
        parts.append(nxt)
    return jnp.concatenate(parts, axis=1) if len(parts) > 1 else parts[0]


def _check_valid_supported(what: str, padding, kh: int, sh: int):
    """VALID windows with kernel > stride SHRINK the global H; the halo
    machinery would instead fill boundary halos and emit full-height output,
    silently diverging — refuse them (no supported model uses VALID)."""
    if padding == "VALID" and kh > sh:
        raise NotImplementedError(
            f"spatial shard_map: {what} uses padding='VALID' with kernel "
            f"{kh} > stride {sh}, which shrinks H at shard boundaries; "
            f"only SAME/explicit paddings are supported on sharded rows")


def _check_rows(what: str, rows: int, sh: int, sp: int):
    if rows % sh != 0:
        raise ValueError(
            f"spatial shard_map: {what} sees {rows} rows/shard with H-stride "
            f"{sh} (spatial={sp}); per-shard rows must be divisible by the "
            f"stride. Place the all_to_all transition before this op or pick "
            f"a resolution/spatial factor whose per-shard rows stay "
            f"stride-aligned.")


# -- op takeovers ---------------------------------------------------------------

def _sharded_conv(mod, x, *, sp: int):
    """Faithful nn.Conv on H-sharded NHWC input: explicit halo + VALID-in-H
    `conv_general_dilated` with the module's own kernel/bias/dtype rules.
    Cites the GSPMD alternative it replaces: mesh.py:46-52."""
    import flax.linen as nn
    from flax.linen.dtypes import promote_dtype

    assert isinstance(mod, nn.Conv)
    if mod.mask is not None:
        raise NotImplementedError("masked conv under spatial shard_map")
    kh, kw = _pair(mod.kernel_size)
    sh, sw = _pair(mod.strides)
    dh, dw = _pair(mod.kernel_dilation)
    if (dh, dw) != (1, 1) or _pair(mod.input_dilation) != (1, 1):
        raise NotImplementedError("dilated conv under spatial shard_map")
    rows = x.shape[1]
    _check_valid_supported(f"conv {mod.path}", mod.padding, kh, sh)
    _check_rows(f"conv {mod.path}", rows, sh, sp)
    (ph_lo, _), wpads = conv_pads(mod.padding, rows * sp, x.shape[2],
                                  kh, kw, sh, sw)
    lo, hi = ph_lo, kh - sh - ph_lo
    x_aug = halo_exchange(x, lo, hi, sp=sp)

    kernel = mod.variables["params"]["kernel"]
    bias = mod.variables["params"].get("bias") if mod.use_bias else None
    x_aug, kernel, bias = promote_dtype(x_aug, kernel, bias, dtype=mod.dtype)
    out = lax.conv_general_dilated(
        x_aug, kernel, window_strides=(sh, sw),
        padding=[(0, 0), tuple(wpads)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=mod.feature_group_count,
        precision=mod.precision)
    if bias is not None:
        out = out + bias
    return out


def _sync_batchnorm(mod, x, use_running_average: bool, axes):
    """flax BatchNorm with statistics psummed over the manual mesh axes —
    the module's own `_compute_stats`/`_normalize` math with axis_name set,
    running averages updated via put_variable. Inside shard_map every rank
    holds a disjoint slice of (batch x rows), so the pmean over the manual
    axes IS the global batch statistic (sync-BN, steps.py:8-9)."""
    from flax.linen.normalization import _compute_stats, _normalize

    if use_running_average:
        return None  # eval: stored stats, elementwise — local math is exact
    feature_axes = (x.ndim - 1,)
    reduction_axes = tuple(range(x.ndim - 1))
    mean, var = _compute_stats(
        x, reduction_axes, dtype=mod.dtype, axis_name=axes,
        axis_index_groups=None, use_fast_variance=mod.use_fast_variance,
        mask=None, force_float32_reductions=mod.force_float32_reductions)
    if not mod.is_initializing():
        ra_mean = mod.get_variable("batch_stats", "mean")
        ra_var = mod.get_variable("batch_stats", "var")
        mod.put_variable("batch_stats", "mean",
                         mod.momentum * ra_mean + (1 - mod.momentum) * mean)
        mod.put_variable("batch_stats", "var",
                         mod.momentum * ra_var + (1 - mod.momentum) * var)
    return _normalize(mod, x, mean, var, reduction_axes, feature_axes,
                      mod.dtype, mod.param_dtype, mod.epsilon,
                      mod.use_bias, mod.use_scale, mod.bias_init,
                      mod.scale_init, mod.force_float32_reductions)


class SpatialShardContext:
    """Per-forward interception state for one shard_map body trace.

    `sharded` starts True (H over 'spatial'); flips False at the `transition`
    module, where one tiled all_to_all turns the spatial axis into extra
    data parallelism (batch splits sp ways, H gathers). BatchNorm keeps the
    ('data','spatial') psum in BOTH regimes — examples are spread over
    exactly those axes either way, so the statistic is global."""

    def __init__(self, *, sp: int, transition: Optional[str],
                 axes=MANUAL_AXES):
        self.sp = sp
        self.transition = transition
        self.axes = tuple(axes)      # manual mesh axes present (BN psums)
        self.sharded = sp > 1

    def assert_transition_consumed(self):
        """Call after the forward: a transition name that matched no module
        would leave H sharded through any trailing global reduction — wrong
        results, no error. Raise instead of trusting the name."""
        if self.transition is not None and self.sharded:
            raise RuntimeError(
                f"spatial shard_map: transition module "
                f"{self.transition!r} was never reached during the forward "
                f"— the all_to_all handoff did not fire, so the name does "
                f"not match any top-level module of this model (check "
                f"default_transition / the model's param tree)")

    def _maybe_transition(self, mod, x):
        if (self.sharded and self.transition is not None
                and mod.path == (self.transition,)):
            if x.shape[0] % self.sp != 0:
                raise ValueError(
                    f"spatial shard_map transition at {self.transition}: "
                    f"per-rank batch {x.shape[0]} must be divisible by "
                    f"spatial={self.sp} for the all_to_all handoff")
            x = lax.all_to_all(x, SPATIAL_AXIS, split_axis=0, concat_axis=1,
                               tiled=True)
            self.sharded = False
        return x

    def interceptor(self, next_fun, args, kwargs, context):
        import flax.linen as nn

        mod = context.module
        if (mod.is_initializing() or not args
                or not isinstance(args[0], jax.Array) or args[0].ndim != 4):
            return next_fun(*args, **kwargs)
        x = args[0]
        new_x = self._maybe_transition(mod, x)
        if new_x is not x:
            return next_fun(new_x, *args[1:], **kwargs)
        if isinstance(mod, nn.BatchNorm):
            ura = kwargs.get("use_running_average")
            if ura is None and len(args) > 1:
                ura = args[1]
            if ura is None:
                ura = mod.use_running_average
            out = _sync_batchnorm(mod, x, bool(ura), self.axes)
            return out if out is not None else next_fun(*args, **kwargs)
        if self.sharded and isinstance(mod, nn.Conv):
            return _sharded_conv(mod, x, sp=self.sp)
        return next_fun(*args, **kwargs)

    @contextlib.contextmanager
    def active(self):
        """intercept_methods + max/avg_pool patches for one forward."""
        import flax.linen as nn

        orig_max, orig_avg = nn.max_pool, nn.avg_pool
        ctx = self

        def max_pool(inputs, window_shape, strides=None, padding="VALID"):
            if not ctx.sharded or inputs.ndim != 4:
                return orig_max(inputs, window_shape, strides, padding)
            kh, kw = _pair(window_shape)
            sh, sw = _pair(strides)
            _check_valid_supported("max_pool", padding, kh, sh)
            _check_rows("max_pool", inputs.shape[1], sh, ctx.sp)
            (ph_lo, _), wpads = conv_pads(padding, inputs.shape[1] * ctx.sp,
                                          inputs.shape[2], kh, kw, sh, sw)
            lo, hi = ph_lo, kh - sh - ph_lo
            x_aug = halo_exchange(inputs, lo, hi, sp=ctx.sp,
                                  fill=float(jnp.finfo(inputs.dtype).min))
            return orig_max(x_aug, (kh, kw), (sh, sw),
                            [(0, 0), tuple(wpads)])

        def avg_pool(inputs, window_shape, strides=None, padding="VALID",
                     count_include_pad=True):
            if not ctx.sharded or inputs.ndim != 4:
                return orig_avg(inputs, window_shape, strides, padding,
                                count_include_pad)
            if not count_include_pad:
                raise NotImplementedError(
                    "avg_pool(count_include_pad=False) under spatial "
                    "shard_map")
            kh, kw = _pair(window_shape)
            sh, sw = _pair(strides)
            _check_valid_supported("avg_pool", padding, kh, sh)
            _check_rows("avg_pool", inputs.shape[1], sh, ctx.sp)
            (ph_lo, _), wpads = conv_pads(padding, inputs.shape[1] * ctx.sp,
                                          inputs.shape[2], kh, kw, sh, sw)
            lo, hi = ph_lo, kh - sh - ph_lo
            x_aug = halo_exchange(inputs, lo, hi, sp=ctx.sp)  # zero pads
            return orig_avg(x_aug, (kh, kw), (sh, sw),
                            [(0, 0), tuple(wpads)], count_include_pad)

        import flax
        nn.max_pool = flax.linen.max_pool = max_pool
        nn.avg_pool = flax.linen.avg_pool = avg_pool
        try:
            with nn.intercept_methods(self.interceptor):
                yield
        finally:
            nn.max_pool = flax.linen.max_pool = orig_max
            nn.avg_pool = flax.linen.avg_pool = orig_avg


def default_transition(model) -> Optional[str]:
    """The all_to_all plan for a model instance, or raise when this backend
    has no plan for its topology (a model with mid-network flattens/global
    reductions outside module boundaries would go silently wrong instead).

    - ResNet family: entry of the last stage's first block (the global mean
      at `resnet.py:159` needs gathered rows; last-stage strides can
      misalign with per-shard rows).
    - CenterNet (ObjectsAsPoints): fully convolutional (dense heads,
      nearest-x2 upsampling — both row-local), so no transition: None keeps
      H sharded end to end.
    - StackedHourglass: also fully convolutional — SAME convs, 2x2/2
      maxpools (kernel == stride: no halo), nearest-x2 upsamples and
      residual adds are all row-local, and the heatmap heads are 1x1 convs
      — so None keeps H sharded end to end (the weighted-MSE loss is dense
      and row-sliceable, make_shardmap_pose_train_step).
    - MobileNetV1: the handoff fires at the entry of the 1024-wide final
      stage (block11) — BEFORE its stride-2 depthwise conv, which at the
      config's own 224px would otherwise see stride-misaligned per-shard
      rows (7 rows/shard at sp=2) — so the last two blocks and the global
      mean run on full-height rows (the exact analogue of the ResNet
      plan's last-stage-entry rule).
    - UNetSegmenter (segmentation): fully convolutional by construction
      (SAME/explicit-pad convs, 3x3/2 maxpool via halo, nearest-x2
      upsamples and channel concats are row-local, f32 1x1 head) — None
      keeps H sharded end to end; the pixel-wise CE is dense and
      row-sliceable (make_shardmap_segmentation_train_step).
    """
    name = type(model).__name__
    if name == "ResNet":
        block = model.block
        block_name = (block.__name__ if isinstance(block, type)
                      else type(block).__name__)
        return resnet_transition(model.stage_sizes, block_name)
    if name == "MobileNetV1":
        from ..models.mobilenet import _V1_BODY
        return f"block{len(_V1_BODY) - 2}"
    if name in ("ObjectsAsPoints", "StackedHourglass", "UNetSegmenter"):
        return None
    raise NotImplementedError(
        f"spatial_backend='shard_map' has no transition plan for "
        f"{name}; supported: ResNet family, MobileNetV1, CenterNet, "
        f"StackedHourglass, UNetSegmenter (+ YOLO/pose via their "
        f"trainers). Use the gspmd backend for this model.")


def resnet_transition(stage_sizes: Sequence[int],
                      block_name: str = "BottleneckBlock") -> str:
    """The safe all_to_all point for the ResNet family: entry of the LAST
    stage's first block (H there is at/below MIN_SPATIAL_ROWS x typical sp,
    and block entry is outside any residual scope, so both branches of every
    skip see the same regime)."""
    return f"{block_name}_{sum(stage_sizes[:-1])}"


# -- the owned-semantics train step --------------------------------------------

def make_shardmap_classification_train_step(
    *,
    mesh: Mesh,
    transition: Optional[str],
    label_smoothing: float = 0.0,
    aux_weight: float = 0.3,
    compute_dtype=jnp.float32,
    input_norm: Optional[tuple] = None,
    log_grad_norm: bool = False,
    donate: bool = True,
    remat: bool = False,
):
    """`(state, images, labels, rng) -> (state, metrics)` with the spatial
    axis handled by THIS module's collectives instead of GSPMD (module
    docstring). Drop-in for `steps.make_classification_train_step` on
    spatial and combined spatial x model meshes — with NO grad_correction
    argument: the explicit psum over ('data','spatial') divided by the rank
    count is the entire cross-rank gradient story. The 'model' mesh axis (if
    any) stays automatic, so `param_sharding_rules` tensor parallelism works
    unchanged inside the body.

    `remat=True` wraps the intercepted forward in `jax.checkpoint` (same
    policy as steps.py): the backward re-runs the forward — including its
    ppermute halos and BN psums, which jax replays inside the shard_map body
    — instead of keeping activations in HBM. The context object is built
    INSIDE the checkpointed function so the replay gets a fresh
    sharded-regime state machine."""
    from ..core import losses
    from ..core.steps import _normalize_input, maybe_grad_norm

    sp = dict(mesh.shape).get(SPATIAL_AXIS, 1)
    dp = dict(mesh.shape)[DATA_AXIS]
    n_ranks = sp * dp
    axes = tuple(a for a in MANUAL_AXES if a in mesh.axis_names)

    def step(state, images, labels, rng):
        images = _normalize_input(images, input_norm, compute_dtype)
        step_rng = jax.random.fold_in(rng, state.step)

        def body(params, batch_stats, images, labels):
            def forward(p, images):
                ctx = SpatialShardContext(sp=sp, transition=transition,
                                          axes=axes)
                with ctx.active():
                    outputs, mutated = state.apply_fn(
                        {"params": p, "batch_stats": batch_stats},
                        images, train=True, mutable=["batch_stats"],
                        rngs={"dropout": step_rng})
                ctx.assert_transition_consumed()
                return outputs, mutated

            if remat:
                forward = jax.checkpoint(
                    forward, policy=jax.checkpoint_policies
                    .dots_with_no_batch_dims_saveable)

            def loss_fn(p):
                outputs, mutated = forward(p, images)
                loss = losses.classification_loss(
                    outputs, labels, label_smoothing=label_smoothing,
                    aux_weight=aux_weight)
                return loss, (outputs, mutated)

            (loss, (outputs, mutated)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            grads = reduce_grads(grads, axes, n_ranks)
            metrics = {"loss": loss,
                       **losses.topk_accuracies(outputs, labels)}
            metrics = {k: lax.pmean(v, axes)
                       for k, v in metrics.items()}
            new_bs = mutated.get("batch_stats", batch_stats)
            return grads, new_bs, metrics

        spatial_in = P(DATA_AXIS, SPATIAL_AXIS if sp > 1 else None)
        grads, new_bs, metrics = jax.shard_map(
            body, mesh=mesh, axis_names=set(axes),
            in_specs=(P(), P(), spatial_in, P((DATA_AXIS, SPATIAL_AXIS))
                      if sp > 1 else P(DATA_AXIS)),
            out_specs=(P(), P(), P()),
            check_vma=False,
        )(state.params, state.batch_stats, images, labels)
        new_state = state.apply_gradients(grads).replace(batch_stats=new_bs)
        metrics = {**metrics, **maybe_grad_norm(log_grad_norm, grads)}
        return new_state, metrics

    jit_kwargs = {}
    if donate:
        jit_kwargs["donate_argnums"] = (0,)
    jit_kwargs["out_shardings"] = (None, NamedSharding(mesh, P()))
    return annotate_step(jax.jit(step, **jit_kwargs), donate=donate,
                         compute_dtype=jnp.dtype(compute_dtype),
                         kind="train", spatial=True)


def make_shardmap_yolo_train_step(
    *,
    num_classes: int,
    grid_sizes: Sequence[int],
    mesh: Mesh,
    compute_dtype=jnp.bfloat16,
    input_norm: Optional[tuple] = None,
    log_grad_norm: bool = False,
    donate: bool = True,
    remat: bool = False,
):
    """YOLO `(state, images, boxes, classes, valid, rng)` step with owned
    spatial semantics — the fourth family on this backend.

    YOLO's loss is NOT row-local (`ops/yolo.py`: cell offsets index the
    global grid, and the ignore mask compares every predicted box against
    the image's full ground truth), so the transition concept moves to the
    HEAD boundary: Darknet-53 + the FPN — where all the FLOPs and big
    activations live — run H-sharded end to end (SAME convs, stride-2
    downsamples, nearest-x2 upsample + channel concat are all handled or
    row-local), then ONE tiled `all_gather` per scale rebuilds the tiny
    (B_local, g, g, 3, 5+C) heads on every spatial rank and the ORACLE's
    own `yolo_loss` runs unchanged on full tensors. The loss is thereby
    computed sp-times redundantly — O(g^2) work, noise next to the backbone
    — and the duplication cancels exactly in the uniform psum/n_ranks rule:
    all_gather transposes to reduce-scatter, so summing the sp identical
    loss copies' grads over ('data','spatial') counts each data slice sp
    times, and /(dp*sp) restores the global-batch mean. Verified against
    the single-device oracle in test_spatial_shardmap.py."""
    from ..core.steps import _normalize_input, maybe_grad_norm
    from ..ops import yolo as yolo_ops

    sp = dict(mesh.shape).get(SPATIAL_AXIS, 1)
    dp = dict(mesh.shape)[DATA_AXIS]
    n_ranks = sp * dp
    axes = tuple(a for a in MANUAL_AXES if a in mesh.axis_names)
    if sp > 1:
        bad = [g for g in grid_sizes if g % sp != 0]
        if bad:
            raise ValueError(
                f"yolo grids {bad} must be divisible by spatial={sp} "
                f"(grid rows are H-sharded through the FPN)")

    def step(state, images, boxes, classes, valid, rng):
        del rng  # YOLO has no dropout; augmentation happens host-side
        images = _normalize_input(images, input_norm, compute_dtype)

        def body(params, batch_stats, images, boxes, classes, valid):
            classes_onehot = jax.nn.one_hot(classes, num_classes,
                                            dtype=jnp.float32)
            y_trues = yolo_ops.encode_labels(classes_onehot, boxes, valid,
                                             grid_sizes)

            def forward(p, images):
                ctx = SpatialShardContext(sp=sp, transition=None, axes=axes)
                with ctx.active():
                    return state.apply_fn(
                        {"params": p, "batch_stats": batch_stats},
                        images, train=True, mutable=["batch_stats"])

            if remat:
                forward = jax.checkpoint(
                    forward, policy=jax.checkpoint_policies
                    .dots_with_no_batch_dims_saveable)

            def loss_fn(p):
                outputs, mutated = forward(p, images)
                if sp > 1:
                    outputs = tuple(
                        lax.all_gather(o, SPATIAL_AXIS, axis=1, tiled=True)
                        for o in outputs)
                comp = yolo_ops.yolo_loss(y_trues, outputs, boxes, valid,
                                          num_classes)
                return jnp.mean(comp["total"]), (comp, mutated)

            (loss, (comp, mutated)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            grads = reduce_grads(grads, axes, n_ranks)
            metrics = {"loss": loss,
                       **{f"{k}_loss": jnp.mean(v)
                          for k, v in comp.items() if k != "total"}}
            metrics = {k: lax.pmean(v, axes) for k, v in metrics.items()}
            new_bs = mutated.get("batch_stats", batch_stats)
            return grads, new_bs, metrics

        spatial_in = P(DATA_AXIS, SPATIAL_AXIS if sp > 1 else None)
        grads, new_bs, metrics = jax.shard_map(
            body, mesh=mesh, axis_names=set(axes),
            in_specs=(P(), P(), spatial_in, P(DATA_AXIS), P(DATA_AXIS),
                      P(DATA_AXIS)),
            out_specs=(P(), P(), P()),
            check_vma=False,
        )(state.params, state.batch_stats, images, boxes, classes, valid)
        new_state = state.apply_gradients(grads).replace(batch_stats=new_bs)
        metrics = {**metrics, **maybe_grad_norm(log_grad_norm, grads)}
        return new_state, metrics

    jit_kwargs = {}
    if donate:
        jit_kwargs["donate_argnums"] = (0,)
    jit_kwargs["out_shardings"] = (None, NamedSharding(mesh, P()))
    return annotate_step(jax.jit(step, **jit_kwargs), donate=donate,
                         compute_dtype=jnp.dtype(compute_dtype),
                         kind="train", spatial=True)


def make_shardmap_pose_train_step(
    *,
    heatmap_size: Tuple[int, int],
    mesh: Mesh,
    compute_dtype=jnp.bfloat16,
    input_norm: Optional[tuple] = None,
    log_grad_norm: bool = False,
    donate: bool = True,
    remat: bool = False,
):
    """Stacked-Hourglass `(state, images, kp_x, kp_y, visibility, rng)` step
    with owned spatial semantics. The model is fully convolutional
    (default_transition: None — H stays sharded end to end), and the
    foreground-weighted MSE (core/pose.py weighted_mse_loss, parity
    `Hourglass/tensorflow/train.py:65-76`) is a dense per-pixel mean, so the
    CenterNet recipe transfers wholesale: gaussian heatmap targets are
    rendered per rank from its batch slice and row-sliced to the spatial
    shard, each rank's loss is the mean over its disjoint (batch x rows)
    slice, and the one controlled psum over ('data','spatial') / n_ranks is
    exactly the global-batch gradient (equal slice sizes make the global
    mean the mean of local means). Verified leaf-exact vs the single-device
    oracle in test_spatial_shardmap.py."""
    from ..core.pose import weighted_mse_loss
    from ..core.steps import _normalize_input, maybe_grad_norm
    from ..ops.heatmap import render_gaussian_heatmaps

    h, w = heatmap_size
    sp = dict(mesh.shape).get(SPATIAL_AXIS, 1)
    dp = dict(mesh.shape)[DATA_AXIS]
    n_ranks = sp * dp
    axes = tuple(a for a in MANUAL_AXES if a in mesh.axis_names)
    if sp > 1 and h % sp != 0:
        raise ValueError(f"pose heatmap height {h} must be divisible by "
                         f"spatial={sp}")

    def step(state, images, kp_x, kp_y, visibility, rng):
        del rng
        images = _normalize_input(images, input_norm, compute_dtype)

        def body(params, batch_stats, images, kp_x, kp_y, visibility):
            labels = jax.vmap(
                lambda x, y, v: render_gaussian_heatmaps(x, y, v, h, w))(
                    kp_x, kp_y, visibility)
            if sp > 1:
                rows = h // sp
                start = lax.axis_index(SPATIAL_AXIS) * rows
                labels = lax.dynamic_slice_in_dim(labels, start, rows, axis=1)

            def forward(p, images):
                ctx = SpatialShardContext(sp=sp, transition=None, axes=axes)
                with ctx.active():
                    return state.apply_fn(
                        {"params": p, "batch_stats": batch_stats},
                        images, train=True, mutable=["batch_stats"])

            if remat:
                forward = jax.checkpoint(
                    forward, policy=jax.checkpoint_policies
                    .dots_with_no_batch_dims_saveable)

            def loss_fn(p):
                outputs, mutated = forward(p, images)
                return weighted_mse_loss(labels, outputs), mutated

            (loss, mutated), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            grads = reduce_grads(grads, axes, n_ranks)
            metrics = {"loss": lax.pmean(loss, axes)}
            new_bs = mutated.get("batch_stats", batch_stats)
            return grads, new_bs, metrics

        spatial_in = P(DATA_AXIS, SPATIAL_AXIS if sp > 1 else None)
        grads, new_bs, metrics = jax.shard_map(
            body, mesh=mesh, axis_names=set(axes),
            in_specs=(P(), P(), spatial_in, P(DATA_AXIS), P(DATA_AXIS),
                      P(DATA_AXIS)),
            out_specs=(P(), P(), P()),
            check_vma=False,
        )(state.params, state.batch_stats, images, kp_x, kp_y, visibility)
        new_state = state.apply_gradients(grads).replace(batch_stats=new_bs)
        metrics = {**metrics, **maybe_grad_norm(log_grad_norm, grads)}
        return new_state, metrics

    jit_kwargs = {}
    if donate:
        jit_kwargs["donate_argnums"] = (0,)
    jit_kwargs["out_shardings"] = (None, NamedSharding(mesh, P()))
    return annotate_step(jax.jit(step, **jit_kwargs), donate=donate,
                         compute_dtype=jnp.dtype(compute_dtype),
                         kind="train", spatial=True)


def make_shardmap_segmentation_train_step(
    *,
    num_classes: int,
    image_size: int,
    mesh: Mesh,
    compute_dtype=jnp.bfloat16,
    input_norm: Optional[tuple] = None,
    device_augment=None,
    dice_weight: float = 0.0,
    log_grad_norm: bool = False,
    donate: bool = True,
    remat: bool = False,
):
    """Segmentation `(state, images, masks, rng)` step with owned spatial
    semantics — the dense-prediction family the spatial backend was built
    toward (ROADMAP item 4). The U-Net is fully convolutional (SAME convs,
    3x3/2 maxpool via halo, nearest-x2 upsamples, channel concats and the
    f32 1x1 head are all row-local), so H stays sharded END TO END through
    encoder AND decoder (transition=None): the (B, S, S) class-id masks are
    row-sliced to the shard exactly like CenterNet's dense targets, each
    rank's pixel-CE is the mean over its disjoint (batch x rows) slice, and
    the one controlled psum over ('data','spatial') / n_ranks is exactly the
    global-batch gradient (equal slice sizes make the global mean the mean
    of local means — the pose-step argument verbatim).

    `device_augment` (the PAIRED image/mask stage) runs INSIDE the jit but
    BEFORE the shard_map: the per-example crop sees full-height tensors
    (only batch-sharded), which is precisely why segmentation passes the
    per-family device-augment capability check that refuses classification
    on spatial meshes. `dice_weight` is refused here: dice is a ratio of
    per-class pixel SUMS, not row-local — use the gspmd backend for the
    xent_dice recipe on spatial meshes."""
    from ..core.segment import pixel_accuracy, segmentation_loss
    from ..core.steps import _normalize_input, maybe_grad_norm

    if dice_weight > 0.0:
        raise NotImplementedError(
            "xent_dice under spatial shard_map: the dice term needs global "
            "per-class pixel sums (not row-local); use the gspmd backend "
            "or loss='softmax_xent' for this mesh")
    del num_classes  # the loss derives C from the logits' last dim
    sp = dict(mesh.shape).get(SPATIAL_AXIS, 1)
    dp = dict(mesh.shape)[DATA_AXIS]
    n_ranks = sp * dp
    axes = tuple(a for a in MANUAL_AXES if a in mesh.axis_names)
    if sp > 1 and image_size % sp != 0:
        raise ValueError(f"segmentation image size {image_size} must be "
                         f"divisible by spatial={sp} (logits and masks are "
                         f"H-sharded at full resolution)")

    def step(state, images, masks, rng):
        step_rng = jax.random.fold_in(rng, state.step)
        if device_augment is not None:
            images, masks = device_augment(
                images, masks, jax.random.fold_in(step_rng, 2))
        else:
            images = _normalize_input(images, input_norm, compute_dtype)
        masks = masks.astype(jnp.int32)

        def body(params, batch_stats, images, masks):
            if sp > 1:
                rows = image_size // sp
                start = lax.axis_index(SPATIAL_AXIS) * rows
                masks_local = lax.dynamic_slice_in_dim(masks, start, rows,
                                                       axis=1)
            else:
                masks_local = masks

            def forward(p, images):
                ctx = SpatialShardContext(sp=sp, transition=None, axes=axes)
                with ctx.active():
                    return state.apply_fn(
                        {"params": p, "batch_stats": batch_stats},
                        images, train=True, mutable=["batch_stats"])

            if remat:
                forward = jax.checkpoint(
                    forward, policy=jax.checkpoint_policies
                    .dots_with_no_batch_dims_saveable)

            def loss_fn(p):
                logits, mutated = forward(p, images)
                comp = segmentation_loss(logits, masks_local)
                return comp["total"], (logits, comp, mutated)

            (loss, (logits, comp, mutated)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            grads = reduce_grads(grads, axes, n_ranks)
            metrics = {"loss": loss,
                       "pixel_acc": pixel_accuracy(logits, masks_local),
                       "ce_loss": comp["ce"]}
            metrics = {k: lax.pmean(v, axes) for k, v in metrics.items()}
            new_bs = mutated.get("batch_stats", batch_stats)
            return grads, new_bs, metrics

        spatial_in = P(DATA_AXIS, SPATIAL_AXIS if sp > 1 else None)
        grads, new_bs, metrics = jax.shard_map(
            body, mesh=mesh, axis_names=set(axes),
            in_specs=(P(), P(), spatial_in, P(DATA_AXIS)),
            out_specs=(P(), P(), P()),
            check_vma=False,
        )(state.params, state.batch_stats, images, masks)
        new_state = state.apply_gradients(grads).replace(batch_stats=new_bs)
        metrics = {**metrics, **maybe_grad_norm(log_grad_norm, grads)}
        return new_state, metrics

    jit_kwargs = {}
    if donate:
        jit_kwargs["donate_argnums"] = (0,)
    jit_kwargs["out_shardings"] = (None, NamedSharding(mesh, P()))
    return annotate_step(jax.jit(step, **jit_kwargs), donate=donate,
                         compute_dtype=jnp.dtype(compute_dtype),
                         kind="train", spatial=True)


def make_shardmap_centernet_train_step(
    *,
    num_classes: int,
    grid: int,
    mesh: Mesh,
    compute_dtype=jnp.bfloat16,
    input_norm: Optional[tuple] = None,
    log_grad_norm: bool = False,
    donate: bool = True,
    remat: bool = False,
):
    """CenterNet `(state, images, boxes, classes, valid, rng)` step with
    owned spatial semantics — the family whose combined spatial x model mesh
    the GSPMD path REFUSES (stem-BN grad ~500x the oracle, PARITY.md §2.8;
    mesh.py calibrate_grad_correction raises). The model is fully
    convolutional, so H stays sharded end to end (transition=None): dense
    targets are encoded per rank and row-sliced to the shard, the
    per-example loss sums/center counts psum over 'spatial'
    (ops/centernet.py axis_name), and grads psum over ('data','spatial')
    divided by the rank count — the SAME uniform rule as the classification
    step. (Each spatial rank computes the identical psum-normalized loss,
    and jax transposes `psum` to `psum`, so every rank's local grad carries
    an extra x-spatial factor from the summed cotangents; /n_ranks nets it
    out. Verified leaf-exact vs the oracle in test_spatial_shardmap.py.)"""
    from ..core.steps import _normalize_input, maybe_grad_norm
    from ..ops import centernet as cn_ops

    sp = dict(mesh.shape).get(SPATIAL_AXIS, 1)
    dp = dict(mesh.shape)[DATA_AXIS]
    n_ranks = sp * dp
    axes = tuple(a for a in MANUAL_AXES if a in mesh.axis_names)
    if sp > 1 and grid % sp != 0:
        raise ValueError(f"centernet grid {grid} must divide spatial={sp}")

    def step(state, images, boxes, classes, valid, rng):
        del rng
        images = _normalize_input(images, input_norm, compute_dtype)

        def body(params, batch_stats, images, boxes, classes, valid):
            targets = cn_ops.encode_labels(boxes, classes, valid, grid,
                                           num_classes)
            if sp > 1:
                rows = grid // sp
                start = lax.axis_index(SPATIAL_AXIS) * rows
                targets = {k: lax.dynamic_slice_in_dim(v, start, rows, axis=1)
                           for k, v in targets.items()}

            def forward(p, images):
                ctx = SpatialShardContext(sp=sp, transition=None, axes=axes)
                with ctx.active():
                    return state.apply_fn(
                        {"params": p, "batch_stats": batch_stats},
                        images, train=True, mutable=["batch_stats"])

            if remat:
                forward = jax.checkpoint(
                    forward, policy=jax.checkpoint_policies
                    .dots_with_no_batch_dims_saveable)

            def loss_fn(p):
                outputs, mutated = forward(p, images)
                comp = cn_ops.centernet_loss(
                    outputs, targets,
                    axis_name=SPATIAL_AXIS if sp > 1 else None)
                return jnp.mean(comp["total"]), (comp, mutated)

            (loss, (comp, mutated)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            grads = reduce_grads(grads, axes, n_ranks)
            metrics = {"loss": loss,
                       **{f"{k}_loss": jnp.mean(v) for k, v in comp.items()
                          if k != "total"}}
            metrics = {k: lax.pmean(v, axes) for k, v in metrics.items()}
            new_bs = mutated.get("batch_stats", batch_stats)
            return grads, new_bs, metrics

        spatial_in = P(DATA_AXIS, SPATIAL_AXIS if sp > 1 else None)
        grads, new_bs, metrics = jax.shard_map(
            body, mesh=mesh, axis_names=set(axes),
            in_specs=(P(), P(), spatial_in, P(DATA_AXIS), P(DATA_AXIS),
                      P(DATA_AXIS)),
            out_specs=(P(), P(), P()),
            check_vma=False,
        )(state.params, state.batch_stats, images, boxes, classes, valid)
        new_state = state.apply_gradients(grads).replace(batch_stats=new_bs)
        metrics = {**metrics, **maybe_grad_norm(log_grad_norm, grads)}
        return new_state, metrics

    jit_kwargs = {}
    if donate:
        jit_kwargs["donate_argnums"] = (0,)
    jit_kwargs["out_shardings"] = (None, NamedSharding(mesh, P()))
    return annotate_step(jax.jit(step, **jit_kwargs), donate=donate,
                         compute_dtype=jnp.dtype(compute_dtype),
                         kind="train", spatial=True)
