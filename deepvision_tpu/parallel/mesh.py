"""Device mesh construction and sharding helpers.

TPU-native replacement for the reference's data-parallel wrappers
(`nn.DataParallel`, `ResNet/pytorch/train.py:352-355`; `tf.distribute.MirroredStrategy`,
`YOLO/tensorflow/train.py:281-294`). Instead of replicate/scatter/gather wrappers we
build a `jax.sharding.Mesh` and let GSPMD insert the collectives: the batch is sharded
over the 'data' axis (gradients all-reduce over ICI automatically), and large params
may be sharded over the 'model' axis.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
SPATIAL_AXIS = "spatial"
MODEL_AXIS = "model"


def make_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    model_parallel: int = 1,
    spatial_parallel: int = 1,
    axis_names: Optional[tuple[str, ...]] = None,
) -> Mesh:
    """Build a (data[, spatial], model) mesh over the given devices.

    With ``model_parallel=spatial_parallel=1`` this is pure data parallelism —
    the idiomatic equivalent of the reference's MirroredStrategy NCCL
    all-reduce, but over ICI.

    ``spatial_parallel>1`` adds a 'spatial' axis: activations are sharded along
    image height and GSPMD spatially partitions the convolutions, exchanging
    kernel-halo rows between neighbors over ICI. This is the vision analog of
    sequence/context parallelism — the lever for resolutions whose activations
    exceed one chip's HBM (SURVEY.md §5.7's "big activation" axis).
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    # spatial_parallel and model_parallel MAY both be >1 ("big activation" AND
    # "big param" together): XLA (jax 0.9.0) over-reduces replicated
    # conv-kernel gradients by the model-axis size on such meshes when the
    # conv's output is spatially sharded (b/433785288-adjacent GSPMD bug),
    # and the trainers compensate with a per-leaf MEASURED correction — see
    # `calibrate_grad_correction` (so an upstream fix auto-disables it).
    # Grad parity vs the single-device oracle: tests/test_spatial.py.
    if n % (model_parallel * spatial_parallel) != 0:
        raise ValueError(
            f"{n} devices not divisible by model_parallel={model_parallel} "
            f"x spatial_parallel={spatial_parallel}")
    if spatial_parallel > 1:
        shape = (n // (model_parallel * spatial_parallel), spatial_parallel,
                 model_parallel)
        names = axis_names or (DATA_AXIS, SPATIAL_AXIS, MODEL_AXIS)
    else:
        shape = (n // model_parallel, model_parallel)
        names = axis_names or (DATA_AXIS, MODEL_AXIS)
    grid = np.asarray(devices).reshape(shape)
    if spatial_parallel > 1 and jax.process_count() > 1:
        # Per-host batch assembly (make_array_from_process_local_data in
        # shard_batch_pytree) infers the global H from the number of
        # PROCESSES the 'spatial' axis spans. If a spatial column crossed
        # hosts, each host's full-height images would be silently stitched
        # as H-slices of composite garbage — reject the layout instead.
        procs = np.vectorize(lambda d: d.process_index)(grid)
        if (procs != procs[:, :1, :]).any():
            raise ValueError(
                "the 'spatial' mesh axis crosses process boundaries; pick "
                "spatial_parallel (x model_parallel) dividing the per-host "
                "device count so each spatial group stays on one host")
    return Mesh(grid, names)


def has_spatial(mesh: Mesh) -> bool:
    return SPATIAL_AXIS in mesh.axis_names and mesh.shape[SPATIAL_AXIS] > 1


# Spatial sharding floor: H is sharded over 'spatial' only while every shard
# keeps at least this many rows. Below it the parallelism is all halo (a 3x3
# conv's 1-row exchange IS the shard) and — worse — XLA's partitioner starts
# flip-flopping between batch- and H-sharded layouts in conv/BN backwards,
# logging "Involuntary full rematerialization" (a full replicate+repartition
# of a gradient tensor every step). Empirically ≥4 rows/shard keeps the
# ResNet-50 backward warning-clean on a (data, spatial) mesh; deep stages
# whose maps shrink below the floor run batch-sharded only, which is also the
# faster layout for them.
MIN_SPATIAL_ROWS = 4


def _spatial_divides(mesh: Mesh, h: int) -> bool:
    sp = mesh.shape[SPATIAL_AXIS]
    return h % sp == 0 and h // sp >= MIN_SPATIAL_ROWS


def batch_sharding(mesh: Mesh, ndim: int = 4,
                   dim1: Optional[int] = None) -> NamedSharding:
    """Shard the leading (batch) dim over 'data'; on a spatial mesh, 4-D
    arrays (NHWC images/heatmaps) also get H sharded over 'spatial';
    replicate the rest.

    Only rank-4 arrays are treated as spatial: lower-rank batch tensors
    (labels, padded box lists (B,100,4)) have no height dim. `dim1` (the
    actual H extent, when known) gates on divisibility and the
    MIN_SPATIAL_ROWS floor, so odd/tiny heights fall back to replicated-H
    rather than failing at device_put or tripping the partitioner."""
    spec = [DATA_AXIS] + [None] * (ndim - 1)
    if ndim == 4 and has_spatial(mesh) and (
            dim1 is None or _spatial_divides(mesh, dim1)):
        spec[1] = SPATIAL_AXIS
    return NamedSharding(mesh, P(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch_pytree(mesh: Mesh, batch):
    """Device-put a host pytree of arrays with the batch dim sharded over 'data'
    (and H over 'spatial' for NHWC arrays on a spatial mesh).

    Multi-process: each array holds this PROCESS's batch rows (the per-host
    pipeline's shard; global batch = rows × process_count), assembled with
    `make_array_from_process_local_data`. Plain `device_put` of a host array
    onto a cross-process sharding would instead treat it as a GLOBAL value
    and allgather-assert equality across hosts — wrong for per-host data, a
    hidden per-batch DCN collective, and deadlock-prone off the main thread
    (the prefetch producer racing the Orbax save barrier)."""
    multiprocess = jax.process_count() > 1

    def _put(x):
        x = np.asarray(x)
        dim1 = x.shape[1] if x.ndim > 1 else None
        sharding = batch_sharding(mesh, x.ndim, dim1=dim1)
        # a fully-addressable mesh (e.g. the process-local calibration
        # oracle on a pod, trainer._verify_correction_at_production_batch)
        # holds a GLOBAL value this process owns outright — plain device_put,
        # even on multi-process runs
        if multiprocess and not sharding.is_fully_addressable:
            return jax.make_array_from_process_local_data(sharding, x)
        return jax.device_put(x, sharding)
    return jax.tree_util.tree_map(_put, batch)


def spatial_activation_constraints(mesh: Optional[Mesh]):
    """Context manager for a model forward on a spatial mesh: pin every
    rank-4 flax module output to (data, spatial|None, None, None).

    Left to itself, GSPMD propagates the input's H-sharding into the deep
    stages where feature maps have shrunk below MIN_SPATIAL_ROWS per shard,
    then cannot represent the layout it wants in the conv/BN backward and
    falls back to "Involuntary full rematerialization" — replicating a
    gradient tensor and re-partitioning it every step. Intercepting every
    module boundary makes the layout an explicit contract: H stays sharded
    exactly while it's worth sharding, and the transition to batch-only
    happens at a module edge the partitioner handles efficiently.

    No-op (nullcontext) on non-spatial meshes — model-parallel layouts are
    chosen by `param_sharding_rules` and need no activation pinning."""
    import contextlib
    if mesh is None or not has_spatial(mesh):
        return contextlib.nullcontext()
    import flax.linen as nn

    def _constrain(x):
        if not isinstance(x, jax.Array) or x.ndim != 4:
            return x
        # batch_sharding owns the spatial-layout policy (floor + divisibility)
        return jax.lax.with_sharding_constraint(
            x, batch_sharding(mesh, 4, dim1=x.shape[1]))

    def interceptor(next_fun, args, kwargs, context):
        out = next_fun(*args, **kwargs)
        return jax.tree_util.tree_map(
            _constrain, out, is_leaf=lambda v: isinstance(v, jax.Array))

    return nn.intercept_methods(interceptor)


def needs_conv_grad_fix(mesh: Optional[Mesh]) -> bool:
    """True on combined spatial×model meshes — the layouts where XLA
    over-reduces replicated conv-kernel grads (see
    `calibrate_grad_correction`)."""
    return (mesh is not None and has_spatial(mesh)
            and dict(mesh.shape).get(MODEL_AXIS, 1) > 1)


def apply_grad_correction(grads, correction):
    """Divide each grad leaf by its measured over-reduction factor
    (`calibrate_grad_correction`). No-op when correction is None. The
    divisors are Python floats closed over at trace time — XLA folds the
    (mostly 1.0) divisions away."""
    if correction is None:
        return grads
    return jax.tree_util.tree_map(lambda g, f: g if f == 1.0 else g / f,
                                  grads, correction)


def calibrate_grad_correction(run_one_step, mesh: Mesh, *,
                              norm_rtol: float = 0.2):
    """MEASURE the per-leaf gradient over-reduction of an actual model on a
    combined spatial×model mesh; return a per-leaf divisor pytree for
    `apply_grad_correction` (None when no leaf needs correcting).

    GSPMD (jax 0.9.0) inserts a spurious model-axis psum into SOME gradient
    computations when activations are spatially sharded — and which ops are
    hit is context-dependent: within one ResNet-50, seven of eight 1x1
    bottleneck convs came back over-reduced and the eighth (`proj`) did not,
    while an isolated 1x1 probe measured no over-reduction at all. No
    archetype probe can predict that, so the correction is calibrated on the
    WHOLE model: `run_one_step(m)` must run ONE seeded train step from an
    identical init on mesh `m` with a LINEAR optimizer (update ∝ grad; sgd —
    adam's first step is gradient-scale-invariant and would hide the factor)
    and return `(init_params, updated_params)` pytrees. It is invoked twice:
    on the pure-DP oracle mesh (same devices, no spatial axis — grads
    provably correct, see tests/test_spatial.py) and on the target mesh,
    uncorrected. Each leaf's update-norm ratio is snapped to {1, model_size};
    anything in between (beyond norm_rtol, wide against the <=3% sync-BN
    reassociation noise) means XLA's behavior changed shape — raise rather
    than train wrong.

    Cost: two extra step compiles + two steps, once per trainer init, only
    on combined meshes. Caveat: the DP oracle replicates params, so models
    that NEED model sharding to fit don't have a runnable oracle — true of
    none of the vision models here."""
    if not needs_conv_grad_fix(mesh):
        return None
    model_size = dict(mesh.shape)[MODEL_AXIS]
    init_o, got_o = run_one_step(make_mesh(list(mesh.devices.flat)))
    init_t, got_t = run_one_step(mesh)

    rows, treedef, global_no = _update_norm_rows(
        init_o, got_o, init_t, got_t, what="grad-correction calibration")
    if global_no == 0.0:
        return None  # fully frozen / zero-grad model: nothing to correct
    # significance floor: a leaf contributing <0.1% of the global update
    # norm (<1e-6 of the squared update) is a near-cancelling sum whose
    # ratio is dominated by float reassociation across layouts (hourglass
    # biases measured 10-55% off at norms 1e-8..1e-3 while every weight
    # matched) — and a factor error there could not affect training
    # measurably anyway. Skipped unless ONE side blows past the floor.
    floor = 1e-3 * global_no
    changed = False
    factors = []
    for path, no, nt in rows:
        if no < floor and nt < floor:
            factors.append(1.0)
            continue
        r = nt / max(no, 1e-12)
        snapped = min((1.0, float(model_size)), key=lambda c: abs(r - c))
        if abs(r - snapped) > norm_rtol * snapped:
            raise RuntimeError(
                f"grad-correction calibration: leaf "
                f"{jax.tree_util.keystr(path)} update-norm ratio {r:.3f} "
                f"(target mesh {dict(mesh.shape)} / DP oracle, norms "
                f"{nt:.3g}/{no:.3g}) snaps to neither 1 nor "
                f"model_size={model_size} within {norm_rtol:.0%}. GSPMD "
                f"mis-partitions this model's gradients on this combined "
                f"spatial x model mesh in a way no uniform rescale can "
                f"correct. Train it on a (data, spatial) or (data, model) "
                f"mesh instead; both are oracle-verified paths.")
        if snapped != 1.0:
            changed = True
        factors.append(snapped)
    if not changed:
        return None
    return jax.tree_util.tree_unflatten(treedef, factors)


def _update_norm_rows(init_o, got_o, init_t, got_t, *, what: str):
    """Shared core of calibrate/verify: structure-check the four pytrees
    (positional zips silently truncate on mismatch — fail loudly instead),
    then per-leaf oracle/target update norms + the global oracle norm."""
    flat_io, treedef = jax.tree_util.tree_flatten_with_path(init_o)
    for name, tree in (("got_oracle", got_o), ("init_target", init_t),
                       ("got_target", got_t)):
        td = jax.tree_util.tree_structure(tree)
        if td != treedef:
            raise RuntimeError(
                f"{what}: {name} pytree structure differs from init_oracle "
                f"({td} vs {treedef}); per-leaf ratios would be misaligned")
    rows = []
    for (path, io), go, it, gt in zip(flat_io,
                                      jax.tree_util.tree_leaves(got_o),
                                      jax.tree_util.tree_leaves(init_t),
                                      jax.tree_util.tree_leaves(got_t)):
        no = float(np.linalg.norm(np.asarray(go) - np.asarray(io)))
        nt = float(np.linalg.norm(np.asarray(gt) - np.asarray(it)))
        rows.append((path, no, nt))
    global_no = float(np.sqrt(sum(no * no for _, no, _ in rows)))
    return rows, treedef, global_no


def verify_update_parity(oracle_pair, target_pair, *, norm_rtol: float = 0.2,
                         context: str = "") -> None:
    """Cross-check one train step on two meshes by per-leaf update norms.

    Each pair is `(init_params, updated_params)` from an identical init and
    batch under a LINEAR optimizer (update ∝ grad). Leaves below the same
    significance floor `calibrate_grad_correction` uses are skipped (their
    ratios are float-reassociation noise). Raises RuntimeError when any
    significant leaf's norm ratio leaves [1-rtol, 1+rtol] — used after
    calibration to confirm the measured factors transfer to the production
    batch shape (GSPMD's spurious psum is context-dependent)."""
    init_o, got_o = oracle_pair
    init_t, got_t = target_pair
    rows, _, global_no = _update_norm_rows(
        init_o, got_o, init_t, got_t, what=f"verify_update_parity{context}")
    if global_no == 0.0:
        return
    floor = 1e-3 * global_no
    for path, no, nt in rows:
        if no < floor and nt < floor:
            continue
        r = nt / max(no, 1e-12)
        if abs(r - 1.0) > norm_rtol:
            raise RuntimeError(
                f"update-norm parity{context}: leaf "
                f"{jax.tree_util.keystr(path)} ratio {r:.3f} (target/oracle "
                f"norms {nt:.3g}/{no:.3g}) outside 1±{norm_rtol:.0%}")


def pad_to_multiple(n: int, k: int) -> int:
    return int(math.ceil(n / k) * k)


def check_batch_divisible(batch_size: int, mesh: Mesh,
                          what: str = "batch_size") -> None:
    """Batches shard over 'data' with no padding — fail early with a remedy
    instead of a deep device_put shape error."""
    data_axis = mesh.shape[DATA_AXIS]
    if batch_size % data_axis != 0:
        down = (batch_size // data_axis) * data_axis
        nearest = max(data_axis,
                      down if batch_size - down <= data_axis // 2
                      else down + data_axis)
        raise ValueError(
            f"global {what}={batch_size} must be divisible by the mesh "
            f"data axis ({data_axis} devices); nearest valid: {nearest}")


def param_sharding_rules(mesh: Mesh, params, min_size_to_shard: int = 2**20):
    """Sharding pytree for params: for big tensors, shard the LAST axis
    (output features of conv HWIO / dense kernels) over 'model' when it
    divides, else the largest divisible axis; replicate everything else.

    When the mesh's model axis is 1 (pure DP) this degenerates to full replication,
    matching the reference's replicated-weights semantics. For wide final projections
    (e.g. the 2048x1000 ResNet-50 head) a model axis > 1 shards the weight so the
    matmul runs as a partial-K/N matmul with an all-reduce inserted by GSPMD.

    Contract (elastic resume, core/reshard.py): this is a PURE function of
    (mesh topology, leaf shapes) — no device identities, no history — so
    the same params re-place deterministically on ANY target mesh. That
    determinism is what lets a resharding restore recompute placement from
    the restore template instead of persisting device assignments in the
    checkpoint; changing the rule only changes layout, never values.
    """
    model_size = mesh.shape[MODEL_AXIS]

    def rule(x):
        if model_size == 1 or x.ndim == 0 or x.size < min_size_to_shard:
            return NamedSharding(mesh, P())
        # Prefer the LAST axis (output features for conv HWIO / dense kernels):
        # output-channel sharding propagates cleanly through the layer's
        # activations, where sharding an inner axis forces GSPMD reshards in
        # the backward pass. Fall back to the largest divisible axis.
        axes = [x.ndim - 1] + sorted(range(x.ndim - 1),
                                     key=lambda a: -x.shape[a])
        for a in axes:
            if x.shape[a] % model_size == 0:
                spec = [None] * x.ndim
                spec[a] = MODEL_AXIS
                return NamedSharding(mesh, P(*spec))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map(rule, params)


# -- predict-side (serving) placement --------------------------------------
#
# Training shards params to fit OPTIMIZER state; serving shards them to fit
# WEIGHTS — per-chip HBM is the objective and there are no gradients, so the
# sharding floor is much lower than training's 1MB: below ~1KB a leaf is
# cheaper to replicate than to manage, above it sharding is pure per-chip
# byte savings (the transfer happens once at placement, never per request).
SERVE_MIN_SHARD_BYTES = 1024


def serve_param_shardings(mesh: Mesh, variables):
    """Param placement for a mesh-sharded PredictEngine: the same pure
    (topology, leaf shapes) -> spec rule training uses, with the serve-side
    size floor. Determinism contract matters double here: hot reload and
    promotion re-place candidate weights with this same function, so equal
    shapes mean equal shardings mean the AOT bucket programs run the new
    generation as-is (zero recompiles)."""
    return param_sharding_rules(mesh, variables,
                                min_size_to_shard=SERVE_MIN_SHARD_BYTES)


def serve_shardings(mesh: Mesh, variables, example_shape: Sequence[int]):
    """The engine's full placement contract on a mesh, as
    ``(param_shardings, input_sharding, output_sharding)``:

    - params sharded over 'model' (`serve_param_shardings`),
    - the input batch over 'data' with H over 'spatial' when it divides
      (`batch_sharding` owns the floor/divisibility policy),
    - outputs fully REPLICATED — every layer above the engine boundary
      (batcher, fleet, promotion, HTTP) sees exactly the single-device
      payload; the gather is compiled into the bucket program.
    """
    h = example_shape[0] if len(example_shape) == 3 else None
    return (serve_param_shardings(mesh, variables),
            batch_sharding(mesh, ndim=1 + len(example_shape), dim1=h),
            replicated(mesh))


def per_chip_bytes(tree) -> int:
    """Largest per-device resident byte count of a placed pytree — the
    HBM-per-chip weight footprint /healthz and the mesh bench report.
    Host (numpy) leaves count in full, as a 1-chip placement would."""
    per_dev: dict = {}
    for leaf in jax.tree_util.tree_leaves(tree):
        shards = getattr(leaf, "addressable_shards", None)
        if shards:
            for sh in shards:
                per_dev[sh.device] = (per_dev.get(sh.device, 0)
                                      + sh.data.nbytes)
        else:
            per_dev[None] = (per_dev.get(None, 0)
                             + np.asarray(leaf).nbytes)
    return max(per_dev.values()) if per_dev else 0


def analytic_per_chip_bytes(shaped_tree, mesh: Optional[Mesh] = None) -> int:
    """Per-chip weight bytes of a (possibly abstract — ShapeDtypeStruct)
    variables tree under the serve placement, WITHOUT placing anything:
    drives `--list-models`' HBM-budget annotation and the mesh bench's
    largest-servable-model scan. Computed through `serve_param_shardings`
    itself, so the estimate can never drift from the real placement."""
    total = 0
    if mesh is None:
        for leaf in jax.tree_util.tree_leaves(shaped_tree):
            total += int(np.prod(leaf.shape)) * np.dtype(leaf.dtype).itemsize
        return total
    shardings = serve_param_shardings(mesh, shaped_tree)
    for leaf, sh in zip(jax.tree_util.tree_leaves(shaped_tree),
                        jax.tree_util.tree_leaves(
                            shardings,
                            is_leaf=lambda s: isinstance(s, NamedSharding))):
        nbytes = int(np.prod(leaf.shape)) * np.dtype(leaf.dtype).itemsize
        div = 1
        for axis in sh.spec:
            if axis is not None:
                div *= mesh.shape[axis]
        total += nbytes // div
    return total


_distributed_initialized = False


def maybe_init_distributed(force: bool = False) -> bool:
    """Multi-host SPMD bring-up (SURVEY.md §5.8): call
    `jax.distributed.initialize()` once per process when a multi-host launch is
    detected, so `jax.devices()` spans the pod and `process_index/count` drive
    the per-host data sharding. DCN coordination is the JAX runtime's job — no
    user-level transport code, unlike the reference's NCCL/MirroredStrategy.

    Detection: explicit coordinator env (JAX_COORDINATOR_ADDRESS /
    COORDINATOR_ADDRESS, as set by pod launchers) or `force=True` (Cloud TPU
    pods auto-discover via metadata). Safe no-op on single-host runs.
    """
    global _distributed_initialized
    if _distributed_initialized:
        return True
    import os
    if not (force or os.environ.get("JAX_COORDINATOR_ADDRESS")
            or os.environ.get("COORDINATOR_ADDRESS")):
        return False
    jax.distributed.initialize()
    _distributed_initialized = True
    return True
