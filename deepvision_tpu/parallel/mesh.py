"""Device mesh construction and sharding helpers.

TPU-native replacement for the reference's data-parallel wrappers
(`nn.DataParallel`, `ResNet/pytorch/train.py:352-355`; `tf.distribute.MirroredStrategy`,
`YOLO/tensorflow/train.py:281-294`). Instead of replicate/scatter/gather wrappers we
build a `jax.sharding.Mesh` and let GSPMD insert the collectives: the batch is sharded
over the 'data' axis (gradients all-reduce over ICI automatically), and large params
may be sharded over the 'model' axis.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    model_parallel: int = 1,
    axis_names: tuple[str, str] = (DATA_AXIS, MODEL_AXIS),
) -> Mesh:
    """Build a (data, model) 2-D mesh over the given devices.

    With ``model_parallel=1`` this is pure data parallelism — the idiomatic
    equivalent of the reference's MirroredStrategy NCCL all-reduce, but over ICI.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if n % model_parallel != 0:
        raise ValueError(f"{n} devices not divisible by model_parallel={model_parallel}")
    grid = np.asarray(devices).reshape(n // model_parallel, model_parallel)
    return Mesh(grid, axis_names)


def batch_sharding(mesh: Mesh, ndim: int = 4) -> NamedSharding:
    """Shard the leading (batch) dim over 'data'; replicate the rest."""
    return NamedSharding(mesh, P(DATA_AXIS, *([None] * (ndim - 1))))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch_pytree(mesh: Mesh, batch):
    """Device-put a host pytree of arrays with the batch dim sharded over 'data'."""
    def _put(x):
        x = np.asarray(x)
        return jax.device_put(x, NamedSharding(mesh, P(DATA_AXIS, *([None] * (x.ndim - 1)))))
    return jax.tree_util.tree_map(_put, batch)


def pad_to_multiple(n: int, k: int) -> int:
    return int(math.ceil(n / k) * k)


def param_sharding_rules(mesh: Mesh, params, min_size_to_shard: int = 2**20):
    """Sharding pytree for params: shard the largest axis of big tensors over 'model',
    replicate everything else.

    When the mesh's model axis is 1 (pure DP) this degenerates to full replication,
    matching the reference's replicated-weights semantics. For wide final projections
    (e.g. the 2048x1000 ResNet-50 head) a model axis > 1 shards the weight so the
    matmul runs as a partial-K/N matmul with an all-reduce inserted by GSPMD.
    """
    model_size = mesh.shape[MODEL_AXIS]

    def rule(x):
        if model_size == 1 or x.ndim == 0 or x.size < min_size_to_shard:
            return NamedSharding(mesh, P())
        # shard the largest divisible axis over 'model'
        axes = sorted(range(x.ndim), key=lambda a: -x.shape[a])
        for a in axes:
            if x.shape[a] % model_size == 0:
                spec = [None] * x.ndim
                spec[a] = MODEL_AXIS
                return NamedSharding(mesh, P(*spec))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map(rule, params)


_distributed_initialized = False


def maybe_init_distributed(force: bool = False) -> bool:
    """Multi-host SPMD bring-up (SURVEY.md §5.8): call
    `jax.distributed.initialize()` once per process when a multi-host launch is
    detected, so `jax.devices()` spans the pod and `process_index/count` drive
    the per-host data sharding. DCN coordination is the JAX runtime's job — no
    user-level transport code, unlike the reference's NCCL/MirroredStrategy.

    Detection: explicit coordinator env (JAX_COORDINATOR_ADDRESS /
    COORDINATOR_ADDRESS, as set by pod launchers) or `force=True` (Cloud TPU
    pods auto-discover via metadata). Safe no-op on single-host runs.
    """
    global _distributed_initialized
    if _distributed_initialized:
        return True
    import os
    if not (force or os.environ.get("JAX_COORDINATOR_ADDRESS")
            or os.environ.get("COORDINATOR_ADDRESS")):
        return False
    jax.distributed.initialize()
    _distributed_initialized = True
    return True
