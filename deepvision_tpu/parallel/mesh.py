"""Device mesh construction and sharding helpers.

TPU-native replacement for the reference's data-parallel wrappers
(`nn.DataParallel`, `ResNet/pytorch/train.py:352-355`; `tf.distribute.MirroredStrategy`,
`YOLO/tensorflow/train.py:281-294`). Instead of replicate/scatter/gather wrappers we
build a `jax.sharding.Mesh` and let GSPMD insert the collectives: the batch is sharded
over the 'data' axis (gradients all-reduce over ICI automatically), and large params
may be sharded over the 'model' axis.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
SPATIAL_AXIS = "spatial"
MODEL_AXIS = "model"


def make_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    model_parallel: int = 1,
    spatial_parallel: int = 1,
    axis_names: Optional[tuple[str, ...]] = None,
) -> Mesh:
    """Build a (data[, spatial], model) mesh over the given devices.

    With ``model_parallel=spatial_parallel=1`` this is pure data parallelism —
    the idiomatic equivalent of the reference's MirroredStrategy NCCL
    all-reduce, but over ICI.

    ``spatial_parallel>1`` adds a 'spatial' axis: activations are sharded along
    image height and GSPMD spatially partitions the convolutions, exchanging
    kernel-halo rows between neighbors over ICI. This is the vision analog of
    sequence/context parallelism — the lever for resolutions whose activations
    exceed one chip's HBM (SURVEY.md §5.7's "big activation" axis).
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    # spatial_parallel and model_parallel MAY both be >1 ("big activation" AND
    # "big param" together): XLA (jax 0.9.0) over-reduces replicated
    # conv-kernel gradients by the model-axis size on such meshes when the
    # conv's output is spatially sharded (b/433785288-adjacent GSPMD bug),
    # and the train-step builders compensate — see
    # `rescale_overreduced_conv_grads` + `conv_grad_overreduction_factor`
    # (measured at runtime, so an upstream fix auto-disables the correction).
    # Grad parity vs the single-device oracle: tests/test_spatial.py.
    if n % (model_parallel * spatial_parallel) != 0:
        raise ValueError(
            f"{n} devices not divisible by model_parallel={model_parallel} "
            f"x spatial_parallel={spatial_parallel}")
    if spatial_parallel > 1:
        shape = (n // (model_parallel * spatial_parallel), spatial_parallel,
                 model_parallel)
        names = axis_names or (DATA_AXIS, SPATIAL_AXIS, MODEL_AXIS)
    else:
        shape = (n // model_parallel, model_parallel)
        names = axis_names or (DATA_AXIS, MODEL_AXIS)
    grid = np.asarray(devices).reshape(shape)
    if spatial_parallel > 1 and jax.process_count() > 1:
        # Per-host batch assembly (make_array_from_process_local_data in
        # shard_batch_pytree) infers the global H from the number of
        # PROCESSES the 'spatial' axis spans. If a spatial column crossed
        # hosts, each host's full-height images would be silently stitched
        # as H-slices of composite garbage — reject the layout instead.
        procs = np.vectorize(lambda d: d.process_index)(grid)
        if (procs != procs[:, :1, :]).any():
            raise ValueError(
                "the 'spatial' mesh axis crosses process boundaries; pick "
                "spatial_parallel (x model_parallel) dividing the per-host "
                "device count so each spatial group stays on one host")
    return Mesh(grid, names)


def has_spatial(mesh: Mesh) -> bool:
    return SPATIAL_AXIS in mesh.axis_names and mesh.shape[SPATIAL_AXIS] > 1


# Spatial sharding floor: H is sharded over 'spatial' only while every shard
# keeps at least this many rows. Below it the parallelism is all halo (a 3x3
# conv's 1-row exchange IS the shard) and — worse — XLA's partitioner starts
# flip-flopping between batch- and H-sharded layouts in conv/BN backwards,
# logging "Involuntary full rematerialization" (a full replicate+repartition
# of a gradient tensor every step). Empirically ≥4 rows/shard keeps the
# ResNet-50 backward warning-clean on a (data, spatial) mesh; deep stages
# whose maps shrink below the floor run batch-sharded only, which is also the
# faster layout for them.
MIN_SPATIAL_ROWS = 4


def _spatial_divides(mesh: Mesh, h: int) -> bool:
    sp = mesh.shape[SPATIAL_AXIS]
    return h % sp == 0 and h // sp >= MIN_SPATIAL_ROWS


def batch_sharding(mesh: Mesh, ndim: int = 4,
                   dim1: Optional[int] = None) -> NamedSharding:
    """Shard the leading (batch) dim over 'data'; on a spatial mesh, 4-D
    arrays (NHWC images/heatmaps) also get H sharded over 'spatial';
    replicate the rest.

    Only rank-4 arrays are treated as spatial: lower-rank batch tensors
    (labels, padded box lists (B,100,4)) have no height dim. `dim1` (the
    actual H extent, when known) gates on divisibility and the
    MIN_SPATIAL_ROWS floor, so odd/tiny heights fall back to replicated-H
    rather than failing at device_put or tripping the partitioner."""
    spec = [DATA_AXIS] + [None] * (ndim - 1)
    if ndim == 4 and has_spatial(mesh) and (
            dim1 is None or _spatial_divides(mesh, dim1)):
        spec[1] = SPATIAL_AXIS
    return NamedSharding(mesh, P(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch_pytree(mesh: Mesh, batch):
    """Device-put a host pytree of arrays with the batch dim sharded over 'data'
    (and H over 'spatial' for NHWC arrays on a spatial mesh).

    Multi-process: each array holds this PROCESS's batch rows (the per-host
    pipeline's shard; global batch = rows × process_count), assembled with
    `make_array_from_process_local_data`. Plain `device_put` of a host array
    onto a cross-process sharding would instead treat it as a GLOBAL value
    and allgather-assert equality across hosts — wrong for per-host data, a
    hidden per-batch DCN collective, and deadlock-prone off the main thread
    (the prefetch producer racing the Orbax save barrier)."""
    multiprocess = jax.process_count() > 1

    def _put(x):
        x = np.asarray(x)
        dim1 = x.shape[1] if x.ndim > 1 else None
        sharding = batch_sharding(mesh, x.ndim, dim1=dim1)
        if multiprocess:
            return jax.make_array_from_process_local_data(sharding, x)
        return jax.device_put(x, sharding)
    return jax.tree_util.tree_map(_put, batch)


def spatial_activation_constraints(mesh: Optional[Mesh],
                                   record: Optional[set] = None):
    """Context manager for a model forward on a spatial mesh: pin every
    rank-4 flax module output to (data, spatial|None, None, None).

    Left to itself, GSPMD propagates the input's H-sharding into the deep
    stages where feature maps have shrunk below MIN_SPATIAL_ROWS per shard,
    then cannot represent the layout it wants in the conv/BN backward and
    falls back to "Involuntary full rematerialization" — replicating a
    gradient tensor and re-partitioning it every step. Intercepting every
    module boundary makes the layout an explicit contract: H stays sharded
    exactly while it's worth sharding, and the transition to batch-only
    happens at a module edge the partitioner handles efficiently.

    `record` (a set, combined spatial×model meshes only): collects
    `(module_path, kind)` for every conv-like module (owns a rank-4 'kernel'
    param) whose output gets pinned spatial-sharded — exactly the kernels
    whose gradients XLA over-reduces by the model-axis size (see
    `rescale_overreduced_conv_grads`). `kind` distinguishes ConvTranspose
    from regular convs because the over-reduction factor is probed per
    primitive family (`conv_grad_overreduction_factor`). Filled at trace
    time.

    No-op (nullcontext) on non-spatial meshes — model-parallel layouts are
    chosen by `param_sharding_rules` and need no activation pinning."""
    import contextlib
    if mesh is None or not has_spatial(mesh):
        return contextlib.nullcontext()
    import flax.linen as nn

    def _constrain(x):
        if not isinstance(x, jax.Array) or x.ndim != 4:
            return x
        # batch_sharding owns the spatial-layout policy (floor + divisibility)
        return jax.lax.with_sharding_constraint(
            x, batch_sharding(mesh, 4, dim1=x.shape[1]))

    def _any_spatial_sharded(tree) -> bool:
        return any(isinstance(v, jax.Array) and v.ndim == 4
                   and _spatial_divides(mesh, v.shape[1])
                   for v in jax.tree_util.tree_leaves(tree))

    def interceptor(next_fun, args, kwargs, context):
        out = next_fun(*args, **kwargs)
        # Over-reduction (measured, see conv_grad_overreduction_factor) hits
        # a conv kernel iff BOTH its input and its output carry the spatial
        # sharding; a conv entered or exited below the floor computes its
        # grad on replicated-H operands and is reduced correctly. (A conv
        # fed through a non-module gap — resize/reshape — has no pinned
        # input; GSPMD shards such a gap whenever H divides, which is what
        # the H-divisibility test on the raw input argument predicts.)
        if (record is not None and _any_spatial_sharded(args)
                and _any_spatial_sharded(out)
                and context.module.has_variable("params", "kernel")
                and context.module.get_variable("params", "kernel").ndim == 4):
            kind = ("conv_transpose"
                    if isinstance(context.module, nn.ConvTranspose)
                    else "conv")
            record.add((context.module.path, kind))
        return jax.tree_util.tree_map(
            _constrain, out, is_leaf=lambda v: isinstance(v, jax.Array))

    return nn.intercept_methods(interceptor)


def needs_conv_grad_fix(mesh: Optional[Mesh]) -> bool:
    """True on combined spatial×model meshes — the layouts where XLA
    over-reduces replicated conv-kernel grads (see
    `conv_grad_overreduction_factor`)."""
    return (mesh is not None and has_spatial(mesh)
            and dict(mesh.shape).get(MODEL_AXIS, 1) > 1)


_overreduction_cache: dict = {}


NO_CONV_GRAD_FIX = {"conv": 1.0, "conv_transpose": 1.0}


def conv_grad_overreduction_factor(mesh: Optional[Mesh]) -> dict:
    """Measure XLA's conv-kernel gradient over-reduction on this mesh,
    per primitive family: `{"conv": factor, "conv_transpose": factor}`.

    On a combined (data, spatial, model) mesh, GSPMD (jax 0.9.0) reduces the
    gradient of a REPLICATED conv kernel over the model axis too whenever the
    conv's output is spatially sharded — each model shard already holds the
    full gradient, so it comes back model_size× too large. Rather than
    hard-coding the bug, tiny probes measure the actual factor once per mesh
    shape (cached): when a future XLA fixes the reduction, the probes return
    1.0 and the correction in `rescale_overreduced_conv_grads` disappears
    with it.

    Probed archetypes (one per way the partitioner can treat the backward):
    a stride-1 conv; a stride-2 conv (the downsampling family — most of the
    kernels actually recorded in practice; its kernel-grad lowers through an
    rhs-dilated backward), a grouped conv (feature_group_count, the depthwise
    family) and a dilated conv, all three REQUIRED to match the stride-1
    conv's factor — the rescale classifies every nn.Conv under "conv", so a
    variant with a different factor would silently mistrain and must raise
    instead; and a stride-2 ConvTranspose (the upsampling family:
    Hourglass/GAN decoders), measured separately because
    `lax.conv_transpose` lowers through a different (lhs-dilated)
    backward."""
    if mesh is None or not needs_conv_grad_fix(mesh):
        return dict(NO_CONV_GRAD_FIX)
    key = (tuple(sorted(mesh.shape.items())),
           tuple(d.id for d in mesh.devices.flat))
    if key in _overreduction_cache:
        return _overreduction_cache[key]
    import jax.numpy as jnp
    from jax import lax

    import numpy as np_

    sp = mesh.shape[SPATIAL_AXIS]
    h = sp * MIN_SPATIAL_ROWS  # smallest H the floor keeps spatial-sharded
    batch = mesh.shape[DATA_AXIS]
    model_size = mesh.shape[MODEL_AXIS]
    out_ch = 2 * model_size  # divisible, so the O-sharded probe is valid
    dn = ("NHWC", "HWIO", "NHWC")

    def probe(what, op, in_ch, out_h, k_in=None, in_h=None,
              check_sharded_layout=True):
        """Median grad ratio (sharded run / unsharded oracle) for one conv
        archetype, measured for both kernel layouts the train steps produce:
        replicated (the common case) and model-sharded via
        param_sharding_rules (large kernels). The rescale is only valid if
        they agree — a layout-dependent factor would corrupt exactly one
        class of kernels, so disagreement raises. `check_sharded_layout=False`
        measures the replicated layout only — used by the grouped/dilated
        family guards, where the O-sharded grouped probe would itself trip an
        involuntary-remat fallback (pure probe noise) and the plain-conv
        probe already covers layout agreement."""
        k_in = in_ch if k_in is None else k_in  # in_ch // groups for grouped
        in_h = h if in_h is None else in_h  # 2h for the strided probe, so
        x = jnp.linspace(-1.0, 1.0,          # its output stays above the floor
                         batch * in_h * in_h * in_ch,
                         dtype=jnp.float32).reshape(batch, in_h, in_h, in_ch)
        k = jnp.linspace(-0.5, 0.5, 3 * 3 * k_in * out_ch,
                         dtype=jnp.float32).reshape(3, 3, k_in, out_ch)

        def grad_of_kernel(x, k, constrain):
            def f(k):
                y = op(x, k)
                if constrain:
                    y = jax.lax.with_sharding_constraint(
                        y, batch_sharding(mesh, 4, dim1=out_h))
                return jnp.sum(y * y)
            return jax.grad(f)(k)

        oracle = np_.asarray(jax.jit(grad_of_kernel,
                                     static_argnums=2)(x, k, False))
        xs = jax.device_put(x, batch_sharding(mesh, 4, dim1=in_h))
        nz = np_.abs(oracle) > 1e-6

        def measure(kernel_sharding):
            ks = jax.device_put(k, kernel_sharding)
            m = np_.asarray(jax.jit(grad_of_kernel,
                                    static_argnums=2)(xs, ks, True))
            return float(np_.median(
                m.ravel()[nz.ravel()] / oracle.ravel()[nz.ravel()]))

        measured_repl = measure(replicated(mesh))
        measured_shrd = (measure(
            NamedSharding(mesh, P(None, None, None, MODEL_AXIS)))
            if check_sharded_layout else measured_repl)
        # snap to the nearest integer: the bug is an extra whole-axis psum,
        # so real factors are 1 or the model-axis size — anything else means
        # the probe itself broke (e.g. a future XLA sharding the probe grad
        # some third way), and dividing grads by it would corrupt training
        factor = float(round(measured_repl))
        if factor not in (1.0, float(model_size)) or \
                round(measured_shrd) != factor:
            raise RuntimeError(
                f"{what} grad over-reduction probe measured "
                f"{measured_repl:.4f} (replicated kernel) / "
                f"{measured_shrd:.4f} (model-sharded kernel) on mesh "
                f"{dict(mesh.shape)} — expected both 1 (fixed upstream) or "
                f"both {model_size} (known GSPMD bug). The XLA behavior has "
                f"changed; re-verify tests/test_spatial.py's combined-mesh "
                f"oracle before training on this mesh.")
        return factor

    def conv(x, k, **kw):
        return lax.conv_general_dilated(
            x, k, window_strides=(1, 1), padding="SAME",
            dimension_numbers=dn, **kw)

    f_conv = probe("conv", conv, in_ch=2, out_h=h)
    for what, op, k_in, in_h, check_sharded in (
            # strided: full layout check — real networks model-shard big
            # downsampling kernels, and its O-sharded probe is remat-clean
            ("strided-conv",
             lambda x, k: lax.conv_general_dilated(
                 x, k, window_strides=(2, 2), padding="SAME",
                 dimension_numbers=dn), 2, 2 * h, True),
            ("grouped-conv",
             lambda x, k: conv(x, k, feature_group_count=2), 1, None, False),
            ("dilated-conv",
             lambda x, k: conv(x, k, rhs_dilation=(2, 2)), 2, None, False)):
        f = probe(what, op, in_ch=2, out_h=h, k_in=k_in, in_h=in_h,
                  check_sharded_layout=check_sharded)
        if f != f_conv:
            raise RuntimeError(
                f"{what} grad over-reduction factor {f} != plain conv's "
                f"{f_conv} on mesh {dict(mesh.shape)}: the uniform 'conv' "
                f"rescale class would mistrain these kernels. Do not train "
                f"on this mesh until the rescale distinguishes them.")
    f_ct = probe(
        "conv_transpose",
        lambda x, k: lax.conv_transpose(x, k, strides=(2, 2), padding="SAME",
                                        dimension_numbers=dn),
        in_ch=2, out_h=2 * h)
    factors = {"conv": f_conv, "conv_transpose": f_ct}
    _overreduction_cache[key] = factors
    return factors


def rescale_overreduced_conv_grads(grads, records, factors: dict):
    """Divide the conv-kernel grads recorded by
    `spatial_activation_constraints(record=...)` — entries are
    `(module_path, kind)` — by the factor measured for that kind. No-op when
    every factor is 1.0 (bug fixed upstream) or nothing was recorded."""
    if not records or all(f == 1.0 for f in factors.values()):
        return grads
    from flax.core import FrozenDict, freeze, unfreeze
    was_frozen = isinstance(grads, FrozenDict)
    g = unfreeze(grads)
    for path, kind in records:
        factor = factors[kind]
        if factor == 1.0:
            continue
        node = g
        for name in path:
            node = node[name]
        node["kernel"] = node["kernel"] / factor
    return freeze(g) if was_frozen else g


def pad_to_multiple(n: int, k: int) -> int:
    return int(math.ceil(n / k) * k)


def check_batch_divisible(batch_size: int, mesh: Mesh,
                          what: str = "batch_size") -> None:
    """Batches shard over 'data' with no padding — fail early with a remedy
    instead of a deep device_put shape error."""
    data_axis = mesh.shape[DATA_AXIS]
    if batch_size % data_axis != 0:
        down = (batch_size // data_axis) * data_axis
        nearest = max(data_axis,
                      down if batch_size - down <= data_axis // 2
                      else down + data_axis)
        raise ValueError(
            f"global {what}={batch_size} must be divisible by the mesh "
            f"data axis ({data_axis} devices); nearest valid: {nearest}")


def param_sharding_rules(mesh: Mesh, params, min_size_to_shard: int = 2**20):
    """Sharding pytree for params: for big tensors, shard the LAST axis
    (output features of conv HWIO / dense kernels) over 'model' when it
    divides, else the largest divisible axis; replicate everything else.

    When the mesh's model axis is 1 (pure DP) this degenerates to full replication,
    matching the reference's replicated-weights semantics. For wide final projections
    (e.g. the 2048x1000 ResNet-50 head) a model axis > 1 shards the weight so the
    matmul runs as a partial-K/N matmul with an all-reduce inserted by GSPMD.
    """
    model_size = mesh.shape[MODEL_AXIS]

    def rule(x):
        if model_size == 1 or x.ndim == 0 or x.size < min_size_to_shard:
            return NamedSharding(mesh, P())
        # Prefer the LAST axis (output features for conv HWIO / dense kernels):
        # output-channel sharding propagates cleanly through the layer's
        # activations, where sharding an inner axis forces GSPMD reshards in
        # the backward pass. Fall back to the largest divisible axis.
        axes = [x.ndim - 1] + sorted(range(x.ndim - 1),
                                     key=lambda a: -x.shape[a])
        for a in axes:
            if x.shape[a] % model_size == 0:
                spec = [None] * x.ndim
                spec[a] = MODEL_AXIS
                return NamedSharding(mesh, P(*spec))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map(rule, params)


_distributed_initialized = False


def maybe_init_distributed(force: bool = False) -> bool:
    """Multi-host SPMD bring-up (SURVEY.md §5.8): call
    `jax.distributed.initialize()` once per process when a multi-host launch is
    detected, so `jax.devices()` spans the pod and `process_index/count` drive
    the per-host data sharding. DCN coordination is the JAX runtime's job — no
    user-level transport code, unlike the reference's NCCL/MirroredStrategy.

    Detection: explicit coordinator env (JAX_COORDINATOR_ADDRESS /
    COORDINATOR_ADDRESS, as set by pod launchers) or `force=True` (Cloud TPU
    pods auto-discover via metadata). Safe no-op on single-host runs.
    """
    global _distributed_initialized
    if _distributed_initialized:
        return True
    import os
    if not (force or os.environ.get("JAX_COORDINATOR_ADDRESS")
            or os.environ.get("COORDINATOR_ADDRESS")):
        return False
    jax.distributed.initialize()
    _distributed_initialized = True
    return True
