"""Closed-jaxpr walking: equation iteration, collectives, and the cost model.

Everything operates on abstract values only — shapes and dtypes from a
`jit(...).trace(...)` of the real step on `ShapeDtypeStruct` inputs — so a
whole-registry sweep costs zero FLOPs and no device memory.
"""

from __future__ import annotations

import math
from typing import Dict, Iterator, List, Tuple

import numpy as np
from jax.core import ClosedJaxpr, Jaxpr, JaxprEqn, Literal

# Collective primitives a jaxpr can carry explicitly (shard_map/pmap
# regions). GSPMD-partitioned jitted steps never contain these — the
# partitioner inserts its collectives at compile time — which is exactly
# what the COLL single-program check pins.
COLLECTIVE_PRIMS = frozenset({
    "psum", "ppermute", "pmax", "pmin", "all_gather", "all_to_all",
    "reduce_scatter", "psum_scatter", "pgather", "pbroadcast",
})

# Heavy compute: the MXU-shaped equations whose dtype IS the compute policy.
HEAVY_PRIMS = frozenset({"conv_general_dilated", "dot_general"})


def pallas_grid_size(eqn: JaxprEqn) -> int:
    """Total program count of a pallas_call: prod of its grid axes (1 for a
    gridless call). The kernel body runs once per program, so this is the
    trip multiplier for every equation inside it — the exact analog of
    scan's `length`."""
    gm = eqn.params.get("grid_mapping")
    grid = getattr(gm, "grid", ()) or ()
    size = 1
    for g in grid:
        size *= int(g) if isinstance(g, (int, np.integer)) else 1
    return size


def pallas_block_bytes(eqn: JaxprEqn) -> int:
    """HBM traffic of a pallas_call under the walker's fusion-blind proxy:
    per grid program, each operand/result block is DMAed between HBM and
    VMEM once — grid_size × Σ prod(block_shape)·itemsize over the block
    mappings. Everything INSIDE the kernel (score tiles, running softmax
    stats) lives in VMEM/registers and never touches HBM, which is the whole
    point of fusing — so kernel-body equations contribute zero bytes and the
    call's cost is exactly its block transfers."""
    gm = eqn.params.get("grid_mapping")
    size = pallas_grid_size(eqn)
    total = 0
    for bm in getattr(gm, "block_mappings", ()) or ():
        shape = tuple(int(d) if isinstance(d, (int, np.integer)) else 1
                      for d in getattr(bm, "block_shape", ()) or ())
        sds = getattr(bm, "array_shape_dtype", None)
        dtype = getattr(sds, "dtype", None)
        try:
            itemsize = np.dtype(dtype).itemsize if dtype is not None else 4
        except TypeError:
            itemsize = 4
        total += int(math.prod(shape)) * itemsize
    return size * total


def _sub_jaxprs(eqn: JaxprEqn) -> Iterator[Tuple[Jaxpr, int, bool]]:
    """(inner jaxpr, trip multiplier, is_pallas_kernel) triples nested in an
    equation's params. scan bodies multiply by `length`; pallas kernel bodies
    multiply by the grid size (one run per grid program); everything else
    counts once (while bodies have no static trip count — counted once, an
    explicit floor)."""
    name = eqn.primitive.name
    if name == "scan":
        mult, kernel = eqn.params.get("length", 1), False
    elif name == "pallas_call":
        mult, kernel = pallas_grid_size(eqn), True
    else:
        mult, kernel = 1, False
    for value in eqn.params.values():
        for item in (value if isinstance(value, (list, tuple)) else (value,)):
            if isinstance(item, ClosedJaxpr):
                yield item.jaxpr, mult, kernel
            elif isinstance(item, Jaxpr):
                yield item, mult, kernel


def iter_eqns(jaxpr: Jaxpr, _mult: int = 1,
              _in_kernel: bool = False) -> Iterator[Tuple[JaxprEqn, int, bool]]:
    """Depth-first (eqn, trip multiplier, inside-pallas-kernel) over a jaxpr
    and every nested sub-jaxpr (pjit bodies, scan/while/cond, custom_vjp,
    remat, pallas kernel bodies)."""
    for eqn in jaxpr.eqns:
        yield eqn, _mult, _in_kernel
        for sub, mult, kernel in _sub_jaxprs(eqn):
            yield from iter_eqns(sub, _mult * mult, _in_kernel or kernel)


def _aval_bytes(aval) -> int:
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    try:
        itemsize = np.dtype(dtype).itemsize
    except TypeError:
        # extended dtypes (typed PRNG keys, `key<fry>`) have no numpy
        # equivalent; their physical payload is a pair of uint32s
        itemsize = 8
    return int(math.prod(shape)) * itemsize


def _axes_of(eqn: JaxprEqn) -> Tuple[str, ...]:
    """Normalized mesh-axis tuple of a collective equation."""
    axes = (eqn.params.get("axes") or eqn.params.get("axis_name")
            or eqn.params.get("axis_names") or ())
    if isinstance(axes, str):
        axes = (axes,)
    return tuple(str(a) for a in axes)


def collect_collectives(closed: ClosedJaxpr) -> Dict[Tuple[str, Tuple[str, ...]], int]:
    """{(primitive, axes): count} over the whole (nested) jaxpr."""
    out: Dict[Tuple[str, Tuple[str, ...]], int] = {}
    for eqn, mult, _in_kernel in iter_eqns(closed.jaxpr):
        if eqn.primitive.name in COLLECTIVE_PRIMS:
            key = (eqn.primitive.name, _axes_of(eqn))
            out[key] = out.get(key, 0) + mult
    return out


def _conv_flops(eqn: JaxprEqn) -> int:
    """2 * |out| * taps-per-output for conv_general_dilated, taps =
    kernel_spatial_elems * C_in / feature_groups, read off the rhs shape via
    the equation's dimension numbers."""
    out = eqn.outvars[0].aval
    dnums = eqn.params["dimension_numbers"]
    rhs = eqn.invars[1].aval.shape
    spatial = [rhs[d] for d in dnums.rhs_spec[2:]]
    c_in = rhs[dnums.rhs_spec[1]]  # per-group input channels
    return 2 * int(math.prod(out.shape)) * int(math.prod(spatial)) * int(c_in)


def _dot_flops(eqn: JaxprEqn) -> int:
    """2 * |out| * K for dot_general (K = product of lhs contracting dims)."""
    out = eqn.outvars[0].aval
    (lhs_c, _), _ = eqn.params["dimension_numbers"]
    lhs = eqn.invars[0].aval.shape
    k = math.prod(lhs[d] for d in lhs_c) if lhs_c else 1
    return 2 * int(math.prod(out.shape)) * int(k)


def heavy_eqns(closed: ClosedJaxpr) -> List[Tuple[JaxprEqn, int, int, bool]]:
    """(eqn, trip multiplier, flops, inside-pallas-kernel) for every
    conv/dot in the jaxpr — including dots inside pallas kernel bodies,
    whose multiplier carries the grid size (each program contracts one tile,
    so grid × tile-flops is the kernel's true MXU work and fused COST rows
    stay comparable to naive ones). The kernel flag lets policy rules
    (DTYPE) treat in-VMEM register precision separately from HBM-visible
    compute."""
    out = []
    for eqn, mult, in_kernel in iter_eqns(closed.jaxpr):
        name = eqn.primitive.name
        if name not in HEAVY_PRIMS:
            continue
        flops = _conv_flops(eqn) if name == "conv_general_dilated" \
            else _dot_flops(eqn)
        out.append((eqn, mult, flops, in_kernel))
    return out


def cost_summary(closed: ClosedJaxpr) -> Dict[str, int]:
    """The jaxvet cost model of one traced step.

    - `flops`: 2*MACs summed over every conv/dot (the MXU work; elementwise
      ops are noise next to it and fuse anyway).
    - `bytes`: every equation's operand + result footprint summed — a
      deliberately fusion-blind upper proxy. The ABSOLUTE number overcounts
      what a compiled program moves through HBM (XLA fuses elementwise
      chains); the DIFF between two revisions of the same step is exactly
      the signal BENCH chases (r05: bf16 BN/residual joins cut cost-model
      bytes 8.3%), and this proxy moves with it deterministically.
    - `eqns`: equation count (trip-weighted) — a retrace/graph-bloat canary.

    Literals (inline scalars) are skipped; consts are counted once via the
    outer jaxpr's constvars.

    pallas_call is NOT an opaque zero-cost call: its kernel body contributes
    grid-weighted FLOPs and equation counts like any scan body, but zero
    bytes — everything inside the kernel lives in VMEM/registers. The call
    itself is charged its block transfers (`pallas_block_bytes`): per grid
    program, each operand/result block crosses HBM↔VMEM once. That is what
    makes a fused-attention COST row comparable to the naive lowering's — the
    naive (N, N) softmax chain is charged at every equation, the kernel only
    at its tile DMAs.
    """
    flops = 0
    nbytes = 0
    n_eqns = 0
    for eqn, mult, in_kernel in iter_eqns(closed.jaxpr):
        n_eqns += mult
        if in_kernel:
            continue  # VMEM traffic, not HBM — charged via the block DMAs
        if eqn.primitive.name == "pallas_call":
            nbytes += mult * pallas_block_bytes(eqn)
            continue
        io = sum(_aval_bytes(v.aval) for v in eqn.invars
                 if not isinstance(v, Literal))
        io += sum(_aval_bytes(v.aval) for v in eqn.outvars)
        nbytes += mult * io
    for _eqn, mult, f, _in_kernel in heavy_eqns(closed):
        flops += mult * f
    nbytes += sum(_aval_bytes(v.aval) for v in closed.jaxpr.constvars)
    return {"flops": int(flops), "bytes": int(nbytes), "eqns": int(n_eqns)}


def param_bytes(closed: ClosedJaxpr, trailing_inputs: int = 1) -> int:
    """Bytes of a predict program's WEIGHT arguments — every input aval
    except the trailing image batch. This is the per-dispatch HBM weight
    traffic the fusion-blind `bytes` proxy cannot isolate (it counts int32
    accumulators and quantize chains that XLA fuses away), and the number
    the int8 serve units halve: on the r05 bandwidth-bound regime, weight
    bytes ARE the serving lever."""
    invars = closed.jaxpr.invars
    keep = invars[:max(0, len(invars) - trailing_inputs)]
    return int(sum(_aval_bytes(v.aval) for v in keep))
