"""Closed-jaxpr walking: equation iteration, collectives, and the cost model.

Everything operates on abstract values only — shapes and dtypes from a
`jit(...).trace(...)` of the real step on `ShapeDtypeStruct` inputs — so a
whole-registry sweep costs zero FLOPs and no device memory.
"""

from __future__ import annotations

import math
from typing import Dict, Iterator, List, Tuple

import numpy as np
from jax.core import ClosedJaxpr, Jaxpr, JaxprEqn, Literal

# Collective primitives a jaxpr can carry explicitly (shard_map/pmap
# regions). GSPMD-partitioned jitted steps never contain these — the
# partitioner inserts its collectives at compile time — which is exactly
# what the COLL single-program check pins.
COLLECTIVE_PRIMS = frozenset({
    "psum", "ppermute", "pmax", "pmin", "all_gather", "all_to_all",
    "reduce_scatter", "psum_scatter", "pgather", "pbroadcast",
})

# Heavy compute: the MXU-shaped equations whose dtype IS the compute policy.
HEAVY_PRIMS = frozenset({"conv_general_dilated", "dot_general"})


def _sub_jaxprs(eqn: JaxprEqn) -> Iterator[Tuple[Jaxpr, int]]:
    """(inner jaxpr, trip multiplier) pairs nested in an equation's params.
    scan bodies multiply by `length`; everything else counts once (while
    bodies have no static trip count — counted once, an explicit floor)."""
    mult = eqn.params.get("length", 1) if eqn.primitive.name == "scan" else 1
    for value in eqn.params.values():
        for item in (value if isinstance(value, (list, tuple)) else (value,)):
            if isinstance(item, ClosedJaxpr):
                yield item.jaxpr, mult
            elif isinstance(item, Jaxpr):
                yield item, mult


def iter_eqns(jaxpr: Jaxpr, _mult: int = 1) -> Iterator[Tuple[JaxprEqn, int]]:
    """Depth-first (eqn, trip multiplier) over a jaxpr and every nested
    sub-jaxpr (pjit bodies, scan/while/cond, custom_vjp, remat)."""
    for eqn in jaxpr.eqns:
        yield eqn, _mult
        for sub, mult in _sub_jaxprs(eqn):
            yield from iter_eqns(sub, _mult * mult)


def _aval_bytes(aval) -> int:
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    try:
        itemsize = np.dtype(dtype).itemsize
    except TypeError:
        # extended dtypes (typed PRNG keys, `key<fry>`) have no numpy
        # equivalent; their physical payload is a pair of uint32s
        itemsize = 8
    return int(math.prod(shape)) * itemsize


def _axes_of(eqn: JaxprEqn) -> Tuple[str, ...]:
    """Normalized mesh-axis tuple of a collective equation."""
    axes = (eqn.params.get("axes") or eqn.params.get("axis_name")
            or eqn.params.get("axis_names") or ())
    if isinstance(axes, str):
        axes = (axes,)
    return tuple(str(a) for a in axes)


def collect_collectives(closed: ClosedJaxpr) -> Dict[Tuple[str, Tuple[str, ...]], int]:
    """{(primitive, axes): count} over the whole (nested) jaxpr."""
    out: Dict[Tuple[str, Tuple[str, ...]], int] = {}
    for eqn, mult in iter_eqns(closed.jaxpr):
        if eqn.primitive.name in COLLECTIVE_PRIMS:
            key = (eqn.primitive.name, _axes_of(eqn))
            out[key] = out.get(key, 0) + mult
    return out


def _conv_flops(eqn: JaxprEqn) -> int:
    """2 * |out| * taps-per-output for conv_general_dilated, taps =
    kernel_spatial_elems * C_in / feature_groups, read off the rhs shape via
    the equation's dimension numbers."""
    out = eqn.outvars[0].aval
    dnums = eqn.params["dimension_numbers"]
    rhs = eqn.invars[1].aval.shape
    spatial = [rhs[d] for d in dnums.rhs_spec[2:]]
    c_in = rhs[dnums.rhs_spec[1]]  # per-group input channels
    return 2 * int(math.prod(out.shape)) * int(math.prod(spatial)) * int(c_in)


def _dot_flops(eqn: JaxprEqn) -> int:
    """2 * |out| * K for dot_general (K = product of lhs contracting dims)."""
    out = eqn.outvars[0].aval
    (lhs_c, _), _ = eqn.params["dimension_numbers"]
    lhs = eqn.invars[0].aval.shape
    k = math.prod(lhs[d] for d in lhs_c) if lhs_c else 1
    return 2 * int(math.prod(out.shape)) * int(k)


def heavy_eqns(closed: ClosedJaxpr) -> List[Tuple[JaxprEqn, int, int]]:
    """(eqn, trip multiplier, flops) for every conv/dot in the jaxpr."""
    out = []
    for eqn, mult in iter_eqns(closed.jaxpr):
        name = eqn.primitive.name
        if name not in HEAVY_PRIMS:
            continue
        flops = _conv_flops(eqn) if name == "conv_general_dilated" \
            else _dot_flops(eqn)
        out.append((eqn, mult, flops))
    return out


def cost_summary(closed: ClosedJaxpr) -> Dict[str, int]:
    """The jaxvet cost model of one traced step.

    - `flops`: 2*MACs summed over every conv/dot (the MXU work; elementwise
      ops are noise next to it and fuse anyway).
    - `bytes`: every equation's operand + result footprint summed — a
      deliberately fusion-blind upper proxy. The ABSOLUTE number overcounts
      what a compiled program moves through HBM (XLA fuses elementwise
      chains); the DIFF between two revisions of the same step is exactly
      the signal BENCH chases (r05: bf16 BN/residual joins cut cost-model
      bytes 8.3%), and this proxy moves with it deterministically.
    - `eqns`: equation count (trip-weighted) — a retrace/graph-bloat canary.

    Literals (inline scalars) are skipped; consts are counted once via the
    outer jaxpr's constvars.
    """
    flops = 0
    nbytes = 0
    n_eqns = 0
    for eqn, mult in iter_eqns(closed.jaxpr):
        n_eqns += mult
        io = sum(_aval_bytes(v.aval) for v in eqn.invars
                 if not isinstance(v, Literal))
        io += sum(_aval_bytes(v.aval) for v in eqn.outvars)
        nbytes += mult * io
    for eqn, mult, f in heavy_eqns(closed):
        flops += mult * f
    nbytes += sum(_aval_bytes(v.aval) for v in closed.jaxpr.constvars)
    return {"flops": int(flops), "bytes": int(nbytes), "eqns": int(n_eqns)}


def param_bytes(closed: ClosedJaxpr, trailing_inputs: int = 1) -> int:
    """Bytes of a predict program's WEIGHT arguments — every input aval
    except the trailing image batch. This is the per-dispatch HBM weight
    traffic the fusion-blind `bytes` proxy cannot isolate (it counts int32
    accumulators and quantize chains that XLA fuses away), and the number
    the int8 serve units halve: on the r05 bandwidth-bound regime, weight
    bytes ARE the serving lever."""
    invars = closed.jaxpr.invars
    keep = invars[:max(0, len(invars) - trailing_inputs)]
    return int(sum(_aval_bytes(v.aval) for v in keep))
