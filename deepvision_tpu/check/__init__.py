"""jaxvet — jaxpr-level static audit of every registered model.

jaxlint (deepvision_tpu/lint) proves hazards at the AST level; the bug
classes that actually bit this repo — donation-aliasing segfaults, f32 leaks
into the bf16 compute path, mis-axed collectives — are ultimately facts
about the *lowered IR*, not the source text. jaxvet closes that gap: for
every registered `(config, model, step-factory)` combination it traces the
REAL train/eval/predict step with abstract inputs (`jax.eval_shape` +
`jit(...).trace` — zero data, zero FLOPs, CPU-safe) and walks the closed
jaxpr to enforce IR-level invariants:

  DTYPE   no f32 conv/dot equations reachable inside a declared-bf16 apply,
          outside the deliberate f32 output heads — the ground-truth
          complement to the AST rule DTY001
  DONATE  the step donates what it claims (steps_per_dispatch == 1 ->
          the whole state), and every donated argument is actually
          aliasable (shape/dtype matches an output) — the PR 1/4 segfault
          class, caught before XLA is
  COLL    spatial shard_map collectives run over the axes
          parallel/spatial_shard.py declares (ppermute halos over
          'spatial', all_to_all transition over 'spatial', grad psum over
          ('data','spatial')), and single-program GSPMD steps contain NO
          explicit collectives
  COST    per-step FLOPs / bytes-accessed derived from the jaxpr, diffed
          against the committed CHECK_COST.json baseline so cost-model
          regressions are visible PR-over-PR
  SERVE   the PredictEngine bucket signatures {1, 8, 32, max_batch} cover
          each servable config's input spec (shape, dtype, policy) —
          config/bucket drift caught before it becomes a recompile storm

CLI:      python -m deepvision_tpu.check [units...] [--format json|github]
                                         [--select DTYPE,DONATE,...]
Library:  audit([...]) -> ([Finding], n_steps)
Division of labor vs jaxlint, rule table, and the cost-baseline workflow:
docs/CHECKING.md. Contract matches the jaxlint CLI: exit 0 clean /
1 findings / 2 usage error.
"""

from .cli import audit, main
from .rules import ALL_CHECKS, Finding

__all__ = ["ALL_CHECKS", "Finding", "audit", "main"]
