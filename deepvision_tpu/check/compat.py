"""Version shims for the audit harness.

The spatial subsystem targets `jax.shard_map` (the stable alias of newer
jax). On runtimes that only ship `jax.experimental.shard_map` the full
shard_map train steps cannot build (and the seed tier-1 suite xfails them),
but jaxvet's COLL probes audit the COLLECTIVE layer of
parallel/spatial_shard.py — plain jax, no flax interception — which traces
fine through the experimental API. This module provides that one adapter so
the probes (and, where the runtime allows, the full spatial steps) run on
both API generations.
"""

from __future__ import annotations

import contextlib

import jax


def shard_map_fn():
    """The runtime's shard_map entry point, adapted to the
    `jax.shard_map(f, mesh=..., in_specs=..., out_specs=..., axis_names=...,
    check_vma=...)` calling convention spatial_shard.py uses. Returns None
    when no shard_map implementation exists at all."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map
    try:
        from jax.experimental.shard_map import shard_map as _sm
    except ImportError:  # pragma: no cover — every supported jax has one
        return None

    def adapted(f, mesh=None, in_specs=None, out_specs=None, axis_names=None,
                check_vma=None, **kw):
        # axis_names -> the experimental API's complement ('auto' axes);
        # check_vma (new name) -> check_rep off: the audit only needs the
        # traced collectives, not the replication checker.
        auto = frozenset(mesh.axis_names) - frozenset(
            axis_names if axis_names is not None else mesh.axis_names)
        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=False, auto=auto)

    return adapted


@contextlib.contextmanager
def shard_map_installed():
    """Temporarily install `jax.shard_map` (when absent) so code written
    against the stable alias — the spatial step factories — can at least be
    TRACED on an experimental-only runtime. Restores jax untouched."""
    if hasattr(jax, "shard_map"):
        yield True
        return
    fn = shard_map_fn()
    if fn is None:  # pragma: no cover
        yield False
        return
    jax.shard_map = fn
    try:
        yield True
    finally:
        del jax.shard_map
