"""`python -m deepvision_tpu.check` — the jaxvet audit CLI."""

import sys

from .cli import main

if __name__ == "__main__":
    sys.exit(main())
