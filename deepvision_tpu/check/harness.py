"""Audit-unit construction: abstract traces of every registered step.

For each registered config this module mirrors the trainer family's step
wiring (`core/trainer.py` / `core/detection.py` / `core/pose.py` /
`core/centernet.py` / `core/gan.py`) and traces the REAL factory-built step
on `ShapeDtypeStruct` inputs — `jax.eval_shape` for the state pytree,
`jit(...).trace(...)` for the step — so a whole-registry sweep runs on CPU
with zero data, zero FLOPs and no device memory.

Determinism contract: every unit traces with `mesh=None` and the fixed
`AUDIT_BATCH`, so the jaxpr (and therefore the COST table) depends only on
the package source — not on the host's device count or the config's pod
batch size. The spatial COLL probes trace the real collective layer of
`parallel/spatial_shard.py` through tiny shard_map bodies over an
`AbstractMesh` (no devices needed at all). The mesh-serve units are the
one deliberate exception: they trace jit-with-shardings over a FIXED
2-device (data=1, model=2) mesh built from the first two host devices, so
their rows too are a pure function of the package source on any host with
>= 2 devices, and they skip gracefully (no row, no finding) below that.
"""

from __future__ import annotations

import dataclasses
import gc
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from .compat import shard_map_fn
from .jaxpr_walk import collect_collectives

# One fixed abstract batch for every unit: trace cost is shape-independent,
# and a fixed batch keeps the COST baseline comparable across configs and
# hosts (a config's pod batch_size is a launch parameter, not an IR fact).
AUDIT_BATCH = 8

S = jax.ShapeDtypeStruct


@dataclasses.dataclass
class TracedUnit:
    """One audited step: the traced jaxpr plus the factory's own claim."""
    name: str                      # "resnet50/train"
    config_name: str               # registry key ("" for spatial probes)
    kind: str                      # train|eval|predict|probe
    closed: Any = None             # ClosedJaxpr (None for eval_shape units)
    donated_avals: list = dataclasses.field(default_factory=list)
    out_avals: list = dataclasses.field(default_factory=list)
    meta: dict = dataclasses.field(default_factory=dict)   # _jaxvet claim
    head_dims: frozenset = frozenset()  # dims that mark deliberate f32 heads
    # COLL probes: {(prim, axes): count} declared vs traced
    declared_collectives: Optional[dict] = None
    traced_collectives: Optional[dict] = None
    # SERVE: bucket coverage facts
    serve: Optional[dict] = None
    # QUANT: int8 predict-twin facts ({"planned": n, "baseline_unit": name})
    quant: Optional[dict] = None
    skipped: Optional[str] = None  # env-skew skip, with reason
    error: Optional[str] = None    # build/trace failure (a finding)


def _abstract_state(model, tx, sample_sds, ema: bool = False):
    """The TrainState a trainer would build, as ShapeDtypeStructs — one
    `jax.eval_shape` over the real init path (`init_model` +
    `TrainState.create`), so optimizer slots, EMA and batch_stats all carry
    their true shapes/dtypes without a single FLOP."""
    from ..core.train_state import TrainState, init_model

    def make(rng, sample):
        params, batch_stats = init_model(model, rng, sample)
        return TrainState.create(model.apply, params, tx, batch_stats,
                                 ema=ema)

    return jax.eval_shape(make, S((2,), jnp.uint32), sample_sds)


def _trace(step, *args) -> Tuple[Any, list, list]:
    """(closed_jaxpr, donated input avals, output avals) of a jitted step
    over abstract args — jax's AOT `.trace`, which also carries the
    donation mask the DONATE family audits."""
    traced = step.trace(*args)
    flat_info = jax.tree_util.tree_leaves(traced.args_info)
    donated = [S(i.shape, i.dtype) for i in flat_info
               if getattr(i, "donated", False)]
    closed = traced.jaxpr
    out_avals = [v.aval for v in closed.jaxpr.outvars]
    return closed, donated, out_avals


def _optimizer_for(cfg):
    from ..core.optim import build_optimizer
    steps_per_epoch = max(1, cfg.data.train_examples // cfg.batch_size)
    return build_optimizer(cfg.optimizer, cfg.schedule, steps_per_epoch,
                           cfg.total_epochs)


def _pin_trace_impls(cfg):
    """Committed COST rows must be platform-independent, but a config whose
    `attention_impl` is "auto" resolves by backend at trace time (fused on
    TPU, naive elsewhere) — an audit run on a TPU host would drift every
    ViT row. Pin "auto" to the portable naive lowering for the config
    units; the fused lowering has its own committed rows via the attn/
    unit family (traced through the interpreter, same jaxpr)."""
    if cfg.model_kwargs.get("attention_impl") == "auto":
        cfg = cfg.replace(model_kwargs={**cfg.model_kwargs,
                                        "attention_impl": "naive"})
    return cfg


def _family_setup(cfg):
    """(model, config, sample SDS, input images SDS, input_norm) shared by
    every supervised family — the host pipeline's uint8-vs-f32 contract
    included (`data.normalize_on_device`)."""
    from ..core.config import UNIT_RANGE_NORM
    from ..core.trainer import build_model_from_config

    cfg = _pin_trace_impls(cfg)
    kwarg = "num_heatmap" if cfg.family == "pose" else "num_classes"
    model, cfg = build_model_from_config(cfg, num_classes_kwarg=kwarg)
    sz, ch = cfg.data.image_size, cfg.data.channels
    input_norm = UNIT_RANGE_NORM if cfg.data.normalize_on_device else None
    images = S((AUDIT_BATCH, sz, sz, ch),
               jnp.uint8 if input_norm is not None else jnp.float32)
    return model, cfg, images, input_norm


def _head_dims(cfg) -> frozenset:
    """Dimensions that identify the DELIBERATE f32 output heads of a
    declared-bf16 model. ONE definition shared with the serving-side int8
    quantization plan (`core/scoring.serving_head_dims`): the equations
    DTYPE exempts as heads are exactly the equations the quantizer leaves
    in float — the two layers cannot drift apart."""
    from ..core.scoring import serving_head_dims
    return serving_head_dims(cfg)


# -- per-family unit builders -------------------------------------------------

def _classification_units(name, cfg) -> List[TracedUnit]:
    from ..core import steps as steps_lib

    model, cfg, images, input_norm = _family_setup(cfg)
    dt = jnp.dtype(cfg.dtype) if cfg.dtype else jnp.bfloat16
    tx = _optimizer_for(cfg)
    state = _abstract_state(model, tx, images, ema=bool(cfg.ema_decay))
    labels = S((AUDIT_BATCH,), jnp.int32)
    rng = S((2,), jnp.uint32)
    head = _head_dims(cfg)
    units = []

    step = steps_lib.make_classification_train_step(
        label_smoothing=cfg.label_smoothing, aux_weight=cfg.aux_loss_weight,
        compute_dtype=dt, mesh=None, remat=cfg.remat,
        mixup_alpha=cfg.mixup_alpha, cutmix_alpha=cfg.cutmix_alpha,
        input_norm=input_norm, log_grad_norm=cfg.log_grad_norm,
        donate=cfg.donate_step())
    closed, donated, outs = _trace(step, state, images, labels, rng)
    units.append(TracedUnit(f"{name}/train", name, "train", closed, donated,
                            outs, dict(getattr(step, "_jaxvet", {})),
                            head_dims=head))

    estep = steps_lib.make_classification_eval_step(
        compute_dtype=dt, mesh=None, input_norm=input_norm)
    mask = S((AUDIT_BATCH,), jnp.float32)
    closed, donated, outs = _trace(estep, state, images, labels, mask)
    units.append(TracedUnit(f"{name}/eval", name, "eval", closed, donated,
                            outs, dict(getattr(estep, "_jaxvet", {})),
                            head_dims=head))
    return units


def _detection_units(name, cfg) -> List[TracedUnit]:
    from ..core import detection as det
    from ..ops.yolo import MAX_BOXES

    model, cfg, images, input_norm = _family_setup(cfg)
    dt = jnp.dtype(cfg.dtype) if cfg.dtype else jnp.bfloat16
    grids = det.yolo_grid_sizes(cfg.data.image_size)
    tx = _optimizer_for(cfg)
    state = _abstract_state(model, tx, images)
    b = AUDIT_BATCH
    boxes = S((b, MAX_BOXES, 4), jnp.float32)
    classes = S((b, MAX_BOXES), jnp.int32)
    valid = S((b, MAX_BOXES), jnp.float32)
    rng = S((2,), jnp.uint32)
    head = _head_dims(cfg)
    units = []

    step = det.make_yolo_train_step(
        num_classes=cfg.data.num_classes, grid_sizes=grids, compute_dtype=dt,
        mesh=None, remat=cfg.remat, input_norm=input_norm,
        log_grad_norm=cfg.log_grad_norm, donate=cfg.donate_step())
    closed, donated, outs = _trace(step, state, images, boxes, classes,
                                   valid, rng)
    units.append(TracedUnit(f"{name}/train", name, "train", closed, donated,
                            outs, dict(getattr(step, "_jaxvet", {})),
                            head_dims=head))

    estep = det.make_yolo_eval_step(
        num_classes=cfg.data.num_classes, grid_sizes=grids, compute_dtype=dt,
        mesh=None, input_norm=input_norm)
    closed, donated, outs = _trace(estep, state, images, boxes, classes,
                                   valid)
    units.append(TracedUnit(f"{name}/eval", name, "eval", closed, donated,
                            outs, dict(getattr(estep, "_jaxvet", {})),
                            head_dims=head))

    pstep = det.make_predict_step(compute_dtype=dt)
    outs = jax.eval_shape(pstep, state, S(images.shape, jnp.float32))
    units.append(TracedUnit(
        f"{name}/predict", name, "predict",
        out_avals=list(jax.tree_util.tree_leaves(outs)),
        meta=dict(getattr(pstep, "_jaxvet", {})), head_dims=head))
    return units


def _pose_units(name, cfg) -> List[TracedUnit]:
    from ..core import pose as pose_lib

    model, cfg, images, input_norm = _family_setup(cfg)
    dt = jnp.dtype(cfg.dtype) if cfg.dtype else jnp.bfloat16
    hm = (cfg.data.image_size // 4, cfg.data.image_size // 4)
    tx = _optimizer_for(cfg)
    state = _abstract_state(model, tx, images)
    b, k = AUDIT_BATCH, cfg.data.num_classes
    kp = S((b, k), jnp.float32)
    rng = S((2,), jnp.uint32)
    head = _head_dims(cfg)
    units = []

    step = pose_lib.make_pose_train_step(
        heatmap_size=hm, compute_dtype=dt, mesh=None, remat=cfg.remat,
        input_norm=input_norm, log_grad_norm=cfg.log_grad_norm,
        donate=cfg.donate_step())
    closed, donated, outs = _trace(step, state, images, kp, kp, kp, rng)
    units.append(TracedUnit(f"{name}/train", name, "train", closed, donated,
                            outs, dict(getattr(step, "_jaxvet", {})),
                            head_dims=head))

    estep = pose_lib.make_pose_eval_step(
        heatmap_size=hm, compute_dtype=dt, mesh=None, input_norm=input_norm)
    closed, donated, outs = _trace(estep, state, images, kp, kp, kp)
    units.append(TracedUnit(f"{name}/eval", name, "eval", closed, donated,
                            outs, dict(getattr(estep, "_jaxvet", {})),
                            head_dims=head))
    return units


def _centernet_units(name, cfg) -> List[TracedUnit]:
    from ..core import centernet as cn
    from ..ops.yolo import MAX_BOXES

    model, cfg, images, input_norm = _family_setup(cfg)
    dt = jnp.dtype(cfg.dtype) if cfg.dtype else jnp.bfloat16
    grid = cfg.data.image_size // 4
    tx = _optimizer_for(cfg)
    state = _abstract_state(model, tx, images)
    b = AUDIT_BATCH
    boxes = S((b, MAX_BOXES, 4), jnp.float32)
    classes = S((b, MAX_BOXES), jnp.int32)
    valid = S((b, MAX_BOXES), jnp.float32)
    rng = S((2,), jnp.uint32)
    head = _head_dims(cfg)
    units = []

    step = cn.make_centernet_train_step(
        num_classes=cfg.data.num_classes, grid=grid, compute_dtype=dt,
        mesh=None, remat=cfg.remat, input_norm=input_norm,
        log_grad_norm=cfg.log_grad_norm, donate=cfg.donate_step())
    closed, donated, outs = _trace(step, state, images, boxes, classes,
                                   valid, rng)
    units.append(TracedUnit(f"{name}/train", name, "train", closed, donated,
                            outs, dict(getattr(step, "_jaxvet", {})),
                            head_dims=head))

    estep = cn.make_centernet_eval_step(
        num_classes=cfg.data.num_classes, grid=grid, compute_dtype=dt,
        mesh=None, input_norm=input_norm)
    closed, donated, outs = _trace(estep, state, images, boxes, classes,
                                   valid)
    units.append(TracedUnit(f"{name}/eval", name, "eval", closed, donated,
                            outs, dict(getattr(estep, "_jaxvet", {})),
                            head_dims=head))

    pstep = cn.make_centernet_predict_step(compute_dtype=dt)
    outs = jax.eval_shape(pstep, state, S(images.shape, jnp.float32))
    units.append(TracedUnit(
        f"{name}/predict", name, "predict",
        out_avals=list(jax.tree_util.tree_leaves(outs)),
        meta=dict(getattr(pstep, "_jaxvet", {})), head_dims=head))
    return units


def _segmentation_units(name, cfg) -> List[TracedUnit]:
    from ..core import segment as seg_lib

    model, cfg, images, input_norm = _family_setup(cfg)
    dt = jnp.dtype(cfg.dtype) if cfg.dtype else jnp.bfloat16
    dice = seg_lib.dice_weight_for(cfg)
    tx = _optimizer_for(cfg)
    state = _abstract_state(model, tx, images)
    b, sz = AUDIT_BATCH, cfg.data.image_size
    masks = S((b, sz, sz), jnp.int32)
    rng = S((2,), jnp.uint32)
    head = _head_dims(cfg)
    units = []

    step = seg_lib.make_segmentation_train_step(
        num_classes=cfg.data.num_classes, compute_dtype=dt, mesh=None,
        remat=cfg.remat, input_norm=input_norm, dice_weight=dice,
        log_grad_norm=cfg.log_grad_norm, donate=cfg.donate_step())
    closed, donated, outs = _trace(step, state, images, masks, rng)
    units.append(TracedUnit(f"{name}/train", name, "train", closed, donated,
                            outs, dict(getattr(step, "_jaxvet", {})),
                            head_dims=head))

    estep = seg_lib.make_segmentation_eval_step(
        num_classes=cfg.data.num_classes, compute_dtype=dt, mesh=None,
        input_norm=input_norm, dice_weight=dice)
    closed, donated, outs = _trace(estep, state, images, masks)
    units.append(TracedUnit(f"{name}/eval", name, "eval", closed, donated,
                            outs, dict(getattr(estep, "_jaxvet", {})),
                            head_dims=head))

    pstep = seg_lib.make_segmentation_predict_step(
        compute_dtype=dt, input_norm=input_norm)
    outs = jax.eval_shape(pstep, state, S(images.shape, jnp.float32))
    units.append(TracedUnit(
        f"{name}/predict", name, "predict",
        out_avals=list(jax.tree_util.tree_leaves(outs)),
        meta=dict(getattr(pstep, "_jaxvet", {})), head_dims=head))
    return units


def _gan_units(name, cfg) -> List[TracedUnit]:
    from ..core import gan as gan_lib
    from ..core.train_state import TrainState, init_model

    rng = S((2,), jnp.uint32)
    b = AUDIT_BATCH
    units = []

    if cfg.model == "dcgan":
        from ..models.gan import DCGANDiscriminator, DCGANGenerator
        noise_dim = 100
        gen, disc = DCGANGenerator(noise_dim=noise_dim), DCGANDiscriminator()
        tx_g, tx_d = _optimizer_for(cfg), _optimizer_for(cfg)

        def make(rng_, noise, image):
            gp, gbs = init_model(gen, rng_, noise)
            dp, dbs = init_model(disc, jax.random.fold_in(rng_, 7), image)
            return (TrainState.create(gen.apply, gp, tx_g, gbs),
                    TrainState.create(disc.apply, dp, tx_d, dbs))

        sz, ch = cfg.data.image_size, cfg.data.channels
        gen_state, disc_state = jax.eval_shape(
            make, S((2,), jnp.uint32), S((2, noise_dim), jnp.float32),
            S((2, sz, sz, ch), jnp.float32))
        step = gan_lib.make_dcgan_train_step(gen.apply, disc.apply,
                                             noise_dim, mesh=None)
        images = S((b, sz, sz, ch), jnp.float32)
        closed, donated, outs = _trace(step, gen_state, disc_state, images,
                                       rng)
        units.append(TracedUnit(f"{name}/train", name, "train", closed,
                                donated, outs,
                                dict(getattr(step, "_jaxvet", {}))))
        return units

    # cyclegan: two generators + two discriminators behind one state each
    from ..models.gan import CycleGANGenerator, PatchGANDiscriminator
    gen, disc = CycleGANGenerator(n_blocks=9), PatchGANDiscriminator()
    tx_g, tx_d = _optimizer_for(cfg), _optimizer_for(cfg)
    sz = cfg.data.image_size

    def make(rng_, sample):
        g_params, g_bs, d_params, d_bs = {}, {}, {}, {}
        for i, nm in enumerate(("a2b", "b2a")):
            g_params[nm], g_bs[nm] = init_model(
                gen, jax.random.fold_in(rng_, i), sample)
        for i, nm in enumerate(("a", "b")):
            d_params[nm], d_bs[nm] = init_model(
                disc, jax.random.fold_in(rng_, 2 + i), sample)
        return (TrainState.create(gen.apply, g_params, tx_g, g_bs),
                TrainState.create(disc.apply, d_params, tx_d, d_bs))

    gen_state, disc_state = jax.eval_shape(
        make, S((2,), jnp.uint32), S((2, sz, sz, 3), jnp.float32))
    real = S((b, sz, sz, 3), jnp.float32)

    gstep = gan_lib.make_cyclegan_generator_step(gen.apply, disc.apply,
                                                 mesh=None)
    closed, donated, outs = _trace(gstep, gen_state, disc_state, real, real)
    units.append(TracedUnit(f"{name}/train_gen", name, "train", closed,
                            donated, outs,
                            dict(getattr(gstep, "_jaxvet", {}))))

    dstep = gan_lib.make_cyclegan_discriminator_step(disc.apply, mesh=None)
    closed, donated, outs = _trace(dstep, disc_state, real, real, real, real)
    units.append(TracedUnit(f"{name}/train_disc", name, "train", closed,
                            donated, outs,
                            dict(getattr(dstep, "_jaxvet", {}))))
    return units


def _serve_unit(name, cfg) -> TracedUnit:
    """SERVE bucket-coverage facts for one servable (non-GAN) config: the
    default PredictEngine bucket signatures {1, 8, 32, max_batch} against
    the config's input spec, plus an abstract forward of the engine's REAL
    predict fn (bf16-compute / f32-out) at the smallest and largest bucket."""
    from ..core.config import UNIT_RANGE_NORM
    from ..core.steps import _normalize_input
    from ..core.trainer import build_model_from_config

    cfg = _pin_trace_impls(cfg)
    kwarg = "num_heatmap" if cfg.family == "pose" else "num_classes"
    model, cfg = build_model_from_config(cfg, num_classes_kwarg=kwarg)
    sz, ch = cfg.data.image_size, cfg.data.channels
    dt = jnp.dtype(cfg.dtype) if cfg.dtype else jnp.bfloat16
    input_norm = UNIT_RANGE_NORM if cfg.data.normalize_on_device else None
    in_dtype = jnp.uint8 if input_norm is not None else jnp.float32
    buckets = (1, 8, 32)
    max_batch = buckets[-1]
    take_first = cfg.family == "classification"
    argmax_mask = cfg.family == "segmentation"  # class-id mask payload

    variables = jax.eval_shape(
        lambda r, x: model.init({"params": r,
                                 "dropout": jax.random.fold_in(r, 1)},
                                x, train=True),
        S((2,), jnp.uint32), S((2, sz, sz, ch), jnp.float32))

    def predict(vars_, images):   # mirrors PredictEngine.__init__'s predict
        x = _normalize_input(images, input_norm, dt)
        out = model.apply(vars_, x, train=False)
        if take_first and isinstance(out, (tuple, list)):
            out = out[0]
        if argmax_mask:
            out = jnp.argmax(out, axis=-1).astype(jnp.int32)
        return jax.tree_util.tree_map(
            lambda y: y.astype(jnp.float32)
            if jnp.issubdtype(y.dtype, jnp.floating) else y, out)

    # one abstract forward at the smallest bucket proves the serving input
    # spec traces end to end; shape/dtype facts at the other buckets follow
    # from batch-dim polymorphism, so re-tracing them buys nothing
    probe_outs = {}
    for bkt in (buckets[0],):
        outs = jax.eval_shape(predict, variables,
                              S((bkt, sz, sz, ch), in_dtype))
        probe_outs[bkt] = list(jax.tree_util.tree_leaves(outs))
    # the FULL trace at the audit batch: gives the serve unit a cost row
    # (flops / bytes / param_bytes) — the bf16 twin the int8 quant units
    # diff their byte cut against, and a drift canary for the predict path
    # in its own right
    closed, donated, outs = _trace(jax.jit(predict), variables,
                                   S((AUDIT_BATCH, sz, sz, ch), in_dtype))
    return TracedUnit(
        f"{name}/serve", name, "predict", closed, donated, outs,
        serve={"buckets": buckets, "max_batch": max_batch,
               "example_shape": (sz, sz, ch), "input_dtype": str(in_dtype),
               "probe_outs": probe_outs},
        meta={"donate": False, "compute_dtype": dt, "kind": "predict"},
        head_dims=_head_dims(cfg))


# -- whole-epoch scan units ---------------------------------------------------

# The epoch-scan wrapper (core/steps.make_epoch_train_step) audited over one
# classification and one segmentation inner step — the two families the
# on-device epoch path ships for first (the paired-augment RNG contract
# rides inside the scanned step). Fixed scan length: the COST rows scale
# linearly with it (scan bodies are trip-weighted), so the baseline stays a
# pure function of the package source.
EPOCH_UNIT_CONFIGS = ("lenet5", "unet_synthetic", "vit_tiny")
EPOCH_SCAN_LEN = 4


def epoch_unit_names() -> List[str]:
    """The audit units the epoch-scan probes contribute — pinned by the
    cost-baseline coverage test next to the per-config unit names."""
    return [f"epoch/{name}" for name in EPOCH_UNIT_CONFIGS]


def _epoch_scan_units() -> List[TracedUnit]:
    """Trace the scanned epoch step abstractly: the outer jit must donate
    the state (and ONLY the state — the resident epoch arrays are reused
    every epoch), carry no explicit collectives, honor the inner step's
    dtype policy through the scan body, and its cost row (scan-length-
    weighted) lands in CHECK_COST.json like any other step's."""
    from ..configs import get_config
    from ..core import segment as seg_lib
    from ..core import steps as steps_lib

    units: List[TracedUnit] = []
    for cname in EPOCH_UNIT_CONFIGS:
        name = f"epoch/{cname}"
        try:
            cfg = get_config(cname)
            model, cfg, images, input_norm = _family_setup(cfg)
            dt = jnp.dtype(cfg.dtype) if cfg.dtype else jnp.bfloat16
            tx = _optimizer_for(cfg)
            state = _abstract_state(model, tx, images,
                                    ema=bool(cfg.ema_decay))
            b, sz = AUDIT_BATCH, cfg.data.image_size
            ep_images = S((EPOCH_SCAN_LEN, *images.shape), images.dtype)
            if cfg.family == "segmentation":
                inner = seg_lib.make_segmentation_train_step(
                    num_classes=cfg.data.num_classes, compute_dtype=dt,
                    mesh=None, input_norm=input_norm,
                    dice_weight=seg_lib.dice_weight_for(cfg),
                    log_grad_norm=cfg.log_grad_norm, donate=False)
                batch_args = (ep_images,
                              S((EPOCH_SCAN_LEN, b, sz, sz), jnp.int32))
            else:
                inner = steps_lib.make_classification_train_step(
                    label_smoothing=cfg.label_smoothing,
                    aux_weight=cfg.aux_loss_weight, compute_dtype=dt,
                    mesh=None, input_norm=input_norm,
                    log_grad_norm=cfg.log_grad_norm, donate=False)
                batch_args = (ep_images,
                              S((EPOCH_SCAN_LEN, b), jnp.int32))
            step = steps_lib.make_epoch_train_step(
                inner, len(batch_args), mesh=None,
                ema_decay=cfg.ema_decay, shuffle=True)
            closed, donated, outs = _trace(step, state, *batch_args,
                                           S((2,), jnp.uint32))
            units.append(TracedUnit(
                name, "", "train", closed, donated, outs,
                dict(getattr(step, "_jaxvet", {})),
                head_dims=_head_dims(cfg)))
        except Exception as e:
            units.append(TracedUnit(name, "", "train",
                                    error=f"{type(e).__name__}: {e}"))
    return units


# -- int8 quantized-predict units ---------------------------------------------

# The serving-side int8 twins (ops/quant.py + serve/quantize.py) audited
# abstractly: the flagship bandwidth-bound config (the r05 motivation) plus
# the tiny fixed config preflight's `quant` gate runs. The quantization
# PLAN is structural, so the audit needs no calibration data — unit
# activation scales stand in (scale VALUES never change the jaxpr shape).
QUANT_UNIT_CONFIGS = ("lenet5", "resnet50", "vit_tiny")


def quant_unit_names() -> List[str]:
    """The audit units the int8 predict twins contribute — pinned by the
    cost-baseline coverage test next to the per-config unit names."""
    return [f"quant/{name}" for name in QUANT_UNIT_CONFIGS]


def _quant_units() -> List[TracedUnit]:
    """Trace each QUANT_UNIT_CONFIG's int8 predict twin: plan the
    quantization over the REAL serve predict's jaxpr (the same function
    `_serve_unit` traces), substitute the int8 equations, and re-trace.
    The QUANT family then audits the result — int8 convs where claimed,
    f32 outputs preserved, param-bytes cut vs the bf16 twin's cost row."""
    units: List[TracedUnit] = []
    for cname in QUANT_UNIT_CONFIGS:
        try:
            units.append(_quant_unit(cname))
        except Exception as e:
            units.append(TracedUnit(f"quant/{cname}", "", "predict",
                                    error=f"{type(e).__name__}: {e}"))
    return units


def _quant_unit(cname: str) -> TracedUnit:
    """One config's int8 predict twin, traced abstractly (the jit here is
    the per-config factory site — every config's quantized predict is a
    distinct function)."""
    from ..core.config import UNIT_RANGE_NORM
    from ..core.steps import _normalize_input
    from ..core.trainer import build_model_from_config
    from ..configs import get_config
    from ..ops import quant as quant_lib

    cfg = _pin_trace_impls(get_config(cname))
    model, cfg = build_model_from_config(cfg)
    sz, ch = cfg.data.image_size, cfg.data.channels
    dt = jnp.dtype(cfg.dtype) if cfg.dtype else jnp.bfloat16
    input_norm = (UNIT_RANGE_NORM if cfg.data.normalize_on_device
                  else None)
    in_dtype = jnp.uint8 if input_norm is not None else jnp.float32
    take_first = cfg.family == "classification"
    head = _head_dims(cfg)
    variables = jax.eval_shape(
        lambda r, x: model.init(
            {"params": r, "dropout": jax.random.fold_in(r, 1)},
            x, train=True),
        S((2,), jnp.uint32), S((2, sz, sz, ch), jnp.float32))

    def predict(vars_, images):
        x = _normalize_input(images, input_norm, dt)
        out = model.apply(vars_, x, train=False)
        if take_first and isinstance(out, (tuple, list)):
            out = out[0]
        return jax.tree_util.tree_map(
            lambda y: y.astype(jnp.float32)
            if jnp.issubdtype(y.dtype, jnp.floating) else y, out)

    images = S((AUDIT_BATCH, sz, sz, ch), in_dtype)
    closed_f32 = jax.jit(predict).trace(variables, images).jaxpr
    plan = quant_lib.plan_quantization(closed_f32, head)
    # unit activation scales: the VALUES are calibration's business
    # (serve/quantize.py); the audited structure is scale-invariant
    plan.act_scales = {q.eqn_index: 1.0 for q in plan.eqns}
    var_specs = [S(tuple(l.shape), l.dtype) for l in
                 jax.tree_util.tree_leaves(variables)]
    qvars = quant_lib.quantized_weight_specs(plan, var_specs)
    qfn = quant_lib.quantized_predict_fn(plan, closed_f32)
    closed, donated, outs = _trace(jax.jit(qfn), qvars, images)
    return TracedUnit(
        f"quant/{cname}", "", "predict", closed, donated, outs,
        meta={"donate": False, "kind": "predict"},
        head_dims=head,
        quant={"planned": len(plan.eqns),
               "skipped_head": plan.skipped_head,
               # the declared float-attention budget: QK^T/PV contractions
               # have no weight operand and deliberately stay float — the
               # QUANT rule allows exactly this many float heavy eqns
               "skipped_attention": plan.skipped_attention,
               "fused_attention": plan.fused_attention,
               "baseline_unit": f"{cname}/serve"})


# -- attention-lowering units (naive vs Pallas fused) -------------------------

# The ViT serve predict traced under BOTH attention lowerings
# (ops/attention.py): the naive einsum path (what CPU serving runs) and the
# Pallas flash kernel (what TPU serving runs — traced via the interpreter
# impl, whose jaxpr is structurally identical to the compiled kernel's, so
# the committed COST rows are a pure function of the package source on any
# host). The pair is the audit-level pin of the kernel's whole point: the
# fused row's bytes proxy must undercut the naive row's (the (N, N) softmax
# chain never reaches HBM) while both carry the same serving contract —
# bench_attn.py enforces the ratio, these rows keep it reviewable PR over PR.
ATTN_UNIT_CONFIG = "vit_tiny"
ATTN_IMPLS = ("naive", "fused")
# Traced at 112 px, not vit_tiny's 32: with patch 8 that is 14 x 14 + cls =
# 197 tokens — the seq ~196 regime the kernel is tiled for. At vit_tiny's
# native 17 tokens the pad-to-BLOCK_K panel (128 keys) would dominate the
# fused row's DMA bytes and the pair would pin the wrong lesson (padding
# overhead, not the (N, N) HBM cut — TUNING.md's regime rule, attention
# edition; docs/ATTENTION.md spells out the crossover).
ATTN_AUDIT_IMAGE = 112


def attn_unit_names() -> List[str]:
    """The audit units the attention-lowering pair contributes — pinned by
    the cost-baseline coverage test next to the per-config unit names."""
    return [f"attn/{ATTN_UNIT_CONFIG}/{impl}" for impl in ATTN_IMPLS]


def _attn_units() -> List[TracedUnit]:
    units: List[TracedUnit] = []
    for impl in ATTN_IMPLS:
        name = f"attn/{ATTN_UNIT_CONFIG}/{impl}"
        try:
            units.append(_attn_unit(name, impl))
        except Exception as e:
            units.append(TracedUnit(name, "", "predict",
                                    error=f"{type(e).__name__}: {e}"))
    return units


def _attn_unit(name: str, impl: str) -> TracedUnit:
    """The ViT serve predict pinned to one attention lowering."""
    from ..core.config import UNIT_RANGE_NORM
    from ..core.steps import _normalize_input
    from ..core.trainer import build_model_from_config
    from ..configs import get_config

    cfg = get_config(ATTN_UNIT_CONFIG)
    # "fused" is traced through the interpreter impl: same kernel, same
    # grid/block structure, platform-independent jaxpr
    traced_impl = "interpret" if impl == "fused" else impl
    cfg = cfg.replace(
        model_kwargs={**cfg.model_kwargs, "attention_impl": traced_impl},
        data=dataclasses.replace(cfg.data, image_size=ATTN_AUDIT_IMAGE))
    model, cfg = build_model_from_config(cfg)
    sz, ch = cfg.data.image_size, cfg.data.channels
    dt = jnp.dtype(cfg.dtype) if cfg.dtype else jnp.bfloat16
    input_norm = UNIT_RANGE_NORM if cfg.data.normalize_on_device else None
    in_dtype = jnp.uint8 if input_norm is not None else jnp.float32

    variables = jax.eval_shape(
        lambda r, x: model.init({"params": r,
                                 "dropout": jax.random.fold_in(r, 1)},
                                x, train=True),
        S((2,), jnp.uint32), S((2, sz, sz, ch), jnp.float32))

    def predict(vars_, images):   # mirrors PredictEngine.__init__'s predict
        x = _normalize_input(images, input_norm, dt)
        out = model.apply(vars_, x, train=False)
        if isinstance(out, (tuple, list)):
            out = out[0]
        return jax.tree_util.tree_map(
            lambda y: y.astype(jnp.float32)
            if jnp.issubdtype(y.dtype, jnp.floating) else y, out)

    closed, donated, outs = _trace(jax.jit(predict), variables,
                                   S((AUDIT_BATCH, sz, sz, ch), in_dtype))
    return TracedUnit(
        name, "", "predict", closed, donated, outs,
        meta={"donate": False, "compute_dtype": dt, "kind": "predict"},
        head_dims=_head_dims(cfg))


# -- mesh-sharded (GSPMD) predict units ---------------------------------------

# The serving mesh axis audited at the IR level: the same predict fn the
# SERVE units trace, re-traced as a GSPMD computation over a FIXED 2-device
# (data=1, model=2) mesh with the engine's own placement rule
# (parallel/mesh.serve_shardings). Same configs as the int8 twins: the
# flagship bandwidth-bound config plus the tiny one preflight runs. Fixed
# topology keeps the jaxpr and the analytic per-chip bytes a pure function
# of the package source on any host with >= 2 devices; 1-device hosts skip
# gracefully (same env-skew pattern as the spatial shard_map step).
MESH_SERVE_CONFIGS = ("lenet5", "resnet50", "vit_tiny")
MESH_SERVE_MODEL_AXIS = 2


def mesh_serve_unit_names() -> List[str]:
    """The audit units the mesh-sharded predict programs contribute —
    pinned by the cost-baseline coverage test next to the per-config unit
    names."""
    return [f"mesh_serve/{name}" for name in MESH_SERVE_CONFIGS]


def _mesh_serve_units() -> List[TracedUnit]:
    units: List[TracedUnit] = []
    for cname in MESH_SERVE_CONFIGS:
        name = f"mesh_serve/{cname}"
        try:
            units.append(_mesh_serve_unit(name, cname))
        except Exception as e:
            units.append(TracedUnit(name, "", "predict",
                                    error=f"{type(e).__name__}: {e}"))
    return units


def _mesh_serve_unit(name: str, cname: str) -> TracedUnit:
    """One config's predict program traced THROUGH jit-with-shardings over
    the serve mesh. The jaxpr must stay collective-free (the COLL bar:
    GSPMD owns placement — `declared_collectives = {}`), its cost row
    gains the analytic per-chip weight bytes, and check_cost's
    divisibility bar holds param_bytes to an even model-axis split."""
    import numpy as np

    from ..configs import get_config
    from ..core.config import UNIT_RANGE_NORM
    from ..core.steps import _normalize_input
    from ..core.trainer import build_model_from_config
    from ..parallel import mesh as mesh_lib

    devs = np.asarray(jax.devices())
    if devs.size < MESH_SERVE_MODEL_AXIS:
        return TracedUnit(
            name, "", "predict",
            skipped=f"needs >= {MESH_SERVE_MODEL_AXIS} devices for a "
                    f"model-parallel serve mesh (have {devs.size})")
    mesh = mesh_lib.make_mesh(devs[:MESH_SERVE_MODEL_AXIS],
                              model_parallel=MESH_SERVE_MODEL_AXIS)
    cfg = _pin_trace_impls(get_config(cname))
    model, cfg = build_model_from_config(cfg)
    sz, ch = cfg.data.image_size, cfg.data.channels
    dt = jnp.dtype(cfg.dtype) if cfg.dtype else jnp.bfloat16
    input_norm = UNIT_RANGE_NORM if cfg.data.normalize_on_device else None
    in_dtype = jnp.uint8 if input_norm is not None else jnp.float32
    take_first = cfg.family == "classification"

    variables = jax.eval_shape(
        lambda r, x: model.init({"params": r,
                                 "dropout": jax.random.fold_in(r, 1)},
                                x, train=True),
        S((2,), jnp.uint32), S((2, sz, sz, ch), jnp.float32))

    def predict(vars_, images):   # mirrors PredictEngine.__init__'s predict
        x = _normalize_input(images, input_norm, dt)
        out = model.apply(vars_, x, train=False)
        if take_first and isinstance(out, (tuple, list)):
            out = out[0]
        return jax.tree_util.tree_map(
            lambda y: y.astype(jnp.float32)
            if jnp.issubdtype(y.dtype, jnp.floating) else y, out)

    param_sh, in_sh, out_sh = mesh_lib.serve_shardings(
        mesh, variables, (sz, sz, ch))
    jitted = jax.jit(predict, in_shardings=(param_sh, in_sh),
                     out_shardings=out_sh)
    closed, donated, outs = _trace(
        jitted, variables, S((AUDIT_BATCH, sz, sz, ch), in_dtype))
    return TracedUnit(
        name, "", "predict", closed, donated, outs,
        meta={"donate": False, "compute_dtype": dt, "kind": "predict",
              "mesh": dict(mesh.shape),
              "param_bytes_per_chip":
                  mesh_lib.analytic_per_chip_bytes(variables, mesh)},
        declared_collectives={},
        head_dims=_head_dims(cfg))


# -- spatial collective probes ------------------------------------------------

def _spatial_probe_units() -> List[TracedUnit]:
    """Trace the REAL spatial collective layer (`parallel/spatial_shard.py`)
    through minimal shard_map bodies over an AbstractMesh and diff the
    collectives found in the jaxpr against the module's own
    DECLARED_COLLECTIVES. This is the layer a mis-axed collective (an
    `all_to_all` over 'data' instead of 'spatial') would corrupt silently."""
    import types

    from jax import lax
    from jax.sharding import AbstractMesh, PartitionSpec as P

    from ..parallel import spatial_shard as ss
    from ..parallel.mesh import DATA_AXIS, SPATIAL_AXIS

    sm = shard_map_fn()
    units: List[TracedUnit] = []
    if sm is None:  # pragma: no cover — every supported jax ships one
        return [TracedUnit("spatial/probes", "", "probe",
                           skipped="no shard_map implementation")]
    mesh = AbstractMesh(((DATA_AXIS, 2), (SPATIAL_AXIS, 2)))

    def probe(probe_name, body, in_specs, out_specs, arg):
        try:
            f = sm(body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   axis_names={DATA_AXIS, SPATIAL_AXIS})
            closed = jax.make_jaxpr(f)(arg)
            return TracedUnit(
                f"spatial/{probe_name}", "", "probe", closed,
                declared_collectives=ss.DECLARED_COLLECTIVES[probe_name],
                traced_collectives=collect_collectives(closed))
        except Exception as e:  # pragma: no cover — env skew
            return TracedUnit(f"spatial/{probe_name}", "", "probe",
                              skipped=f"{type(e).__name__}: {e}")

    x = S((4, 8, 8, 16), jnp.bfloat16)
    units.append(probe(
        "halo_exchange", lambda v: ss.halo_exchange(v, 1, 1, sp=2),
        P(None, SPATIAL_AXIS), P(None, SPATIAL_AXIS), x))

    def transition_body(v):
        ctx = ss.SpatialShardContext(sp=2, transition="handoff")
        mod = types.SimpleNamespace(path=("handoff",))
        out = ctx._maybe_transition(mod, v)
        ctx.assert_transition_consumed()
        return out

    units.append(probe("transition", transition_body,
                       P(None, SPATIAL_AXIS), P(DATA_AXIS), x))

    def grad_body(g):
        return ss.reduce_grads({"w": g}, (DATA_AXIS, SPATIAL_AXIS), 4)["w"]

    units.append(probe("grad_psum", grad_body, P(DATA_AXIS), P(DATA_AXIS),
                       S((8, 16), jnp.float32)))

    # the full shard_map classification step — traceable only where the
    # runtime ships the stable `jax.shard_map` the factories target; on
    # older runtimes this skips with the reason (the same env skew the
    # seed tier-1 suite xfails), while the probes above still ran.
    units.append(_spatial_step_unit())
    return units


def _spatial_step_unit() -> TracedUnit:
    import numpy as np

    from .compat import shard_map_installed
    from ..configs import get_config
    from ..parallel import mesh as mesh_lib
    from ..parallel import spatial_shard as ss

    name = "spatial/shardmap_step"
    try:
        devs = np.asarray(jax.devices())
        if devs.size < 2:
            return TracedUnit(name, "", "probe",
                              skipped=f"needs >= 2 devices for a spatial "
                                      f"mesh (have {devs.size})")
        mesh = mesh_lib.make_mesh(devs[:2], spatial_parallel=2)
        cfg = get_config("resnet50")
        from ..core.trainer import build_model_from_config
        model, cfg = build_model_from_config(cfg)
        tx = _optimizer_for(cfg)
        images = S((AUDIT_BATCH, cfg.data.image_size, cfg.data.image_size,
                    cfg.data.channels), jnp.float32)
        state = _abstract_state(model, tx, images)
        with shard_map_installed():
            step = ss.make_shardmap_classification_train_step(
                mesh=mesh, transition=ss.default_transition(model),
                compute_dtype=jnp.dtype(cfg.dtype),
                label_smoothing=cfg.label_smoothing)
            closed, donated, outs = _trace(
                step, state, images, S((AUDIT_BATCH,), jnp.int32),
                S((2,), jnp.uint32))
        return TracedUnit(name, "resnet50", "train", closed, donated, outs,
                          dict(getattr(step, "_jaxvet", {})),
                          traced_collectives=collect_collectives(closed))
    except Exception as e:
        return TracedUnit(name, "", "probe",
                          skipped=f"{type(e).__name__}: {e}")


# -- registry sweep -----------------------------------------------------------

_FAMILY_BUILDERS: Dict[str, Callable] = {
    "classification": _classification_units,
    "detection": _detection_units,
    "pose": _pose_units,
    "centernet": _centernet_units,
    "segmentation": _segmentation_units,
    "gan": _gan_units,
}


def config_unit_names(name: str) -> List[str]:
    """The audit units a registered config contributes (before tracing) —
    the non-vacuity surface the registry-hygiene test pins the sweep to."""
    from ..configs import CONFIGS
    cfg = CONFIGS.get(name)
    if cfg.family == "gan":
        return ([f"{name}/train"] if cfg.model == "dcgan"
                else [f"{name}/train_gen", f"{name}/train_disc"])
    base = [f"{name}/train", f"{name}/eval", f"{name}/serve"]
    if cfg.family in ("detection", "centernet", "segmentation"):
        base.insert(2, f"{name}/predict")
    return base


def build_units(names: Optional[List[str]] = None,
                progress: Optional[Callable[[str], None]] = None,
                spatial: bool = True, epoch: bool = True,
                quant: bool = True, mesh_serve: bool = True,
                attn: bool = True):
    """Yield TracedUnits for the named configs (default: whole registry,
    plus the spatial collective probes and the epoch-scan units). Each
    unit's jaxpr is yielded and then released by the caller — keeping the
    sweep's live set bounded is what holds the whole-registry wall time
    under the CI budget."""
    from ..configs import CONFIGS

    config_names = CONFIGS.names() if names is None else names
    for cname in config_names:
        cfg = CONFIGS.get(cname)
        builder = _FAMILY_BUILDERS.get(cfg.family)
        if progress:
            progress(cname)
        if builder is None:
            yield TracedUnit(f"{cname}/train", cname, "train",
                             error=f"config family {cfg.family!r} has no "
                                   f"audit builder")
            continue
        try:
            units = builder(cname, cfg)
        except Exception as e:
            yield TracedUnit(f"{cname}/train", cname, "train",
                             error=f"{type(e).__name__}: {e}")
            units = []
        for u in units:
            yield u
        if cfg.family != "gan":
            try:
                yield _serve_unit(cname, cfg)
            except Exception as e:
                yield TracedUnit(f"{cname}/serve", cname, "predict",
                                 error=f"{type(e).__name__}: {e}")
        # the traced object graphs are large; dropping them promptly keeps
        # abstract-eval from slowing down as the sweep accumulates garbage
        del units
        gc.collect()
    if spatial:
        for u in _spatial_probe_units():
            yield u
    if epoch:
        for u in _epoch_scan_units():
            yield u
        gc.collect()
    if quant:
        for u in _quant_units():
            yield u
        gc.collect()
    if attn:
        for u in _attn_units():
            yield u
        gc.collect()
    if mesh_serve:
        for u in _mesh_serve_units():
            yield u
        gc.collect()
