"""jaxvet check families: IR-level invariants over traced audit units.

Each check walks facts the harness extracted from the REAL step's closed
jaxpr (or eval_shape output specs) and compares them against the claim the
factory itself attached via `core.steps.annotate_step` — so what is
verified is exactly what the construction site declared, and neither side
can drift alone. Division of labor vs the AST linter: docs/CHECKING.md.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional

import jax.numpy as jnp

from .harness import TracedUnit
from .jaxpr_walk import collect_collectives, cost_summary, heavy_eqns, \
    param_bytes

ALL_CHECKS: Dict[str, str] = {
    "DTYPE": "no f32 conv/dot reachable inside a declared-bf16 apply "
             "outside the deliberate f32 heads (IR ground truth of the "
             "AST rule DTY001); f32 steps must not silently drop to bf16",
    "DONATE": "the step donates exactly what its factory claims, and every "
              "donated argument is aliasable (shape/dtype matches an "
              "output) — the donation-aliasing segfault class, caught "
              "before XLA",
    "COLL": "spatial shard_map code carries the collectives "
            "parallel/spatial_shard.py declares (ppermute/all_to_all/psum "
            "over the right mesh axes); single-program jit steps carry "
            "none, and mesh-sharded (GSPMD) predict programs carry "
            "exactly what they declare — none, the partitioner owns "
            "collective placement",
    "COST": "per-step FLOPs / bytes-accessed / equation count from the "
            "jaxpr, diffed against the committed CHECK_COST.json baseline; "
            "mesh-sharded predict rows also pin param_bytes_per_chip and "
            "require param_bytes to divide by the model-axis size",
    "SERVE": "PredictEngine bucket signatures {1, 8, 32, max_batch} cover "
             "each servable config's input spec with f32 outputs",
    "QUANT": "the int8 predict twins run their planned conv/dot equations "
             "in int8 (int32 accumulation) with f32 float outputs "
             "preserved, and their weight-argument bytes (param_bytes "
             "cost row) undercut the bf16 twin's by >= 1.8x",
    "TRACE": "every registered (config, model, step-factory) combination "
             "builds and traces abstractly at all",
}

# COST drift tolerances (relative). FLOPs from abstract shapes are exact,
# so any drift is a real model/step change; the bytes proxy may wobble a
# hair with jax's trace-level canonicalization, eqn counts a bit more.
COST_TOLERANCE = {"flops": 1e-6, "bytes": 0.01, "eqns": 0.05,
                  "param_bytes": 1e-6,
                  # mesh-serve rows: the per-chip share is analytic (pure
                  # shapes x sharding rule) and the axis size is topology —
                  # both are exact, any drift is a placement-rule change
                  "param_bytes_per_chip": 1e-6, "mesh_model": 0.0}

# the int8 serve units' hard byte bar: weight-argument bytes must undercut
# the bf16 twin's by at least this factor (f32 -> int8 is ~4x on the
# kernels; BN/bias/head leaves stay f32, so the tree-level cut lands ~3-4x
# — 1.8x is the never-regress floor, enforced per sweep)
QUANT_PARAM_BYTES_FACTOR = 1.8


@dataclasses.dataclass
class Finding:
    unit: str
    check: str
    message: str
    severity: str = "error"

    def format(self) -> str:
        return f"{self.unit}: {self.check} {self.message}"

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


def _is_f32(dtype) -> bool:
    return jnp.dtype(dtype) == jnp.float32


def _eqn_dims(eqn) -> set:
    dims = set()
    for v in list(eqn.invars) + list(eqn.outvars):
        aval = getattr(v, "aval", None)
        if aval is not None and hasattr(aval, "shape"):
            dims.update(int(d) for d in aval.shape)
    return dims


def check_dtype(unit: TracedUnit) -> List[Finding]:
    findings: List[Finding] = []
    policy = unit.meta.get("compute_dtype")
    if unit.closed is None or policy is None:
        # eval_shape units: the serving contract is f32 float outputs
        for aval in unit.out_avals:
            dt = getattr(aval, "dtype", None)
            if dt is not None and jnp.issubdtype(dt, jnp.floating) \
                    and not _is_f32(dt):
                findings.append(Finding(
                    unit.name, "DTYPE",
                    f"float output is {dt}, not float32 — serving/predict "
                    f"outputs must be f32 (engine contract, serve/engine.py)"))
        return findings
    policy = jnp.dtype(policy)
    if unit.meta.get("kind") == "predict":
        # traced predict/serve units keep the engine's f32-output contract
        # on top of the compute-policy audit below
        for aval in unit.out_avals:
            dt = getattr(aval, "dtype", None)
            if dt is not None and jnp.issubdtype(dt, jnp.floating) \
                    and not _is_f32(dt):
                findings.append(Finding(
                    unit.name, "DTYPE",
                    f"float output is {dt}, not float32 — serving/predict "
                    f"outputs must be f32 (engine contract, "
                    f"serve/engine.py)"))
    for eqn, _mult, _flops, in_kernel in heavy_eqns(unit.closed):
        if in_kernel:
            # pallas kernel body: tiles live in VMEM/registers at the
            # kernel's own declared precision (the flash kernel accumulates
            # softmax stats in f32 deliberately) — no HBM traffic, so the
            # bf16 HBM policy does not apply; the kernel's block transfers
            # carry the policy dtype and ARE audited below via their
            # surrounding equations
            continue
        out_dt = jnp.dtype(eqn.outvars[0].aval.dtype)
        float_in = [jnp.dtype(v.aval.dtype) for v in eqn.invars[:2]
                    if hasattr(getattr(v, "aval", None), "dtype")
                    and jnp.issubdtype(v.aval.dtype, jnp.floating)]
        if policy == jnp.bfloat16 and out_dt == jnp.float32 \
                and any(dt == jnp.float32 for dt in float_in):
            # bf16-operand dots that ACCUMULATE in f32 (preferred_element_
            # type, the attention paths) are the policy, not a leak — only
            # an f32 OPERAND betrays f32 data flowing through the step
            if unit.head_dims & _eqn_dims(eqn):
                continue  # deliberate f32 head (models/*.py dtype=f32)
            shape = tuple(eqn.outvars[0].aval.shape)
            findings.append(Finding(
                unit.name, "DTYPE",
                f"f32 {eqn.primitive.name} {shape} inside a declared-"
                f"bfloat16 step (head dims {sorted(unit.head_dims)} not "
                f"involved) — an f32 leak into the compute path, the HBM-"
                f"traffic regression class r05 measured"))
        elif policy == jnp.float32 and out_dt == jnp.bfloat16:
            shape = tuple(eqn.outvars[0].aval.shape)
            findings.append(Finding(
                unit.name, "DTYPE",
                f"bf16 {eqn.primitive.name} {shape} inside a declared-"
                f"float32 step — compute silently below the config's "
                f"precision"))
    return findings


def check_donate(unit: TracedUnit) -> List[Finding]:
    if unit.closed is None:
        return []
    findings: List[Finding] = []
    if "donate" not in unit.meta:
        return [Finding(unit.name, "DONATE",
                        "step carries no _jaxvet claim (factory not built "
                        "through core.steps.annotate_step) — the audit "
                        "cannot verify donation against intent")]
    claimed = bool(unit.meta["donate"])
    if claimed and not unit.donated_avals:
        findings.append(Finding(
            unit.name, "DONATE",
            "factory claims donate=True but the traced step donates no "
            "argument — the state buffers will be copied every step "
            "(double HBM for the largest pytree in the program)"))
    if not claimed and unit.donated_avals:
        findings.append(Finding(
            unit.name, "DONATE",
            f"factory claims donate=False but {len(unit.donated_avals)} "
            f"arguments are donated — a caller reusing its input after "
            f"this step reads freed memory (the PR 1 segfault class)"))
    # aliasability: every donated buffer must have a (shape, dtype)-equal
    # output to alias into, each output absorbing at most one input —
    # otherwise XLA either warns 'donated buffers not usable' or, worse,
    # dies at dispatch with an INTERNAL aliasing size mismatch (the exact
    # failure tests/test_centernet.py shows on jax 0.4.37).
    pool: Dict[tuple, int] = {}
    for aval in unit.out_avals:
        key = (tuple(getattr(aval, "shape", ())), str(getattr(aval, "dtype",
                                                             "?")))
        pool[key] = pool.get(key, 0) + 1
    for aval in unit.donated_avals:
        key = (tuple(aval.shape), str(aval.dtype))
        if pool.get(key, 0) > 0:
            pool[key] -= 1
        else:
            findings.append(Finding(
                unit.name, "DONATE",
                f"donated argument {key[1]}{list(key[0])} has no matching "
                f"output to alias (shape/dtype mismatch) — donation is "
                f"output aliasing, so this buffer is freed for nothing"))
    return findings


def check_coll(unit: TracedUnit) -> List[Finding]:
    findings: List[Finding] = []
    if unit.kind == "probe":
        if unit.skipped or unit.traced_collectives is None:
            return []
        declared = {(p, tuple(a)): n
                    for (p, a), n in (unit.declared_collectives or {}).items()}
        traced = dict(unit.traced_collectives)
        if declared != traced:
            findings.append(Finding(
                unit.name, "COLL",
                f"traced collectives {_fmt_colls(traced)} != declared "
                f"{_fmt_colls(declared)} (parallel/spatial_shard.py "
                f"DECLARED_COLLECTIVES) — a mis-axed collective reduces "
                f"over the wrong ranks and corrupts gradients silently"))
        return findings
    if unit.closed is None:
        return []
    if unit.declared_collectives is not None:
        # mesh-sharded (GSPMD) predict: the traced program must carry
        # EXACTLY what the harness declares — the empty set, because
        # collective insertion (the fc partial-sum all-reduces, the output
        # all-gather) is the partitioner's business at lowering time; an
        # explicit collective in the jaxpr would bake one mesh's topology
        # into code every mesh shape shares
        declared = {(p, tuple(a)): n
                    for (p, a), n in unit.declared_collectives.items()}
        traced = collect_collectives(unit.closed)
        if declared != traced:
            findings.append(Finding(
                unit.name, "COLL",
                f"mesh-sharded predict carries {_fmt_colls(traced)} != "
                f"declared {_fmt_colls(declared)} — GSPMD predict programs "
                f"must leave collective placement to the partitioner"))
        return findings
    if unit.traced_collectives is not None:
        # full shard_map step: the grad psum over both manual axes must be
        # present, and every collective must run over known spatial axes
        traced = unit.traced_collectives
        if not any(p == "psum" and set(a) == {"data", "spatial"}
                   for (p, a) in traced):
            findings.append(Finding(
                unit.name, "COLL",
                f"shard_map train step carries no psum over "
                f"('data', 'spatial') — the controlled gradient reduction "
                f"is missing; found {_fmt_colls(traced)}"))
        for (p, axes) in traced:
            if not set(axes) <= {"data", "spatial"}:
                findings.append(Finding(
                    unit.name, "COLL",
                    f"collective {p} over unknown mesh axes {axes} — the "
                    f"manual axes are ('data', 'spatial')"))
        return findings
    colls = collect_collectives(unit.closed)
    if colls:
        findings.append(Finding(
            unit.name, "COLL",
            f"single-program jit step carries explicit collectives "
            f"{_fmt_colls(colls)} — GSPMD steps must leave collective "
            f"placement to the partitioner"))
    return findings


def _fmt_colls(colls: dict) -> str:
    return "{" + ", ".join(
        f"{p}@{','.join(a)}x{n}" for (p, a), n in sorted(colls.items())) + "}"


def check_serve(unit: TracedUnit) -> List[Finding]:
    if unit.serve is None:
        return []
    findings: List[Finding] = []
    s = unit.serve
    buckets, max_batch = list(s["buckets"]), s["max_batch"]
    if buckets != sorted(set(buckets)) or any(b <= 0 for b in buckets):
        findings.append(Finding(
            unit.name, "SERVE",
            f"bucket signature {buckets} is not strictly ascending "
            f"positive — pick_bucket's search contract"))
    if 1 not in buckets:
        findings.append(Finding(
            unit.name, "SERVE",
            f"bucket signature {buckets} lacks the batch-of-1 bucket — "
            f"single-example requests would pad to {buckets[0]}x"))
    if max_batch < buckets[-1]:
        findings.append(Finding(
            unit.name, "SERVE",
            f"max_batch {max_batch} < largest bucket {buckets[-1]} — the "
            f"batcher would flush batches no compiled program accepts "
            f"(a recompile per oversize flush: the recompile-storm drift)"))
    for bkt, outs in s["probe_outs"].items():
        for aval in outs:
            shape = tuple(getattr(aval, "shape", ()))
            if shape and shape[0] != bkt:
                findings.append(Finding(
                    unit.name, "SERVE",
                    f"predict output {shape} at bucket {bkt} does not keep "
                    f"the batch dim — per-row slicing after padded dispatch "
                    f"would return wrong rows"))
    return findings


def check_quant(unit: TracedUnit) -> List[Finding]:
    """The int8 predict twin really runs int8 where the plan claims: every
    planned heavy equation must take int8 operands and accumulate in int32,
    every float heavy equation left behind must be head-exempt, and the
    dequantized results must keep float32 at the output boundary (the
    engine contract — checked by DTYPE's output rule on the same unit).
    The mutation test widens a quantized conv back to float and this rule
    must fire (tests/test_jaxvet.py)."""
    if unit.quant is None or unit.closed is None:
        return []
    findings: List[Finding] = []
    planned = int(unit.quant.get("planned", 0))
    # a transformer's plan DECLARES its float attention contractions
    # (QK^T/PV have no weight operand — ops/quant.py skipped_attention);
    # exactly that many float heavy equations are budgeted, any excess is
    # the silent-widening regression this rule exists to catch
    attn_budget = int(unit.quant.get("skipped_attention", 0))
    n_int8 = 0
    float_eqns = []
    for eqn, _mult, _flops, in_kernel in heavy_eqns(unit.closed):
        if in_kernel:
            continue  # fused-attention kernel internals: VMEM precision,
            #           declared via the plan's fused_attention count
        in_dt = jnp.dtype(eqn.invars[0].aval.dtype)
        rhs_dt = jnp.dtype(eqn.invars[1].aval.dtype)
        out_dt = jnp.dtype(eqn.outvars[0].aval.dtype)
        if in_dt == jnp.int8 and rhs_dt == jnp.int8:
            if out_dt != jnp.int32:
                findings.append(Finding(
                    unit.name, "QUANT",
                    f"int8 {eqn.primitive.name} accumulates in {out_dt}, "
                    f"not int32 — partial products past 127^2 x taps "
                    f"would wrap silently"))
            n_int8 += 1
            continue
        if jnp.issubdtype(in_dt, jnp.floating) \
                and not unit.head_dims & _eqn_dims(eqn):
            float_eqns.append((eqn, in_dt))
    if len(float_eqns) > attn_budget:
        for eqn, in_dt in float_eqns[attn_budget:]:
            shape = tuple(eqn.outvars[0].aval.shape)
            findings.append(Finding(
                unit.name, "QUANT",
                f"claimed-int8 predict carries a float "
                f"{eqn.primitive.name} {shape} ({in_dt}) outside the f32 "
                f"heads and beyond the plan's declared attention budget "
                f"({attn_budget}) — the quantized path silently widened "
                f"back to float, the exact regression the int8 byte cut "
                f"exists to prevent"))
    if n_int8 < planned:
        findings.append(Finding(
            unit.name, "QUANT",
            f"plan claims {planned} int8 equations but the traced jaxpr "
            f"carries {n_int8} — quantization quietly skipped "
            f"{planned - n_int8} of them"))
    return findings


def check_quant_bytes(unit_name: str, quant_facts: dict,
                      cost_table: dict) -> List[Finding]:
    """The byte-cut bar, enforced against the committed cost rows: the
    int8 unit's weight-argument bytes must undercut its bf16 twin's
    (`<config>/serve`) by QUANT_PARAM_BYTES_FACTOR. Runs in the sweep loop
    (cli.audit) once both rows exist."""
    base_name = quant_facts.get("baseline_unit")
    mine = cost_table.get(unit_name, {}).get("param_bytes")
    theirs = cost_table.get(base_name, {}).get("param_bytes")
    if mine is None or theirs is None:
        return []
    if mine * QUANT_PARAM_BYTES_FACTOR > theirs:
        return [Finding(
            unit_name, "QUANT",
            f"int8 weight-argument bytes {mine} vs bf16 twin "
            f"{base_name} {theirs} — cut is only "
            f"{theirs / max(mine, 1):.2f}x, below the "
            f"{QUANT_PARAM_BYTES_FACTOR:g}x bar (did quantization skip "
            f"the heavy kernels?)")]
    return []


def check_trace(unit: TracedUnit) -> List[Finding]:
    if unit.error:
        return [Finding(unit.name, "TRACE",
                        f"unit failed to build/trace: {unit.error}")]
    return []


def cost_of(unit: TracedUnit) -> Optional[dict]:
    if unit.closed is None or unit.name.startswith("spatial/"):
        return None
    cost = cost_summary(unit.closed)
    if unit.meta.get("kind") == "predict":
        # predict/serve/quant units: the weight bytes one dispatch reads —
        # the serving bandwidth lever the int8 twins halve (the fusion-
        # blind `bytes` proxy cannot see it: int32 accumulators and
        # quantize chains that fuse away dominate it)
        cost["param_bytes"] = param_bytes(unit.closed)
        mesh_axes = unit.meta.get("mesh")
        if mesh_axes:
            # mesh-sharded predict: pin the per-chip share beside the
            # global row (analytic — pure function of leaf shapes and the
            # serve sharding rule, computed by the harness) plus the
            # model-axis size the divisibility bar below checks against
            cost["mesh_model"] = float(mesh_axes.get("model", 1))
            if unit.meta.get("param_bytes_per_chip") is not None:
                cost["param_bytes_per_chip"] = float(
                    unit.meta["param_bytes_per_chip"])
    return cost


def check_cost(unit_name: str, cost: dict,
               baseline_units: Optional[dict]) -> List[Finding]:
    """Diff one unit's cost row against the committed baseline. `None`
    baseline (file absent / --update-cost run) skips the diff; the mesh
    divisibility bar below is baseline-free and always runs."""
    findings: List[Finding] = []
    model_ax = int(cost.get("mesh_model") or 0)
    if (model_ax > 1 and cost.get("param_bytes") is not None
            and int(cost["param_bytes"]) % model_ax):
        # the ISSUE-18 bar: a mesh-sharded predict's weight bytes must
        # divide evenly by the model-axis size, or the placement rule is
        # leaving some chip a ragged share
        findings.append(Finding(
            unit_name, "COST",
            f"mesh-sharded predict param_bytes {int(cost['param_bytes'])} "
            f"does not divide by the model-axis size {model_ax} — per-chip "
            f"shares would be ragged"))
    if baseline_units is None:
        return findings
    base = baseline_units.get(unit_name)
    if base is None:
        findings.append(Finding(
            unit_name, "COST",
            "no baseline row in CHECK_COST.json — run "
            "`python -m deepvision_tpu.check --update-cost` "
            "and commit the diff"))
        return findings
    for field, tol in COST_TOLERANCE.items():
        want, got = base.get(field), cost.get(field)
        if want is None or got is None:
            continue
        denom = max(abs(want), 1)
        if abs(got - want) / denom > tol:
            findings.append(Finding(
                unit_name, "COST",
                f"{field} drifted {want} -> {got} "
                f"({(got - want) / denom:+.2%}, tolerance {tol:.0%}) — if "
                f"intended, refresh the baseline with --update-cost and "
                f"put the diff in the PR"))
    return findings


def load_baseline(path: str) -> Optional[dict]:
    try:
        with open(path) as fp:
            data = json.load(fp)
    except (OSError, ValueError):
        return None
    return data.get("units", {})


def run_checks(unit: TracedUnit, select=None) -> List[Finding]:
    """All non-COST families over one traced unit (COST needs the cross-
    unit baseline and runs in the sweep loop)."""
    out: List[Finding] = []
    wanted = {c.upper() for c in select} if select else None

    def on(check):
        return wanted is None or check in wanted

    if on("TRACE"):
        out.extend(check_trace(unit))
    if unit.error:
        return out
    if unit.kind != "probe":
        # collective probes are bare shard_map bodies, not jitted steps —
        # only COLL speaks about them
        if on("DTYPE"):
            out.extend(check_dtype(unit))
        if on("DONATE"):
            out.extend(check_donate(unit))
        if on("SERVE"):
            out.extend(check_serve(unit))
        if on("QUANT"):
            out.extend(check_quant(unit))
    if on("COLL"):
        out.extend(check_coll(unit))
    return out
