"""jaxvet CLI: `python -m deepvision_tpu.check [configs...] [options]`.

With no positional args, sweeps EVERY registered config (the registry-wide
mode CI runs) plus the spatial collective probes. Positional args name
registered configs to audit alone.

Exit codes (stable, matching the jaxlint CLI contract):
  0 — clean
  1 — findings reported
  2 — usage error (unknown configs/checks, bad flags)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List, Optional, Sequence, Tuple

EXIT_CLEAN, EXIT_FINDINGS, EXIT_USAGE = 0, 1, 2

# the committed cost baseline, PR-over-PR diffable (repo root)
DEFAULT_BASELINE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "CHECK_COST.json")


def audit(names: Optional[Sequence[str]] = None,
          select: Optional[Sequence[str]] = None,
          baseline_path: Optional[str] = None,
          progress=None) -> Tuple[list, dict]:
    """Library entry point: audit the named configs (default: the whole
    registry + spatial probes). Returns (findings, report) where report
    carries the cost table, per-unit status, and skip reasons."""
    from .harness import build_units
    from .rules import Finding, check_cost, cost_of, load_baseline, \
        run_checks
    from ..configs import CONFIGS

    # registry aliases (configs equal in everything but their name, e.g.
    # centernet / objects_as_points) audit identically — trace the first,
    # re-emit its verdicts under the alias's unit names. The sweep still
    # reports one unit set PER REGISTERED NAME (the registry-hygiene
    # non-vacuity contract); it just doesn't pay for the same jaxpr twice.
    # "spatial" / "epoch" / "quant" / "mesh" / "attn" are pseudo-targets:
    # the collective probes, the epoch-scan units, the int8 predict twins,
    # the mesh-sharded predict units, and the attention-lowering units
    # (all part of every full sweep; naming one audits that layer alone)
    full_sweep = not names
    spatial_only = bool(names) and "spatial" in names
    epoch_only = bool(names) and "epoch" in names
    quant_only = bool(names) and "quant" in names
    mesh_only = bool(names) and "mesh" in names
    attn_only = bool(names) and "attn" in names
    pseudo_only = (spatial_only or epoch_only or quant_only or mesh_only
                   or attn_only)
    if pseudo_only:
        names = [n for n in names
                 if n not in ("spatial", "epoch", "quant", "mesh", "attn")]
    requested = (list(names) if names
                 else ([] if pseudo_only else CONFIGS.names()))
    canonical: dict = {}     # config-identity -> first name seen
    alias_of: dict = {}      # alias name -> canonical name
    for n in requested:
        key = repr(CONFIGS.get(n).replace(name="_"))
        if key in canonical:
            alias_of[n] = canonical[key]
        else:
            canonical[key] = n
    sweep_names = [n for n in requested if n not in alias_of]

    wants_cost = select is None or "COST" in {c.upper() for c in select}
    baseline = None
    if wants_cost:
        baseline = load_baseline(baseline_path or DEFAULT_BASELINE)
    findings: list = []
    cost_table: dict = {}
    audited: List[str] = []
    skipped: dict = {}
    by_config: dict = {}     # canonical config -> [(unit suffix, findings,
    #                           cost)] for alias re-emission
    quant_facts: dict = {}   # int8 unit -> facts, for the byte-cut bar
    for unit in build_units(sweep_names, progress=progress,
                            spatial=full_sweep or spatial_only,
                            epoch=full_sweep or epoch_only,
                            quant=full_sweep or quant_only,
                            mesh_serve=full_sweep or mesh_only,
                            attn=full_sweep or attn_only):
        audited.append(unit.name)
        if unit.quant is not None:
            quant_facts[unit.name] = dict(unit.quant)
        if unit.skipped:
            skipped[unit.name] = unit.skipped
            continue
        unit_findings = run_checks(unit, select)
        findings.extend(unit_findings)
        cost = cost_of(unit)
        if cost is not None:
            cost_table[unit.name] = cost
        if unit.config_name:
            suffix = unit.name.split("/", 1)[1] if "/" in unit.name else ""
            by_config.setdefault(unit.config_name, []).append(
                (suffix, unit_findings, cost))
        unit.closed = None  # release the jaxpr before the next trace
    for alias, canon in alias_of.items():
        for suffix, unit_findings, cost in by_config.get(canon, []):
            uname = f"{alias}/{suffix}"
            audited.append(uname)
            findings.extend(Finding(uname, f.check, f.message, f.severity)
                            for f in unit_findings)
            if cost is not None:
                cost_table[uname] = cost
    if wants_cost:
        for uname, cost in cost_table.items():
            findings.extend(check_cost(uname, cost, baseline))
    if select is None or "QUANT" in {c.upper() for c in select}:
        # the int8 byte-cut bar needs BOTH cost rows (the quant unit's and
        # its bf16 twin's), so it runs after the sweep like COST. A
        # quant-only audit skips it when the twin wasn't traced this run.
        from .rules import check_quant_bytes
        for uname, facts in quant_facts.items():
            findings.extend(check_quant_bytes(uname, facts, cost_table))
    findings.sort(key=lambda f: (f.unit, f.check, f.message))
    report = {"units": audited, "skipped": skipped, "cost": cost_table,
              "aliases": alias_of, "n_units": len(audited)}
    return findings, report


def write_baseline(cost_table: dict, path: str) -> None:
    from .harness import AUDIT_BATCH
    payload = {
        "version": 1,
        "audit_batch": AUDIT_BATCH,
        "comment": "jaxvet cost model per traced step (mesh=None, abstract "
                   "batch above): 2*MAC FLOPs over conv/dot, fusion-blind "
                   "bytes proxy, trip-weighted eqn count. Regenerate with "
                   "`python -m deepvision_tpu.check --update-cost` and "
                   "review the diff like a benchmark.",
        "units": {k: cost_table[k] for k in sorted(cost_table)},
    }
    with open(path, "w") as fp:
        json.dump(payload, fp, indent=1, sort_keys=False)
        fp.write("\n")


def _render_text(findings, report, dt) -> str:
    lines = [f.format() for f in findings]
    for name, why in sorted(report["skipped"].items()):
        lines.append(f"# skipped {name}: {why}")
    if findings:
        by_check: dict = {}
        for f in findings:
            by_check[f.check] = by_check.get(f.check, 0) + 1
        summary = ", ".join(f"{k}: {v}" for k, v in sorted(by_check.items()))
        lines.append(f"jaxvet: {len(findings)} finding"
                     f"{'s' if len(findings) != 1 else ''} ({summary}) "
                     f"over {report['n_units']} units in {dt:.1f}s")
    else:
        lines.append(f"jaxvet: clean ({report['n_units']} units, "
                     f"{len(report['skipped'])} skipped) in {dt:.1f}s")
    return "\n".join(lines)


def _render_json(findings, report, dt) -> str:
    by_check: dict = {}
    for f in findings:
        by_check[f.check] = by_check.get(f.check, 0) + 1
    return json.dumps({
        "version": 1,
        "findings": [f.to_json() for f in findings],
        "cost": report["cost"],
        "skipped": report["skipped"],
        "summary": {"units": report["n_units"],
                    "findings": len(findings), "by_check": by_check,
                    "seconds": round(dt, 1)},
    }, indent=2)


def _render_github(findings, report, dt) -> str:
    lines = []
    for f in findings:
        msg = f.message.replace("%", "%25").replace("\n", "%0A")
        lines.append(f"::error title=jaxvet {f.check} ({f.unit})::{msg}")
    if findings:
        lines.append(f"jaxvet: {len(findings)} finding"
                     f"{'s' if len(findings) != 1 else ''}")
    else:
        lines.append(f"jaxvet: clean ({report['n_units']} units) "
                     f"in {dt:.1f}s")
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    from .rules import ALL_CHECKS

    parser = argparse.ArgumentParser(
        prog="python -m deepvision_tpu.check",
        description="jaxvet: jaxpr-level audit of every registered model — "
                    "traces each real train/eval/predict step abstractly "
                    "(zero FLOPs, CPU-safe) and verifies IR invariants. "
                    "Checks: " + "; ".join(
                        f"{cid}: {doc}" for cid, doc in ALL_CHECKS.items()))
    parser.add_argument("configs", nargs="*",
                        help="registered config names to audit "
                             "(default: the whole registry + spatial "
                             "collective probes)")
    parser.add_argument("--format", choices=("text", "json", "github"),
                        default="text",
                        help="github emits ::error workflow annotations")
    parser.add_argument("--select", default=None,
                        help="comma-separated check families to run "
                             "(default: all)")
    parser.add_argument("--baseline", default=None,
                        help="cost baseline JSON (default: repo-root "
                             "CHECK_COST.json)")
    parser.add_argument("--update-cost", action="store_true",
                        help="rewrite the cost baseline from this sweep "
                             "instead of diffing against it")
    parser.add_argument("-v", "--verbose", action="store_true",
                        help="progress lines per config on stderr")
    try:
        args = parser.parse_args(argv)
    except SystemExit as e:
        return EXIT_USAGE if e.code not in (0, None) else 0

    select = None
    if args.select:
        select = [c.strip().upper() for c in args.select.split(",")
                  if c.strip()]
        unknown = [c for c in select if c not in ALL_CHECKS]
        if unknown:
            print(f"usage error: unknown check(s): {', '.join(unknown)}; "
                  f"known: {', '.join(ALL_CHECKS)}", file=sys.stderr)
            return EXIT_USAGE

    from ..configs import CONFIGS
    bad = [n for n in args.configs
           if n not in CONFIGS
           and n not in ("spatial", "epoch", "quant", "mesh", "attn")]
    if bad:
        print(f"usage error: unknown config(s): {', '.join(bad)}; known: "
              f"spatial, epoch, quant, mesh, attn, "
              f"{', '.join(CONFIGS.names())}",
              file=sys.stderr)
        return EXIT_USAGE
    if args.update_cost and args.configs:
        print("usage error: --update-cost rewrites the whole-registry "
              "baseline; run it without config arguments", file=sys.stderr)
        return EXIT_USAGE

    progress = ((lambda name: print(f"[jaxvet] {name}", file=sys.stderr,
                                    flush=True))
                if args.verbose else None)
    t0 = time.perf_counter()
    findings, report = audit(args.configs or None, select,
                             args.baseline, progress=progress)
    dt = time.perf_counter() - t0
    if args.update_cost:
        path = args.baseline or DEFAULT_BASELINE
        write_baseline(report["cost"], path)
        findings = [f for f in findings if f.check != "COST"]
        print(f"wrote {len(report['cost'])} cost rows to {path}",
              file=sys.stderr)

    render = {"json": _render_json, "github": _render_github,
              "text": _render_text}[args.format]
    print(render(findings, report, dt))
    return EXIT_FINDINGS if findings else EXIT_CLEAN


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
