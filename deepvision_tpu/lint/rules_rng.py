"""RNG rules: PRNG key hygiene (RNG001) and the per-step fold invariant
(RNG002).

JAX PRNG keys are values, not stateful generators: drawing from the same key
twice yields the SAME numbers. In this codebase that failure mode is silent
numerics skew — two augmentation draws correlating, or a scanned multi-step
dispatch replaying identical "randomness" k times — not a traceback. Both
rules run on the project call graph (framework.CallGraph): a key handed to a
local helper whose parameter flows into `jax.random.uniform` counts as
consumed at the call site, exactly the `_factor(k_b, ...)` idiom in
data/device_augment.py.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .donation import ProjectIndex
from .framework import (Config, Finding, Module, SCOPE_TYPES, SEVERITY_ERROR,
                        SEVERITY_WARNING, _map_call_args, dotted_str,
                        walk_scope)

Pos = Tuple[int, int]

# jax.random.* that DERIVE or CONSTRUCT keys rather than drawing randomness.
# Deriving (split/fold_in) from one key many times is the blessed tagging
# pattern (core/steps.py folds step_rng with tags 1 and 2); what must never
# repeat is an actual draw.
_NON_DRAWING = {"split", "fold_in", "PRNGKey", "key", "clone", "wrap_key_data",
                "key_data", "key_impl", "default_prng_impl"}


def _drawing_key_arg(call: ast.Call, module: Module) -> Optional[ast.AST]:
    """The key argument of a `jax.random.<sampler>` draw, else None."""
    resolved = module.resolve(call.func)
    if not resolved or not resolved.startswith("jax.random."):
        return None
    fn = resolved.rsplit(".", 1)[-1]
    if fn in _NON_DRAWING:
        return None
    if call.args:
        return call.args[0]
    for kw in call.keywords:
        if kw.arg == "key":
            return kw.value
    return None


def _key_consuming_params(index: ProjectIndex) -> Dict[int, Set[str]]:
    """id(def node) -> parameter names the function consumes as PRNG keys,
    directly (arg 0 of a jax.random draw) or transitively through a resolved
    project callee. Fixpoint over the call graph, memoized per lint run."""
    cached = index.cache.get("rng_key_consumers")
    if cached is not None:
        return cached
    consumers: Dict[int, Set[str]] = {}
    graph = index.graph
    infos = [] if graph is None else [i for lst in graph.defs.values()
                                      for i in lst]
    calls_of = {id(i.node): [c for c in walk_scope(i.node)
                             if isinstance(c, ast.Call)]
                for i in infos if i.params}
    changed = True
    while changed:
        changed = False
        for info in infos:
            params = set(info.params)
            if not params:
                continue
            got = consumers.setdefault(id(info.node), set())
            for call in calls_of[id(info.node)]:
                key = _drawing_key_arg(call, info.module)
                if isinstance(key, ast.Name) and key.id in params \
                        and key.id not in got:
                    got.add(key.id)
                    changed = True
                for callee in graph.resolve_call(info.module, call):
                    callee_consumes = consumers.get(id(callee.node), set())
                    if not callee_consumes:
                        continue
                    skip_self = isinstance(call.func, ast.Attribute)
                    for arg, param in _map_call_args(call, callee, skip_self):
                        if param in callee_consumes \
                                and isinstance(arg, ast.Name) \
                                and arg.id in params and arg.id not in got:
                            got.add(arg.id)
                            changed = True
    index.cache["rng_key_consumers"] = consumers
    return consumers


def _pos(node: ast.AST) -> Pos:
    return (node.lineno, node.col_offset)


def _consumptions(scope: ast.AST, module: Module,
                  index: ProjectIndex) -> Iterator[Tuple[str, ast.Call]]:
    """(key name, call) for every draw in `scope` that consumes a key
    spelled as a plain dotted name."""
    consumers = _key_consuming_params(index)
    for call in walk_scope(scope):
        if not isinstance(call, ast.Call):
            continue
        key = _drawing_key_arg(call, module)
        name = dotted_str(key) if key is not None else None
        if name:
            yield name, call
        if index.graph is not None:
            skip_self = isinstance(call.func, ast.Attribute)
            for callee in index.graph.resolve_call(module, call):
                consumed = consumers.get(id(callee.node), set())
                for arg, param in _map_call_args(call, callee, skip_self):
                    if param in consumed:
                        arg_name = dotted_str(arg)
                        if arg_name:
                            yield arg_name, call


def _stores_of(scope: ast.AST, name: str) -> List[Pos]:
    out = []
    for node in walk_scope(scope):
        if isinstance(node, (ast.Name, ast.Attribute)) \
                and isinstance(getattr(node, "ctx", None),
                               (ast.Store, ast.Del)) \
                and dotted_str(node) == name:
            out.append(_pos(node))
    return out


def _disjoint_branches(module: Module, a: ast.AST, b: ast.AST) -> bool:
    """True when a and b sit in mutually exclusive arms of a shared If (or
    Try handlers): only one of the two draws runs, so no reuse."""
    anc_a = list(module.ancestors(a))
    for anc in module.ancestors(b):
        if isinstance(anc, (ast.If, ast.Try)) and anc in anc_a:
            arms = [anc.body, getattr(anc, "orelse", [])]
            for h in getattr(anc, "handlers", []):
                arms.append(h.body)

            def arm_of(node):
                chain = [node] + list(module.ancestors(node))
                for i, arm in enumerate(arms):
                    if any(n in arm for n in chain):
                        return i
                return None

            ia, ib = arm_of(a), arm_of(b)
            if ia is not None and ib is not None and ia != ib:
                return True
    return False


def _enclosing_loop(module: Module, node: ast.AST,
                    scope: ast.AST) -> Optional[ast.AST]:
    for anc in module.ancestors(node):
        if anc is scope or isinstance(anc, SCOPE_TYPES):
            return None
        if isinstance(anc, (ast.For, ast.While)):
            return anc
    return None


def _terminates_scope(module: Module, node: ast.AST) -> bool:
    """A draw inside `return`/`raise` exits the scope — nothing after it in
    the same scope can run, so it cannot pair with a later draw (the
    early-return branch idiom)."""
    for anc in module.ancestors(node):
        if isinstance(anc, (ast.Return, ast.Raise)):
            return True
        if isinstance(anc, SCOPE_TYPES):
            return False
    return False


def check_rng001(module: Module, index: ProjectIndex,
                 config: Config) -> List[Finding]:
    """RNG001 — the same PRNG key drawn from twice without an intervening
    rebind (straight-line or via loop repetition)."""
    findings: List[Finding] = []
    for scope in module.iter_scopes():
        uses: Dict[str, List[Tuple[Pos, ast.Call]]] = {}
        for name, call in _consumptions(scope, module, index):
            uses.setdefault(name, []).append((_pos(call), call))
        for name, events in uses.items():
            stores = sorted(_stores_of(scope, name))
            events = sorted(set(events), key=lambda e: e[0])
            reported: Set[int] = set()
            for (pa, ca), (pb, cb) in zip(events, events[1:]):
                if any(pa < s <= pb for s in stores):
                    continue
                if _disjoint_branches(module, ca, cb) \
                        or _terminates_scope(module, ca):
                    continue
                if id(cb) in reported:
                    continue
                f = module.finding(
                    cb, "RNG001", SEVERITY_ERROR,
                    f"PRNG key '{name}' is consumed again here (already "
                    f"drawn from at line {pa[0]}) — the same key yields the "
                    f"SAME random numbers, silently correlating the two "
                    f"draws; derive fresh keys first "
                    f"(`jax.random.split({name})` or "
                    f"`jax.random.fold_in({name}, tag)`)")
                if f:
                    findings.append(f)
                    reported.add(id(cb))
            # loop repetition: one textual draw re-runs every iteration
            # with the same key unless the key is rebound inside the loop
            for pos, call in events:
                if id(call) in reported:
                    continue
                loop = _enclosing_loop(module, call, scope)
                if loop is None or _terminates_scope(module, call):
                    continue
                lo, hi = _pos(loop), (getattr(loop, "end_lineno", loop.lineno),
                                      getattr(loop, "end_col_offset", 0))
                if any(lo <= s <= hi for s in stores):
                    continue
                f = module.finding(
                    call, "RNG001", SEVERITY_ERROR,
                    f"PRNG key '{name}' is consumed inside a loop without "
                    f"being rebound in the loop body: every iteration draws "
                    f"the SAME numbers; split per iteration "
                    f"(`keys = jax.random.split({name}, n)`) or fold in the "
                    f"loop index (`jax.random.fold_in({name}, i)`)")
                if f:
                    findings.append(f)
                    reported.add(id(call))
    return findings


# ---------------------------------------------------------------------------
# RNG002 — step key not derived from the step counter
# ---------------------------------------------------------------------------

_RNG_PARAM = {"rng", "key", "prng_key"}
_STATE_ATTRS = {"step", "params", "opt_state", "apply_gradients", "apply_fn",
                "batch_stats", "ema_params"}


def _state_params(fn: ast.AST) -> Set[str]:
    """Parameters that look like a TrainState: some `<param>.<attr>` read in
    the body hits the TrainState surface."""
    args = getattr(fn, "args", None)
    if args is None:
        return set()
    params = {a.arg for a in args.posonlyargs + args.args + args.kwonlyargs}
    out = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Attribute) and node.attr in _STATE_ATTRS \
                and isinstance(node.value, ast.Name) \
                and node.value.id in params:
            out.add(node.value.id)
    return out


def _folds_in_step(fn: ast.AST, module: Module, states: Set[str]) -> bool:
    """True when the body calls `jax.random.fold_in(<x>, <...state.step...>)`
    somewhere — the scan-safe derivation the trainers rely on."""
    for node in ast.walk(fn):
        if not (isinstance(node, ast.Call)
                and module.resolve(node.func) == "jax.random.fold_in"
                and len(node.args) >= 2):
            continue
        for sub in ast.walk(node.args[1]):
            if isinstance(sub, ast.Attribute) and sub.attr == "step" \
                    and isinstance(sub.value, ast.Name) \
                    and sub.value.id in states:
                return True
    return False


def check_rng002(module: Module, index: ProjectIndex,
                 config: Config) -> List[Finding]:
    """RNG002 — a traced step takes a TrainState and an rng, uses the rng,
    but never derives it from `state.step`.

    Why it matters: the trainers pass ONE key per epoch/dispatch and rely on
    every step folding it with the on-device step counter
    (`jax.random.fold_in(rng, state.step)`, core/steps.py). A step that
    consumes the raw key draws identical randomness every invocation under
    `make_multistep_train_step`'s `lax.scan` (the counter advances inside
    the scan, the host key does not) and loses (seed, step)
    reproducibility — the exact invariant the fused device augmentation
    depends on (data/device_augment.py)."""
    findings: List[Finding] = []
    seen: Set[int] = set()
    for fn in (r.info.node for r in index.reached_in(module)):
        if isinstance(fn, ast.Lambda) or id(fn) in seen:
            continue
        seen.add(id(fn))
        args = getattr(fn, "args", None)
        if args is None:
            continue
        params = [a.arg for a in args.posonlyargs + args.args
                  + args.kwonlyargs]
        rng_params = [p for p in params if p in _RNG_PARAM]
        states = _state_params(fn)
        if not rng_params or not states:
            continue
        if _folds_in_step(fn, module, states):
            continue
        for rng in rng_params:
            first_use = next(
                (n for n in ast.walk(fn)
                 if isinstance(n, ast.Name) and n.id == rng
                 and isinstance(n.ctx, ast.Load)), None)
            if first_use is None:
                continue  # `del rng` steps (YOLO/CenterNet/pose): no hazard
            f = module.finding(
                first_use, "RNG002", SEVERITY_WARNING,
                f"traced step consumes '{rng}' without deriving it from the "
                f"step counter: under a scanned multi-step dispatch every "
                f"inner step replays the SAME randomness, and runs lose "
                f"(seed, step) reproducibility — derive "
                f"`step_rng = jax.random.fold_in({rng}, "
                f"{sorted(states)[0]}.step)` first "
                f"(core/steps.py:make_classification_train_step)")
            if f:
                findings.append(f)
            break  # one report per step fn
    return findings
