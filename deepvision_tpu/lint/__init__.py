"""jaxlint — JAX-aware static analysis for deepvision_tpu.

Catches the hazard classes this codebase pays for in pod-hours rather than
tracebacks: use-after-donate aliasing (DON001, the PR 1 checkpoint bug
class), per-call retraces (JIT001), hot-loop host syncs (SYNC001), side
effects under trace (EFF001), tracer bools (TRC001), PRNG key reuse and
un-folded step keys (RNG001/RNG002), dtype-policy leaks (DTY001/DTY002),
mesh-axis / placement inconsistencies (SHD001/SHD002), and the jaxsync
concurrency family for the threaded serving stack — unguarded writes and
non-atomic RMWs against inferred lock guards (LCK001/LCK002), lock-order
deadlock cycles (LCK003), blocking calls under a lock (LCK004), and
never-joined non-daemon threads (THR001). All sixteen rules run on one
shared interprocedural dataflow core (framework.CallGraph +
trace-reach/taint, donation.ProjectIndex), so a hazard that crosses a
function or module boundary is still visible at the call site.

CLI:      python -m deepvision_tpu.lint <paths> [--format json|github]
                                                [--select R,..]
Library:  lint_paths([...]) -> [Finding]
Suppress: `# jaxlint: disable=RULE` inline; `[tool.jaxlint]` in
          pyproject.toml for path excludes. See docs/LINTING.md.

Stdlib-only on purpose: it must run on hosts without jax and must never
trigger backend init.
"""

from .cli import lint_paths, main
from .framework import Config, Finding, load_config
from .rules import ALL_RULES

__all__ = ["ALL_RULES", "Config", "Finding", "lint_paths", "load_config",
           "main"]
