"""jaxlint — JAX-aware static analysis for deepvision_tpu.

Catches the hazard classes this codebase pays for in pod-hours rather than
tracebacks: use-after-donate aliasing (DON001, the PR 1 checkpoint bug
class), per-call retraces (JIT001), hot-loop host syncs (SYNC001), side
effects under trace (EFF001), tracer bools (TRC001), PRNG key reuse and
un-folded step keys (RNG001/RNG002), dtype-policy leaks (DTY001/DTY002),
and mesh-axis / placement inconsistencies (SHD001/SHD002). All eleven rules
run on one shared interprocedural dataflow core (framework.CallGraph +
trace-reach/taint, donation.ProjectIndex), so a hazard that crosses a
function or module boundary is still visible at the call site.

CLI:      python -m deepvision_tpu.lint <paths> [--format json|github]
                                                [--select R,..]
Library:  lint_paths([...]) -> [Finding]
Suppress: `# jaxlint: disable=RULE` inline; `[tool.jaxlint]` in
          pyproject.toml for path excludes. See docs/LINTING.md.

Stdlib-only on purpose: it must run on hosts without jax and must never
trigger backend init.
"""

from .cli import lint_paths, main
from .framework import Config, Finding, load_config
from .rules import ALL_RULES

__all__ = ["ALL_RULES", "Config", "Finding", "lint_paths", "load_config",
           "main"]
