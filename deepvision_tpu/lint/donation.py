"""Project-wide donation inference (pass 1 of the linter).

Answers one question for pass 2's DON001: *which callables donate which
arguments?* Three layers, matching how this codebase actually builds its
jitted steps:

  1. direct — `f = jax.jit(step, donate_argnums=(0,))`, including the
     repo-wide `jit_kwargs` dict idiom:

         jit_kwargs = {}
         if donate:
             jit_kwargs["donate_argnums"] = (0,)
         return jax.jit(step, **jit_kwargs)

  2. factories — a module-level function whose return value is a donating
     `jax.jit(...)` (every `make_*_train_step` in core/ and
     parallel/spatial_shard.py). Indexed by terminal name, project-wide:
     `steps.make_classification_train_step(...)` at a call site in another
     module resolves through this map.

  3. instance attributes — `self.train_step = <factory>(...)` (possibly via
     a lambda-valued `self._step_factory`), so method bodies calling
     `self.train_step(...)` know argument 0 is donated.

Donation inferred from a *conditionally* donating factory (`donate=...`)
is treated as donating: call sites must be written donation-safe for the
donating configuration regardless of the flag's value at runtime.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, NamedTuple, Optional, Set, Tuple

from .framework import (JIT_FNS, CallGraph, Module, ReachedFn,
                        compute_trace_reach, terminal_name, walk_scope)

__all__ = ["JIT_FNS", "Donation", "JittedIndex", "ProjectIndex"]


class Donation(NamedTuple):
    argnums: Tuple[int, ...] = ()
    argnames: Tuple[str, ...] = ()

    def merge(self, other: "Donation") -> "Donation":
        return Donation(tuple(sorted(set(self.argnums) | set(other.argnums))),
                        tuple(sorted(set(self.argnames) | set(other.argnames))))


def _const_positions(node: ast.AST) -> Optional[Tuple[int, ...]]:
    """donate_argnums value: int or tuple/list of ints (constants only)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for el in node.elts:
            if not (isinstance(el, ast.Constant) and isinstance(el.value, int)):
                return None
            out.append(el.value)
        return tuple(out)
    return None


def _const_names(node: ast.AST) -> Optional[Tuple[str, ...]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for el in node.elts:
            if not (isinstance(el, ast.Constant) and isinstance(el.value, str)):
                return None
            out.append(el.value)
        return tuple(out)
    return None


def _dict_donations(scope: ast.AST) -> Dict[str, Donation]:
    """Track `jit_kwargs`-style dicts in a scope: literal keys plus later
    `d["donate_argnums"] = ...` subscript stores. Conservative: any donation
    key ever set on the dict counts."""
    dicts: Dict[str, Donation] = {}
    for node in walk_scope(scope):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            if isinstance(tgt, ast.Name) and isinstance(node.value, ast.Dict):
                don = Donation()
                for key, val in zip(node.value.keys, node.value.values):
                    if not isinstance(key, ast.Constant):
                        continue
                    if key.value == "donate_argnums":
                        don = don.merge(
                            Donation(argnums=_const_positions(val) or (0,)))
                    elif key.value == "donate_argnames":
                        don = don.merge(
                            Donation(argnames=_const_names(val) or ()))
                dicts[tgt.id] = don
            elif (isinstance(tgt, ast.Subscript)
                  and isinstance(tgt.value, ast.Name)
                  and tgt.value.id in dicts
                  and isinstance(tgt.slice, ast.Constant)):
                if tgt.slice.value == "donate_argnums":
                    dicts[tgt.value.id] = dicts[tgt.value.id].merge(
                        Donation(argnums=_const_positions(node.value) or (0,)))
                elif tgt.slice.value == "donate_argnames":
                    dicts[tgt.value.id] = dicts[tgt.value.id].merge(
                        Donation(argnames=_const_names(node.value) or ()))
    return dicts


# Transparent step-metadata wrappers: `return annotate_step(jax.jit(...),
# donate=...)` (core/steps.py — the claim side of jaxvet's IR audit) returns
# the jit callable unchanged, so both indexes must look through it.
STEP_ANNOTATORS = frozenset({"annotate_step"})


def unwrap_annotator(node: ast.AST) -> ast.AST:
    """Peel `annotate_step(<call>, ...)` wrappers off a returned value."""
    while (isinstance(node, ast.Call)
           and terminal_name(node.func) in STEP_ANNOTATORS
           and node.args):
        node = node.args[0]
    return node


def donating_jit_call(call: ast.Call, module: Module,
                      dicts: Dict[str, Donation]) -> Optional[Donation]:
    """Donation of a `jax.jit(...)` call (possibly behind an annotate_step
    wrapper), or None if it doesn't donate (or isn't a jit call at all)."""
    call = unwrap_annotator(call)
    if not isinstance(call, ast.Call) \
            or module.resolve(call.func) not in JIT_FNS:
        return None
    don = Donation()
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            don = don.merge(Donation(argnums=_const_positions(kw.value) or (0,)))
        elif kw.arg == "donate_argnames":
            don = don.merge(Donation(argnames=_const_names(kw.value) or ()))
        elif kw.arg is None:  # **jit_kwargs
            name = kw.value.id if isinstance(kw.value, ast.Name) else None
            if name and name in dicts:
                don = don.merge(dicts[name])
    return don if (don.argnums or don.argnames) else None


class JittedIndex:
    """Which spellings evaluate to ANY jitted callable, donating or not.

    The donation index below answers "does this call donate"; this one
    answers the weaker "is this call a jit dispatch boundary" — what the
    dtype rules need to spot host-side casts crossing into compiled code.
    Same three layers as donation: factories returning a `jax.jit(...)`,
    module-level names bound to one, and instance attrs."""

    def __init__(self) -> None:
        self.factories: Set[str] = set()
        self.module_names: Dict[str, Set[str]] = {}
        self.class_attrs: Dict[str, Set[str]] = {}
        # class name -> attrs holding *factories* (lambda-valued
        # `self._step_factory = lambda ...: make_x_train_step(...)`), so
        # `self.train_step = self._step_factory(...)` resolves as jitted
        self.attr_factories: Dict[str, Set[str]] = {}

    def build(self, modules: Iterable[Module]) -> "JittedIndex":
        modules = list(modules)
        for module in modules:
            for node in ast.walk(module.tree):
                if not isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                for sub in walk_scope(node):
                    if isinstance(sub, ast.Return) \
                            and isinstance(sub.value, ast.Call):
                        ret = unwrap_annotator(sub.value)
                        if isinstance(ret, ast.Call) \
                                and module.resolve(ret.func) in JIT_FNS:
                            self.factories.add(node.name)
        for _ in range(3):  # attrs may chain through factories found above
            changed = False
            for module in modules:
                changed |= self._collect(module)
            if not changed:
                break
        return self

    def _lambda_factory(self, node: ast.AST, module: Module) -> bool:
        """`lambda ...: <jit call or known-factory call>`."""
        return (isinstance(node, ast.Lambda)
                and isinstance(node.body, ast.Call)
                and (module.resolve(node.body.func) in JIT_FNS
                     or terminal_name(node.body.func) in self.factories))

    def _value_jitted(self, node: ast.AST, module: Module,
                      cls_name: Optional[str] = None,
                      self_arg: Optional[str] = None) -> bool:
        if isinstance(node, ast.IfExp):
            return (self._value_jitted(node.body, module, cls_name, self_arg)
                    or self._value_jitted(node.orelse, module, cls_name,
                                          self_arg))
        if not isinstance(node, ast.Call):
            return False
        if module.resolve(node.func) in JIT_FNS:
            return True
        # self._step_factory(...) — attr known to hold a factory lambda
        if (cls_name and isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == self_arg
                and node.func.attr in self.attr_factories.get(cls_name,
                                                              set())):
            return True
        return terminal_name(node.func) in self.factories

    def _collect(self, module: Module) -> bool:
        changed = False
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
                continue
            tgt = node.targets[0]
            scope = module.enclosing_scope(node)
            ctx = module.self_name(scope)
            cls_name = self_arg = None
            if ctx:
                self_arg, cls_name = ctx
            is_self_attr = (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and ctx and tgt.value.id == self_arg)
            if is_self_attr and self._lambda_factory(node.value, module):
                bucket = self.attr_factories.setdefault(cls_name, set())
                if tgt.attr not in bucket:
                    bucket.add(tgt.attr)
                    changed = True
                continue
            if not self._value_jitted(node.value, module, cls_name, self_arg):
                continue
            if isinstance(tgt, ast.Name) \
                    and module.parent(node) is module.tree:
                bucket = self.module_names.setdefault(module.path, set())
                if tgt.id not in bucket:
                    bucket.add(tgt.id)
                    changed = True
            elif is_self_attr:
                bucket = self.class_attrs.setdefault(cls_name, set())
                if tgt.attr not in bucket:
                    bucket.add(tgt.attr)
                    changed = True
        return changed

    def callable_spellings(self, module: Module, scope: ast.AST) -> Set[str]:
        """Dotted spellings that name a jitted callable inside `scope`:
        module-level names, `self.attr` for the enclosing class, and local
        names bound to a jit call / factory call in this scope."""
        out = set(self.module_names.get(module.path, set()))
        ctx = module.self_name(scope)
        self_arg = cls_name = None
        if ctx:
            self_arg, cls_name = ctx
            out |= {f"{self_arg}.{a}"
                    for a in self.class_attrs.get(cls_name, set())}
        for node in walk_scope(scope):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                if self._value_jitted(node.value, module, cls_name, self_arg):
                    out.add(node.targets[0].id)
                elif node.targets[0].id in out:
                    out.discard(node.targets[0].id)
        return out


class ProjectIndex:
    """Dataflow knowledge shared across every file of one lint invocation:
    the donation maps DON001 runs on, the project call graph, the
    interprocedural trace-reach/taint map, and the jitted-callable index."""

    def __init__(self) -> None:
        # factory terminal name -> Donation of the jitted callable it returns
        self.factories: Dict[str, Donation] = {}
        # class name -> attr -> Donation (instance attrs holding jitted steps)
        self.class_attrs: Dict[str, Dict[str, Donation]] = {}
        # class name -> attr -> Donation (attrs holding *factories*, i.e.
        # lambdas whose body calls a donating factory — `self._step_factory`)
        self.attr_factories: Dict[str, Dict[str, Donation]] = {}
        # module path -> top-level name -> Donation
        self.module_names: Dict[str, Dict[str, Donation]] = {}
        self.graph: Optional[CallGraph] = None
        # id(fn node) -> ReachedFn for every function that runs under trace
        self.reach: Dict[int, ReachedFn] = {}
        self.jitted = JittedIndex()
        # scratch space for per-run derived analyses (rule modules memoize
        # their own fixpoints here instead of recomputing per file)
        self.cache: Dict[str, object] = {}

    # -- building ------------------------------------------------------------
    def build(self, modules: Iterable[Module]) -> "ProjectIndex":
        modules = list(modules)
        self.graph = CallGraph(modules)
        self.reach = compute_trace_reach(self.graph)
        self.jitted.build(modules)
        for module in modules:
            self._collect_factories(module)
        # attr assignments can reference factories from other modules and
        # attr-factories assigned in other methods: a short fixpoint settles
        # the `self._step_factory = lambda...` / `self.train_step =
        # self._step_factory(...)` chain regardless of statement order.
        for _ in range(3):
            changed = False
            for module in modules:
                changed |= self._collect_attrs(module)
            if not changed:
                break
        for module in modules:
            self._collect_module_names(module)
        return self

    def reached_in(self, module: Module):
        """ReachedFn entries whose def lives in `module`, i.e. every function
        here that executes under a jax trace (directly or via a call chain
        from another module's traced code)."""
        return [r for r in self.reach.values() if r.info.module is module]

    def _collect_factories(self, module: Module) -> None:
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            dicts = _dict_donations(node)
            for sub in walk_scope(node):
                if isinstance(sub, ast.Return) and isinstance(sub.value,
                                                              ast.Call):
                    don = donating_jit_call(sub.value, module, dicts)
                    if don:
                        self.factories[node.name] = self.factories.get(
                            node.name, Donation()).merge(don)

    def _lambda_factory_donation(self, node: ast.AST,
                                 module: Module) -> Optional[Donation]:
        """`lambda ...: make_x_train_step(...)` -> that factory's donation."""
        if isinstance(node, ast.Lambda) and isinstance(node.body, ast.Call):
            name = terminal_name(node.body.func)
            if name in self.factories:
                return self.factories[name]
        return None

    def value_donation(self, node: ast.AST, module: Module,
                       dicts: Dict[str, Donation],
                       local_factories: Dict[str, Donation],
                       cls_name: Optional[str] = None,
                       self_arg: Optional[str] = None) -> Optional[Donation]:
        """Donation of the callable an expression evaluates to, if any."""
        if isinstance(node, ast.IfExp):
            for branch in (node.body, node.orelse):
                don = self.value_donation(branch, module, dicts,
                                          local_factories, cls_name, self_arg)
                if don:
                    return don
            return None
        if not isinstance(node, ast.Call):
            return None
        don = donating_jit_call(node, module, dicts)
        if don:
            return don
        name = terminal_name(node.func)
        if name in local_factories:
            return local_factories[name]
        # self._step_factory(...) — attr known to hold a donating factory
        if (cls_name and isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == self_arg):
            attr_don = self.attr_factories.get(cls_name, {}).get(node.func.attr)
            if attr_don:
                return attr_don
        if name in self.factories:
            return self.factories[name]
        return None

    def _collect_attrs(self, module: Module) -> bool:
        changed = False
        for cls in ast.walk(module.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            for method in cls.body:
                if not isinstance(method, (ast.FunctionDef,
                                           ast.AsyncFunctionDef)):
                    continue
                if not method.args.args:
                    continue
                self_arg = method.args.args[0].arg
                dicts = _dict_donations(method)
                local_factories: Dict[str, Donation] = {}
                for node in walk_scope(method):
                    if not (isinstance(node, ast.Assign)
                            and len(node.targets) == 1):
                        continue
                    tgt = node.targets[0]
                    lam = self._lambda_factory_donation(node.value, module)
                    if isinstance(tgt, ast.Name) and lam:
                        local_factories[tgt.id] = lam
                        continue
                    if not (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == self_arg):
                        continue
                    if lam:
                        bucket = self.attr_factories.setdefault(cls.name, {})
                        if bucket.get(tgt.attr) != lam:
                            bucket[tgt.attr] = lam
                            changed = True
                        continue
                    don = self.value_donation(node.value, module, dicts,
                                              local_factories, cls.name,
                                              self_arg)
                    if don:
                        bucket = self.class_attrs.setdefault(cls.name, {})
                        merged = bucket.get(tgt.attr, Donation()).merge(don)
                        if bucket.get(tgt.attr) != merged:
                            bucket[tgt.attr] = merged
                            changed = True
        return changed

    def _collect_module_names(self, module: Module) -> None:
        names: Dict[str, Donation] = {}
        dicts = _dict_donations(module.tree)
        for node in module.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                don = self.value_donation(node.value, module, dicts, {})
                if don:
                    names[node.targets[0].id] = don
        if names:
            self.module_names[module.path] = names
