"""Sharding-consistency rules.

  SHD001  a mesh-axis name in a PartitionSpec / shard_map / collective that
          no mesh constructed anywhere in the project defines. GSPMD axis
          names are stringly-typed: a typo ('sptial') compiles fine in the
          editor and dies minutes into a pod bring-up with an XLA error —
          or worse, a P() that silently replicates. The universe of valid
          names is built project-wide from every `Mesh(...)` construction
          and `axis_names=`/pmap-`axis_name=` definition, with constants
          (`DATA_AXIS = "data"`) resolved through the call graph's
          constant index, so `P(DATA_AXIS, SPATIAL_AXIS)` in
          parallel/spatial_shard.py checks against the axes
          parallel/mesh.py actually builds.
  SHD002  `jax.device_put(x)` with no explicit sharding/device inside a hot
          train/serve loop: placement falls to the default device and the
          first collective re-shards the value EVERY step — a hidden
          per-batch transfer. Batches crossing into a mesh must carry their
          sharding (parallel/mesh.py:shard_batch_pytree is the pattern).
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from .donation import ProjectIndex
from .framework import (Config, Finding, Module, SEVERITY_ERROR,
                        SEVERITY_WARNING, _is_hot_loop, _loop_statements,
                        walk_scope)

_MESH_FNS = {"jax.sharding.Mesh", "jax.interpreters.pxla.Mesh", "Mesh",
             "jax.make_mesh", "jax.sharding.make_mesh"}
_SPEC_FNS = {"jax.sharding.PartitionSpec", "PartitionSpec",
             "jax.experimental.pjit.PartitionSpec"}
_SHARD_MAP_FNS = {"jax.shard_map", "jax.experimental.shard_map.shard_map"}
_AXIS_DEFINERS = {"jax.pmap", "jax.vmap"}
_COLLECTIVES = {
    "jax.lax.psum", "jax.lax.pmean", "jax.lax.pmax", "jax.lax.pmin",
    "jax.lax.ppermute", "jax.lax.pshuffle", "jax.lax.all_gather",
    "jax.lax.all_to_all", "jax.lax.psum_scatter", "jax.lax.axis_index",
    "jax.lax.axis_size",
}


def _axis_universe(index: ProjectIndex) -> Set[str]:
    """Every axis name any mesh construction (or pmap/vmap axis definition)
    in the project can produce. Memoized per lint run; an empty universe
    disables SHD001 (the project builds its meshes elsewhere)."""
    cached = index.cache.get("shd_axis_universe")
    if cached is not None:
        return cached
    universe: Set[str] = set()
    graph = index.graph
    for module in ([] if graph is None else graph.modules):
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            resolved = module.resolve(node.func)
            scope = module.enclosing_scope(node)
            if resolved in _MESH_FNS and len(node.args) >= 2:
                universe.update(graph.resolve_strings(module, node.args[1],
                                                      scope))
            for kw in node.keywords:
                if kw.arg == "axis_names" \
                        and resolved not in _SHARD_MAP_FNS:
                    # axis_names DEFINES axes everywhere except shard_map,
                    # where it selects manual axes of an existing mesh (a
                    # use, checked below)
                    universe.update(graph.resolve_strings(module, kw.value,
                                                          scope))
                elif kw.arg == "axis_name" and resolved in _AXIS_DEFINERS:
                    universe.update(graph.resolve_strings(module, kw.value,
                                                          scope))
    index.cache["shd_axis_universe"] = universe
    return universe


def _check_axes(module: Module, index: ProjectIndex, node: ast.AST,
                expr: ast.AST, universe: Set[str], what: str,
                findings: List[Finding]) -> None:
    graph = index.graph
    if graph is None:
        return
    scope = module.enclosing_scope(node)
    for name in graph.resolve_strings(module, expr, scope):
        if name in universe:
            continue
        f = module.finding(
            node, "SHD001", SEVERITY_ERROR,
            f"mesh axis '{name}' in {what} is not defined by any mesh "
            f"constructed in this project (known axes: "
            f"{', '.join(sorted(universe))}) — a typo'd axis name "
            f"compiles locally and fails (or silently replicates) on the "
            f"pod; use the shared axis constants "
            f"(parallel/mesh.py:DATA_AXIS/SPATIAL_AXIS/MODEL_AXIS)")
        if f:
            findings.append(f)


def check_shd001(module: Module, index: ProjectIndex,
                 config: Config) -> List[Finding]:
    universe = _axis_universe(index)
    if not universe:
        return []
    findings: List[Finding] = []
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        resolved = module.resolve(node.func)
        if resolved in _SPEC_FNS:
            for arg in node.args:
                _check_axes(module, index, node, arg, universe,
                            "PartitionSpec", findings)
        elif resolved in _SHARD_MAP_FNS:
            for kw in node.keywords:
                if kw.arg == "axis_names":
                    _check_axes(module, index, node, kw.value, universe,
                                "shard_map axis_names", findings)
        elif resolved in _COLLECTIVES:
            axis_expr: Optional[ast.AST] = None
            if len(node.args) >= 2:
                axis_expr = node.args[1]
            for kw in node.keywords:
                if kw.arg == "axis_name":
                    axis_expr = kw.value
            if axis_expr is not None:
                _check_axes(module, index, node, axis_expr, universe,
                            f"{resolved.rsplit('.', 1)[-1]}(axis_name=...)",
                            findings)
    return findings


def check_shd002(module: Module, index: ProjectIndex,
                 config: Config) -> List[Finding]:
    findings: List[Finding] = []
    for loop in ast.walk(module.tree):
        if not isinstance(loop, (ast.For, ast.While)):
            continue
        # outermost hot loop only, mirroring SYNC001; serving dispatch loops
        # (predict/submit callees) count as hot here too
        if any(isinstance(a, (ast.For, ast.While))
               and _is_hot_loop(a, config, serve=True)
               for a in module.ancestors(loop)):
            continue
        if not _is_hot_loop(loop, config, serve=True):
            continue
        for node in _loop_statements(loop):
            if not (isinstance(node, ast.Call)
                    and module.resolve(node.func) == "jax.device_put"
                    and len(node.args) == 1
                    and not any(kw.arg in ("device", "sharding") or
                                kw.arg is None
                                for kw in node.keywords)):
                continue
            f = module.finding(
                node, "SHD002", SEVERITY_WARNING,
                "jax.device_put without an explicit sharding inside a hot "
                "loop: the batch lands on the default device and gets "
                "implicitly re-sharded by the first computation that "
                "needs it — a hidden per-step transfer; pass the batch "
                "sharding (parallel/mesh.py:shard_batch_pytree / "
                "batch_sharding) or hoist the put to setup time")
            if f:
                findings.append(f)
    return findings
