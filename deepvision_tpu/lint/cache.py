"""mtime-keyed jaxlint result cache (`.cache/jaxlint/` under the project).

Soundness model. A file's findings are a pure function of (its source, the
project-wide ProjectIndex, the [tool.jaxlint] config, the linter's own
code). The index is built from EVERY file, so per-file reuse is only sound
when the index inputs are provably unchanged:

  * full skip — every file's (mtime_ns, size) stamp matches the cache:
    return the stored findings without parsing anything (make semantics;
    the `make lint` / preflight double-run path, ~6s -> ~0.3s of work).
  * per-file reuse — some stamps changed: parse everything, rebuild the
    index, and hash every file's CONTENT into one project key. Files whose
    own stamp matches AND whose stored project key equals the fresh one
    reuse their stored findings — this is exactly the touch-without-edit
    case (mtime moved, content didn't, index provably identical). Any real
    content change anywhere changes the project key and re-runs the rules
    everywhere (conservative: interprocedural rules mean a change in file
    B may alter findings in file A).

The cache key also folds in the linter package's own file stamps and the
pyproject's content, so upgrading a rule or editing config invalidates
everything. `--no-cache` bypasses reads and writes entirely; `--select`
runs never touch the cache (their findings are a subset).
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

from .framework import Finding

# v2: the LCK/THR concurrency family landed — caches written by the
# 11-rule linter must never serve silence for rules they didn't run
CACHE_VERSION = 2
CACHE_DIR = os.path.join(".cache", "jaxlint")
CACHE_NAME = "cache.json"


def cache_file(root: str) -> str:
    return os.path.join(root, CACHE_DIR, CACHE_NAME)


def file_stamp(path: str) -> Optional[Tuple[int, int]]:
    try:
        st = os.stat(path)
    except OSError:
        return None
    return (st.st_mtime_ns, st.st_size)


def _lint_pkg_stamp() -> str:
    """Stamp of the linter's own sources — a rule edit must invalidate."""
    pkg = os.path.dirname(os.path.abspath(__file__))
    parts = []
    for fn in sorted(os.listdir(pkg)):
        if fn.endswith(".py"):
            parts.append((fn, file_stamp(os.path.join(pkg, fn))))
    return hashlib.sha1(repr(parts).encode()).hexdigest()


def meta_key(config_path: Optional[str]) -> str:
    """Environment half of the cache key: linter code + config content."""
    cfg = ""
    if config_path and os.path.isfile(config_path):
        with open(config_path, "rb") as fp:
            cfg = hashlib.sha1(fp.read()).hexdigest()
    return f"v{CACHE_VERSION}:{_lint_pkg_stamp()}:{cfg}"


def project_key(root: str, contents: Dict[str, bytes]) -> str:
    """Content hash over every linted file — equality proves the
    ProjectIndex inputs (and therefore the index) are unchanged."""
    h = hashlib.sha1()
    for path in sorted(contents):
        rel = os.path.relpath(path, root)
        h.update(rel.encode())
        h.update(hashlib.sha1(contents[path]).digest())
    return h.hexdigest()


class LintCache:
    def __init__(self, root: str, config_path: Optional[str]):
        self.root = root
        self.path = cache_file(root)
        self.meta = meta_key(config_path)
        self._data: dict = {}
        try:
            with open(self.path) as fp:
                data = json.load(fp)
            if data.get("meta") == self.meta:
                self._data = data
        except (OSError, ValueError):
            pass

    # -- reads ---------------------------------------------------------------
    def full_skip(self, files: Sequence[str]) -> Optional[List[Finding]]:
        """All stamps match -> the stored findings verbatim, else None."""
        entries = self._data.get("files", {})
        if set(entries) != set(files):
            return None
        findings: List[Finding] = []
        for path in files:
            e = entries[path]
            if file_stamp(path) != tuple(e["stamp"]):
                return None
            findings.extend(Finding(**f) for f in e["findings"])
        return findings

    def reusable(self, path: str, fresh_project_key: str) -> Optional[list]:
        """Stored findings for one file, iff its own stamp matches AND the
        project content key proves the index unchanged."""
        if self._data.get("project_key") != fresh_project_key:
            return None
        e = self._data.get("files", {}).get(path)
        if e is None or file_stamp(path) != tuple(e["stamp"]):
            return None
        return [Finding(**f) for f in e["findings"]]

    # -- writes --------------------------------------------------------------
    def store(self, fresh_project_key: str,
              per_file: Dict[str, List[Finding]]) -> None:
        payload = {
            "meta": self.meta,
            "project_key": fresh_project_key,
            "files": {
                path: {"stamp": list(file_stamp(path) or (0, 0)),
                       "findings": [f.to_json() for f in findings]}
                for path, findings in per_file.items()
            },
        }
        try:
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
            tmp = self.path + ".tmp"
            with open(tmp, "w") as fp:
                json.dump(payload, fp)
            os.replace(tmp, self.path)
        except OSError:
            pass  # a read-only tree lints fine, just uncached
