"""jaxlint CLI: `python -m deepvision_tpu.lint [paths] [options]`.

With no paths, lints the whole project rooted at the nearest pyproject.toml
(the default lint set: the package, tools/, tests/, the per-model
entrypoints, AND the repo-root scripts — bench*.py, __graft_entry__.py —
minus `[tool.jaxlint] exclude`).

Exit codes (stable, for CI):
  0 — clean
  1 — findings reported
  2 — usage error (unknown paths/rules, bad flags, no project root found)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional, Sequence, Tuple

from .cache import LintCache, project_key
from .donation import ProjectIndex
from .framework import Config, Finding, Module, find_pyproject, load_config
from .rules import ALL_RULES

EXIT_CLEAN, EXIT_FINDINGS, EXIT_USAGE = 0, 1, 2


def collect_files(paths: Sequence[str], config: Config,
                  root: str) -> List[str]:
    files: List[str] = []
    for path in paths:
        if os.path.isfile(path):
            # a file named explicitly is linted even if excluded — excludes
            # govern directory walks, not direct requests (fixture debugging)
            if path.endswith(".py"):
                files.append(path)
        else:
            for dirpath, dirnames, filenames in os.walk(path):
                dirnames[:] = sorted(
                    d for d in dirnames
                    if d != "__pycache__" and not d.startswith(".")
                    and not config.is_excluded(os.path.join(dirpath, d), root))
                for fn in sorted(filenames):
                    full = os.path.join(dirpath, fn)
                    if fn.endswith(".py") and not config.is_excluded(full,
                                                                     root):
                        files.append(full)
    return files


def _lint(paths: Sequence[str], config: Optional[Config],
          select: Optional[Sequence[str]],
          root: Optional[str],
          use_cache: bool = True) -> Tuple[List[Finding], int]:
    pyproject = None
    if config is None:
        pyproject = find_pyproject(os.path.abspath(paths[0]) if paths
                                   else os.getcwd())
        config = load_config(pyproject)
        if root is None and pyproject:
            root = os.path.dirname(pyproject)
    root = root or os.getcwd()
    if pyproject is None:
        guess = os.path.join(root, "pyproject.toml")
        pyproject = guess if os.path.isfile(guess) else None
    files = collect_files(paths, config, root)

    # mtime-keyed result cache (lint/cache.py): a --select run checks a
    # subset of rules, so its findings never enter or leave the cache
    cache = None
    if use_cache and select is None:
        cache = LintCache(root, pyproject)
        stored = cache.full_skip(files)
        if stored is not None:
            stored.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
            return stored, len(files)

    modules: List[Module] = []
    findings: List[Finding] = []
    per_file: dict = {}
    contents: dict = {}
    for path in files:
        if cache is not None:
            try:
                with open(path, "rb") as fp:
                    contents[path] = fp.read()
            except OSError:
                contents[path] = b""
        try:
            modules.append(Module.from_path(path))
        except SyntaxError as e:
            bad = Finding(path, e.lineno or 1, e.offset or 1,
                          "SYNTAX", "error",
                          f"cannot parse file: {e.msg}")
            findings.append(bad)
            per_file[path] = [bad]

    index = ProjectIndex().build(modules)
    fresh_key = project_key(root, contents) if cache is not None else ""
    wanted = {r.upper() for r in select} if select else None
    for module in modules:
        reused = (cache.reusable(module.path, fresh_key)
                  if cache is not None else None)
        if reused is not None:
            module_findings = reused
        else:
            module_findings = []
            for rule_id, (_, check, _doc) in ALL_RULES.items():
                if wanted is not None and rule_id not in wanted:
                    continue
                if not config.rule_enabled(rule_id):
                    continue
                module_findings.extend(check(module, index, config))
        per_file[module.path] = module_findings
        findings.extend(module_findings)
    if cache is not None:
        cache.store(fresh_key, per_file)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings, len(files)


def lint_paths(paths: Sequence[str], config: Optional[Config] = None,
               select: Optional[Sequence[str]] = None,
               root: Optional[str] = None,
               use_cache: bool = True) -> List[Finding]:
    """Library entry point: lint files/directories, return sorted findings.
    `config=None` loads `[tool.jaxlint]` from the nearest pyproject.toml."""
    return _lint(paths, config, select, root, use_cache=use_cache)[0]


def _render_github(findings: List[Finding], n_files: int) -> str:
    """GitHub Actions workflow annotations: one `::error`/`::warning`
    command per finding (rendered inline on the PR diff), then the same
    human summary line the text format ends with."""
    lines = []
    for f in findings:
        kind = "error" if f.severity == "error" else "warning"
        # the message lands in the annotation body; newlines must be %0A
        msg = f.message.replace("%", "%25").replace("\n", "%0A")
        lines.append(f"::{kind} file={f.path},line={f.line},col={f.col},"
                     f"title=jaxlint {f.rule}::{msg}")
    if findings:
        lines.append(f"jaxlint: {len(findings)} finding"
                     f"{'s' if len(findings) != 1 else ''}")
    else:
        lines.append(f"jaxlint: clean ({n_files} files)")
    return "\n".join(lines)


def _render_text(findings: List[Finding], n_files: int) -> str:
    lines = [f.format() for f in findings]
    if findings:
        by_rule: dict = {}
        for f in findings:
            by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
        summary = ", ".join(f"{k}: {v}" for k, v in sorted(by_rule.items()))
        lines.append(f"jaxlint: {len(findings)} finding"
                     f"{'s' if len(findings) != 1 else ''} ({summary})")
    else:
        lines.append(f"jaxlint: clean ({n_files} files)")
    return "\n".join(lines)


def _render_json(findings: List[Finding], n_files: int) -> str:
    by_rule: dict = {}
    for f in findings:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    return json.dumps({
        "version": 1,
        "findings": [f.to_json() for f in findings],
        "summary": {"files": n_files, "findings": len(findings),
                    "by_rule": by_rule},
    }, indent=2)


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m deepvision_tpu.lint",
        description="JAX-aware static analysis: donation-aliasing, retrace, "
                    "host-sync, trace-side-effect, tracer-bool, and "
                    "thread/lock-discipline hazards. "
                    "Rules: " + "; ".join(
                        f"{rid}: {doc}"
                        for rid, (_, _, doc) in ALL_RULES.items()))
    parser.add_argument("paths", nargs="*",
                        help="files or directories to lint (default: the "
                             "project rooted at the nearest pyproject.toml)")
    parser.add_argument("--format", choices=("text", "json", "github"),
                        default="text",
                        help="github emits ::error/::warning workflow "
                             "annotations for Actions")
    parser.add_argument("--select", default=None,
                        help="comma-separated rule ids or family prefixes "
                             "to run, e.g. DON001 or LCK,THR "
                             "(default: all)")
    parser.add_argument("--config", default=None,
                        help="pyproject.toml to read [tool.jaxlint] from "
                             "(default: nearest to the first path)")
    parser.add_argument("--no-cache", action="store_true",
                        help="bypass the mtime-keyed result cache under "
                             ".cache/jaxlint/ (reads and writes)")
    try:
        args = parser.parse_args(argv)
    except SystemExit as e:
        return EXIT_USAGE if e.code not in (0, None) else 0
    if not args.paths:
        # default lint set: everything under the project root, so the
        # repo-root scripts (bench*.py, __graft_entry__.py) are swept too
        anchor = (os.path.dirname(os.path.abspath(args.config))
                  if args.config else os.getcwd())
        pyproject = find_pyproject(anchor)
        if not pyproject:
            print("usage error: no paths given and no pyproject.toml found "
                  "upward of the working directory", file=sys.stderr)
            return EXIT_USAGE
        args.paths = [os.path.dirname(pyproject) or "."]
    for path in args.paths:
        if not os.path.exists(path):
            print(f"usage error: no such path: {path}", file=sys.stderr)
            return EXIT_USAGE
    select = None
    if args.select:
        select, unknown = [], []
        for token in (r.strip().upper() for r in args.select.split(",")):
            if not token:
                continue
            if token in ALL_RULES:
                select.append(token)
                continue
            # a family prefix selects the whole family: LCK -> LCK001..4
            family = [r for r in ALL_RULES if r.startswith(token)]
            if family:
                select.extend(family)
            else:
                unknown.append(token)
        if unknown:
            print(f"usage error: unknown rule(s): {', '.join(unknown)}; "
                  f"known: {', '.join(ALL_RULES)}", file=sys.stderr)
            return EXIT_USAGE

    config = root = None
    if args.config is not None:
        if not os.path.isfile(args.config):
            print(f"usage error: config not found: {args.config}",
                  file=sys.stderr)
            return EXIT_USAGE
        config = load_config(args.config)
        root = os.path.dirname(os.path.abspath(args.config))

    findings, n_files = _lint(args.paths, config, select, root,
                              use_cache=not args.no_cache)
    render = {"json": _render_json, "github": _render_github,
              "text": _render_text}[args.format]
    print(render(findings, n_files))
    return EXIT_FINDINGS if findings else EXIT_CLEAN


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
